"""The explain drill: prove the decision-provenance plane earns its keep.

ISSUE 14's acceptance instrument: a deterministic 10k-pod problem over the
full fleet catalog whose pods are split into labelled failure categories —

  - ``fit``       fitting pods (tolerate the drill taint, small requests),
  - ``taintpod``  taint-blocked (no toleration for the provisioner taint),
  - ``selpod``    requirement-blocked (node selector names an instance
                  type the catalog does not sell),
  - ``hugepod``   resource-blocked (4000-core request no type can fit),
  - ``aaz``       affinity-blocked (zone anti-affinity group larger than
                  the zone universe; surplus pods are pinned to the
                  sentinel no-zone and become unschedulable) —

and the drill asserts three things:

  1. **attribution** — every unschedulable group (100% of unassigned
     pods) gets a ranked mask-attribution verdict, and each category's
     dominant dimension is the one the mix was built to trip;
  2. **parity** — every attribution ``reason`` clause is string-identical
     (``==``) to the scalar oracle's ``diagnose_unschedulable`` verdict
     for the same pod — the north-star audit for the explain plane;
  3. **overhead** — min-of-repeats solve wall with the explain plane ON
     is within 1% of the plane-disabled baseline (the plane is lazy:
     nothing on the solve hot path), with the interleaved-p50 delta
     recorded alongside.

The artifact lands at benchmarks/results/explain/explain_drill.json
(deterministic path — re-running overwrites) and coverage/parity/overhead
are recorded through benchmarks/ledger.py so `make perf-regress` gates
them like any other perf metric. Run via `make explain-drill`.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

OUT_DIR = os.path.join(os.path.dirname(__file__), "results", "explain")
ARTIFACT = os.path.join(OUT_DIR, "explain_drill.json")

PODS = 10_000
REPEATS = 9
WARMUP = 2
MAX_OVERHEAD_SHARE = 0.01
N_DEVICES = 8
AAZ_COUNT = 8  # > the fleet's 3 zones, so 5 surplus pods cannot place

# pod-name prefix -> the mask dimension that category was built to trip
# (None = the category must schedule). aaz surplus pods carry the no-zone
# sentinel requirement after the zone-spread pre-pass, so their verdict
# is the requirements clause — on the REWRITTEN spec, same as the oracle.
CATEGORY_EXPECT = {
    "fit": None,
    "taintpod": "taints",
    "selpod": "requirements",
    "hugepod": "resources",
    "aaz": "requirements",
}


def drill_problem(n_pods: int = PODS):
    """(catalog, provisioners, pods): full fleet catalog, two provisioners
    both carrying the drill taint, and the labelled category mix."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.pod import Taint, Toleration, make_pod
    from karpenter_tpu.models.requirements import OP_IN, Requirements
    from karpenter_tpu.providers.instancetypes import generate_fleet_catalog

    catalog = generate_fleet_catalog()
    taint = (Taint(key="drill", effect="NoSchedule"),)
    provisioners = []
    for name, ct in (("drill-mixed", ["spot", "on-demand"]),
                     ("drill-od", ["on-demand"])):
        p = Provisioner(name=name, taints=taint,
                        requirements=Requirements.of(
                            (wk.LABEL_CAPACITY_TYPE, OP_IN, ct)))
        p.set_defaults()
        provisioners.append(p)

    tol = (Toleration(key="drill", operator="Exists"),)
    n_fit = n_pods - 1000 - 1000 - (1000 - AAZ_COUNT) - AAZ_COUNT
    pods = []
    # fitting: 10 deployments of small pods that tolerate the taint
    per = n_fit // 10
    for d in range(10):
        for i in range(per + (1 if d < n_fit % 10 else 0)):
            pods.append(make_pod(
                f"fit-d{d}-{i}", cpu=f"{250 * (d % 4 + 1)}m",
                memory=f"{512 * (d % 4 + 1)}Mi", tolerations=tol))
    # taint-blocked: no toleration, otherwise schedulable
    pods += [make_pod(f"taintpod-{i}", cpu="250m", memory="512Mi")
             for i in range(1000)]
    # requirement-blocked: selector names a type the catalog does not sell
    pods += [make_pod(f"selpod-{i}", cpu="250m", memory="512Mi",
                      tolerations=tol,
                      node_selector={wk.LABEL_INSTANCE_TYPE:
                                     "drill.absent-type"})
             for i in range(1000)]
    # resource-blocked: no instance type fits 4000 cores
    pods += [make_pod(f"hugepod-{i}", cpu="4000", memory="1Gi",
                      tolerations=tol)
             for i in range(1000 - AAZ_COUNT)]
    # affinity-blocked: zone anti-affinity wider than the zone universe
    pods += [make_pod(f"aaz-{i}", cpu="250m", memory="512Mi",
                      tolerations=tol, anti_affinity_zone=True)
             for i in range(AAZ_COUNT)]
    assert len(pods) == n_pods, len(pods)
    return catalog, provisioners, pods


def _category(pod_name: str) -> str:
    return pod_name.split("-", 1)[0]


def audit_attribution(result, provisioners, catalog) -> dict:
    """Attribute every unschedulable group; compare each verdict with the
    scalar oracle's clause (==) and with the category's expected
    dimension. Returns coverage/parity/per-category evidence."""
    from karpenter_tpu import explain
    from karpenter_tpu.models.encode import (build_grid,
                                             diagnose_unschedulable,
                                             kubelet_arrays)

    grid = build_grid(catalog)
    kub = kubelet_arrays(provisioners, catalog)
    groups_total = len(result.unschedulable)
    attributed = parity_ok = 0
    pods_unassigned = 0
    categories: "dict[str, dict]" = {}
    mismatches: "list[dict]" = []
    samples: "list[dict]" = []
    t0 = time.perf_counter()
    for g_idx, count in sorted(result.unschedulable.items()):
        group = result.groups[g_idx]
        spec = group.spec
        oracle = diagnose_unschedulable(spec, provisioners, catalog,
                                        grid=grid, kubelet=kub)
        verdict = explain.attribute_pod(spec, provisioners, catalog,
                                        grid=grid, kubelet=kub)
        attributed += 1
        pods_unassigned += count
        ok = verdict["reason"] == oracle
        parity_ok += ok
        cat = _category(group.pod_names[0])
        expected = CATEGORY_EXPECT.get(cat)
        slot = categories.setdefault(cat, {
            "pods": 0, "groups": 0, "dimension": verdict["dimension"],
            "expected_dimension": expected,
            "dimension_ok": True, "parity_ok": True})
        slot["pods"] += count
        slot["groups"] += 1
        slot["parity_ok"] &= ok
        slot["dimension_ok"] &= (verdict["dimension"] == expected)
        if not ok:
            mismatches.append({"group": g_idx, "pod": group.pod_names[0],
                               "oracle": oracle,
                               "attribution": verdict["reason"]})
        if len(samples) < 4 and cat not in {s["category"] for s in samples}:
            samples.append({"category": cat, "pod": group.pod_names[0],
                            "reason": verdict["reason"],
                            "summary": verdict["summary"],
                            "ranked": verdict["ranked"],
                            "nearest": verdict["nearest"]})
    wall = time.perf_counter() - t0
    coverage = attributed / groups_total if groups_total else 1.0
    parity = parity_ok / groups_total if groups_total else 1.0
    return {
        "groups_unschedulable": groups_total,
        "pods_unassigned": pods_unassigned,
        "groups_attributed": attributed,
        "attribution_coverage": round(coverage, 6),
        "reason_parity": round(parity, 6),
        "parity_mismatches": mismatches,
        "categories": {k: categories[k] for k in sorted(categories)},
        "categories_ok": all(c["dimension_ok"] and c["parity_ok"]
                             for c in categories.values()),
        "samples": samples,
        "attribution_wall_ms": round(wall * 1e3, 3),
        "attribution_ms_per_group": round(
            wall * 1e3 / max(groups_total, 1), 4),
    }


def measure_overhead(solver, pods, repeats: int = REPEATS,
                     warmup: int = WARMUP) -> dict:
    """Solve walls with the explain plane ON vs OFF, interleaved with
    alternating order (the profile_drill idiom) so allocator / jit-cache
    warm-drift cancels instead of billing one side. min-of-repeats is the
    gated overhead estimator (container noise is additive-positive); the
    p50 delta over the same interleaved samples is recorded alongside."""
    from karpenter_tpu import explain

    for _ in range(warmup):
        solver.solve(pods)
    prev = explain.set_enabled(True)
    walls_on: "list[float]" = []
    walls_off: "list[float]" = []
    try:
        for i in range(repeats):
            for side in (("on", "off") if i % 2 == 0 else ("off", "on")):
                if side == "on":
                    t0 = time.perf_counter()
                    solver.solve(pods)
                    walls_on.append(time.perf_counter() - t0)
                else:
                    with explain.disabled():
                        t0 = time.perf_counter()
                        solver.solve(pods)
                        walls_off.append(time.perf_counter() - t0)
    finally:
        explain.set_enabled(prev)
    on_min, off_min = min(walls_on), min(walls_off)
    on_p50 = statistics.median(walls_on)
    off_p50 = statistics.median(walls_off)
    overhead = max(0.0, (on_min - off_min) / off_min) if off_min > 0 else 0.0
    p50_delta = (on_p50 - off_p50) / off_p50 if off_p50 > 0 else 0.0
    return {
        "repeats": repeats,
        "wall_ms_min_on": round(on_min * 1e3, 3),
        "wall_ms_min_off": round(off_min * 1e3, 3),
        "wall_ms_p50_on": round(on_p50 * 1e3, 3),
        "wall_ms_p50_off": round(off_p50 * 1e3, 3),
        "overhead_share": round(overhead, 6),
        "p50_delta_share": round(p50_delta, 6),
    }


def run_drill(repeats: int = REPEATS) -> dict:
    from karpenter_tpu.utils.jaxenv import pin_cpu

    pin_cpu(N_DEVICES)
    from benchmarks import ledger
    from karpenter_tpu.solver.core import TPUSolver

    catalog, provisioners, pods = drill_problem()
    solver = TPUSolver(catalog, provisioners)
    result = solver.solve(pods)
    audit = audit_attribution(result, provisioners, catalog)
    overhead = measure_overhead(solver, pods, repeats)

    passed = (audit["attribution_coverage"] == 1.0
              and audit["reason_parity"] == 1.0
              and audit["categories_ok"]
              and audit["pods_unassigned"] > 0
              and overhead["overhead_share"] < MAX_OVERHEAD_SHARE)
    record = {
        "tool": "karpenter_tpu.explain_drill",
        "schema": 1,
        "pods": PODS,
        "nodes": len(result.nodes),
        "thresholds": {"max_overhead_share": MAX_OVERHEAD_SHARE},
        "attribution": audit,
        "overhead": overhead,
        "passed": passed,
    }
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(ARTIFACT, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    workload = {"name": "explain_drill", "pods": PODS,
                "unassigned": audit["pods_unassigned"]}
    for metric, value in (
            ("explain_attribution_coverage", audit["attribution_coverage"]),
            ("explain_reason_parity", audit["reason_parity"]),
            ("explain_overhead_share", overhead["overhead_share"])):
        ledger.record(metric, value, "ratio",
                      source="benchmarks.explain_drill", backend="cpu",
                      workload=workload, degraded=not passed,
                      artifact=ARTIFACT)
    return record


def main(argv=None) -> int:
    record = run_drill()
    print(json.dumps({
        "passed": record["passed"],
        "pods_unassigned": record["attribution"]["pods_unassigned"],
        "attribution_coverage": record["attribution"][
            "attribution_coverage"],
        "reason_parity": record["attribution"]["reason_parity"],
        "overhead_share": record["overhead"]["overhead_share"],
        "p50_delta_share": record["overhead"]["p50_delta_share"],
        "artifact": ARTIFACT,
    }))
    return 0 if record["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
