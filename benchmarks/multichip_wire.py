#!/usr/bin/env python
"""Wire-served sharded parity check (`make multichip`).

`dryrun_multichip` proved the mesh kernel bit-exact — but only as a
hand-driven entry point. This harness proves the SERVING path: the same
sharded solve reached through the gRPC solver service (Sync + Solve over
real sockets, shape router forced to the mesh with crossover=0), with
three assertions:

  1. wire routing: the service reports routing=tpu-sharded and a
     device_count matching the mesh — the sharded kernel genuinely served
     the RPC, it didn't quietly fall back to single-chip;
  2. bit-parity: the mesh dispatch's flat result buffer equals the
     single-device dispatch elementwise on the same padded problem
     (core-level, same ShapeRouter inputs the service used);
  3. decision parity: the decoded wire response's (type, zone,
     capacityType, pods) decisions equal the native C++ scan's on the same
     problem (an independent implementation of the FFD semantics).

Writes benchmarks/results/multichip_wire_<ts>.json. Fixed problem
construction (benchmarks.baseline_configs.stress_problem_50k is
deterministic), so reruns are comparable.

Usage: python -m benchmarks.multichip_wire [--pods N] [--devices N]
(CPU mesh: run under the Makefile's CPU_ENV for 8 virtual devices.)
"""

from __future__ import annotations

import argparse
import json
import os
import time


def run(n_pods: int, n_devices: int, out_dir: "str | None") -> dict:
    from karpenter_tpu.utils.jaxenv import pin_cpu

    jax = pin_cpu(n_devices)
    import numpy as np

    devs = jax.devices("cpu")
    assert len(devs) >= n_devices, (
        f"need {n_devices} devices, have {len(devs)}; run with "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}")

    from benchmarks.baseline_configs import stress_problem_50k
    from karpenter_tpu.models.encode import encode_problem
    from karpenter_tpu.solver import solver_pb2 as pb
    from karpenter_tpu.solver import wire
    from karpenter_tpu.solver.client import RemoteSolver
    from karpenter_tpu.solver.core import (NativeSolver, TPUSolver,
                                           build_pack_inputs,
                                           dispatch_pack_inputs)
    from karpenter_tpu.solver.service import SolverService, serve

    catalog, provisioners, pods = stress_problem_50k(n_pods)

    # crossover_cells=0: EVERY solve routes to the mesh — the parity run
    # must exercise the sharded path regardless of problem size
    service = SolverService(crossover_cells=0)
    server, port, service = serve(service=service)
    try:
        client = RemoteSolver(catalog, provisioners,
                              target=f"127.0.0.1:{port}", timeout=600.0)
        client.sync()
        req = pb.SolveRequest(
            catalog_seqnum=catalog.seqnum,
            catalog_hash=client.catalog_content_hash(),
            provisioner_hash=client._prov_hash,
            pods=[wire.pod_to_wire(p) for p in pods],
        )
        t0 = time.perf_counter()
        resp = client._call("Solve", req)
        wire_ms = (time.perf_counter() - t0) * 1000
        decoded = client._decode(resp, pods)

        # 1) the wire actually served the mesh kernel
        assert resp.routing == "tpu-sharded", (
            f"wire solve routed {resp.routing!r}, expected tpu-sharded")
        assert resp.device_count == n_devices, (
            resp.device_count, n_devices)
        placed = sum(n.pod_count for n in decoded.nodes)
        assert placed + decoded.unschedulable_count() == len(pods), (
            placed, decoded.unschedulable_count(), len(pods))

        # 2) bit-parity: same padded problem through the service's resident
        # mesh context vs the single-device dispatch
        solver, _ = service._cache[(req.catalog_hash,
                                    req.provisioner_hash)]
        enc = encode_problem(solver.catalog, solver.provisioners, pods, (),
                             None, None, grid=solver.grid(),
                             group_cache=solver._group_cache)
        inputs, dims, use_pallas = build_pack_inputs(
            enc, solver._dev_alloc_t, solver._dev_tiebreak)
        flat_sharded = np.asarray(solver._mesh_ctx.dispatch_flat(
            inputs, dims[1], use_pallas, enc.grid))
        flat_single = np.asarray(
            dispatch_pack_inputs(inputs, dims, use_pallas))
        bit_parity = (flat_sharded.shape == flat_single.shape
                      and bool((flat_sharded == flat_single).all()))
        assert bit_parity, "mesh/single flat-result divergence"

        # 3) decision parity vs the independent native scan
        native = NativeSolver(catalog, provisioners).solve(pods)
        decision_parity = decoded.decisions() == native.decisions()
        assert decision_parity, (
            f"native divergence: {len(decoded.decisions())} vs "
            f"{len(native.decisions())} decisions")
    finally:
        server.stop(0)

    record = {
        "captured_at": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        "harness": "benchmarks.multichip_wire",
        "n_pods": len(pods),
        "n_types": len(catalog.types),
        "devices": n_devices,
        "mesh": solver._mesh_ctx.describe(),
        "routing": resp.routing,
        "bucket": resp.bucket,
        "wire_solve_ms": round(wire_ms, 3),
        "service_solve_ms": round(resp.solve_ms, 3),
        "nodes": len(decoded.nodes),
        "unschedulable": decoded.unschedulable_count(),
        "bit_parity": bit_parity,
        "decision_parity": decision_parity,
        "decisions": len(decoded.decisions()),
        "backend": jax.devices()[0].platform,
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"multichip_wire_{record['captured_at']}.json")
        with open(path, "w") as f:
            json.dump(record, f, indent=1)
        record["artifact"] = path
        from benchmarks import ledger

        wl = {"n_pods": record["n_pods"], "devices": record["devices"],
              "mesh": record["mesh"]}
        degraded = not (bit_parity and decision_parity)
        for field in ("wire_solve_ms", "service_solve_ms"):
            ledger.record(f"multichip_{field}", record[field], "ms",
                          source="benchmarks.multichip_wire",
                          backend=record["backend"], degraded=degraded,
                          workload=wl, artifact=path,
                          detail={"routing": record["routing"]})
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pods", type=int, default=50_000)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--no-record", action="store_true",
                    help="don't write an artifact under benchmarks/results")
    args = ap.parse_args(argv)
    out_dir = None if args.no_record else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results")
    record = run(args.pods, args.devices, out_dir)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
