"""Run the benchmark ladder and RECORD the results (VERDICT r2 ask #8).

The reference's benchmark tier prints numbers that CI then archives per run
(interruption_benchmark_test.go:61-76 scale ladder); rounds 1-2 here ran
`make benchmark` and discarded the output. This wrapper:

  1. runs benchmarks.interruption_bench (scale ladder incl. 15k) and
     benchmarks.baseline_configs (all configs incl. 3: consolidation-500
     and 4: stress-50k-sharded),
  2. writes one dated record into benchmarks/results/bench_<utc>.json,
  3. diffs against the previous record and prints per-metric deltas, so
     round-over-round regressions are visible in CI, not folklore.

Usage: python -m benchmarks.record [--skip-stress]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS_DIR = os.path.join(REPO, "benchmarks", "results")


def _run_json_lines(argv: "list[str]") -> "tuple[list[dict], int]":
    # the recorder archives and ledgers every line itself; the benches must
    # not also write their standalone artifacts (one artifact, not two)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KARPENTER_TPU_BENCH_ARTIFACT="0")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never grab the real chip here
    try:
        proc = subprocess.run([sys.executable, "-m", *argv], cwd=REPO, env=env,
                              capture_output=True, text=True, timeout=3600)
    except subprocess.TimeoutExpired:
        # the stress ladder (50k/200k/1M shapes share one subprocess) can
        # trip this on a slow box: fail the benchmark gracefully, never
        # the recorder
        print(f"{argv[0]} TIMED OUT after 3600s; no entries recorded",
              file=sys.stderr)
        return [], 124
    out = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    if proc.returncode != 0:
        print(f"{argv[0]} FAILED rc={proc.returncode}:\n"
              f"{proc.stderr[-800:]}", file=sys.stderr)
    return out, proc.returncode


def _key(rec: dict) -> str:
    if rec.get("bench") == "baseline_config":
        return f"config{rec['config']}:{rec.get('name', '')}"
    if "messages" in rec:  # interruption + wire_interruption ladders
        return f"{rec.get('bench', 'interruption')}:{rec['messages']}"
    if "pods" in rec:
        return f"{rec.get('bench', '?')}:{rec['pods']}"
    return rec.get("bench", rec.get("metric", "?"))


def _metric_ms(rec: dict):
    for field in ("ms", "p50_ms", "wall_ms", "value"):
        if field in rec:
            return rec[field]
    if "cycle_seconds" in rec:
        return rec["cycle_seconds"] * 1000
    if "seconds" in rec:
        return rec["seconds"] * 1000
    return None


def previous_record() -> "dict | None":
    try:
        names = sorted(n for n in os.listdir(RESULTS_DIR)
                       if n.startswith("bench_") and n.endswith(".json"))
    except FileNotFoundError:
        return None
    if not names:
        return None
    with open(os.path.join(RESULTS_DIR, names[-1])) as f:
        return json.load(f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-stress", action="store_true",
                    help="skip the stress configs 4, 7 and 9 (50k/200k/1M "
                         "sharded; minutes on CPU)")
    args = ap.parse_args(argv)

    prev = previous_record()
    results, rc1 = _run_json_lines(["benchmarks.interruption_bench"])
    configs = "0,1,2,3,5,6,8" if args.skip_stress else "0,1,2,3,4,5,6,7,8,9"
    more, rc2 = _run_json_lines(["benchmarks.baseline_configs",
                                 "--configs", configs])
    results += more
    # the deployed-topology tier (VERDICT r4 ask #7): HttpKubeStore over a
    # real HTTP socket + the gRPC solver sidecar, recorded in the same
    # ladder so the wire tax stays attributable round-over-round
    wire, rc3 = _run_json_lines(["benchmarks.wire_bench"])
    if rc3 == 0:
        results += wire
    else:
        # partial wire lines must not become the baseline the next run
        # diffs against (same invariant as rc1/rc2 below)
        print("wire benchmark failed; recording in-process entries only",
              file=sys.stderr)
    if rc1 != 0 or rc2 != 0:
        # a broken harness must FAIL the run (and never become the baseline
        # the next run diffs against)
        print("benchmark harness failed; no record written", file=sys.stderr)
        return 1

    ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    record = {"recorded_at": ts, "backend": "cpu", "entries": results}
    os.makedirs(RESULTS_DIR, exist_ok=True)
    path = os.path.join(RESULTS_DIR, f"bench_{ts}.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
    print(f"recorded {len(results)} entries -> {path}")
    from benchmarks import ledger
    n = ledger.record_artifact_entries(record, os.path.relpath(path, REPO),
                                       "benchmarks.record")
    print(f"perf ledger: {n} entries -> {ledger.ledger_path()}")

    if prev:
        prev_by_key = {_key(r): r for r in prev.get("entries", [])}
        print(f"vs {prev.get('recorded_at', 'previous')}:")
        for rec in results:
            k = _key(rec)
            cur = _metric_ms(rec)
            old = _metric_ms(prev_by_key.get(k, {}))
            if cur is None or old in (None, 0):
                continue
            print(f"  {k}: {old:.1f} -> {cur:.1f} ms "
                  f"({(cur / old - 1) * 100:+.0f}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
