{{/* Resource name — reference analogue: karpenter.fullname */}}
{{- define "karpenter-tpu.fullname" -}}
{{ .Values.fullnameOverride | default .Release.Name }}
{{- end }}

{{/* Solver gRPC endpoint the controller dials (localhost sidecar) */}}
{{- define "karpenter-tpu.solverEndpoint" -}}
{{ .Values.solver.host }}:{{ .Values.solver.port }}
{{- end }}
