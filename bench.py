#!/usr/bin/env python
"""Headline benchmark: scheduling-cycle latency @ 10k pending pods x ~600
instance types (BASELINE.json metric; north-star < 100 ms on one TPU chip).

Prints ONE JSON line:
  {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": 100/p50}

vs_baseline > 1.0 means faster than the 100 ms north-star budget.
Measures END-TO-END solve: host encode (mask folding) + device pack kernel +
decode — the full scheduling cycle the controller would pay per batch window.

Robustness (round-2 hardening): the env's tunneled TPU ("axon" platform) is
flaky — backend init can hang indefinitely, and sitecustomize pre-imports jax
so env vars alone can't redirect it. We therefore
  1. probe the TPU backend in a SUBPROCESS with a hard timeout (a hang in
     PJRT init — even at interpreter startup — only costs the probe);
  2. retry the probe with backoff, then pin this process to whichever
     platform survived via jax.config.update *before* any device touch;
  3. run a watchdog that emits a parseable JSON line (degraded or error)
     and exits if a device call wedges mid-benchmark.
"""

import argparse
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from karpenter_tpu.utils.jaxenv import pin, probe_tpu

WATCHDOG_BUDGET_S = int(os.environ.get("KARPENTER_TPU_BENCH_BUDGET_S", "900"))

_state = {"times": [], "detail": {}, "emitted": False, "lock": threading.Lock()}


def _emit(value, vs, detail, exit_code=None, degraded=False):
    with _state["lock"]:
        if _state["emitted"]:
            return
        _state["emitted"] = True
    record = {
        "metric": "scheduling_cycle_p50_ms_10k_pods_600_types",
        "value": value,
        "unit": "ms",
        "vs_baseline": vs,
        # round-over-round comparability (VERDICT r3 ask #8): the measured
        # backend plus BOTH curves at top level, so BENCH_r{N}.json diffs
        # against r{N-1} without digging through detail history. onchip_ms
        # is this run's device p50 when the backend is the TPU, else the
        # freshest recorded capture's.
        "backend": detail.get("backend"),
        "native_routed_ms": detail.get("routed_native_p50_ms"),
        "onchip_ms": (value if detail.get("backend") == "tpu" else
                      (detail.get("latest_tpu_capture") or {}).get("p50_ms")),
        # escape-hatch metrics measured by THIS run (no longer capture-only
        # nulls): steady-state resident-buffer waves, callback-transport
        # headline, the post-callback link sentinel, and streaming-regime
        # consolidation — hack/check_headline_provenance.py reads these as
        # the fallback evidence for degraded artifacts
        "wave_steady_per_solve_ms": ((detail.get("wave_steady") or {})
                                     .get("per_solve_p50_ms")),
        "callback_headline_ms": ((detail.get("callback_headline") or {})
                                 .get("p50_ms")),
        "io_escape_sync_after_ms": (((detail.get("io_callback_escape")
                                      or {}).get("sync_after") or {})
                                    .get("p50_ms")),
        "consolidation_500_streaming_ms": (
            (detail.get("consolidation_500_streaming") or {}).get("p50_ms")),
        "detail": detail,
    }
    if degraded:
        record["degraded"] = True  # partial reps only — do not trust as headline
    # headline provenance (lint contract, hack/check_headline_provenance.py):
    # a non-degraded on-chip value stands on its own; anything else must
    # name the fallback metric its claim leans on
    if record["backend"] == "tpu" and not degraded:
        record["headline_provenance"] = {"source": "onchip-this-run"}
    else:
        fallback = next(
            (m for m in ("wave_steady_per_solve_ms", "native_routed_ms",
                         "onchip_ms") if record.get(m) is not None), None)
        record["headline_provenance"] = {
            "source": "degraded-fallback",
            "fallback_metric": fallback,
            "fallback_value": record.get(fallback) if fallback else None,
        }
    print(json.dumps(record), flush=True)
    # perf ledger (benchmarks/ledger.py): the emitted record becomes a
    # recorded artifact under benchmarks/results/ (stdout alone is not
    # citable) and the headline lands in the trend file with provenance
    try:
        from benchmarks import ledger as _ledger

        ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "results")
        os.makedirs(out_dir, exist_ok=True)
        artifact = os.path.join(out_dir, f"headline_{ts}.json")
        with open(artifact, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        _ledger.record(
            record["metric"], record["value"], record["unit"],
            source="bench.py", backend=record.get("backend"),
            degraded=bool(record.get("degraded")),
            workload={"pods": 10_000, "types": 600},
            artifact=artifact,
            detail={"provenance": record.get("headline_provenance"),
                    "capture_history_errors":
                        detail.get("capture_history_errors", 0)})
    except Exception as e:  # noqa: BLE001 — the ledger must not eat the line
        print(f"perf-ledger record failed: {e}", file=sys.stderr, flush=True)
    if exit_code is not None:
        os._exit(exit_code)


def _watchdog():
    """If the benchmark wedges (tunnel stall mid-solve), emit what we have.
    Started AFTER the probe so probe attempts/backoff don't eat the budget."""
    time.sleep(WATCHDOG_BUDGET_S)
    times = list(_state["times"])
    detail = dict(_state["detail"])
    detail["watchdog"] = f"budget {WATCHDOG_BUDGET_S}s exceeded"
    if times:
        p50 = statistics.median(times)
        detail["reps_completed"] = len(times)
        _emit(round(p50, 3), round(100.0 / p50, 3), detail, exit_code=0,
              degraded=True)
    else:
        detail["error"] = "no completed reps before watchdog budget"
        _emit(None, None, detail, exit_code=1)


def workload_10k():
    """BASELINE.json configs[1]-style: mixed cpu/mem pods, zone selectors,
    topology spread, across 8 deployments -> 10k pods. One shared definition
    with the capture tool so recorded numbers are comparable."""
    from benchmarks.workloads import mixed_workload

    return mixed_workload(10_000)


def _phase_breakdown(catalog, pods):
    """One full CONTROLLER reconcile at the benchmark workload under the
    fake cloud, attributed per phase from the tracing recorder. The
    headline above measures the bare solver; this shows where the cycle
    around it (mask build, routed solve, launch+bind) spends wall time."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.nodetemplate import NodeTemplate
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.apis.settings import Settings
    from karpenter_tpu.fake.cloud import FakeCloud
    from karpenter_tpu.models.requirements import OP_IN, Requirements
    from karpenter_tpu.operator import Operator
    from karpenter_tpu.tracing import TRACER
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    op = Operator(FakeCloud(catalog=catalog, clock=clock),
                  Settings(cluster_name="bench",
                           cluster_endpoint="https://bench",
                           batch_idle_duration=0.0, batch_max_duration=0.0),
                  catalog, clock=clock)
    try:
        op.kube.create("nodetemplates", "default", NodeTemplate(
            name="default", subnet_selector={"id": "subnet-zone-1a"},
            security_group_selector={"id": "sg-default"}))
        op.cloudprovider.register_nodetemplate(
            op.kube.get("nodetemplates", "default"))
        prov = Provisioner(name="default", provider_ref="default",
                           requirements=Requirements.of(
                               (wk.LABEL_CAPACITY_TYPE, OP_IN,
                                ["spot", "on-demand"]),
                               (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"])))
        prov.set_defaults()
        op.kube.create("provisioners", "default", prov)
        for p in pods:
            op.kube.create("pods", p.name, p)
        TRACER.clear()
        op.provisioning.reconcile_once()
        spans = {s.name: s for s in TRACER.finished_spans()
                 if s.name.startswith("provisioning.")}
        out = {}
        for phase in ("cycle", "mask", "solve", "bind"):
            s = spans.get(f"provisioning.{phase}")
            if s is not None:
                out[f"{phase}_ms"] = round((s.duration_s or 0.0) * 1e3, 3)
        solve = spans.get("provisioning.solve")
        if solve is not None:
            out["routing"] = solve.attributes.get("routing")
            out["compile_cache"] = solve.attributes.get("compile_cache")
            out["transfer_ms"] = solve.attributes.get("transfer_ms")
        return out
    finally:
        op.stop()


def _steady_section(solver, pods, reps: int):
    """Steady-state per-solve latency with resident buffers: waves of K
    identical problems ride ONE vmapped dispatch + ONE fetch against the
    device-resident catalog (solve_many), measured over `reps` waves after
    a warmup wave compiled the [K, ...] program. The per-solve number is
    the marginal cost of one more solve in a warm serving process — the
    figure the solver service pays per Solve once Sync residency and the
    compile cache have done their work."""
    K = 8
    probs = [{"pods": pods}] * K
    solver.solve_many(probs)  # warmup wave (compile + group-cache folds)
    per = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        solver.solve_many(probs)
        per.append((time.perf_counter() - t0) * 1000 / K)
    per.sort()
    _state["detail"]["wave_steady"] = {
        "wave_k": K, "reps": len(per),
        "per_solve_p50_ms": round(statistics.median(per), 3),
        "per_solve_p99_ms": round(per[min(len(per) - 1,
                                          int(len(per) * 0.99))], 3),
    }


def _escape_sections(jax, solver, pods):
    """Run the headline through the callback readback transport (results
    streamed host-ward via io_callback instead of a blocking first read —
    the 68 ms after_first_read penalty is what this dodges), then take the
    link sentinel AFTER: sub-ms sync_after means the escape hatch kept the
    session streaming."""
    import jax.numpy as jnp

    import karpenter_tpu.solver.core as _score
    from hack.tpu_capture import _link_sentinel

    saved = _score._READBACK
    _score._READBACK = "callback"
    try:
        solver.solve(pods)  # warm the callback-transport program
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            solver.solve(pods)
            ts.append((time.perf_counter() - t0) * 1000)
        _state["detail"]["callback_headline"] = {
            "p50_ms": round(statistics.median(ts), 3), "reps": len(ts)}
        _state["detail"]["io_callback_escape"] = {
            "sync_after": _link_sentinel(jax, jnp)}
    finally:
        _score._READBACK = saved


def _consolidation_streaming(catalog, reps: int = 5):
    """BASELINE configs[3] (500-node consolidation sweep) since the
    incremental plane landed: `stream_consolidation` (fixed-lane candidate
    chunks, type-pruned dispatch — the default deprovisioning path when
    KARPENTER_TPU_INCREMENTAL is on) vs the legacy one-shot mega-encode,
    both on the DEFAULT readback transport (the deployed CPU path). The
    callback-transport stream time is kept alongside for comparability
    with the on-chip streaming-regime capture, which records through that
    transport."""
    import karpenter_tpu.solver.core as _score
    from hack.tpu_capture import _consolidation_cluster
    from karpenter_tpu.ops.consolidate import (run_consolidation,
                                               stream_consolidation,
                                               stream_lanes)

    cluster, cprov = _consolidation_cluster(catalog, 500)

    def timed(fn, n):
        fn(cluster, catalog, [cprov])  # warm (compile + grid caches)
        out = []
        for _ in range(max(1, n)):
            t0 = time.perf_counter()
            fn(cluster, catalog, [cprov])
            out.append((time.perf_counter() - t0) * 1000)
        return out

    ts = timed(stream_consolidation, reps)
    lt = timed(run_consolidation, reps)
    saved = _score._READBACK
    _score._READBACK = "callback"
    try:
        cb = timed(stream_consolidation, max(1, reps - 2))
    finally:
        _score._READBACK = saved
    _state["detail"]["consolidation_500_streaming"] = {
        "p50_ms": round(statistics.median(ts), 3), "reps": len(ts),
        "stream_lanes": stream_lanes(),
        "oneshot_p50_ms": round(statistics.median(lt), 3),
        "callback_p50_ms": round(statistics.median(cb), 3)}


def _fleet_bench(args, jax):
    """Open-loop fleet serving benchmark (--fleet): N tenants submit at a
    fixed offered rate through one FleetFrontend over one SolverService —
    the multi-tenant mega-solve path (karpenter_tpu/fleet/), not the bare
    solver. Open loop is the point: submission times are scheduled, never
    gated on completion, so queueing delay is measured instead of hidden.
    Records sustained solves/sec plus end-to-end p50/p99 THROUGH the
    admission queue, and re-checks the fairness invariant on the drained
    frontend. One JSON line + benchmarks/results/fleet/fleet_bench.json."""
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.chaos.invariants import check_fairness_never_starves
    from karpenter_tpu.fleet import FleetFrontend
    from karpenter_tpu.models.instancetype import Catalog, make_instance_type
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.models.requirements import OP_IN, Requirements
    from karpenter_tpu.solver.service import SolverService

    backend = jax.devices()[0].platform
    catalog = Catalog(types=[
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    prov.set_defaults()

    svc = SolverService()
    frontend = FleetFrontend(svc, tick_interval_s=0.01,
                             max_wave=max(16, args.fleet_tenants * 2),
                             name="bench-fleet")
    # identical content for every tenant — the fleet's common case — so
    # all of them dedupe onto ONE resident solver and batch together
    tenants = [f"tenant-{i}" for i in range(args.fleet_tenants)]
    for tid in tenants:
        frontend.register(tid, catalog, [prov])
    frontend.start()

    def pods_for(tid, i):
        return [make_pod(f"{tid}-r{i}-p{j}", cpu="1", memory="2Gi")
                for j in range(4)]

    # warmup: one synchronous solve per tenant, then concurrent bursts to
    # compile every wave rung (solve_many pads the batch axis to x2 rungs
    # — each K the measured window will see must be jitted BEFORE the
    # clock starts, or the first mega-solve at a fresh K stalls the queue
    # behind a compile)
    for tid in tenants:
        frontend.solve(tid, pods_for(tid, -1), timeout=120.0)
    for k in (2, 4, 8, 16):
        warm = [frontend.submit(tenants[i % len(tenants)],
                                pods_for(tenants[i % len(tenants)], -2 - k))
                for i in range(k)]
        for tk in warm:
            tk.wait(timeout=120.0)

    interval = 1.0 / max(0.1, args.fleet_rate)
    n_per = max(1, int(args.fleet_seconds * args.fleet_rate))

    def open_loop(seconds):
        count = max(1, int(seconds * args.fleet_rate))
        tickets = {tid: [] for tid in tenants}

        def submitter(tid):
            nxt = time.perf_counter()
            for i in range(count):
                tickets[tid].append(
                    frontend.submit(tid, pods_for(tid, i)))
                nxt += interval
                delay = nxt - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=submitter, args=(tid,),
                                    daemon=True) for tid in tenants]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for per in tickets.values():
            for tk in per:
                tk.wait(timeout=120.0)
        return tickets, time.perf_counter() - t0

    # throwaway open-loop pass settles allocator/cache state, then the
    # ledgers reset so the measured window starts clean
    open_loop(min(1.0, args.fleet_seconds))
    frontend.reset_stats()
    tickets, wall = open_loop(args.fleet_seconds)
    frontend.stop()

    lats = sorted(tk.latency_s * 1000 for per in tickets.values()
                  for tk in per if tk.latency_s is not None)
    served = len(lats)
    evidence = frontend.evidence()
    violations = [v.as_dict()
                  for v in check_fairness_never_starves(evidence)]
    fstats = frontend.stats()
    record = {
        "metric": "fleet_sustained_solves_per_sec",
        "value": round(served / wall, 3) if wall > 0 else None,
        "unit": "solves/s",
        "backend": backend,
        "tenants": len(tenants),
        "offered_rate_per_tenant": args.fleet_rate,
        "offered_total_per_sec": round(args.fleet_rate * len(tenants), 3),
        "requests": sum(len(per) for per in tickets.values()),
        "served": served,
        "wall_s": round(wall, 3),
        "p50_ms": round(statistics.median(lats), 3) if lats else None,
        "p99_ms": (round(lats[min(served - 1, int(served * 0.99))], 3)
                   if lats else None),
        "mega_solves": fstats["mega_solves"],
        "ticks": fstats["ticks"],
        "mean_batch": (round(served / fstats["mega_solves"], 3)
                       if fstats["mega_solves"] else None),
        "tick_interval_s": fstats["tick_interval_s"],
        "max_wave": fstats["max_wave"],
        "starvation_bound": fstats["starvation_bound"],
        "max_wait_ticks": max(
            st["max_wait_ticks"] for st in evidence["tenants"].values()),
        "violations": violations,
        "passed": not violations,
    }
    # cardinality-bounded tenant telemetry: the top-K table plus the
    # per-family series counts — proof the metric surface stayed O(K)
    # even when --tenants dwarfs K
    telemetry = fstats.get("tenant_telemetry", {})
    series = telemetry.get("series_per_family", {})
    record["tenant_telemetry"] = {
        "k": telemetry.get("k"),
        "top": telemetry.get("tracked", [])[:16],
        "series_per_family": series,
        "series_max": max(series.values()) if series else 0,
    }
    print(json.dumps(record), flush=True)
    out_dir = os.environ.get(
        "KARPENTER_TPU_FLEET_BENCH_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "benchmarks", "results", "fleet"))
    os.makedirs(out_dir, exist_ok=True)
    artifact = os.path.join(out_dir, "fleet_bench.json")
    with open(artifact, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
    from benchmarks import ledger as _ledger

    wl = {"tenants": record["tenants"], "requests": record["requests"]}
    _ledger.record(record["metric"], record["value"], record["unit"],
                   source="bench.py --fleet", backend=record["backend"],
                   degraded=not record["passed"], workload=wl,
                   artifact=artifact)
    if record["p99_ms"] is not None:
        _ledger.record("fleet_p99_ms", record["p99_ms"], "ms",
                       source="bench.py --fleet", backend=record["backend"],
                       degraded=not record["passed"], workload=wl,
                       artifact=artifact)
    _ledger.record("fleet_tenant_series_max",
                   record["tenant_telemetry"]["series_max"], "series",
                   source="bench.py --fleet", backend=record["backend"],
                   degraded=not record["passed"], workload=wl,
                   artifact=artifact)
    return 0 if record["passed"] else 1


def _rss_mb():
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return None


def _peak_rss_mb():
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return None


def _profile_bench(args):
    """Attribution mode (--profile): bench-sized workloads through the
    profiling gap ledger (benchmarks/profile_drill.run_path) — per-phase
    ms, the unaccounted residue, profiler overhead and the roofline ratio
    per workload, ledgered as profile_unaccounted_share so the residue
    trends per workload like any other bench metric. The 10k-pod
    acceptance proof on BOTH routing paths is `make profile-drill`; this
    mode is the quick per-workload read."""
    from karpenter_tpu.utils.jaxenv import pin_cpu

    pin_cpu(8)
    from benchmarks import ledger as _ledger
    from benchmarks.baseline_configs import stress_problem_50k
    from benchmarks.profile_drill import MAX_UNACCOUNTED_SHARE, run_path
    from karpenter_tpu.solver.core import TPUSolver

    from karpenter_tpu.profiling import critical as _critical

    n = max(100, args.profile_pods)
    catalog, provisioners, pods = stress_problem_50k(n)
    solver = TPUSolver(catalog, provisioners)
    _critical.set_enabled(True)
    workloads = {}
    for label, wl_pods in ((f"stress-{n}", pods),
                           (f"stress-{max(100, n // 4)}",
                            pods[:max(100, n // 4)])):
        workloads[label] = run_path("single", solver, wl_pods,
                                    repeats=3, warmup=1)
    # bench mode gates on ATTRIBUTION only: at bench-sized (few-ms) walls
    # the enabled-vs-disabled overhead A/B is dominated by scheduler
    # jitter, and the <5% overhead acceptance belongs to the 10k drill
    passed = all(w["unaccounted_share"] < MAX_UNACCOUNTED_SHARE
                 for w in workloads.values())
    critical_summary = _bench_critical_summary()
    record = {
        "tool": "karpenter_tpu.bench_profile",
        "mode": "profile",
        "backend": "cpu",
        "pods": n,
        "workloads": workloads,
        "critical": critical_summary,
        "passed": passed,
    }
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks", "results", "profiling")
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, "bench_profile.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
    print(json.dumps({
        "mode": "profile", "passed": passed,
        "workloads": {k: {"unaccounted_share": w["unaccounted_share"],
                          "overhead_share": w["overhead_share"],
                          "roofline_ratio": (w["roofline"] or {}).get("ratio")}
                      for k, w in workloads.items()},
        "artifact": out}), flush=True)
    for label, w in workloads.items():
        _ledger.record("profile_unaccounted_share", w["unaccounted_share"],
                       "ratio", source="bench.py --profile", backend="cpu",
                       degraded=w["unaccounted_share"] >= MAX_UNACCOUNTED_SHARE,
                       workload={"name": label, "pods": n}, artifact=out)
    if critical_summary:
        _ledger.record("critical_overlap_ratio",
                       critical_summary["overlap_ratio"], "ratio",
                       source="bench.py --profile", backend="cpu",
                       workload={"name": "bench_profile", "pods": n},
                       detail=critical_summary, artifact=out)
    return 0 if passed else 1


def _bench_critical_summary(limit: int = 6) -> "dict | None":
    """The critical-path read of the solves a bench mode just ran: median
    overlap ratio (the serial baseline), the phase owning the biggest
    chain share, and the measured-roofline rung count — the bench-sized
    echo of `make critical-drill` (None when the plane recorded
    nothing)."""
    import statistics

    from karpenter_tpu.profiling import critical, roofline

    rows = critical.CRITICAL.rows()[-limit:]
    if not rows:
        return None
    shares: "dict[str, list[float]]" = {}
    for r in rows:
        for p, s in (r.get("critical_share") or {}).items():
            shares.setdefault(p, []).append(s)
    med_share = {p: round(statistics.median(v), 6)
                 for p, v in shares.items()}
    top = max(med_share, key=med_share.get) if med_share else None
    measured = roofline.measured_snapshot()
    return {
        "overlap_ratio": round(statistics.median(
            r["overlap_ratio"] for r in rows), 6),
        "critical_path_ms": round(statistics.median(
            r["critical_path_ms"] for r in rows), 4),
        "top_critical_phase": top,
        "critical_share": med_share,
        "roofline_measured_rungs": len(measured.get("rungs") or {}),
        "roofline_drift_flagged": measured.get("drift_flagged") or [],
    }


def _soak_bench(args):
    """Columnar-state soak (--soak): the controller-side reconcile sweeps at
    100k nodes / 1M bound pods under 200-QPS-equivalent churn — the scale
    claim of docs/designs/columnar-state.md, measured where the reference
    controllers actually spend their cycles (emptiness/expiration column
    scans, dirty-driven consolidation candidate generation, provisioning
    mask construction over existing capacity), NOT the solver. Pure host
    path: numpy columns only, no device is touched, no TPU probe runs.

    Also records the 10k-pod x 603-type mask-construction before/after
    (legacy existing_views() per-node Python loop vs existing_columns()
    vectorized fold) with a bit-identical encode_problem parity check, so
    the speedup claim and the "same solver inputs" claim ride one artifact.

    Emits one JSON line + benchmarks/results/soak/soak_<N>x<M>.json."""
    import dataclasses
    import random
    import resource

    import numpy as np

    from benchmarks.workloads import mixed_workload
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.controllers.deprovisioning import \
        DeprovisioningController
    from karpenter_tpu.models.cluster import ClusterState, StateNode
    from karpenter_tpu.models.encode import (_ex_label_fit, encode_problem,
                                             existing_fit_vector)
    from karpenter_tpu.models.pod import group_pods, make_pod
    from karpenter_tpu.models.requirements import OP_IN, Requirements
    from karpenter_tpu.providers.instancetypes import generate_fleet_catalog
    from karpenter_tpu.utils.clock import FakeClock

    rng = random.Random(20260805)
    n_nodes = args.soak_nodes
    pods_per = max(1, args.soak_pods // n_nodes)
    now = 1_000_000.0
    clock = FakeClock(now)

    # TTLs huge on purpose: the sweeps must run their full column scans every
    # cycle without ever firing an action (an action path would need the
    # whole termination/cloud stack and would drain the very population the
    # soak is sized on)
    provs = [
        Provisioner(name="p-empty", ttl_seconds_after_empty=10**9),
        Provisioner(name="p-expire", ttl_seconds_until_expired=10**9),
        Provisioner(name="p-both", ttl_seconds_after_empty=10**9,
                    ttl_seconds_until_expired=10**9),
        Provisioner(name="p-plain"),
    ]
    for p in provs:
        p.set_defaults()
    prov_names = [p.name for p in provs]

    class _Kube:
        def provisioners(self):
            return provs

    class _Termination:
        def request_deletion(self, name):
            return False

    zones = ("zone-1a", "zone-1b", "zone-1c")
    alloc = wk.capacity_vector({wk.RESOURCE_CPU: 16_000,
                                wk.RESOURCE_MEMORY: 64 * 2**30,
                                wk.RESOURCE_PODS: 110})
    # shared frozen templates: 1M pods are dataclasses.replace clones that
    # share requests/requirements tuples — per-pod cost is one small object,
    # which is what keeps 1M pods inside a bounded-RSS budget
    templates = [make_pod(f"tmpl-{i}", cpu=f"{250 * (1 + i % 4)}m",
                          memory=f"{512 * (1 + i % 4)}Mi",
                          owner_kind="ReplicaSet")
                 for i in range(8)]

    def fresh_node(name, with_pods=True):
        pods = []
        if with_pods:
            pods = [dataclasses.replace(templates[j % len(templates)],
                                        name=f"{name}-p{j}", node_name=name)
                    for j in range(pods_per)]
        i = rng.randrange(1 << 30)
        return StateNode(
            name=name,
            labels={wk.LABEL_ZONE: zones[i % 3],
                    wk.LABEL_CAPACITY_TYPE: ("spot" if i % 4 == 0
                                             else "on-demand"),
                    wk.LABEL_INSTANCE_TYPE: f"m.size{i % 6}",
                    "team": f"t{i % 12}"},
            allocatable=list(alloc),
            provisioner_name=prov_names[i % len(prov_names)],
            price=0.05 + (i % 100) / 1000.0,
            created_ts=now - (i % 86_400),
            pods=pods)

    t0 = time.perf_counter()
    cluster = ClusterState()
    node_names = []
    for k in range(n_nodes):
        name = f"soak-{k:06d}"
        # ~2% start empty so the emptiness sweep tracks a live population
        cluster.add_node(fresh_node(name, with_pods=(k % 50 != 0)))
        node_names.append(name)
    build_s = time.perf_counter() - t0
    build_rss = _rss_mb()

    ctrl = DeprovisioningController(
        kube=_Kube(), cloudprovider=None, cluster=cluster,
        termination=_Termination(), clock=clock, use_tpu_solver=False)

    # provisioning-mask specs: the 8 headline deployment shapes, deduped
    mask_specs = [g.spec for g in group_pods(mixed_workload(80))]

    # -- incremental plane: resident twins of the four sweeps ---------------
    # Each timed incremental cycle does EXACTLY the work the legacy phases
    # redo from scratch — dirty detection, mask patch, candidate-verdict
    # patch, emptiness/expiration sets — but patched at dirty rows, with
    # the cost routed through the gap ledger's extract/warm_start phases.
    # Per-cycle parity audits (untimed) pin the resident structures
    # bit-identical to the legacy recomputes.
    from karpenter_tpu import incremental as inc_plane
    from karpenter_tpu.incremental import (DeltaTracker, ResidentCandidates,
                                           ResidentMasks, account_residency,
                                           empty_node_rows,
                                           expired_node_rows)
    from karpenter_tpu.profiling.gapledger import GAP_LEDGER

    inc_on = inc_plane.enabled()
    rmasks = ResidentMasks(cluster)
    rcands = ResidentCandidates(cluster)
    tracker = DeltaTracker(cluster)
    tracker.advance()

    def inc_cycle():
        """One incremental reconcile cycle: (wall ms, dirty rows, patched
        rows, (empty_rows, expired_rows)). The gap ledger attributes the
        split: extract = dirty bookkeeping, warm_start = resident patch +
        vectorized sweep sets."""
        t0 = time.perf_counter()
        with GAP_LEDGER.solve_scope("soak-incremental"):
            te = time.perf_counter()
            dirty_names, _complete = tracker.dirty_names()
            tracker.advance()
            GAP_LEDGER.note("extract", time.perf_counter() - te)
            tw = time.perf_counter()
            patched = rmasks.sync(mask_specs)
            patched += rcands.sync()
            rcands.eligible_rows()
            _, ttl_e = ctrl._prov_ttl_columns("ttl_seconds_after_empty")
            _, ttl_x = ctrl._prov_ttl_columns("ttl_seconds_until_expired")
            e_rows = empty_node_rows(cluster, ttl_e)
            x_rows = expired_node_rows(cluster, ttl_x, clock.now())
            account_residency(rmasks, rcands)
            GAP_LEDGER.note("warm_start", time.perf_counter() - tw)
        ms = (time.perf_counter() - t0) * 1000
        return ms, len(dirty_names), patched, (e_rows, x_rows)

    def churn(cycle, qps=None):
        """One cycle's worth of watch-stream deltas: soak_qps events per
        simulated second (1 cycle == 1s)."""
        for j in range(args.soak_qps if qps is None else qps):
            op = rng.random()
            name = node_names[rng.randrange(len(node_names))]
            node = cluster.nodes[name]
            if op < 0.45:
                t = templates[rng.randrange(len(templates))]
                cluster.bind_pod(name, dataclasses.replace(
                    t, name=f"churn-{cycle}-{j}", node_name=name))
            elif op < 0.75:
                if node.pods:
                    node.pods.pop(rng.randrange(len(node.pods)))
            elif op < 0.85:
                node.marked_for_deletion = not node.marked_for_deletion
            elif op < 0.95:
                node.labels["team"] = f"t{rng.randrange(12)}"
            else:
                idx = node_names.index(name)
                cluster.delete_node(name)
                node_names[idx] = f"soak-r{cycle}-{j}"
                cluster.add_node(fresh_node(node_names[idx]))

    phases = {"emptiness": [], "expiration": [], "candidates": [], "mask": []}
    cycle_ms, reevals, rss_series = [], [], []
    inc_cycle_ms, inc_dirty, inc_patched, inc_parity = [], [], [], []
    for cycle in range(args.soak_cycles):
        churn(cycle)
        clock.step(1.0)

        if inc_on:
            # the incremental twin of the four legacy phases below, timed
            # as one cycle. Runs FIRST so the resident patch pays the
            # dirty rows' evictability recomputes itself instead of
            # riding the legacy sweep's cache (the legacy numbers this
            # run are therefore cache-flattered; the recorded baseline
            # artifact is the honest legacy reference).
            ms, n_dirty, n_patched, _sets = inc_cycle()
            inc_cycle_ms.append(ms)
            inc_dirty.append(n_dirty)
            inc_patched.append(n_patched)

        t0 = time.perf_counter()
        ctrl.reconcile_emptiness()
        phases["emptiness"].append((time.perf_counter() - t0) * 1000)

        t0 = time.perf_counter()
        ctrl.reconcile_expiration()
        phases["expiration"].append((time.perf_counter() - t0) * 1000)

        rc0 = cluster.evict_recomputes
        t0 = time.perf_counter()
        cands = cluster.consolidation_candidates()
        phases["candidates"].append((time.perf_counter() - t0) * 1000)
        reevals.append(cluster.evict_recomputes - rc0)

        t0 = time.perf_counter()
        ex = cluster.existing_columns()
        legacy_vecs = [existing_fit_vector(ex, spec) for spec in mask_specs]
        phases["mask"].append((time.perf_counter() - t0) * 1000)

        cycle_ms.append(sum(p[-1] for p in phases.values()))
        rss_series.append(_rss_mb())

        if inc_on:
            # untimed bit-parity audit: resident masks vs the fresh folds,
            # resident candidate verdicts vs the legacy sweep (nothing
            # churned between the two, so both saw identical state)
            mask_ok = all(
                np.array_equal(rmasks.mask_for(ex, s), lv)
                for s, lv in zip(mask_specs, legacy_vecs))
            cand_ok = (rcands.candidate_names()
                       == sorted(n.name for n in cands))
            inc_parity.append(bool(mask_ok and cand_ok))

    def pct(xs, q):
        ys = sorted(xs)
        return round(ys[min(len(ys) - 1, int(len(ys) * q))], 3)

    # warm-cache steady state excludes cycle 0: the first candidate pass
    # seeds the evictability cache for the whole fleet (by design — that is
    # the one full sweep the dirty-set then amortizes away). Its cost is
    # reported separately as first_cycle_ms.
    first_cycle_ms = cycle_ms[0]
    if len(cycle_ms) > 1:
        cycle_ms = cycle_ms[1:]
        phases = {k: v[1:] for k, v in phases.items()}
    steady_reevals = reevals[1:] or reevals
    reeval_p50 = statistics.median(steady_reevals)
    reeval_frac = reeval_p50 / max(1, len(node_names))

    # -- mask-construction before/after @ 10k pods x full 603-type fleet ----
    cat = generate_fleet_catalog()
    small = ClusterState()
    for k in range(args.soak_mask_nodes):
        small.add_node(fresh_node(f"mask-{k:05d}"))
    pods_10k = mixed_workload(10_000)
    specs_10k = [g.spec for g in group_pods(pods_10k)]

    views = small.existing_views()

    def legacy_masks():
        return [np.array([_ex_label_fit(e, s) for e in views], dtype=bool)
                for s in specs_10k]

    def columnar_masks():
        ex = small.existing_columns()
        return [existing_fit_vector(ex, s) for s in specs_10k]

    legacy = legacy_masks()
    columnar = columnar_masks()
    mask_parity = all(np.array_equal(a, b)
                      for a, b in zip(legacy, columnar))
    lt, ct = [], []
    for _ in range(5):
        t0 = time.perf_counter()
        legacy_masks()
        lt.append((time.perf_counter() - t0) * 1000)
        t0 = time.perf_counter()
        columnar_masks()
        ct.append((time.perf_counter() - t0) * 1000)
    legacy_ms = round(statistics.median(lt), 3)
    columnar_ms = round(statistics.median(ct), 3)

    # full encode parity: the solver must see bit-identical inputs whether
    # it was fed the compat views or the column snapshot
    mprov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"])))
    mprov.set_defaults()
    enc_fields = ("group_vec", "group_count", "group_cap", "group_feas",
                  "group_newprov", "ex_alloc", "ex_used", "ex_feas",
                  "daemon_overhead", "ex_cap", "group_origin")

    def enc(existing_of):
        encode_problem(cat, [mprov], pods_10k, existing=existing_of())
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = encode_problem(cat, [mprov], pods_10k, existing=existing_of())
            ts.append((time.perf_counter() - t0) * 1000)
        return r, statistics.median(ts)

    ra, ea = enc(lambda: small.existing_views())
    rb, eb = enc(lambda: small.existing_columns())
    encode_parity = ra.n_slots == rb.n_slots
    for f in enc_fields:
        x, y = getattr(ra, f, None), getattr(rb, f, None)
        if (x is None) != (y is None) or (
                x is not None and not np.array_equal(np.asarray(x),
                                                     np.asarray(y))):
            encode_parity = False

    first = [r for r in rss_series[:10] if r is not None]
    last = [r for r in rss_series[-10:] if r is not None]
    rss_growth = (round(statistics.mean(last) - statistics.mean(first), 1)
                  if first and last else None)
    # "re-evaluated ≪ total": steady-state re-evals track the churn rate
    # (each delta dirties one row), not the fleet size
    reeval_bounded = (reeval_p50 <= 2 * args.soak_qps
                      or reeval_frac < 0.05)
    passed = bool(mask_parity and encode_parity and reeval_bounded)
    record = {
        "metric": "columnar_soak_cycle_p99_ms",
        "value": pct(cycle_ms, 0.99),
        "unit": "ms",
        "nodes": len(node_names),
        "pods": sum(len(n.pods) for n in cluster.nodes.values()),
        "cycles": args.soak_cycles,
        "churn_qps_equiv": args.soak_qps,
        "build_s": round(build_s, 3),
        "build_rss_mb": build_rss,
        # cycle 0 seeds the fleet-wide evictability cache (one-time by
        # design); steady-state percentiles below exclude it
        "first_cycle_ms": round(first_cycle_ms, 3),
        "cycle_p50_ms": pct(cycle_ms, 0.50),
        "cycle_p99_ms": pct(cycle_ms, 0.99),
        "phase_p50_ms": {k: pct(v, 0.50) for k, v in phases.items()},
        "phase_p99_ms": {k: pct(v, 0.99) for k, v in phases.items()},
        # the tentpole claim: churn dirties O(qps) rows, so the candidate
        # pass re-runs its per-node pod scans on ~qps nodes, not the fleet
        "reevaluated_nodes_per_cycle_p50": reeval_p50,
        "reevaluated_nodes_per_cycle_max": max(steady_reevals),
        "reevaluated_first_cycle": reevals[0],
        "reeval_fraction_of_total": round(reeval_frac, 5),
        "rss_first10_mean_mb": round(statistics.mean(first), 1) if first else None,
        "rss_last10_mean_mb": round(statistics.mean(last), 1) if last else None,
        "rss_growth_mb": rss_growth,
        "peak_rss_mb": _peak_rss_mb(),
        "ru_maxrss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1),
        "mask_10k_603types": {
            "existing_nodes": args.soak_mask_nodes,
            "groups": len(specs_10k),
            "legacy_views_ms": legacy_ms,
            "columnar_ms": columnar_ms,
            "speedup": (round(legacy_ms / columnar_ms, 1)
                        if columnar_ms else None),
            "parity": mask_parity,
        },
        "encode_10k_603types": {
            "legacy_views_ms": round(ea, 3),
            "columnar_ms": round(eb, 3),
            "bit_identical": encode_parity,
            "fields": list(enc_fields),
        },
        # the chain view of whatever solves the soak drove (None on the
        # pure-host sweep — no solve scope opened, honestly absent)
        "critical": _bench_critical_summary(),
        "passed": passed,
    }
    print(json.dumps(record), flush=True)
    # KARPENTER_TPU_SOAK_DIR redirects artifacts (presubmit's small config
    # writes to /tmp — the fleet-drill-small idiom)
    base_dir = os.environ.get("KARPENTER_TPU_SOAK_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "results")
    out_dir = os.path.join(base_dir, "soak")
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir,
                       f"soak_{len(node_names)}x{record['pods']}.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
    from benchmarks import ledger as _ledger

    wl = {"nodes": record["nodes"], "pods": record["pods"]}
    _ledger.record(record["metric"], record["value"], record["unit"],
                   source="bench.py --soak", backend="cpu",
                   degraded=not passed, workload=wl, artifact=out)
    _ledger.record("soak_cycle_p50_ms", record["cycle_p50_ms"], "ms",
                   source="bench.py --soak", backend="cpu",
                   degraded=not passed, workload=wl, artifact=out)
    if record["critical"]:
        _ledger.record("critical_overlap_ratio",
                       record["critical"]["overlap_ratio"], "ratio",
                       source="bench.py --soak", backend="cpu",
                       workload=wl, detail=record["critical"], artifact=out)

    # -- incremental plane artifact -----------------------------------------
    if inc_on and inc_cycle_ms:
        # steady state excludes cycle 0 (the cold full build of the
        # resident masks + candidate verdicts), same convention as above
        steady_inc = inc_cycle_ms[1:] or inc_cycle_ms
        steady_dirty = inc_dirty[1:] or inc_dirty
        parity_green = bool(inc_parity) and all(inc_parity)
        edges = (25, 50, 100, 200, 400, 800, 1600, 3200)
        hist: "dict[str, int]" = {}
        for d in steady_dirty:
            label = next((f"<{e}" for e in edges if d < e), f">={edges[-1]}")
            hist[label] = hist.get(label, 0) + 1
        # churn-proportionality sweep: fleet size FIXED, qps varied — the
        # incremental cycle cost must track the churn rate (the legacy
        # sweeps' cost is flat in qps and linear in fleet)
        scaling = []
        for q in sorted({max(1, args.soak_qps // 4), args.soak_qps,
                         args.soak_qps * 2}):
            ms_list, d_list = [], []
            for c in range(8):
                churn(100_000 + q * 10 + c, q)
                clock.step(1.0)
                ms, nd, _p, _sets = inc_cycle()
                ms_list.append(ms)
                d_list.append(nd)
            scaling.append({
                "qps": q,
                "cycle_p50_ms": round(statistics.median(ms_list), 3),
                "dirty_p50": statistics.median(d_list)})
        gap_rows = [r for r in GAP_LEDGER.rows()
                    if r.get("source") == "soak-incremental"]
        extract_ms = round(sum(r["phases_ms"].get("extract", 0.0)
                               for r in gap_rows), 3)
        warm_ms = round(sum(r["phases_ms"].get("warm_start", 0.0)
                            for r in gap_rows), 3)
        wall_ms_total = sum(r["wall_ms"] for r in gap_rows)
        attributed_share = round((extract_ms + warm_ms)
                                 / max(wall_ms_total, 1e-9), 4)
        # THE churn-proportionality number: steady-state incremental cycle
        # cost as a share of the legacy full-recompute cycle at the same
        # fleet/churn. The perf-regress gate watches this — a structural
        # regression (patching drifting back toward fleet-proportional
        # work) shows up here before the absolute p99 does.
        encode_share = round(pct(steady_inc, 0.99)
                             / max(record["cycle_p99_ms"], 1e-9), 4)
        inc_record = {
            "tool": "karpenter-tpu-incremental-soak",
            "schema": 1,
            "nodes": record["nodes"],
            "pods": record["pods"],
            "cycles": args.soak_cycles,
            "churn_qps_equiv": args.soak_qps,
            "first_cycle_incremental_ms": round(inc_cycle_ms[0], 3),
            "cycle_p50_incremental_ms": pct(steady_inc, 0.50),
            "cycle_p99_incremental_ms": pct(steady_inc, 0.99),
            "legacy_cycle_p99_ms": record["cycle_p99_ms"],
            "dirty_rows_p50": statistics.median(steady_dirty),
            "dirty_set_histogram": hist,
            "patched_rows_p50": statistics.median(inc_patched[1:]
                                                  or inc_patched),
            "parity_green_every_cycle": parity_green,
            "parity_cycles": len(inc_parity),
            "per_cycle": [
                {"dirty": d, "ms": round(ms, 3)}
                for d, ms in zip(inc_dirty, inc_cycle_ms)],
            "churn_scaling": scaling,
            "steady_encode_share_of_legacy_cycle": encode_share,
            "gap_ledger": {
                "source": "soak-incremental",
                "rows": len(gap_rows),
                "extract_ms_total": extract_ms,
                "warm_start_ms_total": warm_ms,
                "attributed_share_of_wall": attributed_share,
            },
            "resident_bytes": rmasks.nbytes() + rcands.nbytes(),
            "plane_counters": inc_plane.activity(),
        }
        print(json.dumps({
            "metric": "cycle_p99_incremental_ms",
            "value": inc_record["cycle_p99_incremental_ms"],
            "unit": "ms", "parity_green": parity_green}), flush=True)
        inc_dir = os.path.join(base_dir, "incremental")
        os.makedirs(inc_dir, exist_ok=True)
        inc_out = os.path.join(
            inc_dir, f"incremental_{record['nodes']}x{record['pods']}.json")
        with open(inc_out, "w") as f:
            json.dump(inc_record, f, indent=2, sort_keys=True)
        # workload key must match _incremental_entries' backfill key
        # exactly, or ledger backfill stops being a noop
        inc_wl = {**wl, "qps": args.soak_qps}
        _ledger.record("cycle_p99_incremental_ms",
                       inc_record["cycle_p99_incremental_ms"], "ms",
                       source="bench.py --soak", backend="cpu",
                       degraded=not parity_green, workload=inc_wl,
                       artifact=inc_out,
                       detail={"dirty_set_histogram": hist,
                               "dirty_rows_p50":
                                   inc_record["dirty_rows_p50"],
                               "parity_green": parity_green})
        _ledger.record("incremental_steady_encode_share", encode_share,
                       "share",
                       source="bench.py --soak", backend="cpu",
                       degraded=not parity_green, workload=inc_wl,
                       artifact=inc_out)
        passed = passed and parity_green
    return 0 if passed else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steady", type=int, default=5, metavar="N",
                    help="steady-state waves to measure (resident-buffer "
                         "solve_many reps after warmup; 0 disables)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet serving mode: open-loop multi-tenant "
                         "benchmark through the FleetFrontend (sustained "
                         "solves/sec + p99 through the admission queue) "
                         "instead of the single-solver headline")
    ap.add_argument("--fleet-tenants", type=int, default=8, metavar="N",
                    help="concurrent tenants in --fleet mode")
    ap.add_argument("--tenants", type=int, default=None, metavar="N",
                    help="alias for --fleet-tenants (grows the tenant "
                         "axis; the cardinality guard keeps per-tenant "
                         "series bounded at K+1 no matter how large)")
    ap.add_argument("--fleet-rate", type=float, default=10.0, metavar="R",
                    help="offered solves/sec PER TENANT in --fleet mode")
    ap.add_argument("--fleet-seconds", type=float, default=4.0, metavar="S",
                    help="open-loop submission window in --fleet mode")
    ap.add_argument("--soak", action="store_true",
                    help="columnar-state soak: controller reconcile sweeps "
                         "at --soak-nodes/--soak-pods under --soak-qps "
                         "churn (pure host path; no device, no TPU probe)")
    ap.add_argument("--soak-nodes", type=int, default=100_000, metavar="N")
    ap.add_argument("--soak-pods", type=int, default=1_000_000, metavar="M")
    ap.add_argument("--soak-cycles", type=int, default=60, metavar="C")
    ap.add_argument("--soak-qps", type=int, default=200, metavar="Q",
                    help="watch-stream deltas per simulated second")
    ap.add_argument("--soak-mask-nodes", type=int, default=1_500, metavar="K",
                    help="existing-node count for the 10k-pod mask "
                         "before/after section (legacy per-node loop must "
                         "still terminate)")
    ap.add_argument("--profile", action="store_true",
                    help="attribution mode: per-phase ms + unaccounted "
                         "residue + roofline ratio through the profiling "
                         "gap ledger (benchmarks/profile_drill.py paths), "
                         "ledgered as profile_unaccounted_share per "
                         "workload (CPU path; no TPU probe)")
    ap.add_argument("--profile-pods", type=int, default=2_000, metavar="N",
                    help="pod count per measured workload in --profile "
                         "mode (the full 10k-pod proof is `make "
                         "profile-drill`)")
    args = ap.parse_args()
    if args.tenants is not None:
        args.fleet_tenants = args.tenants
    if args.soak:  # host-only path: columns + numpy, no jax device needed
        sys.exit(_soak_bench(args))
    if args.profile:  # CPU attribution path: pin_cpu inside, no probe
        sys.exit(_profile_bench(args))
    forced = os.environ.get("KARPENTER_TPU_BENCH_PLATFORM")
    if forced:  # operator knows the tunnel state; skip the probe entirely
        tpu_ok, note = forced == "axon", f"forced via KARPENTER_TPU_BENCH_PLATFORM={forced}"
    else:
        # FAST-FAIL probe (VERDICT r4 ask #6): one attempt, hard 20s budget.
        # The old 3x60s ladder burned 3+ minutes before surrendering the TPU
        # column; a healthy tunnel answers PJRT init in seconds, and when it
        # doesn't, the freshest recorded capture (latest_tpu_capture below)
        # is the chip evidence anyway — hack/tpu_capture.py --loop keeps it
        # current whenever the tunnel breathes.
        tpu_ok, note = probe_tpu(attempts=1, timeout_s=20)
    if not args.fleet:  # fleet mode has bounded waits; no watchdog needed
        threading.Thread(target=_watchdog, daemon=True).start()

    platform = "axon" if tpu_ok else "cpu"
    jax, warning = pin(platform)
    if warning:
        _state["detail"]["platform_pin_warning"] = warning
    if args.fleet:
        sys.exit(_fleet_bench(args, jax))

    _state["detail"]["probe"] = note
    _state["detail"]["requested_backend"] = platform
    # Most recent on-chip capture recorded by hack/tpu_capture.py — the chip
    # evidence survives even when the tunnel is down at driver-collection
    # time (VERDICT r2 ask #1: capture is a process, not an event).
    try:
        from karpenter_tpu.utils.capture import latest_capture
        cap = latest_capture()
        if cap:
            _state["detail"]["latest_tpu_capture"] = {
                "captured_at": cap.get("captured_at"),
                # a salvaged partial (relay wedged mid-capture) reports
                # only the sections that completed — flagged so a missing
                # section reads as "not measured", never "regressed"
                "partial": cap.get("partial", False),
                "p50_ms": (cap.get("headline") or {}).get("p50_ms",
                                                          cap.get("value")),
                "crossover_pods": cap.get("crossover_pods"),
                "exec_crossover_pods": cap.get("exec_crossover_pods"),
                "backend": cap.get("backend", "tpu"),
                # attribution fields (round 4): consolidation number, the
                # link-state sentinels, and streaming-mode kernel time, so a
                # capture taken in a degraded relay phase can't masquerade
                # as a kernel regression (docs/designs/solver-boundary.md)
                "consolidation_500_ms": (cap.get("consolidation_500")
                                         or {}).get("p50_ms"),
                # streaming-regime consolidation through the callback
                # transport (the routing-table entry; VERDICT r4 ask #2)
                "consolidation_500_streaming_ms": (
                    cap.get("consolidation_500_streaming") or {}).get("p50_ms"),
                "transition_in": (cap.get("link_state")
                                  or {}).get("transition_in"),
                "link_state": cap.get("link_state"),
                "exec_only_10k_ms": (cap.get("exec_only_10k")
                                     or {}).get("p50_ms"),
                "wave_per_solve_ms": (cap.get("wave_pipelined")
                                      or {}).get("per_solve_ms"),
                "wave_steady_per_solve_ms": (cap.get("wave_steady")
                                             or {}).get("per_solve_ms"),
                # escape-hatch outcome: sub-ms sync_after means io_callback
                # readback kept the link streaming (solver-boundary.md)
                "io_escape_sync_after_ms": ((cap.get("io_callback_escape")
                                             or {}).get("sync_after")
                                            or {}).get("p50_ms"),
                "callback_headline_ms": (cap.get("callback_headline")
                                         or {}).get("p50_ms"),
            }
    except Exception as e:
        # capture history must never break the bench — but a perf plane
        # must not eat its own errors either (docs/designs/slo.md): log
        # the failure and COUNT it in the artifact, so a run whose history
        # went missing says so instead of silently claiming "no capture"
        import logging as _logging

        _logging.getLogger("karpenter.bench").warning(
            "latest_tpu_capture read failed: %s: %s", type(e).__name__, e)
        _state["detail"]["latest_tpu_capture_error"] = {
            "type": type(e).__name__, "error": str(e)[:120]}
        _state["detail"]["capture_history_errors"] = (
            _state["detail"].get("capture_history_errors", 0) + 1)
    try:  # newest RECORDED profiler-trace evidence (clearly dated — this is
        # archive evidence for the on-chip kernel time, not this run's data)
        import glob as _glob
        summaries = sorted(_glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks", "results", "trace_summary_*.json")))
        if summaries:
            with open(summaries[-1]) as _f:
                ts = json.load(_f)
            _state["detail"]["recorded_device_trace"] = {
                "captured_at": ts.get("captured_at"),
                "device_exec_per_run_ms": ts.get("device_exec_per_run_ms"),
                "workload": ts.get("workload"),
                "trace": ts.get("trace"),
            }
    except Exception as e:
        _state["detail"]["recorded_device_trace_error"] = str(e)[:120]
    # A probe-failure CPU fallback is NOT a TPU number — flag it so the
    # recorded artifact can't masquerade as the round's chip result.
    fallback_degraded = not tpu_ok and forced != "cpu"

    try:
        backend = jax.devices()[0].platform
    except Exception as e:
        _emit(None, None,
              {**_state["detail"], "error": f"device init failed after probe: {e}"},
              exit_code=1)
        return
    _state["detail"]["backend"] = backend

    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.requirements import OP_IN, Requirements
    from karpenter_tpu.providers.instancetypes import generate_fleet_catalog
    from karpenter_tpu.solver.core import TPUSolver

    if backend != "cpu":
        # escape-hatch gate (docs/designs/solver-boundary.md): BEFORE any
        # literal read, probe whether io_callback readback keeps the relay
        # in streaming mode AND actually delivers (shared judgment:
        # hack/tpu_capture.io_probe_gate); if so, route every read of this
        # run through the callback transport — the headline then measures
        # the crossover-flipping path. A negative probe changes nothing.
        try:
            import jax.numpy as jnp

            from hack.tpu_capture import io_probe_gate

            probe, _streaming, transport_ok = io_probe_gate(jax, jnp, reps=5)
            _state["detail"]["io_callback_probe"] = probe
            if transport_ok:
                import karpenter_tpu.solver.core as _score

                _score._READBACK = "callback"
                _state["detail"]["readback"] = "callback"
        except Exception as e:
            _state["detail"]["io_callback_probe_error"] = str(e)[:120]

    catalog = generate_fleet_catalog()
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"]),
        (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"]),
    ))
    prov.set_defaults()
    solver = TPUSolver(catalog, [prov])
    pods = workload_10k()

    # warmup: compile + grid build. If the gate enabled the callback
    # transport and the FULL-SIZE transfer then fails (the probe only
    # proved a scalar), fall back to the literal-get path instead of
    # breaking the one-JSON-line contract.
    try:
        res = solver.solve(pods)
    except Exception as e:
        if _state["detail"].get("readback") != "callback":
            raise
        import karpenter_tpu.solver.core as _score

        _score._READBACK = "get"
        _state["detail"]["readback"] = f"get (callback fallback: {str(e)[:80]})"
        res = solver.solve(pods)
    placed = sum(n.pod_count for n in res.nodes)
    assert placed + res.unschedulable_count() == len(pods), (placed, res.unschedulable_count())

    # settle tunnel/device caches AND the host-side allocator: the first
    # few repeats still shift ~2ms on the shared-core runner, which is
    # real at an 18ms headline
    for _ in range(4):
        solver.solve(pods)
    for _ in range(20):
        t0 = time.perf_counter()
        res = solver.solve(pods)
        _state["times"].append((time.perf_counter() - t0) * 1000)
    times = _state["times"]
    p50 = statistics.median(times)

    # The ROUTED scheduling cycle: the controller's measured routing policy
    # (docs/designs/solver-boundary.md) prefers the native C++ scan on this
    # hardware (tunnel RTT dominates the device path), so this is the p50 a
    # production cycle actually pays. Cheap to measure; recorded alongside.
    try:
        from karpenter_tpu.solver.core import NativeSolver

        nat = NativeSolver(catalog, [prov])
        nat.solve(pods)  # warm (grid + native lib)
        nat_times = []
        for _ in range(10):
            t0 = time.perf_counter()
            nat.solve(pods)
            nat_times.append((time.perf_counter() - t0) * 1000)
        _state["detail"]["routed_native_p50_ms"] = round(
            statistics.median(nat_times), 3)
    except Exception as e:  # native unavailable: routing falls back anyway
        _state["detail"]["routed_native_error"] = str(e)[:120]

    # escape-hatch sections: each guarded — a failure records an error
    # field instead of breaking the one-JSON-line contract
    if args.steady > 0:
        try:
            _steady_section(solver, pods, args.steady)
        except Exception as e:
            _state["detail"]["wave_steady_error"] = str(e)[:120]
    try:
        _escape_sections(jax, solver, pods)
    except Exception as e:
        _state["detail"]["callback_headline_error"] = str(e)[:120]
    try:
        _consolidation_streaming(catalog)
    except Exception as e:
        _state["detail"]["consolidation_streaming_error"] = str(e)[:120]

    _state["detail"].update({
        "n_types": len(catalog.types),
        "n_pods": len(pods),
        "nodes_provisioned": len(res.nodes),
        "unschedulable": res.unschedulable_count(),
        "p_min_ms": round(min(times), 3),
        "p_max_ms": round(max(times), 3),
    })
    # per-phase attribution of a full controller cycle (mask/solve/bind)
    # from the tracing recorder — must never break the one-JSON-line
    # contract, so any failure is recorded instead of raised
    try:
        _state["detail"]["phase_breakdown_ms"] = _phase_breakdown(
            catalog, pods)
    except Exception as e:
        _state["detail"]["phase_breakdown_error"] = str(e)[:120]
    if backend != "cpu":
        try:  # link-state attribution for THIS run's headline numbers
            import jax.numpy as jnp

            from hack.tpu_capture import _link_sentinel

            _state["detail"]["link_sync_after_headline"] = _link_sentinel(
                jax, jnp)
        except Exception as e:
            _state["detail"]["link_sentinel_error"] = str(e)[:120]
    _emit(round(p50, 3), round(100.0 / p50, 3), _state["detail"],
          degraded=fallback_degraded)


if __name__ == "__main__":
    main()
