#!/usr/bin/env python
"""Headline benchmark: scheduling-cycle latency @ 10k pending pods x ~600
instance types (BASELINE.json metric; north-star < 100 ms on one TPU chip).

Prints ONE JSON line:
  {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": 100/p50}

vs_baseline > 1.0 means faster than the 100 ms north-star budget.
Measures END-TO-END solve: host encode (mask folding) + device pack kernel +
decode — the full scheduling cycle the controller would pay per batch window.
"""

import json
import statistics
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from karpenter_tpu.apis import wellknown as wk
from karpenter_tpu.apis.provisioner import Provisioner
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
from karpenter_tpu.models.requirements import Requirements, OP_IN
from karpenter_tpu.providers.instancetypes import generate_fleet_catalog
from karpenter_tpu.solver.core import TPUSolver


def workload_10k():
    """BASELINE.json configs[1]-style: mixed cpu/mem pods, zone selectors,
    topology spread, across 8 deployments -> 10k pods."""
    pods = []
    spread = (TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE),)
    deployments = [
        ("web", 3000, "500m", "1Gi", {}, spread),
        ("api", 2000, "1", "2Gi", {}, ()),
        ("cache", 1000, "2", "8Gi", {}, ()),
        ("batch", 1500, "250m", "512Mi", {}, ()),
        ("etl", 800, "4", "8Gi", {}, ()),
        ("zone-a", 700, "1", "1Gi", {wk.LABEL_ZONE: "zone-1a"}, ()),
        ("zone-b", 500, "1", "1Gi", {wk.LABEL_ZONE: "zone-1b"}, ()),
        ("mem", 500, "500m", "4Gi", {}, ()),
    ]
    for name, count, cpu, mem, sel, topo in deployments:
        for i in range(count):
            pods.append(make_pod(f"{name}-{i}", cpu=cpu, memory=mem,
                                 node_selector=dict(sel), topology=topo))
    assert len(pods) == 10_000
    return pods


def main():
    catalog = generate_fleet_catalog()
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"]),
        (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"]),
    ))
    prov.set_defaults()
    solver = TPUSolver(catalog, [prov])
    pods = workload_10k()

    # warmup: compile + grid build
    res = solver.solve(pods)
    placed = sum(n.pod_count for n in res.nodes)
    assert placed + res.unschedulable_count() == len(pods), (placed, res.unschedulable_count())

    solver.solve(pods)  # second warmup: settle tunnel/device caches
    times = []
    for _ in range(20):
        t0 = time.perf_counter()
        res = solver.solve(pods)
        times.append((time.perf_counter() - t0) * 1000)
    p50 = statistics.median(times)

    import jax
    print(json.dumps({
        "metric": "scheduling_cycle_p50_ms_10k_pods_600_types",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(100.0 / p50, 3),
        "detail": {
            "n_types": len(catalog.types),
            "n_pods": len(pods),
            "nodes_provisioned": len(res.nodes),
            "unschedulable": res.unschedulable_count(),
            "p_min_ms": round(min(times), 3),
            "p_max_ms": round(max(times), 3),
            "backend": jax.devices()[0].platform,
        },
    }))


if __name__ == "__main__":
    main()
