#!/usr/bin/env python3
"""Decision-reason lint: the explain vocabulary cannot drift.

The provenance plane's contract is that every decision cites a reason
from one registry (karpenter_tpu/explain/reasons.py), and that registry
stays in lockstep with the code that produces the decisions. Four
AST-level checks (no package import — the lint must run without jax, the
check_phase_accounting idiom):

1. reasons.DIMENSIONS equals solver/core.py MASK_DIMENSIONS exactly (the
   mask factors the dense admission rule multiplies are the dimensions
   attribution decomposes);
2. reasons.CLAUSES covers the dimensions 1:1 in order, and its clause
   strings are EXACTLY the literals models/encode.py
   diagnose_unschedulable returns — the parity audit compares verdicts
   with `==`, so a reworded oracle clause without the registry edit (or
   vice versa) fails here before it fails in production;
3. every literal `reason` passed to note_shed() in karpenter_tpu/ is a
   SHED_REASONS entry, and every entry is cited somewhere (a dead reason
   row would make the docs lie);
4. every literal `verdict` passed to _note_verdict() (ops/consolidate.py
   per-lane capture) is a CONSOLIDATION_VERDICTS entry, and every entry
   is cited somewhere;
5. every literal `reason` passed to note_drain() (interruption's reactive
   reclaim path, spot/rebalance.py's proactive path) is a DRAIN_REASONS
   entry, and every entry is cited somewhere.

Run via `make reasons` (part of `make presubmit`).
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "karpenter_tpu"
REASONS = PACKAGE / "explain" / "reasons.py"
SOLVER_CORE = PACKAGE / "solver" / "core.py"
ENCODE = PACKAGE / "models" / "encode.py"

# call name -> (positional index of the cited literal, registry name)
CITING_CALLS = {
    "note_shed": (2, "SHED_REASONS"),
    "_note_verdict": (2, "CONSOLIDATION_VERDICTS"),
    "note_drain": (2, "DRAIN_REASONS"),
}


def _module_assign(path: pathlib.Path, name: str):
    """The AST value node of a module-level `name = ...` assignment."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return node.value
    raise SystemExit(f"check_decision_reasons: {name} not found in {path}")


def _oracle_clauses() -> "set[str]":
    """Constant strings returned by diagnose_unschedulable (implicit
    string concatenation is already one ast.Constant)."""
    tree = ast.parse(ENCODE.read_text(), filename=str(ENCODE))
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "diagnose_unschedulable":
            return {r.value.value for r in ast.walk(node)
                    if isinstance(r, ast.Return)
                    and isinstance(r.value, ast.Constant)
                    and isinstance(r.value.value, str)}
    raise SystemExit(
        f"check_decision_reasons: diagnose_unschedulable not in {ENCODE}")


def _cited_literals() -> "dict[str, list[tuple[str, int, str]]]":
    """registry name -> [(relpath, lineno, literal)] for every citing
    call site in karpenter_tpu/ (the registry module itself excluded)."""
    out: "dict[str, list[tuple[str, int, str]]]" = {
        reg: [] for _, reg in CITING_CALLS.values()}
    for path in sorted(PACKAGE.rglob("*.py")):
        if path == REASONS:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        rel = str(path.relative_to(ROOT))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name not in CITING_CALLS:
                continue
            idx, reg = CITING_CALLS[name]
            if len(node.args) > idx and \
                    isinstance(node.args[idx], ast.Constant) and \
                    isinstance(node.args[idx].value, str):
                out[reg].append((rel, node.lineno, node.args[idx].value))
    return out


def main() -> int:
    problems: "list[str]" = []
    dimensions = tuple(ast.literal_eval(_module_assign(REASONS,
                                                       "DIMENSIONS")))
    clauses = tuple(ast.literal_eval(_module_assign(REASONS, "CLAUSES")))
    shed_reasons = tuple(ast.literal_eval(_module_assign(REASONS,
                                                         "SHED_REASONS")))
    verdicts = tuple(ast.literal_eval(
        _module_assign(REASONS, "CONSOLIDATION_VERDICTS")))
    drain_reasons = tuple(ast.literal_eval(
        _module_assign(REASONS, "DRAIN_REASONS")))
    mask_dims = tuple(ast.literal_eval(
        _module_assign(SOLVER_CORE, "MASK_DIMENSIONS")))

    # 1) the registry's dimensions ARE the solver's mask factors
    if dimensions != mask_dims:
        problems.append(
            f"explain/reasons.py DIMENSIONS {dimensions!r} != "
            f"solver/core.py MASK_DIMENSIONS {mask_dims!r}")

    # 2) clauses cover the dimensions 1:1 in order, strings match the
    # scalar oracle verbatim
    if tuple(dim for dim, _ in clauses) != dimensions:
        problems.append(
            f"explain/reasons.py CLAUSES keys "
            f"{tuple(d for d, _ in clauses)!r} != DIMENSIONS "
            f"{dimensions!r} (1:1, same order)")
    registry_clauses = {clause for _, clause in clauses}
    oracle = _oracle_clauses()
    for clause in sorted(registry_clauses - oracle):
        problems.append(
            f"explain/reasons.py clause {clause!r} is not returned by "
            f"models/encode.py diagnose_unschedulable (parity audit "
            f"compares with ==)")
    for clause in sorted(oracle - registry_clauses):
        problems.append(
            f"models/encode.py diagnose_unschedulable returns {clause!r} "
            f"which is not in explain/reasons.py CLAUSES")

    # 3+4) every cited literal is registered; every registry row is cited
    cited = _cited_literals()
    for reg, vocab in (("SHED_REASONS", shed_reasons),
                       ("CONSOLIDATION_VERDICTS", verdicts),
                       ("DRAIN_REASONS", drain_reasons)):
        seen: "set[str]" = set()
        for rel, lineno, literal in cited[reg]:
            seen.add(literal)
            if literal not in vocab:
                problems.append(
                    f"{rel}:{lineno}: cites {literal!r} which is not in "
                    f"explain/reasons.py {reg}")
        for entry in vocab:
            if entry not in seen:
                problems.append(
                    f"explain/reasons.py {reg} entry {entry!r} is cited "
                    f"nowhere in karpenter_tpu/ (dead vocabulary rows "
                    f"make the docs lie)")

    for p in problems:
        print(f"check_decision_reasons: {p}", file=sys.stderr)
    if problems:
        print(f"check_decision_reasons: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    n_cited = sum(len(v) for v in cited.values())
    print(f"check_decision_reasons: ok ({len(dimensions)} dimensions, "
          f"{len(clauses)} oracle clauses, {len(shed_reasons)} shed "
          f"reasons, {len(verdicts)} consolidation verdicts, "
          f"{len(drain_reasons)} drain reasons, "
          f"{n_cited} citing call sites)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
