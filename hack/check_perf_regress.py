#!/usr/bin/env python3
"""Perf regression gate: tier-1-sized micro-benches vs the ledger's noise
bands (docs/designs/slo.md).

Runs two micro-benchmarks small enough for presubmit — the in-process
interruption drain at 1000 messages and the inflate-100 baseline config —
and compares each against the noise band of its own history in the perf
ledger (benchmarks/results/ledger.jsonl). The band is

    median ± max(K_MAD * MAD, REL_FLOOR * median)

over non-degraded history for the same (metric, backend, workload, host):
absolute wall-clock numbers only trend within one machine (this repo's
history spans boxes that differ 10x on the same drain), so the band is
keyed by a host fingerprint (KARPENTER_TPU_PERF_HOST env, else
platform.node()) carried in each entry's detail. MAD alone collapses to
~0 on a quiet history, so the relative floor keeps single-machine jitter
from tripping the gate. The comparison is direction-aware: throughput
metrics (msgs/s) only fail when they fall BELOW the band, latency metrics
(ms) only when they rise ABOVE it — getting faster is never a regression.

With fewer than MIN_SAMPLES same-host history points the gate SEEDS
instead of judging: it appends the measurement to the ledger (detail
marks it a gate seed) and passes, so a fresh machine builds its own band
over its first few presubmits rather than being judged against someone
else's hardware. Passing measured runs keep recording (gate samples), so
the band is a moving window over the newest RECENT_N same-host points —
it tracks gradual host drift without ever absorbing a failing number.

Falsifiability hooks (exercised by tests/test_slo.py):
    --inject METRIC=VALUE   use VALUE as the measured number instead of
                            running that micro-bench (never seeds)
    --ledger PATH           read/seed bands at PATH instead of the
                            committed ledger

Run via `make perf-regress` (part of `make presubmit`).
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

K_MAD = 5.0        # band half-width in MADs...
REL_FLOOR = 0.5    # ...but never narrower than 50% of the median
MIN_SAMPLES = 3    # seed (don't judge) below this much same-host history
RECENT_N = 20      # band over at most this many newest same-host entries


def _fingerprint() -> str:
    return os.environ.get("KARPENTER_TPU_PERF_HOST") or platform.node()


def _bench_interruption() -> float:
    from benchmarks.interruption_bench import run_scale

    return float(run_scale(1000)["msgs_per_sec"])


def _bench_inflate() -> float:
    from benchmarks.baseline_configs import config_0_inflate

    return float(config_0_inflate()["ms"])


def _bench_profile_unaccounted() -> float:
    """Gap-ledger attribution probe (benchmarks/profile_drill.gate_probe):
    one warmed 400-pod solve; the gate trends the unaccounted residue
    share so attribution rot (a new unspanned phase creeping into the
    solve path) fails presubmit like any other regression."""
    from benchmarks.profile_drill import gate_probe

    return float(gate_probe()["unaccounted_share"])


def _bench_incremental_share() -> float:
    """Incremental-plane probe (benchmarks/incremental_probe.gate_probe):
    a small churned fleet reconciled both ways; the gate trends the
    steady-state encode share (incremental cycle p50 over the legacy
    full-recompute cycle p50) so resident patching drifting back toward
    fleet-proportional work fails presubmit. The probe raises on any
    mask/candidate parity divergence rather than report a fast-but-wrong
    share."""
    from benchmarks.incremental_probe import gate_probe

    return float(gate_probe()["encode_share"])


def _bench_churn_thrash() -> float:
    """Overload-plane probe (benchmarks/churn_drill.gate_probe): 60 churn
    syncs (zipf hot set + 55% one-shot hashes) through an in-process
    SolverService under an HBM cap, admission filter ON; the gate trends
    the thrash ratio (re-installs of recently evicted keys per install)
    so a regression in the anti-thrash eviction plane — filter earn
    logic, low-water hysteresis, probation handling — fails presubmit."""
    from benchmarks.churn_drill import gate_probe

    return float(gate_probe()["thrash_ratio"])


def _bench_critical_serialize() -> float:
    """Critical-path probe (benchmarks/critical_drill.gate_probe): a
    warmed 400-pod Solve through the in-process service; the gate trends
    the serialize share of the critical path so wire encode/decode
    creeping onto the chain (where the wall clock alone hides it behind
    faster phases) fails presubmit like any other regression."""
    from benchmarks.critical_drill import gate_probe

    return float(gate_probe()["critical_serialize_share"])


# (metric, workload filter, backend, unit, direction, runner). `direction`
# is the GOOD direction: "higher" fails below the band, "lower" above it.
GATES = (
    ("interruption_msgs_per_sec", {"messages": 1000}, "cpu", "msgs/s",
     "higher", _bench_interruption),
    ("baseline_config_ms", {"name": "inflate-100"}, "cpu", "ms",
     "lower", _bench_inflate),
    ("profile_unaccounted_share", {"name": "profile_gate", "pods": 400},
     "cpu", "ratio", "lower", _bench_profile_unaccounted),
    ("incremental_steady_encode_share",
     {"name": "incremental_gate", "nodes": 1500}, "cpu", "share",
     "lower", _bench_incremental_share),
    ("critical_serialize_share",
     {"name": "critical_gate", "pods": 400}, "cpu", "share",
     "lower", _bench_critical_serialize),
    ("churn_eviction_thrash_ratio",
     {"name": "churn_gate", "syncs": 60}, "cpu", "ratio",
     "lower", _bench_churn_thrash),
)


def _band(ledger, metric: str, backend: str, workload: dict, host: str,
          path: "str | None"):
    """The noise band for one gate: same metric, backend, workload shape,
    AND host fingerprint (an interruption drain at 15k — or on different
    hardware — must not widen the band this 1k drain is judged against)."""
    es = [e for e in ledger.entries(path)
          if (e.get("detail") or {}).get("host") == host
          and all((e.get("workload") or {}).get(k) == v
                  for k, v in workload.items())]
    return ledger.noise_band(metric, backend=backend,
                             ledger_entries=es[-RECENT_N:])


def check_gate(metric, workload, backend, unit, direction, runner,
               injected: "dict[str, float]", ledger_path: "str | None",
               host: str):
    """-> (status, report_line); status in {"ok", "seeded", "regress"}."""
    from benchmarks import ledger

    band = _band(ledger, metric, backend, workload, host, ledger_path)
    what = f"{metric} {json.dumps(workload, sort_keys=True)}"
    if metric in injected:
        measured, how = injected[metric], "injected"
    else:
        measured, how = runner(), "measured"
    n = 0 if band is None else band["n"]
    if n < MIN_SAMPLES:
        if how == "measured":
            ledger.record(metric, round(measured, 3), unit,
                          source="hack.check_perf_regress", backend=backend,
                          workload=workload, path=ledger_path,
                          detail={"host": host, "gate_seed": True})
        return "seeded", (f"SEED  {what}: {how} {measured:.3f} {unit}; only "
                          f"{n} point(s) for host {host!r} (need "
                          f"{MIN_SAMPLES}) — recorded, not judged")
    tol = max(K_MAD * band["mad"], REL_FLOOR * band["median"])
    lo, hi = band["median"] - tol, band["median"] + tol
    detail = (f"{how} {measured:.3f} {unit} vs median {band['median']:.3f} "
              f"±{tol:.3f} (n={band['n']}, mad={band['mad']:.3f}, "
              f"good={direction}, host={host!r})")
    regressed = (measured < lo) if direction == "higher" else (measured > hi)
    if regressed:
        return "regress", f"FAIL  {what}: {detail}"
    # a PASSING measured run joins the band (detail marks it a gate
    # sample; injected values never record). Without this the band stays
    # frozen at its MIN_SAMPLES seeds forever, and ordinary host drift —
    # a shared-tenancy VM slowing 30% week over week — eventually fails
    # every presubmit on both the working tree AND the seed commit. With
    # it the band is a moving window (newest RECENT_N same-host points)
    # that tracks the machine while still trapping step regressions: a
    # real slowdown fails the CURRENT band before it can pull the median.
    if how == "measured":
        ledger.record(metric, round(measured, 3), unit,
                      source="hack.check_perf_regress", backend=backend,
                      workload=workload, path=ledger_path,
                      detail={"host": host, "gate_sample": True})
    return "ok", f"ok    {what}: {detail}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--inject", action="append", default=[],
                    metavar="METRIC=VALUE",
                    help="use VALUE as the measured number for METRIC "
                         "(falsifiability hook; skips running that bench)")
    ap.add_argument("--ledger", default=None,
                    help="read/seed noise bands at this ledger file "
                         "instead of the committed one")
    args = ap.parse_args(argv)

    injected: "dict[str, float]" = {}
    for spec in args.inject:
        name, _, val = spec.partition("=")
        try:
            injected[name] = float(val)
        except ValueError:
            ap.error(f"--inject expects METRIC=VALUE, got {spec!r}")

    host = _fingerprint()
    failures = 0
    for gate in GATES:
        status, line = check_gate(*gate, injected=injected,
                                  ledger_path=args.ledger, host=host)
        print(f"check_perf_regress: {line}")
        if status == "regress":
            failures += 1
    if failures:
        print(f"check_perf_regress: {failures} metric(s) regressed past "
              f"the ledger noise band (history: "
              f"`python -m benchmarks.ledger band METRIC`)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
