#!/usr/bin/env python3
"""Phase-accounting lint: the attribution vocabulary cannot drift.

Three AST-level checks (no package import — the lint must run without jax,
the check_crashpoints idiom):

1. every Tracer span name the gap-ledger PHASES table maps onto
   (karpenter_tpu/profiling/gapledger.py) exists in the Tracer phase
   registry (karpenter_tpu/tracing/__init__.py PHASE_REGISTRY);
2. every LITERAL span name passed to start_span()/record_span() anywhere
   in karpenter_tpu/ is registered (or matches a DYNAMIC_PHASE_PREFIXES
   family) — a new span recorded without registering it fails presubmit,
   so the gap ledger can never silently lose a phase;
3. every registry entry is actually recorded somewhere — dead registry
   entries would make the docs lie about what the tracer emits.

f-string span names (e.g. the client's solver.rpc.<Method>) are checked
by their static prefix against DYNAMIC_PHASE_PREFIXES; non-literal names
(variables) are skipped — they are the Tracer API's own plumbing.

Run via `make phaseacct` (part of `make presubmit`).
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "karpenter_tpu"
GAPLEDGER = PACKAGE / "profiling" / "gapledger.py"
TRACING = PACKAGE / "tracing" / "__init__.py"

SPAN_CALLS = ("start_span", "record_span")


def _module_assign(path: pathlib.Path, name: str):
    """The AST value node of a module-level `name = ...` assignment."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return node.value
    raise SystemExit(f"check_phase_accounting: {name} not found in {path}")


def load_phases() -> "dict[str, tuple[str, ...]]":
    value = _module_assign(GAPLEDGER, "PHASES")
    phases = ast.literal_eval(value)
    return {phase: tuple(spans) for phase, spans in phases}


def load_registry() -> "tuple[tuple[str, ...], tuple[str, ...]]":
    registry = ast.literal_eval(_module_assign(TRACING, "PHASE_REGISTRY"))
    prefixes = ast.literal_eval(
        _module_assign(TRACING, "DYNAMIC_PHASE_PREFIXES"))
    return tuple(registry), tuple(prefixes)


def _span_name_args(tree: ast.AST):
    """Yield (node, first-positional-arg) of every start_span/record_span
    call in the tree."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name in SPAN_CALLS:
            yield node, node.args[0]


def _literal_strings(arg: ast.expr):
    """Constant-string values an expression can evaluate to: plain
    constants, and both arms of conditional expressions (core.py picks
    dispatch.execute vs dispatch.compile with an IfExp)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg.value
    elif isinstance(arg, ast.IfExp):
        yield from _literal_strings(arg.body)
        yield from _literal_strings(arg.orelse)


def _static_prefix(joined: ast.JoinedStr) -> str:
    """Leading constant text of an f-string ('solver.rpc.' of
    f'solver.rpc.{name}')."""
    out = []
    for part in joined.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            out.append(part.value)
        else:
            break
    return "".join(out)


def main() -> int:
    phases = load_phases()
    registry, prefixes = load_registry()
    problems: "list[str]" = []

    # 1) gap-ledger table maps onto registered spans only
    for phase, spans in phases.items():
        for span in spans:
            if span not in registry:
                problems.append(
                    f"{GAPLEDGER.relative_to(ROOT)}: gap phase {phase!r} "
                    f"maps to span {span!r} which is not in "
                    f"tracing.PHASE_REGISTRY")

    # 2) every literal call site is registered; 3) registry has no dead rows
    used: "set[str]" = set()
    for path in sorted(PACKAGE.rglob("*.py")):
        if path == TRACING:
            continue  # the Tracer's own API plumbing passes names through
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            problems.append(f"{path.relative_to(ROOT)}: unparseable: {e}")
            continue
        rel = path.relative_to(ROOT)
        for node, arg in _span_name_args(tree):
            names = list(_literal_strings(arg))
            if names:
                for value in names:
                    used.add(value)
                    if value not in registry and not any(
                            value.startswith(p) for p in prefixes):
                        problems.append(
                            f"{rel}:{node.lineno}: span {value!r} is not "
                            f"in tracing.PHASE_REGISTRY (register it, or "
                            f"the gap ledger can never account for it)")
            elif isinstance(arg, ast.JoinedStr):
                prefix = _static_prefix(arg)
                if not any(prefix.startswith(p) for p in prefixes):
                    problems.append(
                        f"{rel}:{node.lineno}: dynamic span name with "
                        f"static prefix {prefix!r} matches no "
                        f"DYNAMIC_PHASE_PREFIXES entry")
    for span in registry:
        if span not in used and not any(span.startswith(p)
                                        for p in prefixes):
            problems.append(
                f"{TRACING.relative_to(ROOT)}: PHASE_REGISTRY entry "
                f"{span!r} is recorded nowhere in karpenter_tpu/ "
                f"(dead registry rows make the docs lie)")

    for p in problems:
        print(f"check_phase_accounting: {p}", file=sys.stderr)
    if problems:
        print(f"check_phase_accounting: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print(f"check_phase_accounting: ok ({len(phases)} gap phases, "
          f"{len(registry)} registered spans, {len(used)} literal call "
          f"sites)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
