#!/usr/bin/env python3
"""Phase-accounting lint: the attribution vocabulary cannot drift.

Three AST-level checks (no package import — the lint must run without jax,
the check_crashpoints idiom):

1. every Tracer span name the gap-ledger PHASES table maps onto
   (karpenter_tpu/profiling/gapledger.py) exists in the Tracer phase
   registry (karpenter_tpu/tracing/__init__.py PHASE_REGISTRY);
2. every LITERAL span name passed to start_span()/record_span() anywhere
   in karpenter_tpu/ is registered (or matches a DYNAMIC_PHASE_PREFIXES
   family) — a new span recorded without registering it fails presubmit,
   so the gap ledger can never silently lose a phase;
3. every registry entry is actually recorded somewhere — dead registry
   entries would make the docs lie about what the tracer emits.

Plus the critical-path plane's lane/wait lockstep (same drift argument,
one vocabulary over in karpenter_tpu/profiling/critical.py):

4. every literal `lane=` at a note()/note_wait() call site is in LANES,
   every PHASE_LANES key is a gap-ledger phase and every value a lane,
   and no lane is dead (unreachable from PHASE_LANES defaults or a
   literal call-site override — a dead lane would render as an empty
   Perfetto track forever);
5. every literal wait kind passed to note_wait() is in WAITS, and every
   WAITS entry is producible — by a note_wait() literal somewhere, or by
   the gap classifier in critical.py itself.

f-string span names (e.g. the client's solver.rpc.<Method>) are checked
by their static prefix against DYNAMIC_PHASE_PREFIXES; non-literal names
(variables) are skipped — they are the Tracer API's own plumbing.

Run via `make phaseacct` (part of `make presubmit`).
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "karpenter_tpu"
GAPLEDGER = PACKAGE / "profiling" / "gapledger.py"
CRITICAL = PACKAGE / "profiling" / "critical.py"
TRACING = PACKAGE / "tracing" / "__init__.py"

SPAN_CALLS = ("start_span", "record_span")
NOTE_CALLS = ("note", "note_wait")


def _module_assign(path: pathlib.Path, name: str):
    """The AST value node of a module-level `name = ...` assignment."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return node.value
    raise SystemExit(f"check_phase_accounting: {name} not found in {path}")


def load_phases() -> "dict[str, tuple[str, ...]]":
    value = _module_assign(GAPLEDGER, "PHASES")
    phases = ast.literal_eval(value)
    return {phase: tuple(spans) for phase, spans in phases}


def load_registry() -> "tuple[tuple[str, ...], tuple[str, ...]]":
    registry = ast.literal_eval(_module_assign(TRACING, "PHASE_REGISTRY"))
    prefixes = ast.literal_eval(
        _module_assign(TRACING, "DYNAMIC_PHASE_PREFIXES"))
    return tuple(registry), tuple(prefixes)


def load_critical() -> "tuple[tuple, tuple, dict]":
    lanes = tuple(ast.literal_eval(_module_assign(CRITICAL, "LANES")))
    waits = tuple(ast.literal_eval(_module_assign(CRITICAL, "WAITS")))
    phase_lanes = dict(ast.literal_eval(
        _module_assign(CRITICAL, "PHASE_LANES")))
    return lanes, waits, phase_lanes


def _note_calls(tree: ast.AST):
    """Yield (node, method-name) of every .note()/.note_wait() call."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else None
        if name in NOTE_CALLS:
            yield node, name


def _span_name_args(tree: ast.AST):
    """Yield (node, first-positional-arg) of every start_span/record_span
    call in the tree."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name in SPAN_CALLS:
            yield node, node.args[0]


def _literal_strings(arg: ast.expr):
    """Constant-string values an expression can evaluate to: plain
    constants, and both arms of conditional expressions (core.py picks
    dispatch.execute vs dispatch.compile with an IfExp)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg.value
    elif isinstance(arg, ast.IfExp):
        yield from _literal_strings(arg.body)
        yield from _literal_strings(arg.orelse)


def _static_prefix(joined: ast.JoinedStr) -> str:
    """Leading constant text of an f-string ('solver.rpc.' of
    f'solver.rpc.{name}')."""
    out = []
    for part in joined.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            out.append(part.value)
        else:
            break
    return "".join(out)


def main() -> int:
    phases = load_phases()
    registry, prefixes = load_registry()
    lanes, waits, phase_lanes = load_critical()
    problems: "list[str]" = []

    # 4a) the PHASE_LANES defaults stay in lockstep with both vocabularies
    for phase, lane in sorted(phase_lanes.items()):
        if phase not in phases:
            problems.append(
                f"{CRITICAL.relative_to(ROOT)}: PHASE_LANES key {phase!r} "
                f"is not a gap-ledger phase")
        if lane not in lanes:
            problems.append(
                f"{CRITICAL.relative_to(ROOT)}: PHASE_LANES maps {phase!r} "
                f"to unknown lane {lane!r}")

    # 1) gap-ledger table maps onto registered spans only
    for phase, spans in phases.items():
        for span in spans:
            if span not in registry:
                problems.append(
                    f"{GAPLEDGER.relative_to(ROOT)}: gap phase {phase!r} "
                    f"maps to span {span!r} which is not in "
                    f"tracing.PHASE_REGISTRY")

    # 2) every literal call site is registered; 3) registry has no dead rows
    used: "set[str]" = set()
    lane_literals: "set[str]" = set()
    wait_literals: "set[str]" = set()
    for path in sorted(PACKAGE.rglob("*.py")):
        if path == TRACING:
            continue  # the Tracer's own API plumbing passes names through
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            problems.append(f"{path.relative_to(ROOT)}: unparseable: {e}")
            continue
        rel = path.relative_to(ROOT)
        # 4b/5a) literal lane overrides and wait kinds at note call sites
        # stay inside the critical.py vocabularies (gapledger.py is the
        # API's own plumbing — its defs, not calls, carry the kwargs)
        if path != GAPLEDGER:
            for node, name in _note_calls(tree):
                for kw in node.keywords:
                    if kw.arg != "lane":
                        continue
                    for value in _literal_strings(kw.value):
                        lane_literals.add(value)
                        if value not in lanes:
                            problems.append(
                                f"{rel}:{node.lineno}: lane {value!r} is "
                                f"not in critical.LANES")
                if name == "note_wait" and node.args:
                    for value in _literal_strings(node.args[0]):
                        wait_literals.add(value)
                        if value not in waits:
                            problems.append(
                                f"{rel}:{node.lineno}: wait kind "
                                f"{value!r} is not in critical.WAITS")
        for node, arg in _span_name_args(tree):
            names = list(_literal_strings(arg))
            if names:
                for value in names:
                    used.add(value)
                    if value not in registry and not any(
                            value.startswith(p) for p in prefixes):
                        problems.append(
                            f"{rel}:{node.lineno}: span {value!r} is not "
                            f"in tracing.PHASE_REGISTRY (register it, or "
                            f"the gap ledger can never account for it)")
            elif isinstance(arg, ast.JoinedStr):
                prefix = _static_prefix(arg)
                if not any(prefix.startswith(p) for p in prefixes):
                    problems.append(
                        f"{rel}:{node.lineno}: dynamic span name with "
                        f"static prefix {prefix!r} matches no "
                        f"DYNAMIC_PHASE_PREFIXES entry")
    for span in registry:
        if span not in used and not any(span.startswith(p)
                                        for p in prefixes):
            problems.append(
                f"{TRACING.relative_to(ROOT)}: PHASE_REGISTRY entry "
                f"{span!r} is recorded nowhere in karpenter_tpu/ "
                f"(dead registry rows make the docs lie)")

    # 4c) no dead lanes: every lane must be reachable, via a PHASE_LANES
    # default or a literal call-site override
    reachable = set(phase_lanes.values()) | lane_literals
    for lane in lanes:
        if lane not in reachable:
            problems.append(
                f"{CRITICAL.relative_to(ROOT)}: lane {lane!r} is neither a "
                f"PHASE_LANES default nor a literal lane= at any note call "
                f"site (a dead lane renders as an empty track forever)")

    # 5b) no dead waits: every wait kind must be producible — a literal
    # note_wait() somewhere, or attributed by the gap classifier in
    # critical.py (its out[...] subscripts carry the kind literals)
    crit_tree = ast.parse(CRITICAL.read_text(), filename=str(CRITICAL))
    classifier_kinds: "set[str]" = set()
    for fn in ast.walk(crit_tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in ast.walk(fn):
            if (isinstance(n, ast.Subscript)
                    and isinstance(n.slice, ast.Constant)
                    and isinstance(n.slice.value, str)):
                classifier_kinds.add(n.slice.value)
    for kind in waits:
        if kind not in wait_literals and kind not in classifier_kinds:
            problems.append(
                f"{CRITICAL.relative_to(ROOT)}: wait kind {kind!r} is "
                f"produced nowhere (no note_wait literal, not attributed "
                f"by the classifier) — dead vocabulary rows make the docs "
                f"lie")

    for p in problems:
        print(f"check_phase_accounting: {p}", file=sys.stderr)
    if problems:
        print(f"check_phase_accounting: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print(f"check_phase_accounting: ok ({len(phases)} gap phases, "
          f"{len(registry)} registered spans, {len(used)} literal call "
          f"sites, {len(lanes)} lanes, {len(waits)} wait kinds)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
