#!/usr/bin/env python
"""Presubmit lint: every BENCH_*.json headline claim must carry provenance.

The headline metric (scheduling_cycle_p50_ms_10k_pods_600_types) is only a
chip claim when it was measured on the chip. A CPU-fallback run is a fine
*recorded* artifact, but it must say so: `degraded: true` plus a NAMED
non-null fallback metric the round's claim actually leans on (the routed
native p50, the steady-state wave number, a prior on-chip capture...).
Without this gate a tunnel outage silently turns "129 ms on-chip" rounds
into "18 ms" rounds and nobody notices the unit changed.

Rules per artifact (BENCH_*.json at the repo root; the driver wraps the
bench's JSON line in {"parsed": ...}):

  1. no headline value        -> skip (crashed run; claims nothing)
  2. backend == "tpu" AND not degraded -> OK (a real on-chip number)
  3. degraded (or non-TPU backend)     -> must carry `degraded: true` AND
     at least one non-null fallback metric from FALLBACK_METRICS (or a
     headline_provenance block naming one)
  4. anything else            -> FAIL

Artifacts written before this lint existed are grandfathered BY NAME with
a reason — the list is append-only and new artifacts can never join it.
"""

from __future__ import annotations

import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Top-level fields that count as fallback evidence for a degraded headline.
FALLBACK_METRICS = (
    "wave_steady_per_solve_ms",
    "callback_headline_ms",
    "native_routed_ms",
    "routed_native_p50_ms",
    "onchip_ms",
)

# Append-only waivers for artifacts recorded before the provenance contract
# existed. A NEW artifact can never be added here to dodge the lint — the
# reviewer diff on this file is the enforcement.
GRANDFATHERED = {
    "BENCH_r02.json": "recorded before fallback metrics existed; degraded "
                      "flag present but no fallback fields in the schema",
}


def _record(path: str) -> dict:
    with open(path) as f:
        d = json.load(f)
    return d.get("parsed") or d


def _backend(rec: dict) -> "str | None":
    return rec.get("backend") or (rec.get("detail") or {}).get("backend")


def check(path: str) -> "str | None":
    """Returns a failure message, or None when the artifact passes."""
    name = os.path.basename(path)
    try:
        rec = _record(path)
    except Exception as e:
        return f"{name}: unreadable ({e})"
    if rec.get("value") is None:
        return None  # no headline claim to police
    degraded = bool(rec.get("degraded"))
    backend = _backend(rec)
    if backend == "tpu" and not degraded:
        return None  # genuine on-chip headline
    if name in GRANDFATHERED:
        return None
    if not degraded:
        return (f"{name}: headline {rec.get('value')} ms measured on "
                f"backend={backend!r} but carries no degraded flag — a "
                f"non-TPU number must be marked degraded: true")
    prov = rec.get("headline_provenance") or {}
    named = prov.get("fallback_metric")
    if named and rec.get(named) is not None:
        return None
    for m in FALLBACK_METRICS:
        if rec.get(m) is not None:
            return None
    return (f"{name}: degraded headline names no usable fallback metric "
            f"(need a non-null one of {', '.join(FALLBACK_METRICS)})")


def main() -> int:
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_*.json")))
    failures = [msg for p in paths if (msg := check(p))]
    for msg in failures:
        print(f"FAIL {msg}")
    ok = len(paths) - len(failures)
    print(f"headline provenance: {ok}/{len(paths)} artifacts pass"
          + (f", {len(failures)} FAIL" if failures else ""))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
