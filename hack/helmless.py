#!/usr/bin/env python
"""Minimal `helm template` renderer for the in-repo charts.

The deployment image has no helm binary, so this implements the exact
template subset charts/ uses — enough that `python hack/helmless.py render
charts/karpenter-tpu` reproduces `helm template` output for these charts,
and tests/test_helm_chart.py can assert the default-values render is
byte-equivalent to the static manifests in deploy/ (VERDICT r3 ask #7;
reference analogue: charts/karpenter/values.yaml:134-142 + 16 templates).

Supported template syntax (the honest subset, no more):
  {{ .Values.a.b }} / {{ .Chart.Name }} / {{ .Chart.Version }}
  {{ .Release.Name }} / {{ .Release.Namespace }}
  {{ include "name" . }}            — named templates from _helpers.tpl
  pipelines: | quote | default X | toYaml | nindent N | indent N | int
  {{ if PIPELINE }} / {{ else }} / {{ end }}   (truthiness: Go-template)
  {{- ... -}} whitespace chomping, exactly like text/template:
     "{{-" trims immediately-preceding whitespace incl. the last newline,
     "-}}" trims following whitespace incl. the next newline.

Values precedence: chart values.yaml deep-merged under --set / --values
overrides (helm semantics).
"""

from __future__ import annotations

import argparse
import os
import re
import sys

import yaml

TOKEN = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.S)


def _chomp_split(src: str):
    """Split template source into literal/action parts applying {{- / -}}
    whitespace chomping like text/template."""
    parts = []  # ("lit", text) | ("act", expr)
    pos = 0
    for m in TOKEN.finditer(src):
        lit = src[pos:m.start()]
        if m.group(0).startswith("{{-"):
            # text/template trims ALL trailing whitespace incl. newlines
            lit = re.sub(r"\s+$", "", lit)
        parts.append(("lit", lit))
        parts.append(("act", m.group(1), m.group(0).endswith("-}}")))
        pos = m.end()
    parts.append(("lit", src[pos:]))
    # apply -}} forward chomp: drop leading whitespace of the following literal
    out = []
    chomp_next = False
    for p in parts:
        if p[0] == "lit":
            text = p[1]
            if chomp_next:
                text = re.sub(r"^\s+", "", text)
                chomp_next = False
            out.append(("lit", text))
        else:
            out.append(("act", p[1]))
            chomp_next = p[2]
    return out


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _to_yaml(value, _indent=0) -> str:
    return yaml.safe_dump(value, default_flow_style=False,
                          sort_keys=False).rstrip("\n")


def _truthy(v) -> bool:
    return not (v is None or v is False or v == "" or v == 0 or v == {} or v == [])


class Renderer:
    def __init__(self, chart_dir: str, overrides: "dict | None" = None,
                 release_name: str = "karpenter-tpu",
                 namespace: "str | None" = None):
        self.chart_dir = chart_dir
        with open(os.path.join(chart_dir, "Chart.yaml")) as f:
            self.chart = yaml.safe_load(f)
        vals_path = os.path.join(chart_dir, "values.yaml")
        vals = {}
        if os.path.exists(vals_path):
            with open(vals_path) as f:
                vals = yaml.safe_load(f) or {}
        self.values = _deep_merge(vals, overrides or {})
        self.release = {"Name": release_name,
                        "Namespace": namespace or release_name}
        self.helpers: "dict[str, str]" = {}
        tpl = os.path.join(chart_dir, "templates", "_helpers.tpl")
        if os.path.exists(tpl):
            with open(tpl) as f:
                self._load_helpers(f.read())

    def _load_helpers(self, src: str):
        for m in re.finditer(
                r'\{\{-?\s*define\s+"([^"]+)"\s*-?\}\}(.*?)\{\{-?\s*end\s*-?\}\}',
                src, re.S):
            body = m.group(2)
            # helm convention: define bodies start/end with a chomped newline
            self.helpers[m.group(1)] = body.strip("\n")

    # ---- expression evaluation ------------------------------------------------

    def _lookup(self, path: str):
        if path == ".":
            return None
        node: object
        segs = path.lstrip(".").split(".")
        if segs[0] == "Values":
            node = self.values
        elif segs[0] == "Chart":
            node = {"Name": self.chart.get("name"),
                    "Version": self.chart.get("version"),
                    "AppVersion": self.chart.get("appVersion")}
        elif segs[0] == "Release":
            node = self.release
        else:
            raise KeyError(f"unknown root .{segs[0]}")
        for s in segs[1:]:
            if not isinstance(node, dict) or s not in node:
                return None
            node = node[s]
        return node

    def _atom(self, tok: str):
        tok = tok.strip()
        if tok.startswith('"') and tok.endswith('"'):
            return tok[1:-1]
        if re.fullmatch(r"-?\d+", tok):
            return int(tok)
        if tok in ("true", "false"):
            return tok == "true"
        if tok.startswith("."):
            return self._lookup(tok)
        m = re.fullmatch(r'include\s+"([^"]+)"\s+\.', tok)
        if m:
            return self._render_str(self.helpers[m.group(1)])
        raise ValueError(f"unsupported atom: {tok!r}")

    def _pipeline(self, expr: str):
        stages = [s.strip() for s in expr.split("|")]
        # leading function-application form: {{ toYaml .Values.x | ... }}
        head = stages[0].split(None, 1)
        if len(head) == 2 and head[0] in ("eq", "ne"):
            toks = re.findall(r'"[^"]*"|\S+', head[1])
            a, b = self._atom(toks[0]), self._atom(toks[1])
            return (a == b) if head[0] == "eq" else (a != b)
        if len(head) == 2 and head[0] in ("toYaml", "quote", "int"):
            val = self._atom(head[1])
            stages[0] = head[0]  # re-run the function as a stage
            stages.insert(0, None)  # placeholder consumed below
        else:
            val = self._atom(stages[0])
        for st in stages[1:]:
            parts = st.split(None, 1)
            fn, arg = parts[0], (parts[1] if len(parts) > 1 else None)
            if fn == "quote":
                if val is None:
                    s = ""
                elif val is True or val is False:  # Go-template booleans
                    s = "true" if val else "false"
                else:
                    s = str(val)
                val = '"%s"' % s
            elif fn == "default":
                dv = self._atom(arg)
                val = dv if not _truthy(val) else val
            elif fn == "toYaml":
                val = _to_yaml(val)
            elif fn == "int":
                val = int(val)
            elif fn in ("nindent", "indent"):
                n = int(arg)
                pad = " " * n
                text = val if isinstance(val, str) else _to_yaml(val)
                body = "\n".join(pad + line if line else line
                                 for line in text.split("\n"))
                val = ("\n" + body) if fn == "nindent" else body
            else:
                raise ValueError(f"unsupported function: {fn}")
        return val

    # ---- rendering ------------------------------------------------------------

    def _render_str(self, src: str) -> str:
        parts = _chomp_split(src)
        out: "list[str]" = []
        # conditional stack: each entry is (taking_branch, seen_true)
        stack: "list[list[bool]]" = []

        def emitting() -> bool:
            return all(s[0] for s in stack)

        for p in parts:
            if p[0] == "lit":
                if emitting():
                    out.append(p[1])
                continue
            expr = p[1]
            if expr.startswith("if "):
                cond = _truthy(self._pipeline(expr[3:])) if emitting() else False
                stack.append([cond, cond])
            elif expr == "else":
                if not stack:
                    raise ValueError("else without if")
                top = stack[-1]
                top[0] = (not top[1]) and all(s[0] for s in stack[:-1])
                top[1] = top[1] or top[0]
            elif expr == "end":
                if not stack:
                    raise ValueError("end without if")
                stack.pop()
            elif expr.startswith("define") or expr.startswith("/*"):
                continue  # helper defs / comments render to nothing
            else:
                if emitting():
                    v = self._pipeline(expr)
                    out.append("" if v is None else
                               v if isinstance(v, str) else
                               ("true" if v is True else
                                "false" if v is False else str(v)))
        return "".join(out)

    def render(self) -> "dict[str, str]":
        """template filename -> rendered content (empty renders dropped,
        like helm)."""
        tdir = os.path.join(self.chart_dir, "templates")
        out = {}
        for name in sorted(os.listdir(tdir)):
            if name.startswith("_") or name.startswith("."):
                continue
            with open(os.path.join(tdir, name)) as f:
                body = self._render_str(f.read())
            if body.strip():
                out[name] = body
        return out


def _parse_set(exprs: "list[str]") -> dict:
    overrides: dict = {}
    for e in exprs or []:
        key, _, raw = e.partition("=")
        try:
            val = yaml.safe_load(raw)
        except yaml.YAMLError:
            val = raw
        node = overrides
        segs = key.split(".")
        for s in segs[:-1]:
            node = node.setdefault(s, {})
        node[segs[-1]] = val
    return overrides


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("render", help="render a chart to stdout")
    r.add_argument("chart")
    r.add_argument("--set", action="append", default=[],
                   help="override, e.g. --set controller.replicas=3")
    r.add_argument("--namespace")
    r.add_argument("--output-dir")
    args = ap.parse_args()

    rend = Renderer(args.chart, _parse_set(args.set),
                    namespace=args.namespace)
    docs = rend.render()
    if args.output_dir:
        os.makedirs(args.output_dir, exist_ok=True)
        for name, body in docs.items():
            with open(os.path.join(args.output_dir, name), "w") as f:
                f.write(body)
        print(f"rendered {len(docs)} manifests -> {args.output_dir}")
    else:
        for name, body in docs.items():
            print(f"---\n# Source: {os.path.basename(rend.chart_dir)}/templates/{name}")
            sys.stdout.write(body if body.endswith("\n") else body + "\n")


if __name__ == "__main__":
    main()
