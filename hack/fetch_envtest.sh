#!/usr/bin/env bash
# Fetch the envtest control-plane binaries (kube-apiserver, etcd, kubectl)
# into hack/bin/envtest/ so tests/test_foreign_apiserver.py can run the
# wire-compat tier against a kube-apiserver this repo did NOT write
# (VERDICT r3 ask #5; reference analogue: the envtest tier of
# pkg/cloudprovider/suite_test.go:74-101).
#
# Zero-egress environments skip cleanly: the test is gated on the binaries
# being present (or KUBEBUILDER_ASSETS pointing at them).
set -euo pipefail

K8S_VERSION="${K8S_VERSION:-1.28.3}"
GOOS="$(uname | tr '[:upper:]' '[:lower:]')"
GOARCH="$(uname -m | sed -e s/x86_64/amd64/ -e s/aarch64/arm64/)"
DEST="$(dirname "$0")/bin/envtest"

if [ -x "$DEST/kube-apiserver" ] && [ -x "$DEST/etcd" ]; then
    echo "envtest binaries already present in $DEST"
    exit 0
fi

URL="https://go.kubebuilder.io/test-tools/${K8S_VERSION}/${GOOS}/${GOARCH}"
echo "fetching envtest ${K8S_VERSION} for ${GOOS}/${GOARCH}..."
mkdir -p "$DEST"
if ! curl -fsSL --max-time 300 "$URL" -o /tmp/envtest.tgz; then
    echo "download failed (offline?); the foreign-apiserver tier will skip" >&2
    exit 1
fi
tar -xzf /tmp/envtest.tgz -C "$DEST" --strip-components=2
rm -f /tmp/envtest.tgz
chmod +x "$DEST"/*
echo "installed: $(ls "$DEST")"
