#!/usr/bin/env python
"""Persistent opportunistic TPU capture (VERDICT r2 ask #1).

The deployment tunnel to the real chip is flaky: one-shot probing at a fixed
instant (bench.py rounds 1-2) missed it two rounds running. This tool makes
capture a *process*, not an event:

  --once   probe; if the tunnel answers, run the capture suite and record it.
  --loop   run forever: probe on a backoff schedule across the whole round,
           capture whenever a probe succeeds, re-capture every
           --recapture-s to keep the freshest number, survive wedges (the
           capture itself runs in a subprocess with a hard timeout).

Each successful capture writes benchmarks/results/tpu_<utc>.json:

  {"captured_at": ..., "headline": {p50_ms @ 10k pods x ~600 types, ...},
   "sweep": [{"n_pods": N, "tpu_p50_ms": ..., "native_p50_ms": ...}, ...],
   "crossover_pods": N}   # smallest size where the device beats the C++ host
                          # scan — the routing threshold for
                          # controllers/provisioning.py size-based routing

bench.py reports the most recent of these files alongside its live number,
so the driver's BENCH_r{N}.json always carries the best chip evidence the
round produced even if the tunnel is down at collection time.

Reference analogue: the scale ladder of
/root/reference/pkg/controllers/interruption/interruption_benchmark_test.go:61-76
(recorded numbers, not one-off prints).
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS_DIR = os.path.join(REPO, "benchmarks", "results")
SWEEP_SIZES = (100, 300, 1000, 3000, 10000)


def _link_sentinel(jax, jnp, reps: int = 5) -> dict:
    """Trivial dispatch+block timings — the tunnel link-state probe.
    Healthy streaming mode syncs in <1ms; after the session's first
    device->host read the relay drops to ~65-85ms per sync (measured,
    docs/designs/solver-boundary.md). Captures carry both states so the
    recorded numbers are attributable."""
    import statistics as st

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    f(x).block_until_ready()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append((time.perf_counter() - t0) * 1000)
    return {"p50_ms": round(st.median(ts), 3), "min_ms": round(min(ts), 3)}


def _io_callback_probe(jax, jnp, reps: int = 5) -> dict:
    """Escape-hatch experiment: does an io_callback-based readback (results
    pushed host-ward from inside the jitted computation) avoid the
    streaming->degraded transition that jax.device_get triggers? Returns
    timing + a sync sentinel taken AFTER the probe so the caller can tell
    whether the link survived (sync_after.p50_ms sub-ms) or the probe
    consumed the transition itself. effects_barrier is inside the timed
    span: block_until_ready alone does not wait for host callbacks, and a
    sub-ms number that excluded delivery would read as 'streaming readback
    is free' when nothing reached the host."""
    import statistics as st

    import numpy as np

    try:
        from jax.experimental import io_callback

        inbox = []

        def _sink(x):
            inbox.append(np.asarray(x))
            return np.int32(0)

        @jax.jit
        def _f(x):
            y = x + 1
            io_callback(_sink, jax.ShapeDtypeStruct((), jnp.int32),
                        y.sum(), ordered=True)
            return y

        x = jnp.arange(1024, dtype=jnp.int32)
        t0 = time.perf_counter()
        _f(x).block_until_ready()
        jax.effects_barrier()
        first_ms = (time.perf_counter() - t0) * 1000
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _f(x).block_until_ready()
            jax.effects_barrier()
            ts.append((time.perf_counter() - t0) * 1000)
        return {"first_ms": round(first_ms, 3),
                "p50_ms": round(st.median(ts), 3),
                "values_received": len(inbox),
                "sync_after": _link_sentinel(jax, jnp)}
    except Exception as e:  # experimental API: record, never fail a capture
        return {"error": str(e)[:200]}


def io_probe_gate(jax, jnp, reps: int = 5) -> "tuple[dict, bool, bool]":
    """Run the io_callback probe and judge it. Returns
    (probe, still_streaming, transport_ok):

    - still_streaming: the link's sync sentinel stayed sub-ms (or the
      probe never ran device work) — the attribution question.
    - transport_ok: additionally EVERY callback value actually reached
      the host (warmup + reps deliveries) and nothing errored — the
      "safe to route production reads through callbacks" question. A
      sub-ms sentinel with zero deliveries is exactly the false positive
      the delivery count guards against."""
    probe = _io_callback_probe(jax, jnp, reps=reps)
    still_streaming, transport_ok = judge_io_probe(probe, reps)
    return probe, still_streaming, transport_ok


def judge_io_probe(probe: dict, reps: int) -> "tuple[bool, bool]":
    """Pure judgment half of io_probe_gate (unit-tested separately)."""
    errored = "error" in probe
    still_streaming = errored or (
        (probe.get("sync_after") or {}).get("p50_ms", 999.0) < 5.0)
    transport_ok = (not errored and still_streaming
                    and probe.get("values_received") == reps + 1)
    return still_streaming, transport_ok


def _consolidation_cluster(catalog, n_nodes: int = 500):
    """The BASELINE configs[3] shape: n under-utilized m5.2xlarge nodes,
    one small pod each (shared by the streaming- and degraded-regime
    consolidation sections so their numbers are comparable)."""
    from karpenter_tpu.apis import wellknown as wkk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.cluster import ClusterState, StateNode
    from karpenter_tpu.models.pod import make_pod

    cluster = ClusterState()
    big = catalog.by_name["m5.2xlarge"]
    for i in range(n_nodes):
        cluster.add_node(StateNode(
            name=f"n-{i}",
            labels={**big.labels_dict(), wkk.LABEL_ZONE: "zone-1a",
                    wkk.LABEL_CAPACITY_TYPE: "on-demand",
                    wkk.LABEL_PROVISIONER: "default"},
            allocatable=big.allocatable_vector(),
            instance_type=big.name, zone="zone-1a",
            capacity_type="on-demand", price=big.offerings[0].price,
            provisioner_name="default",
            pods=[make_pod(f"p-{i}", cpu="500m", memory="1Gi",
                           node_name=f"n-{i}")]))
    cprov = Provisioner(name="default", consolidation_enabled=True)
    cprov.set_defaults()
    return cluster, cprov


def _capture_payload(reps_headline: int, reps_sweep: int,
                     partial_path: "str | None" = None) -> dict:
    """Run inside the pinned-to-axon subprocess: headline + crossover sweep.

    When partial_path is given, every completed section is checkpointed
    there (atomic rename) — a relay wedge mid-capture then still banks the
    sections that finished instead of losing the whole attempt (the
    40-minute all-or-nothing failure mode this replaces)."""
    sys.path.insert(0, REPO)
    from karpenter_tpu.utils.jaxenv import pin

    jax, _ = pin("axon")
    import jax.numpy as jnp

    rec: dict = {}

    def bank(**sections) -> None:
        rec.update(sections)
        if partial_path:
            tmp = partial_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, partial_path)

    backend = jax.devices()[0].platform
    bank(backend=backend)
    link_fresh = _link_sentinel(jax, jnp)  # BEFORE any d2h read
    bank(link_state={"fresh": link_fresh})

    from benchmarks.workloads import mixed_workload
    from karpenter_tpu.apis import wellknown as wk
    from karpenter_tpu.apis.provisioner import Provisioner
    from karpenter_tpu.models.requirements import OP_IN, Requirements
    from karpenter_tpu.providers.instancetypes import generate_fleet_catalog
    from karpenter_tpu.solver.core import NativeSolver, TPUSolver

    catalog = generate_fleet_catalog()
    prov = Provisioner(name="default", requirements=Requirements.of(
        (wk.LABEL_CAPACITY_TYPE, OP_IN, ["spot", "on-demand"]),
        (wk.LABEL_ARCH, OP_IN, ["amd64", "arm64"]),
    ))
    prov.set_defaults()
    tpu = TPUSolver(catalog, [prov])
    native = NativeSolver(catalog, [prov])

    # ---- streaming-mode section: NO device->host read happens until the
    # wave fetch below, so these numbers are the healthy-link truth --------
    import statistics as st

    from karpenter_tpu.models.encode import encode_problem
    from karpenter_tpu.solver.core import dispatch_pack

    # exec-only SWEEP across all sizes while the link still streams: the
    # device time a locally-attached (non-tunneled) TPU deployment would
    # pay per size. Compared against the native per-size numbers measured
    # later (host-only, link-state independent) it yields
    # exec_crossover_pods — the kernel crossover with transport factored
    # out, vs the wall-clock crossover_pods this deployment's relay nulls.
    exec_sweep = []
    workloads = {n: mixed_workload(n) for n in SWEEP_SIZES}
    for n in SWEEP_SIZES:
        pods_n = workloads[n]
        enc = encode_problem(catalog, [prov], pods_n, (), None, None,
                             grid=tpu.grid(), group_cache=tpu._group_cache)
        flat, _dims = dispatch_pack(enc, tpu._dev_alloc_t, tpu._dev_tiebreak)
        flat.block_until_ready()  # compile outside the clock
        ts = []
        for _ in range(max(5, reps_sweep)):
            t0 = time.perf_counter()
            f2, _ = dispatch_pack(enc, tpu._dev_alloc_t, tpu._dev_tiebreak)
            f2.block_until_ready()
            ts.append((time.perf_counter() - t0) * 1000)
        exec_sweep.append({"n_pods": n, "p50_ms": round(st.median(ts), 3),
                           "min_ms": round(min(ts), 3)})
        bank(exec_sweep=exec_sweep)
    exec_only = {**next(r for r in exec_sweep if r["n_pods"] == 10_000),
                 "note": "host encode excluded; put+exec+block, no d2h read"}
    pods10k = workloads[10_000]
    link_after_exec = _link_sentinel(jax, jnp)
    bank(exec_only_10k=exec_only,
         link_state={"fresh": link_fresh, "after_exec_only": link_after_exec})

    # escape-hatch probe, run LAST in the streaming section: if its
    # sync_after sentinel stays sub-ms, io_callback readback avoids the
    # first-read degradation and the wall-clock crossover vs the native
    # scan flips. If instead the probe itself consumed the transition,
    # the wave/link_state notes below are made conditional so the
    # recorded attribution stays truthful either way.
    io_escape, streaming_after_io, io_ok = io_probe_gate(
        jax, jnp, reps=max(5, reps_sweep))
    transition_in = "wave"  # who consumed the streaming->degraded flip
    if "error" not in io_escape and not streaming_after_io:
        transition_in = "io_callback_probe"
    bank(io_callback_escape=io_escape)

    # If the escape works, MEASURE it at the headline shape immediately
    # (still streaming): full solves routed through the callback readback
    # (KARPENTER_TPU_READBACK=callback path, solver/core.py) — the
    # crossover-flipping number if sync_after stays sub-ms afterwards.
    callback_headline = None
    if io_ok:  # transport verified: streaming survived AND all delivered
        import karpenter_tpu.solver.core as score

        prev_rb = score._READBACK
        score._READBACK = "callback"
        try:
            tpu.solve(pods10k)  # compile the callback-readback program
            ts = []
            for _ in range(max(5, reps_sweep)):
                t0 = time.perf_counter()
                res_cb = tpu.solve(pods10k)
                ts.append((time.perf_counter() - t0) * 1000)
            assert res_cb.unschedulable_count() == 0
            callback_headline = {
                "n_pods": 10_000, "p50_ms": round(st.median(ts), 3),
                "min_ms": round(min(ts), 3),
                "sync_after": _link_sentinel(jax, jnp)}
        except Exception as e:
            callback_headline = {"error": str(e)[:200]}
        finally:
            score._READBACK = prev_rb
        if "error" not in callback_headline:
            still = ((callback_headline.get("sync_after") or
                      {}).get("p50_ms", 999.0) < 5.0)
            if not still:  # this block only runs while still streaming
                transition_in = "callback_headline"
            streaming_after_io = still
        bank(callback_headline=callback_headline)

    # If the callback transport held (link still streaming), measure the
    # 500-candidate consolidation sweep THROUGH it before any literal read:
    # device consolidation in the streaming regime is the routing-table
    # entry that decides where the device beats the 88-180ms host path
    # (VERDICT r4 ask #2). The degraded-regime number is still taken later.
    if io_ok and streaming_after_io:
        import karpenter_tpu.ops.consolidate as cmod
        import karpenter_tpu.solver.core as score

        prev_rb = score._READBACK
        score._READBACK = "callback"
        try:
            cluster_s, cprov_s = _consolidation_cluster(catalog, 500)
            cmod.run_consolidation(cluster_s, catalog, [cprov_s])  # warm
            cts, cphases = [], []
            for _ in range(max(3, reps_sweep)):
                t0 = time.perf_counter()
                cact = cmod.run_consolidation(cluster_s, catalog, [cprov_s])
                cts.append((time.perf_counter() - t0) * 1000)
                if cmod.last_timings:  # per-rep, like the degraded block
                    cphases.append(cmod.last_timings)
            bank(consolidation_500_streaming={
                "candidates": 500, "p50_ms": round(st.median(cts), 3),
                "action": cact.kind if cact else None,
                "phase_split": cphases,
                "sync_after": _link_sentinel(jax, jnp)})
        except Exception as e:
            bank(consolidation_500_streaming={
                "error": str(e)[:200],
                # the failed attempt may itself have consumed the
                # streaming->degraded flip — record the sentinel so the
                # attribution below can't silently lie
                "sync_after": _link_sentinel(jax, jnp)})
        finally:
            score._READBACK = prev_rb
        # did THIS section consume the transition? (mirrors the
        # callback_headline attribution discipline above)
        cs = rec.get("consolidation_500_streaming") or {}
        still = (cs.get("sync_after") or {}).get("p50_ms", 999.0) < 5.0
        if not still:
            transition_in = "consolidation_500_streaming"
            streaming_after_io = False

    # wave: K pipelined solves, ONE concatenated read (solver.solve_many)
    K = 8
    t0 = time.perf_counter()
    wave_res = tpu.solve_many([{"pods": pods10k}] * K)
    wave_ms = (time.perf_counter() - t0) * 1000
    assert all(r.unschedulable_count() == 0 for r in wave_res)
    wave = {"k": K, "n_pods": 10_000, "total_ms": round(wave_ms, 3),
            "per_solve_ms": round(wave_ms / K, 3),
            "note": ("includes the session's first d2h read (the relay's "
                     "multi-second streaming->degraded transition, "
                     "linkprobe first_read_ms) — see wave_steady for the "
                     "amortized cost" if streaming_after_io else
                     f"link already degraded during {transition_in} — "
                     "the transition cost is not in this number")}
    link_after_read = _link_sentinel(jax, jnp)  # first d2h happened above
    bank(wave_pipelined=wave,
         link_state={"fresh": link_fresh, "after_exec_only": link_after_exec,
                     "after_first_read": link_after_read,
                     "transition_in": transition_in})

    # steady-state wave: same K solves AFTER the link already degraded —
    # what a long-lived controller session actually pays per wave
    t0 = time.perf_counter()
    wave_res2 = tpu.solve_many([{"pods": pods10k}] * K)
    wave2_ms = (time.perf_counter() - t0) * 1000
    assert all(r.unschedulable_count() == 0 for r in wave_res2)
    wave_steady = {"k": K, "n_pods": 10_000, "total_ms": round(wave2_ms, 3),
                   "per_solve_ms": round(wave2_ms / K, 3)}
    bank(wave_steady=wave_steady)

    def p50(solver, pods, reps):
        solver.solve(pods)  # warmup: compile/grid-build outside the clock
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            solver.solve(pods)
            times.append((time.perf_counter() - t0) * 1000)
        return round(statistics.median(times), 3), times

    sweep = []
    for n in SWEEP_SIZES:
        pods = workloads[n]
        t_tpu, _ = p50(tpu, pods, reps_sweep)
        t_nat, _ = p50(native, pods, reps_sweep)
        sweep.append({"n_pods": n, "tpu_p50_ms": t_tpu, "native_p50_ms": t_nat})
        bank(sweep=sweep)

    pods = workloads[10_000]
    head_p50, times = p50(tpu, pods, reps_headline)
    res = tpu.solve(pods)
    bank(headline={
        "metric": "scheduling_cycle_p50_ms_10k_pods_600_types",
        "p50_ms": head_p50, "p_min_ms": round(min(times), 3),
        "p_max_ms": round(max(times), 3), "reps": len(times),
        "n_types": len(catalog.types), "n_pods": len(pods),
        "nodes_provisioned": len(res.nodes),
        "unschedulable": res.unschedulable_count()})
    # phase attribution of the degraded-mode solve (needs the
    # KARPENTER_TPU_SOLVE_TIMING=1 env capture_once sets): which of
    # encode / dispatch(h2d+enqueue) / fetch(the one sync) / decode owns
    # the wall clock above the ~66ms sync floor
    phases = []
    for _ in range(3):
        tpu.solve(pods)
        t = getattr(tpu, "last_timings", None)
        if t:
            phases.append(t)
    rec["headline"]["phase_split"] = phases
    bank()  # checkpoint the phase attribution before the device-heavy tail

    crossover = None
    for row in sweep:  # smallest size where the device wins
        if row["tpu_p50_ms"] < row["native_p50_ms"]:
            crossover = row["n_pods"]
            break
    nat_by_n = {row["n_pods"]: row["native_p50_ms"] for row in sweep}
    exec_crossover = None
    for row in exec_sweep:  # smallest size where the KERNEL beats native
        if row["n_pods"] not in nat_by_n:  # no comparison data: not a win
            continue
        if row["p50_ms"] < nat_by_n[row["n_pods"]]:
            exec_crossover = row["n_pods"]
            break
    bank(crossover_pods=crossover, exec_crossover_pods=exec_crossover)

    # Consolidation sweep on-chip: 500 candidate lanes in ONE vmapped
    # dispatch — the shape where a single device round trip amortizes over
    # the whole search (vs per-candidate host scans). Comparable with the
    # recorded CPU number in benchmarks/results/bench_*.json (config 3).
    consolidation = None
    try:
        import karpenter_tpu.ops.consolidate as _cmod
        from karpenter_tpu.ops.consolidate import run_consolidation

        cluster, cprov = _consolidation_cluster(catalog, 500)
        run_consolidation(cluster, catalog, [cprov])  # compile + warm
        ctimes, phases = [], []
        for _ in range(max(3, reps_sweep)):
            t0 = time.perf_counter()
            action = run_consolidation(cluster, catalog, [cprov])
            ctimes.append((time.perf_counter() - t0) * 1000)
            if _cmod.last_timings:  # per-rep, like the headline phase_split
                phases.append(_cmod.last_timings)
        consolidation = {
            "candidates": 500,
            "p50_ms": round(statistics.median(ctimes), 3),
            "action": action.kind if action else None,
            # which phase owns the wall clock (encode/flatten/put/
            # dispatch/fetch/decode — needs KARPENTER_TPU_SOLVE_TIMING=1,
            # which capture_once sets); one entry per rep
            "phase_split": phases,
        }
    except Exception as e:
        consolidation = {"error": str(e)[:200]}
    bank(consolidation_500=consolidation)

    # Pair sweep on-chip (weak #6, round 3): 64 nodes whose singles can't
    # consolidate -> the multi-node grid (2016 pair lanes) runs as one
    # vmapped dispatch + one [C,3] verdict read.
    pair_sweep = None
    try:
        from karpenter_tpu.apis import wellknown as wkk
        from karpenter_tpu.models.cluster import ClusterState, StateNode
        from karpenter_tpu.models.pod import make_pod
        from karpenter_tpu.ops.consolidate import run_consolidation

        cluster = ClusterState()
        big = catalog.by_name["m5.2xlarge"]
        for i in range(64):
            cluster.add_node(StateNode(
                name=f"pn-{i}",
                labels={**big.labels_dict(), wkk.LABEL_ZONE: "zone-1a",
                        wkk.LABEL_CAPACITY_TYPE: "on-demand",
                        wkk.LABEL_PROVISIONER: "default"},
                allocatable=big.allocatable_vector(),
                instance_type=big.name, zone="zone-1a",
                capacity_type="on-demand", price=big.offerings[0].price,
                provisioner_name="default",
                pods=[make_pod(f"pp-{i}-{j}", cpu="2", memory="12Gi",
                               node_name=f"pn-{i}") for j in range(3)]))
        pprov = Provisioner(name="default", consolidation_enabled=True)
        pprov.set_defaults()
        run_consolidation(cluster, catalog, [pprov])  # compile + warm
        ptimes = []
        for _ in range(max(3, reps_sweep)):
            t0 = time.perf_counter()
            run_consolidation(cluster, catalog, [pprov])
            ptimes.append((time.perf_counter() - t0) * 1000)
        pair_sweep = {"nodes": 64,
                      "p50_ms": round(st.median(ptimes), 3)}
    except Exception as e:
        pair_sweep = {"error": str(e)[:200]}
    # every key was checkpointed as its section completed
    bank(pair_sweep_64=pair_sweep)
    return rec


def _finalize(rec: dict, partial_file: "str | None" = None) -> dict:
    """Stamp + write a capture record to RESULTS_DIR (shared by the full
    and salvaged paths so the on-disk format cannot fork)."""
    ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    rec["captured_at"] = ts
    rec["device"] = "tunneled TPU (platform=axon)"
    path = os.path.join(RESULTS_DIR, f"tpu_{ts}.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    if partial_file:
        try:
            os.unlink(partial_file)
        except FileNotFoundError:
            pass
    print(f"captured -> {path}" if not rec.get("partial") else
          f"salvaged partial capture ({len(rec)} sections) -> {path}")
    return rec


def _salvage_partial(partial: str, **how) -> "dict | None":
    """Bank the checkpointed sections of a dead capture as a partial
    record: the relay wedge (or a crash) loses the attempt, not the
    evidence. `how` records the death mode verbatim (wedged_after_s=N for
    a timeout kill, crashed_rc=N for a subprocess exit)."""
    try:
        with open(partial) as f:
            rec = json.load(f)
    except (FileNotFoundError, ValueError):
        return None
    if not rec or list(rec) == ["backend"]:
        return None  # nothing measured before the death
    rec["partial"] = True
    rec.update(how)
    return _finalize(rec, partial_file=partial)


def latest_capture() -> "dict | None":
    """Most recent recorded capture, or None (read side lives in the
    package: karpenter_tpu.utils.capture)."""
    sys.path.insert(0, REPO)
    from karpenter_tpu.utils.capture import latest_capture as _lc

    return _lc(RESULTS_DIR)


def capture_once(timeout_s: int, reps_headline: int, reps_sweep: int) -> "dict | None":
    """Probe + capture in a killable subprocess. Returns the record or None."""
    from karpenter_tpu.utils.jaxenv import probe_tpu

    ok, note = probe_tpu(attempts=1, timeout_s=90)
    if not ok:
        print(f"probe failed: {note}", file=sys.stderr)
        return None
    os.makedirs(RESULTS_DIR, exist_ok=True)
    partial = os.path.join(RESULTS_DIR, ".capture_partial.json")
    try:
        os.unlink(partial)
    except FileNotFoundError:
        pass
    code = (f"import sys, json; sys.path.insert(0, {REPO!r})\n"
            "from hack.tpu_capture import _capture_payload\n"
            f"print('CAPTURE::' + json.dumps(_capture_payload("
            f"{reps_headline}, {reps_sweep}, partial_path={partial!r})))")
    env = dict(os.environ, JAX_PLATFORMS="axon",
               KARPENTER_TPU_SOLVE_TIMING="1")  # phase-attributed headline
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"capture wedged; killed after {timeout_s}s", file=sys.stderr)
        return _salvage_partial(partial, wedged_after_s=timeout_s)
    for line in r.stdout.splitlines():
        if line.startswith("CAPTURE::"):
            rec = json.loads(line[len("CAPTURE::"):])
            return _finalize(rec, partial_file=partial)
    print(f"capture failed rc={r.returncode}: {(r.stderr or r.stdout)[-300:]}",
          file=sys.stderr)
    # a crash (not a timeout) may still have checkpointed sections
    return _salvage_partial(partial, crashed_rc=r.returncode)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--loop", action="store_true",
                    help="probe/capture forever on a backoff schedule")
    ap.add_argument("--probe-interval-s", type=int, default=300,
                    help="base wait between failed probes (doubles to "
                         "--probe-backoff-max-s)")
    ap.add_argument("--probe-backoff-max-s", type=int, default=1800,
                    help="backoff ceiling; lower it when a capture window "
                         "must not be missed (e.g. end of a round)")
    ap.add_argument("--recapture-s", type=int, default=7200,
                    help="refresh a successful capture this often")
    ap.add_argument("--capture-timeout-s", type=int, default=1800)
    ap.add_argument("--reps-headline", type=int, default=20)
    ap.add_argument("--reps-sweep", type=int, default=5)
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    if not args.loop:
        rec = capture_once(args.capture_timeout_s, args.reps_headline,
                           args.reps_sweep)
        # a salvaged partial banks evidence but is NOT a successful capture:
        # exit 1 so automation retries for a complete record
        sys.exit(0 if rec and not rec.get("partial") else 1)

    wait = args.probe_interval_s
    while True:
        rec = capture_once(args.capture_timeout_s, args.reps_headline,
                           args.reps_sweep)
        if rec and not rec.get("partial"):
            wait = args.probe_interval_s
            time.sleep(args.recapture_s)
        else:
            # failed OR partial: keep retrying on the probe backoff — a
            # partial must not suppress the retry that completes it
            time.sleep(wait)
            wait = min(wait * 2, args.probe_backoff_max_s)


if __name__ == "__main__":
    main()
