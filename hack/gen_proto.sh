#!/bin/sh
# Regenerate karpenter_tpu/solver/solver_pb2.py from solver.proto.
# (Reference analogue: hack/code generators, Makefile codegen targets.)
set -e
cd "$(dirname "$0")/.."
protoc -I karpenter_tpu/solver --python_out=karpenter_tpu/solver karpenter_tpu/solver/solver.proto
echo "generated karpenter_tpu/solver/solver_pb2.py"
