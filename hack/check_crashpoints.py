#!/usr/bin/env python3
"""Lint: the crashpoint catalog and its call sites stay in lockstep.

The crash drill (docs/designs/recovery.md) proves recovery for every
named crashpoint in `recovery/crashpoints.py:CRASHPOINTS` — so the
catalog and the code must never drift:

1. every `crashpoint("...")` call site uses a catalogued name (a typo'd
   or ad-hoc site would silently never be drilled);
2. every catalogued name has EXACTLY one call site (zero means the drill
   kills a site that no longer exists; two means the drill's "index 0"
   kill no longer pins a unique program point);
3. every file that writes write-ahead intent records
   (`<something>.journal.record(...)`) declares at least one crashpoint —
   a new journaled action without a crashpoint is recovery code the drill
   never exercises;
4. the site argument must be a string literal — the whole point is a
   statically enumerable catalog.

Detection is AST-based like hack/check_no_adhoc_retry.py. The catalog is
read by parsing crashpoints.py (no package import: the lint must run in a
bare interpreter).

Run via `make presubmit` (or directly: python hack/check_crashpoints.py).
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "karpenter_tpu"
CATALOG_FILE = PACKAGE / "recovery" / "crashpoints.py"


def load_catalog() -> "tuple[str, ...]":
    tree = ast.parse(CATALOG_FILE.read_text(), filename=str(CATALOG_FILE))
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "CRASHPOINTS":
                value = node.value
                if isinstance(value, (ast.Tuple, ast.List)):
                    return tuple(
                        el.value for el in value.elts
                        if isinstance(el, ast.Constant)
                        and isinstance(el.value, str))
    raise SystemExit(f"{CATALOG_FILE}: CRASHPOINTS tuple literal not found")


def _is_crashpoint_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name) and f.id == "crashpoint":
        return True
    return isinstance(f, ast.Attribute) and f.attr == "crashpoint"


def _is_journal_record_call(node: ast.AST) -> bool:
    """`<expr>.journal.record(...)` — a write-ahead intent write."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "record"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "journal")


def check_file(path: pathlib.Path, catalog: "tuple[str, ...]",
               sites: "dict[str, list[str]]") -> "list[str]":
    rel = path.relative_to(ROOT)
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: "list[str]" = []
    records = 0
    crashpoints_here = 0
    for node in ast.walk(tree):
        if _is_journal_record_call(node):
            records += 1
        if not _is_crashpoint_call(node):
            continue
        crashpoints_here += 1
        args = node.args
        if len(args) != 1 or not (isinstance(args[0], ast.Constant)
                                  and isinstance(args[0].value, str)):
            problems.append(
                f"{rel}:{node.lineno}: crashpoint() site must be a single "
                f"string literal (the catalog is static)")
            continue
        name = args[0].value
        if name not in catalog:
            problems.append(
                f"{rel}:{node.lineno}: crashpoint {name!r} is not in "
                f"recovery/crashpoints.py:CRASHPOINTS — the drill will "
                f"never exercise it")
        else:
            sites[name].append(f"{rel}:{node.lineno}")
    if records and not crashpoints_here:
        problems.append(
            f"{rel}: writes journal records ({records} .journal.record "
            f"call(s)) but declares no crashpoint — the crash drill never "
            f"exercises this file's recovery path")
    return problems


def main() -> int:
    catalog = load_catalog()
    sites: "dict[str, list[str]]" = {name: [] for name in catalog}
    problems: "list[str]" = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path == CATALOG_FILE:
            continue  # the defining module (and its docstring examples)
        problems.extend(check_file(path, catalog, sites))
    for name in catalog:
        hits = sites[name]
        if len(hits) == 0:
            problems.append(
                f"CRASHPOINTS entry {name!r} has no call site — the drill "
                f"kills a program point that no longer exists")
        elif len(hits) > 1:
            problems.append(
                f"CRASHPOINTS entry {name!r} has {len(hits)} call sites "
                f"({', '.join(hits)}) — the drill's kill index no longer "
                f"pins a unique program point")
    if problems:
        print("\n".join(problems), file=sys.stderr)
        print(f"\n{len(problems)} crashpoint catalog violation(s); see "
              f"hack/check_crashpoints.py docstring for the rules",
              file=sys.stderr)
        return 1
    print(f"crashpoints: clean ({len(catalog)} catalogued, all uniquely "
          f"sited)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
