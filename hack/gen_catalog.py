#!/usr/bin/env python
"""Real-data fleet-catalog codegen (VERDICT r4 ask #3).

Replaces the synthetic shape-grammar catalog with one generated from the
AWS-authoritative data artifacts that the reference toolchain itself
produces from live AWS APIs and checks in:

  prices      /root/reference/pkg/cloudprovider/zz_generated.pricing.go
              (output of hack/code/prices_gen.go:38+ — us-east-1 on-demand
              price table, stamped 2023-02-13T13:10:27Z)
  ENI limits  /root/reference/pkg/cloudprovider/zz_generated.vpclimits.go
              (output of hack/code/vpc_limits_gen.go — per-type interface /
              IPv4-per-interface / trunking / branch-interface limits,
              stamped 2023-01-26T19:39:15Z)
  anchors     /root/reference/pkg/fake/zz_generated.describe_instance_types.go
              (output of hack/code/instancetype_testdata_gen.go — ten full
              DescribeInstanceTypes fixtures) — used to VALIDATE the
              name-derived vCPU/memory against real API data; generation
              fails if any derivation disagrees with an anchor.

What is extracted is DATA — facts about AWS instance types — not code.
vCPU and memory are derived from the published instance-type naming
convention (size suffix -> vCPU; per-family MiB-per-vCPU ratios from
public spec sheets) with explicit overrides for legacy/irregular
families; every family present in the inputs must have a ratio entry or
generation fails loudly.

Pod density uses the reference's formula (instancetype.go:229-234):
    pods = ENIs * (IPv4-per-ENI - 1) + 2
Pod-ENI branch capacity comes straight from the limits table
(instancetype.go:174-181 awsPodENI), baked into capacity for
trunking-compatible types; the provider's enablePodENI gate strips or
keeps it (providers/instancetypes.py).

Output: karpenter_tpu/providers/data/fleet_catalog.json (sorted, stable —
regeneration is diff-clean when inputs are unchanged). Regenerate with
`make catalog`. The fake cloud backend serves its DescribeInstanceTypes
analogue from this same dataset, mirroring how the reference's fake EC2
serves zz_generated.describe_instance_types.go.
"""

import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference/pkg"
OUT = os.path.join(REPO, "karpenter_tpu", "providers", "data",
                   "fleet_catalog.json")

# -- naming-convention derivation tables -------------------------------------------

_SIZE_VCPU = {"nano": 1, "micro": 1, "small": 1, "medium": 1, "large": 2,
              "xlarge": 4}

# MiB of memory per vCPU, by family (public spec-sheet ratios). A family
# missing here fails generation — no silent defaults.
_MIB_PER_VCPU = {
    # compute optimized
    "c1": 896, "cc2": 1936, "c3": 1920, "c4": 1920,
    "c5": 2048, "c5a": 2048, "c5ad": 2048, "c5d": 2048, "c5n": 2688,
    "c6a": 2048, "c6g": 2048, "c6gd": 2048, "c6gn": 2048, "c6i": 2048,
    "c6id": 2048, "c6in": 2048, "c7g": 2048, "hpc6a": 4096,
    # general purpose
    "a1": 2048, "m1": 3840, "m2": 8755, "m3": 3840, "m4": 4096,
    "m5": 4096, "m5a": 4096, "m5ad": 4096, "m5d": 4096, "m5dn": 4096,
    "m5n": 4096, "m5zn": 4096, "m6a": 4096, "m6g": 4096, "m6gd": 4096,
    "m6i": 4096, "m6id": 4096, "m6idn": 4096, "m6in": 4096, "m7g": 4096,
    "mac1": 2731, "mac2": 2048,
    # burstable (per-size table below overrides vCPU+memory)
    "t1": 627, "t2": 4096, "t3": 4096, "t3a": 4096, "t4g": 4096,
    # memory optimized
    "r3": 7808, "r4": 7808, "r5": 8192, "r5a": 8192, "r5ad": 8192,
    "r5b": 8192, "r5d": 8192, "r5dn": 8192, "r5n": 8192, "r6a": 8192,
    "r6g": 8192, "r6gd": 8192, "r6i": 8192, "r6id": 8192, "r6idn": 8192,
    "r6in": 8192, "r7g": 8192, "u": None,  # u-*: memory parsed from name
    "x1": 15616, "x1e": 31232, "x2gd": 16384, "x2idn": 16384,
    "x2iedn": 32768, "x2iezn": 32768, "z1d": 8192,
    # storage / dense-IO
    "d2": 7808, "d3": 8192, "d3en": 4096, "h1": 4096,
    "i2": 7808, "i3": 7808, "i3en": 8192, "i4i": 8192,
    "im4gn": 4096, "is4gen": 6144,
    # accelerated
    "dl1": 8192, "f1": 15616, "g2": 1920, "g3": 7808, "g3s": 7808,
    "g4ad": 4096, "g4dn": 4096, "g5": 4096, "g5g": 2048,
    "inf1": 2048, "p2": 15616, "p3": 7808, "p3dn": 8192,
    "p4d": 12288, "p4de": 12288, "trn1": 4096, "vt1": 2048,
}

# burstable families share sizes but t2 keeps 1-vCPU small sizes while the
# nitro t3/t3a/t4g floor at 2 vCPU: (vcpu, memory MiB) per size
_T_SIZES = {
    "t2": {"nano": (1, 512), "micro": (1, 1024), "small": (1, 2048),
           "medium": (2, 4096), "large": (2, 8192), "xlarge": (4, 16384),
           "2xlarge": (8, 32768)},
    "t3": {"nano": (2, 512), "micro": (2, 1024), "small": (2, 2048),
           "medium": (2, 4096), "large": (2, 8192), "xlarge": (4, 16384),
           "2xlarge": (8, 32768)},
}
_T_SIZES["t3a"] = _T_SIZES["t4g"] = _T_SIZES["t3"]

# legacy / irregular whole-type overrides: name -> (vcpu, memory MiB)
_TYPE_OVERRIDES = {
    "c1.medium": (2, 1740), "c1.xlarge": (8, 7168),
    "cc2.8xlarge": (32, 61952),
    "m1.small": (1, 1740), "m1.medium": (1, 3840),
    "m1.large": (2, 7680), "m1.xlarge": (4, 15360),
    "m2.xlarge": (2, 17510), "m2.2xlarge": (4, 35020),
    "m2.4xlarge": (8, 70041),
    "m3.medium": (1, 3840),
    "t1.micro": (1, 627),
    "g2.2xlarge": (8, 15360), "g2.8xlarge": (32, 61440),
    "is4gen.medium": (2, 6144),
    "f1.16xlarge": (64, 999424),
    "mac1.metal": (12, 32768), "mac2.metal": (8, 16384),
    # c5n memory is non-linear above 4xlarge (real: 96/192 GiB)
    "c5n.9xlarge": (36, 98304), "c5n.18xlarge": (72, 196608),
    "p4d.24xlarge": (96, 1179648), "p4de.24xlarge": (96, 1179648),
    "i3.metal": (72, 524288), "c5n.metal": (72, 196608),
    "g4dn.metal": (96, 393216), "r5b.metal": (96, 786432),
}

# metal vCPU when it differs from the family's largest listed size
_METAL_VCPU = {"m5": 96, "m5d": 96, "m5zn": 48, "r5": 96, "r5d": 96,
               "c5": 96, "c5d": 96, "c6g": 64, "c6gd": 64, "m6g": 64,
               "m6gd": 64, "r6g": 64, "r6gd": 64, "z1d": 48, "i4i": 128,
               "c6i": 128, "c6id": 128, "m6i": 128, "m6id": 128,
               "r6i": 128, "r6id": 128, "x2gd": 64, "c7g": 64, "m7g": 64,
               "r7g": 64, "c6a": 192, "m6a": 192, "r6a": 192}

# accelerator families: (k8s resource, device name, default count,
# per-size count overrides). f1 (FPGA) and vt1 (video transcode) have no
# standard k8s device resource and are skipped.
_ACCEL = {
    "p2":   ("nvidia.com/gpu", "k80", None,
             {"xlarge": 1, "8xlarge": 8, "16xlarge": 16}),
    "p3":   ("nvidia.com/gpu", "v100", None,
             {"2xlarge": 1, "8xlarge": 4, "16xlarge": 8}),
    "p3dn": ("nvidia.com/gpu", "v100", None, {"24xlarge": 8}),
    "p4d":  ("nvidia.com/gpu", "a100", None, {"24xlarge": 8}),
    "p4de": ("nvidia.com/gpu", "a100", None, {"24xlarge": 8}),
    "g2":   ("nvidia.com/gpu", "k520", None, {"2xlarge": 1, "8xlarge": 4}),
    "g3":   ("nvidia.com/gpu", "m60", None,
             {"4xlarge": 1, "8xlarge": 2, "16xlarge": 4}),
    "g3s":  ("nvidia.com/gpu", "m60", None, {"xlarge": 1}),
    "g4dn": ("nvidia.com/gpu", "t4", 1, {"12xlarge": 4, "metal": 8}),
    "g5":   ("nvidia.com/gpu", "a10g", 1,
             {"12xlarge": 4, "24xlarge": 4, "48xlarge": 8}),
    "g5g":  ("nvidia.com/gpu", "t4g", 1, {"16xlarge": 2, "metal": 2}),
    "g4ad": ("amd.com/gpu", "radeon-pro-v520", 1,
             {"8xlarge": 2, "16xlarge": 4}),
    "dl1":  ("habana.ai/gaudi", "gaudi-hl-205", None, {"24xlarge": 8}),
    "inf1": ("aws.amazon.com/neuron", "inferentia", None,
             {"xlarge": 1, "2xlarge": 1, "6xlarge": 4, "24xlarge": 16}),
    "trn1": ("aws.amazon.com/neuron", "trainium", None,
             {"2xlarge": 1, "32xlarge": 16}),
}

# Multi-network-card types: the vpclimits table sums interfaces across ALL
# cards, but the reference's pod-density formula consumes per-card
# MaximumNetworkInterfaces from DescribeInstanceTypes (instancetype.go:
# 232-234), so density uses the per-card figure (eni-max-pods.txt values:
# 15*(50-1)+2 = 737 for p4d/dl1, 5*(50-1)+2 = 247 for trn1.32xlarge).
_PODS_IFACE_OVERRIDE = {"p4d.24xlarge": 15, "p4de.24xlarge": 15,
                        "dl1.24xlarge": 15, "trn1.32xlarge": 5}

_CATEGORY = {"a": "general", "c": "compute", "cc": "compute", "d": "storage",
             "dl": "training", "f": "accel", "g": "gpu", "h": "storage",
             "hpc": "hpc", "i": "storage", "im": "storage", "is": "storage",
             "inf": "inference", "m": "general", "mac": "general",
             "p": "gpu", "r": "memory", "t": "burst", "trn": "training",
             "u": "memory", "vt": "accel", "x": "memory", "z": "memory"}


def parse_prices(path: str):
    txt = open(path).read()
    stamp = re.search(r"generated at ([0-9TZ:\-]+)", txt).group(1)
    m = re.search(
        r'initialOnDemandPrices\["us-east-1"\] = map\[string\]float64\{(.*?)\n\t\}',
        txt, re.S)
    return {k: float(v) for k, v in
            re.findall(r'"([a-z0-9.\-]+)":\s*([0-9.]+)', m.group(1))}, stamp


def parse_vpclimits(path: str):
    txt = open(path).read()
    stamp = re.search(r"generated at ([0-9TZ:\-]+)", txt).group(1)
    out = {}
    for name, iface, ipv4, trunk, branch in re.findall(
            r'"([a-z0-9.\-]+)":\s*\{Interface:\s*(\d+), IPv4PerInterface:\s*(\d+), '
            r'IsTrunkingCompatible:\s*(true|false), BranchInterface:\s*(\d+)\}',
            txt):
        out[name] = {"interfaces": int(iface), "ipv4_per_interface": int(ipv4),
                     "trunking": trunk == "true", "branches": int(branch)}
    return out, stamp


def parse_anchors(path: str):
    """name -> (vcpu, memory MiB, total gpu count) from the checked-in
    DescribeInstanceTypes fixtures."""
    txt = open(path).read()
    anchors = {}
    for block in re.split(r"\n\t\t\{\n", txt)[1:]:
        name = re.search(r'InstanceType:\s+aws\.String\("([^"]+)"\)', block)
        vcpu = re.search(r"DefaultVCpus:\s+aws\.Int64\((\d+)\)", block)
        mem = re.search(r"SizeInMiB:\s+aws\.Int64\((\d+)\)", block)
        if not (name and vcpu and mem):
            continue
        gpus = 0
        if "Gpus: []*ec2.GpuDeviceInfo" in block:
            gpu_sec = block.split("Gpus: []*ec2.GpuDeviceInfo", 1)[1]
            gpu_sec = gpu_sec.split("TotalGpuMemoryInMiB", 1)[0]
            gpus = sum(int(c) for c in
                       re.findall(r"Count:\s+aws\.Int64\((\d+)\)", gpu_sec))
        anchors[name.group(1)] = (int(vcpu.group(1)), int(mem.group(1)), gpus)
    return anchors


def derive(name: str, fam: str, size: str, family_types: "dict[str, list]"):
    """(vcpu, memory MiB) from the naming convention + tables."""
    if name in _TYPE_OVERRIDES:
        return _TYPE_OVERRIDES[name]
    if fam in _T_SIZES:
        return _T_SIZES[fam][size]
    if fam == "u":  # u-6tb1.112xlarge: memory is in the family token
        mem_tib = int(re.match(r"u-(\d+)tb", name).group(1))
        vcpu = _size_vcpu(size, fam, family_types)
        return vcpu, mem_tib * 1024 * 1024
    per = _MIB_PER_VCPU[fam]
    vcpu = _size_vcpu(size, fam, family_types)
    return vcpu, vcpu * per


def _size_vcpu(size: str, fam: str, family_types: "dict[str, list]") -> int:
    if size in _SIZE_VCPU:
        return _SIZE_VCPU[size]
    m = re.fullmatch(r"(\d+)xlarge", size)
    if m:
        return 4 * int(m.group(1))
    if size == "metal":
        if fam in _METAL_VCPU:
            return _METAL_VCPU[fam]
        # default: the family's largest listed non-metal size
        return max(_size_vcpu(s, fam, family_types)
                   for s in family_types[fam] if s != "metal")
    raise ValueError(f"unknown size {size!r}")


def family_of(name: str) -> "tuple[str, str]":
    if name.startswith("u-"):  # u-6tb1.112xlarge -> family "u"
        return "u", name.split(".", 1)[1]
    fam, size = name.split(".", 1)
    return fam, size


def is_graviton(fam: str) -> bool:
    return fam == "a1" or bool(re.match(r"^[a-z]+\d+g", fam))


def main():
    if not os.path.isdir(REF):
        sys.exit("reference data artifacts not present at /root/reference — "
                 "the checked-in karpenter_tpu/providers/data/"
                 "fleet_catalog.json is the (already generated) output; "
                 "regeneration needs the source artifacts")
    prices, price_stamp = parse_prices(
        os.path.join(REF, "cloudprovider", "zz_generated.pricing.go"))
    limits, limits_stamp = parse_vpclimits(
        os.path.join(REF, "cloudprovider", "zz_generated.vpclimits.go"))
    anchors = parse_anchors(
        os.path.join(REF, "fake", "zz_generated.describe_instance_types.go"))

    names = sorted(set(prices) & set(limits))
    family_types: "dict[str, list]" = {}
    for n in names:
        fam, size = family_of(n)
        family_types.setdefault(fam, []).append(size)

    missing = sorted(f for f in family_types
                     if f not in _MIB_PER_VCPU and f not in _T_SIZES)
    if missing:
        sys.exit(f"no MiB-per-vCPU ratio for families: {missing}")

    types = []
    for name in names:
        fam, size = family_of(name)
        vcpu, mem_mib = derive(name, fam, size, family_types)
        lim = limits[name]
        ifaces = _PODS_IFACE_OVERRIDE.get(name, lim["interfaces"])
        pods = ifaces * (lim["ipv4_per_interface"] - 1) + 2
        accel = {}
        gpu_name = None
        if fam in _ACCEL:
            res, dev, default, by_size = _ACCEL[fam]
            count = by_size.get(size, default)
            if count:
                accel[res] = count
                gpu_name = dev
        gen_m = re.search(r"(\d+)", fam)
        entry = {
            "name": name,
            "vcpu": vcpu,
            "memory_mib": mem_mib,
            "arch": "arm64" if is_graviton(fam) else "amd64",
            "pods": pods,
            "trunking": lim["trunking"],
            "pod_eni_branches": lim["branches"] if lim["trunking"] else 0,
            "od_price_usd": prices[name],
            "family": fam,
            "size": size,
            "generation": int(gen_m.group(1)) if gen_m else 1,
            "category": _CATEGORY[re.match(r"[a-z]+", fam).group(0)],
        }
        if accel:
            entry["accelerators"] = accel
            entry["accelerator_name"] = gpu_name
        types.append(entry)

    # anchor validation: derived specs must match real DescribeInstanceTypes
    bad = []
    for aname, (avcpu, amem, agpu) in sorted(anchors.items()):
        if aname not in {t["name"] for t in types}:
            continue
        t = next(t for t in types if t["name"] == aname)
        # fixtures report GpuInfo devices only (nvidia/amd/gaudi); neuron
        # rides a different API section the fixtures don't carry counts for
        dgpu = sum(v for k, v in t.get("accelerators", {}).items()
                   if k != "aws.amazon.com/neuron")
        if (t["vcpu"], t["memory_mib"]) != (avcpu, amem) or dgpu != agpu:
            bad.append(f"{aname}: derived (vcpu={t['vcpu']}, "
                       f"mem={t['memory_mib']}, accel={dgpu}) != real "
                       f"({avcpu}, {amem}, {agpu})")
    if bad:
        sys.exit("anchor validation failed:\n  " + "\n  ".join(bad))

    record = {
        "provenance": {
            "pricing": {"source": "reference zz_generated.pricing.go "
                                  "(hack/code/prices_gen.go output)",
                        "region": "us-east-1", "generated_at": price_stamp},
            "eni_limits": {"source": "reference zz_generated.vpclimits.go "
                                     "(hack/code/vpc_limits_gen.go output)",
                           "generated_at": limits_stamp},
            "derivation": "vcpu/memory from the published instance naming "
                          "convention (hack/gen_catalog.py tables), "
                          f"validated against {len(anchors)} "
                          "DescribeInstanceTypes fixtures",
            "pods_formula": "interfaces * (ipv4_per_interface - 1) + 2",
        },
        "types": types,
    }
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"{len(types)} types -> {OUT}")
    print(f"anchors validated: "
          f"{len(set(anchors) & {t['name'] for t in types})}/{len(anchors)}")


if __name__ == "__main__":
    main()
