#!/usr/bin/env python
"""Raw tunnel-link characterization: d2h/h2d latency + bandwidth curve.

Completes the floor decomposition of docs/designs/solver-boundary.md with
transfer-size data: the link sentinels established WHEN the relay degrades
(the session's first device->host read); this tool establishes the COST
MODEL afterwards — per-op latency and sustained bandwidth in both
directions — so multi-MB readbacks (e.g. the 10k-pod wave's concatenated
PackResult) are attributable to latency x ops + bytes / bandwidth.

Writes benchmarks/results/linkprobe_<utc>.json. Run while the tunnel is
answering (hack/tpu_capture.py records link_state; this goes deeper).
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SIZES = (8, 1 << 10, 1 << 17, 1 << 20, 1 << 22, 1 << 24)  # 8B .. 16MB
REPS = 5


def _sync_sentinel(jax, jnp, reps=5):
    # one sentinel implementation for all tools (shape: {p50_ms, min_ms})
    from hack.tpu_capture import _link_sentinel

    return _link_sentinel(jax, jnp, reps=reps)["p50_ms"]


def _h2d_sweep(jax, np):
    """device_put latency/bandwidth across SIZES (puts never flip the
    relay's link state, so this measures whichever state is current)."""
    rows = []
    for size in SIZES:
        host = np.zeros(size // 4, np.int32)
        jax.device_put(host).block_until_ready()  # first-touch alloc
        ts = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            jax.device_put(host).block_until_ready()
            ts.append((time.perf_counter() - t0) * 1000)
        ms = statistics.median(ts)
        rows.append({"bytes": size, "p50_ms": round(ms, 3),
                     "mb_per_s": round(size / 2**20 / (ms / 1000), 1) if ms else None})
    return rows


def main():
    from karpenter_tpu.utils.jaxenv import pin, probe_tpu

    ok, note = probe_tpu(attempts=1)
    if not ok:
        print(json.dumps({"error": "tunnel not answering", "probe": note}))
        return 1
    jax, _ = pin("axon")
    import jax.numpy as jnp
    import numpy as np

    rec = {"device": str(jax.devices()[0]),
           "sync_fresh_ms": _sync_sentinel(jax, jnp)}

    rec["h2d_streaming"] = _h2d_sweep(jax, np)
    rec["sync_after_h2d_ms"] = _sync_sentinel(jax, jnp)

    # ESCAPE-HATCH EXPERIMENT (before the first literal read, while still
    # streaming): does an io_callback-based readback — results pushed
    # host-ward from inside the jitted computation — avoid the
    # streaming->degraded transition that jax.device_get triggers? One
    # shared implementation with the capture tool (incl. effects_barrier
    # inside the timed span — block_until_ready alone does not wait for
    # host callback delivery).
    from hack.tpu_capture import _io_callback_probe

    rec["io_callback_escape"] = _io_callback_probe(jax, jnp, reps=REPS)
    io_degraded = (rec["io_callback_escape"].get("sync_after") or
                   {}).get("p50_ms", 0.0) >= 5.0

    # d2h: the FIRST read flips the relay out of streaming mode — record it
    # separately, then sweep sizes in the degraded state the production
    # readback actually experiences. (If the io probe above already
    # consumed the transition, first_read_ms is just a degraded-state
    # read — flagged so the recorded evidence can't contradict itself.)
    dev8 = jax.device_put(np.zeros(2, np.int32))
    t0 = time.perf_counter()
    np.asarray(jax.device_get(dev8))
    rec["first_read_ms"] = round((time.perf_counter() - t0) * 1000, 3)
    if io_degraded:
        rec["first_read_note"] = ("transition consumed by the io_callback "
                                  "probe; this is a degraded-state read, "
                                  "not the streaming->degraded flip")

    # Each rep reads a FRESH device-computed buffer (a re-get of the same
    # buffer is served from PJRT's host-side copy and measures nothing);
    # the producing op is blocked on *before* the clock so the timed span
    # is the transfer alone, not the degraded-mode dispatch sync.
    d2h = []
    for size in SIZES:
        dev = jax.device_put(np.zeros(size // 4, np.int32))
        bump = jax.jit(lambda x, s: x + s)
        bump(dev, 0).block_until_ready()
        ts = []
        for rep in range(REPS):
            y = bump(dev, rep + 1)
            y.block_until_ready()
            t0 = time.perf_counter()
            np.asarray(jax.device_get(y))
            ts.append((time.perf_counter() - t0) * 1000)
        ms = statistics.median(ts)
        d2h.append({"bytes": size, "p50_ms": round(ms, 3),
                    "mb_per_s": round(size / 2**20 / (ms / 1000), 1) if ms else None})
    rec["d2h_degraded"] = d2h

    # What a solve actually pays: get() of a just-enqueued (unsynced)
    # result — dispatch sync + transfer in one span.
    unsynced = []
    for size in (8, 1 << 17, 1 << 22):
        dev = jax.device_put(np.zeros(size // 4, np.int32))
        bump = jax.jit(lambda x, s: x * 1 + s)
        bump(dev, 0).block_until_ready()
        ts = []
        for rep in range(REPS):
            y = bump(dev, rep + 1)
            t0 = time.perf_counter()
            np.asarray(jax.device_get(y))
            ts.append((time.perf_counter() - t0) * 1000)
        unsynced.append({"bytes": size,
                         "p50_ms": round(statistics.median(ts), 3)})
    rec["d2h_unsynced"] = unsynced
    rec["sync_after_d2h_ms"] = _sync_sentinel(jax, jnp)

    # h2d in the DEGRADED state (the streaming sweep above ran before the
    # first read): what consolidation/solve input shipping actually pays
    # in a long-lived session. NOTE each rep blocks, so small sizes read
    # as the degraded sync floor; bandwidth shows at the large sizes.
    rec["h2d_degraded"] = _h2d_sweep(jax, np)

    # latency/bandwidth fit: ms ~= a + bytes/bw  (least squares over sweep)
    xs = np.array([e["bytes"] for e in d2h], float)
    ys = np.array([e["p50_ms"] for e in d2h], float)
    A = np.stack([np.ones_like(xs), xs], axis=1)
    (a, b), *_ = np.linalg.lstsq(A, ys, rcond=None)
    rec["d2h_fit"] = {"latency_ms": round(float(a), 3),
                      "bandwidth_mb_s": round(1.0 / b / 1048.576, 1) if b > 0 else None}

    rec["captured_at"] = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    out = os.path.join(REPO, "benchmarks", "results",
                       f"linkprobe_{rec['captured_at']}.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))
    print(f"-> {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
