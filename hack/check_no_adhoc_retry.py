#!/usr/bin/env python3
"""Lint: no ad-hoc retry loops outside the resilience plane.

Every retry in the controller half must flow through
`resilience.RetryPolicy` so it spends from the per-dependency budget,
feeds the breaker, and uses seeded, clock-injectable backoff
(docs/designs/resilience.md). The historical failure mode this guards
against: a helper grows its own `while ...: try/except + time.sleep`
loop, works fine in review, and during the next regional 5xx burst
multiplies into a retry storm the budget never saw.

Detection is AST-based, not textual: a `while`/`for` loop that contains
BOTH an exception handler and a `time.sleep(...)` (or bare `sleep(...)`
imported from time) call in the same loop body is flagged. Sleeping
without catching, or catching without sleeping, is fine — only the
retry-with-backoff shape is reserved for the resilience plane.

Allowlisted files carry sleeps that are genuinely not dependency
retries (startup polling for a subprocess the test itself owns, the
TPU-tunnel environment probe). Add to the allowlist only with a
comment saying why the loop is not a dependency retry.

Run via `make presubmit` (or directly: python hack/check_no_adhoc_retry.py).
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "karpenter_tpu"

# the one place retry-with-backoff loops are allowed to live
EXEMPT_DIR = PACKAGE / "resilience"

ALLOWLIST = {
    # interpreter-boot TPU tunnel probe: retries the axon relay BEFORE the
    # operator (and its hub) can exist
    PACKAGE / "utils" / "jaxenv.py",
    # CLI serve-loop waits for its OWN subprocess/port to come up — process
    # supervision, not a dependency call
    PACKAGE / "__main__.py",
}


def _is_sleep_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "sleep" \
            and isinstance(f.value, ast.Name) and f.value.id == "time":
        return True
    return isinstance(f, ast.Name) and f.id == "sleep"


def _loop_retries(loop: "ast.While | ast.For") -> bool:
    """True when the loop body both handles exceptions and sleeps —
    nested loops are scanned separately, so their bodies are skipped."""
    has_handler = has_sleep = False
    stack = list(loop.body) + list(loop.orelse)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.While, ast.For, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.Lambda)):
            continue  # its own scope; flagged on its own if it retries
        if isinstance(node, ast.ExceptHandler):
            has_handler = True
        if _is_sleep_call(node):
            has_sleep = True
        if has_handler and has_sleep:
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def check_file(path: pathlib.Path) -> "list[str]":
    tree = ast.parse(path.read_text(), filename=str(path))
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.While, ast.For)) and _loop_retries(node):
            rel = path.relative_to(ROOT) if ROOT in path.parents else path
            out.append(
                f"{rel}:{node.lineno}: ad-hoc retry loop (except + "
                f"time.sleep); route it through resilience.RetryPolicy "
                f"(docs/designs/resilience.md)")
    return out


def main() -> int:
    problems: "list[str]" = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if EXEMPT_DIR in path.parents or path in ALLOWLIST:
            continue
        problems.extend(check_file(path))
    if problems:
        print("\n".join(problems), file=sys.stderr)
        print(f"\n{len(problems)} ad-hoc retry loop(s); retries must spend "
              f"from the shared budget (hack/check_no_adhoc_retry.py "
              f"docstring has the rules)", file=sys.stderr)
        return 1
    print("no-adhoc-retry: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
