#!/usr/bin/env python3
"""Lint: every benchmark number quoted in docs must cite a recorded artifact
or a perf-ledger entry.

Round docs and the README quote performance numbers (ms, msgs/s, speedup
factors). Unattributed numbers rot: the next round can neither reproduce
nor refute them. This lint walks README.md and docs/rounds/*.md at
paragraph granularity and requires any paragraph quoting a benchmark
number to also cite where it was recorded — an artifact path
(benchmarks/results/..., a bench_*/tpu_*/linkprobe_*/chaos_seed*/
chaos_burst_*/chaos_crash_*/chaos_storm_*/fleet_* JSON — the fleet
family covers both fleet_bench.json and the real-replica drill's
fleet_drill*.json — a flight-recorder bundle_*.json diagnostics bundle,
a .trace.json capture),
the harness that records one (benchmarks/*.py), or a perf-ledger citation
`ledger:<metric>` naming a metric that actually has entries in
benchmarks/results/ledger.jsonl (a citation to a metric the ledger has
never recorded is itself a lint error — see docs/designs/slo.md).

Numbers that are configuration, not measurement (batcher windows, TTLs),
are waived inline with:

    <!-- no-artifact: <why this number is config, not a measurement> -->

Run via `make presubmit` (or directly: python hack/check_round_claims.py).
"""

from __future__ import annotations

import json
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

# a paragraph "quotes a benchmark number" when it matches any of these
CLAIM_PATTERNS = [
    re.compile(r"\b\d+(?:\.\d+)?(?:-\d+(?:\.\d+)?)?\s*ms\b"),
    re.compile(r"\b\d[\d,.]*k?\s*(?:msgs?|ops|pods)/s"),
    re.compile(r"~?\d+(?:\.\d+)?\s*[x×]\s*(?:faster|slower|speedup|warm|cheaper)"),
]

# ...and "cites an artifact" when it matches any of these
ARTIFACT_PATTERNS = [
    re.compile(r"benchmarks/[\w./*-]+"),
    re.compile(r"\b(?:tpu|bench|trace_summary|linkprobe|chaos_seed"
               r"|chaos_burst|chaos_crash|chaos_storm|failover|fleet"
               r"|bundle_|explain|incremental|soak|critical|churn"
               r"|spotstorm|spot_)"
               r"[\w*-]*\.json(?:\.gz)?"),
    re.compile(r"[\w*-]+\.trace\.json(?:\.gz)?"),
]

# ...or cites the perf ledger trend by metric name: `ledger:<metric>`
LEDGER_CITE = re.compile(r"ledger:([A-Za-z_][\w]*)")

WAIVER = re.compile(r"<!--\s*no-artifact:\s*\S[^>]*-->")

LINTED = ["README.md"]


def _ledger_metrics() -> "set[str]":
    """Metric names that actually have entries in the committed ledger."""
    metrics: "set[str]" = set()
    path = ROOT / "benchmarks" / "results" / "ledger.jsonl"
    try:
        for line in path.read_text().splitlines():
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if isinstance(e, dict) and isinstance(e.get("metric"), str):
                metrics.add(e["metric"])
    except OSError:
        pass
    return metrics


def paragraphs(text: str):
    """(first_line_number, paragraph) blocks, blank-line separated."""
    block, start = [], 1
    for i, line in enumerate(text.splitlines(), 1):
        if line.strip():
            if not block:
                start = i
            block.append(line)
        elif block:
            yield start, "\n".join(block)
            block = []
    if block:
        yield start, "\n".join(block)


def lint_file(path: pathlib.Path,
              known_metrics: "set[str]") -> "list[str]":
    problems = []
    rel = path.relative_to(ROOT)
    for lineno, para in paragraphs(path.read_text()):
        cited = LEDGER_CITE.findall(para)
        for metric in cited:
            if metric not in known_metrics:
                problems.append(
                    f"{rel}:{lineno}: ledger citation ledger:{metric} names "
                    f"a metric with no entries in benchmarks/results/"
                    f"ledger.jsonl (typo, or the bench never recorded?)")
        claims = [m.group(0) for pat in CLAIM_PATTERNS
                  for m in pat.finditer(para)]
        if not claims:
            continue
        if WAIVER.search(para):
            continue
        if any(m in known_metrics for m in cited):
            continue
        if any(pat.search(para) for pat in ARTIFACT_PATTERNS):
            continue
        problems.append(
            f"{rel}:{lineno}: benchmark number(s) {claims[:3]} without a "
            f"recorded-artifact citation (add a benchmarks/results/ path "
            f"or a ledger:<metric> citation, or waive config constants "
            f"with <!-- no-artifact: why -->)")
    return problems


def main() -> int:
    targets = [ROOT / p for p in LINTED]
    targets += sorted((ROOT / "docs" / "rounds").glob("*.md"))
    known_metrics = _ledger_metrics()
    problems = []
    for path in targets:
        if path.exists():
            problems += lint_file(path, known_metrics)
    if problems:
        print(f"check_round_claims: {len(problems)} unattributed "
              f"benchmark claim(s):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(f"check_round_claims: OK ({len(targets)} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
