#!/usr/bin/env python3
"""Lint: no unbounded identity labels on metric call sites.

The cardinality guard (karpenter_tpu/metrics/cardinality.py) exists
because one `tenant=tenant_id` on a counter is all it takes to grow one
series per tenant forever — fine at test scale, a label explosion that
melts the metrics plane at 1000+ tenants. The guard bounds tenant
families at K+1 series, but only when call sites actually route their
label values through it. This lint enforces that: a later change that
files a raw tenant/pod/node identity straight into `.inc()/.set()/
.observe()` fails presubmit instead of shipping a time bomb.

Mechanics, AST-based not textual:

  * Every call whose callee attribute is `inc`, `set`, or `observe` is a
    metric call site; every keyword argument whose name is in UNBOUNDED
    (tenant/tenant_id/pod/pod_name/node/node_name — labels whose value
    universe is the fleet, not a code-enumerable set) is checked.
  * The value passes when it is provably bounded or guarded:
      - a string literal (code-enumerable by definition);
      - a call through the guard — `tenant_label(...)`, `tenant_peek(...)`,
        `<guard>.label(...)`, `<guard>.peek(...)`;
      - a name that carries a guarded value by convention: `tlabel`,
        `OTHER`, or any identifier containing "label" (the guard helpers
        return label values; call sites bind them to *label names).
  * Anything else — a raw identifier, an f-string, str(x), a subscript —
    is flagged unless the line (or the contiguous comment block directly
    above it) carries `# label-cardinality-ok: <why>`. Add new allowlist
    entries only with a comment proving the value set is bounded.
  * fleet/metrics.py MUST keep registering tenant families with the
    guard (`TENANT_GUARD.watch`) — deleting the guard does not pass.

Run via `make presubmit` (or directly: python
hack/check_label_cardinality.py [files...]; with no arguments the whole
karpenter_tpu package is scanned).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "karpenter_tpu"

# metric-mutation method names; a keyword on anything else is not a label
METRIC_METHODS = {"inc", "set", "observe"}

# label names whose value universe is the fleet (unbounded at runtime)
UNBOUNDED = {"tenant", "tenant_id", "pod", "pod_name", "node", "node_name"}

# calls that ARE the guard: their return value is cardinality-bounded
GUARD_FUNCS = {"tenant_label", "tenant_peek", "label", "peek"}

# names that carry a guarded value by repo convention
SAFE_NAMES = {"tlabel", "OTHER"}

# the guard registration that must not silently disappear
GUARDED_REGISTRATION = PACKAGE / "fleet" / "metrics.py"

_OK = re.compile(r"#\s*label-cardinality-ok")


def allowlisted(lines: "list[str]", lineno: int) -> bool:
    """label-cardinality-ok on the call's line, or in the contiguous
    comment block directly above it."""
    if _OK.search(lines[lineno - 1]):
        return True
    i = lineno - 2
    while i >= 0:
        if _OK.search(lines[i]):
            return True
        if not lines[i].strip().startswith("#"):
            return False
        i -= 1
    return False


def _callee_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def value_is_guarded(value: ast.AST) -> bool:
    """Provably bounded: a literal, a guard call, or a name bound to a
    guarded value by convention."""
    if isinstance(value, ast.Constant) and isinstance(value.value, str):
        return True
    if isinstance(value, ast.Call):
        return _callee_name(value.func) in GUARD_FUNCS
    if isinstance(value, ast.Name):
        return value.id in SAFE_NAMES or "label" in value.id.lower()
    if isinstance(value, ast.Attribute):
        return value.attr in SAFE_NAMES or "label" in value.attr.lower()
    return False


def check_file(path: pathlib.Path) -> "list[str]":
    src = path.read_text()
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}: unparseable ({e})"]
    errors = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _callee_name(node.func) not in METRIC_METHODS:
            continue
        for kw in node.keywords:
            if kw.arg not in UNBOUNDED:
                continue
            if value_is_guarded(kw.value):
                continue
            if allowlisted(lines, node.lineno):
                continue
            errors.append(
                f"{path}:{node.lineno}: label `{kw.arg}=` fed from an "
                "unbounded runtime value — route it through the "
                "cardinality guard (fleet.metrics.tenant_label/"
                "tenant_peek) or annotate `# label-cardinality-ok: "
                "<why bounded>`")
    return errors


def main(argv: "list[str]") -> int:
    targets = ([pathlib.Path(a) for a in argv]
               if argv else sorted(PACKAGE.rglob("*.py")))
    errors: "list[str]" = []
    for path in targets:
        errors.extend(check_file(path))
    if not argv and "TENANT_GUARD.watch" not in \
            GUARDED_REGISTRATION.read_text():
        errors.append(
            f"{GUARDED_REGISTRATION}: tenant families are no longer "
            "registered with the cardinality guard (TENANT_GUARD.watch) — "
            "the K+1 series bound is gone")
    if errors:
        print("label-cardinality lint FAILED:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    print(f"label-cardinality lint ok ({len(targets)} files scanned)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
