#!/bin/sh
# Build the native packer shared library (controller-half fallback solver).
set -e
cd "$(dirname "$0")/.."
g++ -O2 -Wall -shared -fPIC -o karpenter_tpu/native/libktpack.so karpenter_tpu/native/ktpack.cc
echo "built karpenter_tpu/native/libktpack.so"
