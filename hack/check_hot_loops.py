#!/usr/bin/env python3
"""Lint: no per-pod/per-node Python `for` loops in marked hot sections.

The columnar cluster-state refactor (docs/designs/columnar-state.md) moved
the reconcile hot paths — provisioning mask construction, the
deprovisioning sweeps, solver encode over existing capacity — onto
contiguous numpy columns. The failure mode this lint guards against: a
later change quietly reintroduces a `for pod in pods` / `for node in
nodes` scan inside one of those sections, works fine at test scale, and
at 100k nodes turns a column scan back into a multi-second fleet walk
(the soak artifact in benchmarks/results/soak/ is sized on these loops
NOT existing).

Mechanics, AST-based not textual:

  * Hot sections are delimited by `# HOT:BEGIN(name)` / `# HOT:END(name)`
    comment pairs in the source. Pairs must balance per file.
  * Inside a section, any `ast.For` whose iterator expression references a
    per-pod/per-node collection identifier (BANNED below, exact match on
    Name ids and Attribute attrs) is flagged. Loops over already-filtered
    subsets (`np.nonzero(mask)[0]`, `np.unique(codes)`, dirty rows) and
    per-GROUP loops (groups are deduped, O(10) not O(pods)) pass.
  * `# hot-loop-ok: <why>` on the loop's line, or in the contiguous
    comment block directly above it, allowlists the loop. Today's uses are
    the legacy dataclass-view compatibility branches in encode.py — kept
    for oracle callers and old tests, never the production path. Add new
    ones only with a comment saying why the loop is not O(fleet).
  * The three files that own the hot paths MUST carry at least one marker
    each (REQUIRED_MARKED) — deleting the markers does not pass the lint.

Run via `make presubmit` (or directly: python hack/check_hot_loops.py).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "karpenter_tpu"

# identifiers that mean "the whole pod/node population"
BANNED = {
    "pods", "pending", "all_pods", "non_daemon_pods",
    "nodes", "all_nodes", "node_names",
    "existing", "views", "all_views",
    "resident_counts",
}

# these own the reconcile hot paths; each must keep its markers
REQUIRED_MARKED = (
    PACKAGE / "models" / "encode.py",
    PACKAGE / "controllers" / "provisioning.py",
    PACKAGE / "controllers" / "deprovisioning.py",
)

_BEGIN = re.compile(r"#\s*HOT:BEGIN\(([\w-]+)\)")
_END = re.compile(r"#\s*HOT:END\(([\w-]+)\)")
_OK = re.compile(r"#\s*hot-loop-ok")


def hot_ranges(lines: "list[str]", path: pathlib.Path
               ) -> "tuple[list[tuple[int, int, str]], list[str]]":
    """(1-indexed inclusive line ranges, errors) from the marker comments."""
    ranges, errors = [], []
    open_at: "tuple[int, str] | None" = None
    for i, line in enumerate(lines, start=1):
        b, e = _BEGIN.search(line), _END.search(line)
        if b:
            if open_at is not None:
                errors.append(f"{path}:{i}: HOT:BEGIN({b.group(1)}) inside "
                              f"unclosed HOT:BEGIN({open_at[1]})")
            open_at = (i, b.group(1))
        elif e:
            if open_at is None:
                errors.append(f"{path}:{i}: HOT:END({e.group(1)}) "
                              "without HOT:BEGIN")
            else:
                if open_at[1] != e.group(1):
                    errors.append(
                        f"{path}:{i}: HOT:END({e.group(1)}) closes "
                        f"HOT:BEGIN({open_at[1]})")
                ranges.append((open_at[0], i, open_at[1]))
                open_at = None
    if open_at is not None:
        errors.append(f"{path}:{open_at[0]}: unclosed "
                      f"HOT:BEGIN({open_at[1]})")
    return ranges, errors


def allowlisted(lines: "list[str]", lineno: int) -> bool:
    """hot-loop-ok on the loop line, or in the contiguous comment block
    (possibly the tail of the preceding code line) directly above it."""
    if _OK.search(lines[lineno - 1]):
        return True
    i = lineno - 2
    while i >= 0:
        stripped = lines[i].strip()
        if _OK.search(lines[i]):
            return True
        if not stripped.startswith("#"):
            return False
        i -= 1
    return False


def iter_names(expr: ast.AST) -> "set[str]":
    out = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def check_file(path: pathlib.Path) -> "list[str]":
    src = path.read_text()
    lines = src.splitlines()
    ranges, errors = hot_ranges(lines, path)
    if not ranges:
        return errors
    tree = ast.parse(src, filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.For):
            continue
        section = next((name for lo, hi, name in ranges
                        if lo <= node.lineno <= hi), None)
        if section is None:
            continue
        banned = iter_names(node.iter) & BANNED
        if not banned:
            continue
        if allowlisted(lines, node.lineno):
            continue
        errors.append(
            f"{path}:{node.lineno}: per-pod/per-node `for` over "
            f"{sorted(banned)} inside HOT section ({section}) — vectorize "
            "over the columns, or annotate `# hot-loop-ok: <why>` if the "
            "loop is provably not O(fleet)")
    return errors


def main() -> int:
    errors: "list[str]" = []
    for path in sorted(PACKAGE.rglob("*.py")):
        errors.extend(check_file(path))
    for path in REQUIRED_MARKED:
        if "HOT:BEGIN(" not in path.read_text():
            errors.append(f"{path}: no HOT:BEGIN markers — the hot sections "
                          "must stay marked (see docs/designs/"
                          "columnar-state.md)")
    if errors:
        print("hot-loop lint FAILED:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    print("hot-loop lint ok "
          f"({sum(1 for _ in PACKAGE.rglob('*.py'))} files scanned)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
