// Native (host CPU) implementation of the FFD group-scan packer.
//
// Same semantics spec as the JAX kernel (karpenter_tpu/ops/packer.py) and the
// scalar oracle (karpenter_tpu/oracle/scheduler.py): this is the controller
// half's in-process fallback when the TPU solver sidecar is unreachable, and
// the fast path for small solves where a device round trip (~tens of ms over
// a tunneled chip) would dominate. Differential-tested for bit-parity against
// pack_impl in tests/test_native_pack.py.
//
// Reference analogue: the FFD spec at /root/reference/designs/bin-packing.md
// (sort pods desc; greedy fill; cheapest-offering tie-break per
// /root/reference/pkg/cloudprovider/instance.go:445-462). This is NOT a port
// of the Go loop: it consumes the same dense encoded problem (masks already
// folded by models/encode.py) as the device kernel, so all three backends
// share one semantics boundary.
//
// Build: hack/build_native.sh  ->  karpenter_tpu/native/libktpack.so

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int32_t INT_BIG = 1 << 30;

inline int32_t clip(int64_t v, int64_t lo, int64_t hi) {
  if (v < lo) return static_cast<int32_t>(lo);
  if (v > hi) return static_cast<int32_t>(hi);
  return static_cast<int32_t>(v);
}

// How many vec-sized pods fit into avail (length R): min over resources of
// floor(avail/vec); zero-demand resources ignored; negative avail with
// demand => -1 (mirrors _quotient in ops/packer.py).
int32_t quotient(const int32_t* avail, const int32_t* vec, int R) {
  int64_t q = INT_BIG;
  for (int r = 0; r < R; ++r) {
    int64_t qr;
    bool pos = vec[r] > 0;
    if (avail[r] < 0) {
      qr = pos ? -1 : INT_BIG;
    } else {
      qr = pos ? avail[r] / vec[r] : INT_BIG;
    }
    if (qr < q) q = qr;
  }
  return clip(q, -1, INT_BIG);
}

// Extra pods the kubelet pods cap admits (mirrors _pods_cap_quotient in
// ops/packer.py): floor(cap_avail/vec_pods), zero-demand => INT_BIG,
// negative => -1.
int32_t pods_cap_quotient(int64_t cap_avail, int32_t vec_pods) {
  if (vec_pods <= 0) return INT_BIG;
  if (cap_avail < 0) return -1;
  return clip(cap_avail / vec_pods, -1, INT_BIG);
}

}  // namespace

extern "C" {

// Returns 0 on success. All arrays are row-major int32/uint8 as documented in
// PackInputs (ops/packer.py); outputs must be pre-allocated by the caller.
int kt_pack(const int32_t* alloc_t,      // [T,R]
            const int32_t* tiebreak,     // [T,S]
            const int32_t* group_vec,    // [G,R]
            const int32_t* group_count,  // [G]
            const int32_t* group_cap,    // [G]
            const uint8_t* group_feas,   // [G,Pv,T,S]
            const int32_t* group_newprov,// [G]
            const int32_t* overhead,     // [R]
            const int32_t* ex_alloc,     // [Ne,R]
            const int32_t* ex_used_in,   // [Ne,R]
            const uint8_t* ex_feas,      // [G,Ne]
            const int32_t* ex_cap,       // [G,Ne] or nullptr (remaining group
                                         //   cap per existing node, resident
                                         //   pods already subtracted)
            const int32_t* group_origin, // [G] or nullptr (origin row whose
                                         //   per-node cap budget this row
                                         //   shares; zone-split subgroups of
                                         //   one deployment share one budget)
            const int32_t* prov_overhead,// [Pv,R] or nullptr (kubelet reserved)
            const int32_t* prov_pods_cap,// [Pv,T] or nullptr (kubelet pods cap)
            int pods_i,                  // index of the pods resource on R
            int G, int Pv, int T, int S, int R, int Ne, int N,
            int32_t* assign,             // out [G,N]
            int32_t* ex_assign,          // out [G,Ne]
            int32_t* unsched,            // out [G]
            uint8_t* active,             // out [N]
            int32_t* nprov,              // out [N]
            int32_t* decided,            // out [N]
            int32_t* n_open_out) {       // out [1]
  const int TS = T * S;
  std::vector<int32_t> used(static_cast<size_t>(N) * R, 0);
  std::vector<uint8_t> optmask(static_cast<size_t>(N) * TS, 0);
  std::vector<int32_t> ex_used(ex_used_in, ex_used_in + static_cast<size_t>(Ne) * R);
  std::vector<int32_t> q_nt(static_cast<size_t>(N));   // per-node best quotient
  std::vector<int32_t> qt(static_cast<size_t>(T));     // per-type quotient scratch
  std::vector<int32_t> m_n(static_cast<size_t>(N));
  std::vector<int32_t> avail(static_cast<size_t>(R));  // hoisted: the inner
  // loops below run G x N x T times; a per-iteration vector would cost
  // millions of allocations per solve
  // in-run pods placed per (origin row, node): the shared cap budget consumed
  // so far by all subgroups of an origin (oracle group_counts under okey)
  std::vector<int32_t> ex_placed(static_cast<size_t>(G) * Ne, 0);
  std::vector<int32_t> claim_placed(static_cast<size_t>(G) * N, 0);
  int32_t n_open = 0;

  std::memset(assign, 0, sizeof(int32_t) * G * N);
  std::memset(ex_assign, 0, sizeof(int32_t) * G * Ne);
  std::memset(unsched, 0, sizeof(int32_t) * G);
  std::memset(active, 0, sizeof(uint8_t) * N);
  for (int n = 0; n < N; ++n) nprov[n] = -1;

  for (int g = 0; g < G; ++g) {
    const int32_t* vec = group_vec + static_cast<size_t>(g) * R;
    const int32_t cap = group_cap[g];
    const int og = group_origin ? group_origin[g] : g;
    int64_t rem = group_count[g];

    // ---- 1) existing nodes, first-fit in index order ------------------------
    for (int e = 0; e < Ne && rem > 0; ++e) {
      if (!ex_feas[static_cast<size_t>(g) * Ne + e]) continue;
      for (int r = 0; r < R; ++r)
        avail[r] = ex_alloc[static_cast<size_t>(e) * R + r] -
                   ex_used[static_cast<size_t>(e) * R + r];
      int64_t fill = quotient(avail.data(), vec, R);
      // remaining cap: static residual minus pods placed in-run by any
      // subgroup sharing the origin (oracle: resident + group_counts[okey])
      const int64_t cap_e =
          (ex_cap ? ex_cap[static_cast<size_t>(g) * Ne + e] : cap) -
          ex_placed[static_cast<size_t>(og) * Ne + e];
      if (fill > cap_e) fill = cap_e;
      if (fill <= 0) continue;
      if (fill > rem) fill = rem;
      ex_assign[static_cast<size_t>(g) * Ne + e] = static_cast<int32_t>(fill);
      ex_placed[static_cast<size_t>(og) * Ne + e] += static_cast<int32_t>(fill);
      for (int r = 0; r < R; ++r)
        ex_used[static_cast<size_t>(e) * R + r] += static_cast<int32_t>(fill) * vec[r];
      rem -= fill;
    }

    // ---- 2) open claims, first-fit in creation order ------------------------
    // per-node max quotient over surviving feasible (t,s) options
    for (int n = 0; n < n_open; ++n) {
      m_n[n] = 0;
      if (!active[n] || rem <= 0) { q_nt[n] = -1; continue; }
      int pidx = nprov[n] < 0 ? 0 : nprov[n];
      const uint8_t* feas =
          group_feas + ((static_cast<size_t>(g) * Pv + pidx) * TS);
      const uint8_t* om = optmask.data() + static_cast<size_t>(n) * TS;
      int32_t qmax = -1;
      for (int t = 0; t < T; ++t) {
        bool any = false;
        for (int s = 0; s < S; ++s) {
          if (om[t * S + s] && feas[t * S + s]) { any = true; break; }
        }
        if (!any) { qt[t] = -1; continue; }
        for (int r = 0; r < R; ++r)
          avail[r] = alloc_t[static_cast<size_t>(t) * R + r] -
                     used[static_cast<size_t>(n) * R + r];
        qt[t] = quotient(avail.data(), vec, R);
        if (prov_pods_cap != nullptr) {
          int32_t capq = pods_cap_quotient(
              static_cast<int64_t>(prov_pods_cap[static_cast<size_t>(pidx) * T + t]) -
                  used[static_cast<size_t>(n) * R + pods_i],
              vec[pods_i]);
          if (capq < qt[t]) qt[t] = capq;
        }
        if (qt[t] > qmax) qmax = qt[t];
      }
      q_nt[n] = qmax;
      // per-claim budget shared across subgroups of the origin
      const int64_t cap_n =
          static_cast<int64_t>(cap) - claim_placed[static_cast<size_t>(og) * N + n];
      int64_t fill = qmax > cap_n ? cap_n : qmax;
      if (fill <= 0) continue;
      if (fill > rem) fill = rem;
      m_n[n] = static_cast<int32_t>(fill);
      claim_placed[static_cast<size_t>(og) * N + n] += m_n[n];
      rem -= fill;
      // place + shrink option mask: survive iff feasible for this group AND
      // the type still fits the node's new load (q_nt >= m_n)
      for (int r = 0; r < R; ++r)
        used[static_cast<size_t>(n) * R + r] += m_n[n] * vec[r];
      int pidx2 = nprov[n] < 0 ? 0 : nprov[n];
      const uint8_t* feas2 =
          group_feas + ((static_cast<size_t>(g) * Pv + pidx2) * TS);
      uint8_t* om2 = optmask.data() + static_cast<size_t>(n) * TS;
      for (int t = 0; t < T; ++t) {
        // recompute per-type quotient against the PRE-placement load (qt[t]
        // was computed above for all types of this node)
        bool fits = qt[t] >= m_n[n];
        for (int s = 0; s < S; ++s) {
          om2[t * S + s] =
              (om2[t * S + s] && feas2[t * S + s] && fits) ? 1 : 0;
        }
      }
      assign[static_cast<size_t>(g) * N + n] += m_n[n];
    }

    // ---- 3) bulk-open fresh nodes ------------------------------------------
    int32_t p = group_newprov[g];
    int64_t kstar = 0;
    std::vector<int32_t> ovh_p(overhead, overhead + R);
    if (p >= 0 && prov_overhead != nullptr)
      for (int r = 0; r < R; ++r)
        ovh_p[r] += prov_overhead[static_cast<size_t>(p) * R + r];
    if (p >= 0) {
      const uint8_t* feas =
          group_feas + ((static_cast<size_t>(g) * Pv + p) * TS);
      for (int t = 0; t < T; ++t) {
        bool any = false;
        for (int s = 0; s < S; ++s)
          if (feas[t * S + s]) { any = true; break; }
        for (int r = 0; r < R; ++r)
          avail[r] = alloc_t[static_cast<size_t>(t) * R + r] - ovh_p[r];
        qt[t] = quotient(avail.data(), vec, R);  // q0 (also reused below)
        if (prov_pods_cap != nullptr) {
          int32_t capq = pods_cap_quotient(
              static_cast<int64_t>(prov_pods_cap[static_cast<size_t>(p) * T + t]) -
                  ovh_p[pods_i],
              vec[pods_i]);
          if (capq < qt[t]) qt[t] = capq;
        }
        if (any && qt[t] > kstar) kstar = qt[t];
      }
    } else {
      for (int t = 0; t < T; ++t) qt[t] = -1;
    }
    if (kstar > cap) kstar = cap;
    if (kstar < 0) kstar = 0;
    int64_t n_new = kstar > 0 ? (rem + kstar - 1) / kstar : 0;
    if (n_new > N - n_open) n_new = N - n_open;
    int64_t placed_new = n_new > 0 ? (n_new - 1) * kstar : 0;
    int64_t last_cnt = rem - placed_new;
    if (last_cnt < 0) last_cnt = 0;
    if (last_cnt > kstar) last_cnt = kstar;
    for (int64_t i = 0; i < n_new; ++i) {
      int n = static_cast<int>(n_open + i);
      int64_t cnt = (i == n_new - 1) ? last_cnt : kstar;
      for (int r = 0; r < R; ++r)
        used[static_cast<size_t>(n) * R + r] =
            ovh_p[r] + static_cast<int32_t>(cnt) * vec[r];
      const uint8_t* feas =
          group_feas + ((static_cast<size_t>(g) * Pv + p) * TS);
      uint8_t* om = optmask.data() + static_cast<size_t>(n) * TS;
      for (int t = 0; t < T; ++t) {
        bool fits = qt[t] >= cnt;
        for (int s = 0; s < S; ++s)
          om[t * S + s] = (feas[t * S + s] && fits) ? 1 : 0;
      }
      active[n] = 1;
      nprov[n] = p;
      assign[static_cast<size_t>(g) * N + n] += static_cast<int32_t>(cnt);
      claim_placed[static_cast<size_t>(og) * N + n] += static_cast<int32_t>(cnt);
      rem -= cnt;
    }
    n_open += static_cast<int32_t>(n_new);
    unsched[g] = static_cast<int32_t>(rem);
  }

  // ---- decision: cheapest surviving option per active claim -----------------
  for (int n = 0; n < N; ++n) {
    int32_t best_rank = INT_BIG;
    int32_t best = -1;
    if (active[n]) {
      const uint8_t* om = optmask.data() + static_cast<size_t>(n) * TS;
      for (int t = 0; t < T; ++t) {
        for (int s = 0; s < S; ++s) {
          int32_t rank = om[t * S + s] ? tiebreak[t * S + s] : INT_BIG;
          if (rank < best_rank) {  // strict: first min wins (argmin parity)
            best_rank = rank;
            best = t * S + s;
          }
        }
      }
    }
    decided[n] = (active[n] && best_rank < INT_BIG) ? best : -1;
  }
  *n_open_out = n_open;
  return 0;
}

}  // extern "C"
