"""ctypes binding for the native (C++) FFD packer fallback.

Loads libktpack.so (hack/build_native.sh), rebuilding it on demand with g++
when the shared object is missing or older than its source. The binding
exposes native_pack() with the exact PackInputs/PackResult contract of the
JAX kernel (ops/packer.py) — bit-parity is enforced by
tests/test_native_pack.py.

Why native and not just the Python oracle: the fallback runs inside the
controller's scheduling-cycle budget when the TPU sidecar is down; the C++
scan is ~100-1000x the Python oracle's throughput and needs no JAX runtime.
(Reference analogue for graceful degradation: embedded static pricing
fallback, /root/reference/pkg/cloudprovider/pricing.go:100-116.)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ktpack.cc")
_LIB = os.path.join(_HERE, "libktpack.so")

_lock = threading.Lock()
_lib = None
_load_error: "Optional[NativeUnavailable]" = None


class NativeUnavailable(RuntimeError):
    pass


def _build() -> None:
    subprocess.run(
        ["g++", "-O2", "-Wall", "-shared", "-fPIC", "-o", _LIB, _SRC],
        check=True, capture_output=True, text=True,
    )


def _load():
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            # negative cache: don't re-spawn g++ on every fallback solve
            raise _load_error
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_LIB)
        except (OSError, subprocess.CalledProcessError) as e:
            _load_error = NativeUnavailable(f"native packer unavailable: {e}")
            raise _load_error
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.kt_pack.restype = ctypes.c_int
        lib.kt_pack.argtypes = (
            [i32p, i32p, i32p, i32p, i32p, u8p, i32p, i32p, i32p, i32p, u8p]
            + [i32p, i32p]                 # ex_cap, group_origin (nullable)
            + [i32p, i32p, ctypes.c_int]   # prov_overhead, prov_pods_cap, pods_i
            + [ctypes.c_int] * 7
            + [i32p, i32p, i32p, u8p, i32p, i32p, i32p]
        )
        _lib = lib
        return lib


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a), dtype=np.int32)


def _u8(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a), dtype=np.uint8)


def _ptr(a: np.ndarray):
    if a.dtype == np.int32:
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def native_pack(inputs, n_slots: int):
    """PackInputs -> PackResult via the C++ scan. Accepts the same (possibly
    jax-array) fields as pack_impl; everything is materialized to host numpy."""
    from ..ops.packer import PackResult

    lib = _load()
    alloc_t = _i32(inputs.alloc_t)
    tiebreak = _i32(inputs.tiebreak)
    group_vec = _i32(inputs.group_vec)
    group_count = _i32(inputs.group_count)
    group_cap = _i32(inputs.group_cap)
    group_feas = _u8(inputs.group_feas)
    group_newprov = _i32(inputs.group_newprov)
    overhead = _i32(inputs.overhead)
    ex_alloc = _i32(inputs.ex_alloc)
    ex_used = _i32(inputs.ex_used)
    ex_feas = _u8(inputs.ex_feas)
    ex_cap = getattr(inputs, "ex_cap", None)
    ex_cap = None if ex_cap is None else _i32(ex_cap)
    group_origin = getattr(inputs, "group_origin", None)
    group_origin = None if group_origin is None else _i32(group_origin)
    prov_overhead = getattr(inputs, "prov_overhead", None)
    prov_pods_cap = getattr(inputs, "prov_pods_cap", None)
    prov_overhead = None if prov_overhead is None else _i32(prov_overhead)
    prov_pods_cap = None if prov_pods_cap is None else _i32(prov_pods_cap)

    G, Pv, T, S = group_feas.shape
    R = group_vec.shape[1]
    Ne = ex_alloc.shape[0]
    N = int(n_slots)

    assign = np.zeros((G, N), np.int32)
    ex_assign = np.zeros((G, Ne), np.int32)
    unsched = np.zeros((G,), np.int32)
    active = np.zeros((N,), np.uint8)
    nprov = np.zeros((N,), np.int32)
    decided = np.zeros((N,), np.int32)
    n_open = np.zeros((1,), np.int32)

    from ..apis import wellknown as wk

    null_i32 = ctypes.POINTER(ctypes.c_int32)()
    rc = lib.kt_pack(
        _ptr(alloc_t), _ptr(tiebreak), _ptr(group_vec), _ptr(group_count),
        _ptr(group_cap), _ptr(group_feas), _ptr(group_newprov), _ptr(overhead),
        _ptr(ex_alloc), _ptr(ex_used), _ptr(ex_feas),
        null_i32 if ex_cap is None else _ptr(ex_cap),
        null_i32 if group_origin is None else _ptr(group_origin),
        null_i32 if prov_overhead is None else _ptr(prov_overhead),
        null_i32 if prov_pods_cap is None else _ptr(prov_pods_cap),
        wk.RESOURCE_INDEX[wk.RESOURCE_PODS],
        G, Pv, T, S, R, Ne, N,
        _ptr(assign), _ptr(ex_assign), _ptr(unsched), _ptr(active),
        _ptr(nprov), _ptr(decided), _ptr(n_open),
    )
    if rc != 0:
        raise NativeUnavailable(f"kt_pack returned {rc}")
    return PackResult(
        assign=assign, ex_assign=ex_assign, unsched=unsched,
        used=np.zeros((0,), np.int32), active=active.astype(bool),
        nprov=nprov, decided=decided, n_open=np.int32(n_open[0]),
    )
