"""Anti-thrash admission filter for the resident-solver LRU.

Under catalog churn the content-hash LRU's failure mode is an eviction
storm: a stream of one-shot catalog hashes (a tenant mutating its
catalog every submission) each lands in the cache, evicts a warm solver
some OTHER tenant is about to reuse, and is itself evicted one request
later — the cache does maximal work to retain nothing. The classic fix
is frequency-based admission (TinyLFU's shape): a newcomer must prove
it is not one-shot before it may displace a warm entry.

:class:`AdmissionFilter` reuses the space-saving sketch the cardinality
guard already ships (`metrics/cardinality.py` TenantTracker) as that
frequency estimate: every solver-key offer lands in a small sketch, and
a key has "earned" residency once its estimated count reaches
``EARN_COUNT``. The solver service consults it ONLY when the cache is
full and eviction would be forced — an unearned key is still served
(the solve itself is never refused here; backpressure is the guard's
job), it just runs un-cached instead of evicting a warm solver.

Strict-noop contract: the service consults the filter only while the
plane is enabled; :meth:`offer` itself also checks, so a disabled plane
moves no sketch state and no counter in :func:`counters`.
"""
from __future__ import annotations

import threading
from typing import Optional

from . import metrics as om
from . import state
from ..metrics.cardinality import TenantTracker

# estimated observations before a key may displace a warm resident
# (2 = "seen again since first sight": one-shot hashes never qualify)
EARN_COUNT = 2

# sketch width: frequency estimation over recent solver keys, NOT a
# tenant table — 4x the service LRU capacity is enough to tell one-shot
# traffic from the hot set without tracking the whole churn stream
DEFAULT_SKETCH_K = 16

_counters_lock = threading.Lock()
_counters = {
    "filter_offers": 0,
    "filter_earned": 0,
    "filter_probation": 0,
    "lowwater_passes": 0,
    "lowwater_evictions": 0,
}


def _count(key: str, amount: int = 1) -> None:
    with _counters_lock:
        _counters[key] += amount


def counters() -> "dict[str, int]":
    with _counters_lock:
        return dict(_counters)


def note_lowwater(evicted: int) -> None:
    """One pressure low-water eviction pass freed `evicted` residents
    (service.py cites this so the pass is visible in activity())."""
    _count("lowwater_passes")
    if evicted:
        _count("lowwater_evictions", evicted)
        om.EVICTIONS.inc(evicted, cause="pressure-low-water")


class AdmissionFilter:
    """Frequency-gated admission for a full LRU (module docstring)."""

    def __init__(self, k: "Optional[int]" = None,
                 earn_count: int = EARN_COUNT):
        self._lock = threading.Lock()
        self._sketch = TenantTracker(DEFAULT_SKETCH_K if k is None else k)
        self.earn_count = earn_count

    def offer(self, key: str) -> bool:
        """One observation of solver key `key` (the hbm_key string).
        Returns True when the key has earned the right to displace a
        warm resident; False keeps it on probation (serve uncached)."""
        if not state.enabled():
            return True  # disabled: behave exactly like the plain LRU
        with self._lock:
            self._sketch.offer(key)
            # earn on the sketch's LOWER bound (count - error), never the
            # raw count: space-saving displacement hands a newcomer the
            # evicted slot's floor, so once one-shot traffic saturates
            # the sketch every fresh hash would inherit count >= 2 and
            # "earn" instantly — the exact flood this filter exists to
            # keep out of the cache
            earned = self._sketch.lower_bound(key) >= self.earn_count
        _count("filter_offers")
        if earned:
            _count("filter_earned")
            om.ADMISSION.inc(verdict="earned")
        else:
            _count("filter_probation")
            om.ADMISSION.inc(verdict="probation")
        return earned

    def seen(self, key: str) -> float:
        """Estimated observation count (upper bound; test surface)."""
        with self._lock:
            return self._sketch.tracked().get(key, 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"k": self._sketch.k,
                    "earn_count": self.earn_count,
                    "offers": self._sketch.offers,
                    "sketch_evictions": self._sketch.evictions,
                    "tracked": len(self._sketch.tracked())}
