"""Metric families for the overload-control plane.

All ``karpenter_overload_*`` families live here (fleet/metrics.py
idiom: module-level registration against the process registry so the
docs generator's boot-and-walk sees them). None carries a tenant label
— per-tenant shed attribution already flows through the guarded
``karpenter_fleet_tenant_shed_total`` family with the new ``overload-*``
reasons, so this module adds no cardinality surface.

Strict-noop note: these families are written ONLY from code paths gated
on :func:`..overload.enabled` — with the plane disabled they are as
frozen as the :func:`..overload.activity` counters the chaos invariant
diffs.
"""
from __future__ import annotations

from ..metrics import NAMESPACE, REGISTRY

PRESSURE = REGISTRY.gauge(
    f"{NAMESPACE}_overload_pressure",
    "Bounded [0,1] overload pressure per input (backlog/deadline/hbm/rss) "
    "plus the max as input=\"overall\".", ("input",))

LEVEL = REGISTRY.gauge(
    f"{NAMESPACE}_overload_level",
    "Current backpressure ladder level (0=accept 1=defer 2=shed "
    "3=brownout).")

DECISIONS = REGISTRY.counter(
    f"{NAMESPACE}_overload_decisions_total",
    "Per-submission guard verdicts (accept/defer/shed/brownout).",
    ("decision",))

TRANSITIONS = REGISTRY.counter(
    f"{NAMESPACE}_overload_transitions_total",
    "Ladder level transitions by direction (up moves may skip levels; "
    "down moves are always single-step).", ("direction",))

ADMISSION = REGISTRY.counter(
    f"{NAMESPACE}_overload_admission_offers_total",
    "Resident-LRU admission-filter verdicts: \"earned\" keys may evict a "
    "warm solver, \"probation\" keys may only fill free capacity.",
    ("verdict",))

EVICTIONS = REGISTRY.counter(
    f"{NAMESPACE}_overload_evictions_total",
    "Plane-governed resident-solver evictions by cause (capacity / "
    "the pressure low-water pass).", ("cause",))

THRASH_RATIO = REGISTRY.gauge(
    f"{NAMESPACE}_overload_eviction_thrash_ratio",
    "Share of resident-solver installs that re-installed a recently "
    "evicted key (the eviction-storm signature; measured always-on at "
    "the service, published here while the plane is enabled).")
