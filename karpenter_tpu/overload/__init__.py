"""Overload-control plane: graduated backpressure + anti-thrash eviction.

Three cooperating pieces (ISSUE 20):

* :class:`OverloadGuard` (guard.py) — one bounded [0,1] pressure signal
  from frontend backlog vs the fairness contract, remaining cycle
  deadline budget, HBM ledger pressure vs the capacity cap, and host
  RSS vs a soft cap; driving a graduated ladder
  accept -> defer -> shed -> brownout with spike-up/monotone-down
  hysteresis. Brownout rides the existing resilience DegradeLadder.
  Every shed the frontend takes on a guard verdict is a DecisionRecord
  citing an ``overload-*`` SHED_REASONS entry.
* :class:`AdmissionFilter` (eviction.py) — frequency-gated admission
  for the solver service's content-hash resident LRU (the space-saving
  sketch from metrics/cardinality.py): a one-shot catalog hash must
  earn residency before it may evict a warm solver, and HBM-pressure
  eviction drains to a low-water mark in one pass instead of
  per-request.
* ``karpenter_overload_*`` metric families (metrics.py) and a statusz
  section; chaos fault kinds host-memory-pressure / watch-event-flood /
  kube-429-throttle exercise the plane deterministically.

Strict-noop contract: with ``KARPENTER_TPU_OVERLOAD=0`` nothing here
runs and no counter in :func:`activity` moves (chaos invariant
``overload-strict-noop``); frontend admission decisions are identical
to a build without the plane.
"""
from __future__ import annotations

from .eviction import AdmissionFilter, DEFAULT_SKETCH_K, EARN_COUNT
from .guard import (DEFAULT_TENANT_BACKLOG_MAX, OverloadGuard,
                    RSS_SOFT_CAP_ENV, TENANT_BACKLOG_MAX_ENV,
                    host_rss_bytes, note_queue_overflow,
                    rss_soft_cap_default, set_simulated_rss,
                    tenant_backlog_max_default)
from .state import FLAG_ENV, disabled, enabled, set_enabled

from . import eviction as _eviction_mod
from . import guard as _guard_mod

__all__ = [
    "AdmissionFilter", "DEFAULT_SKETCH_K", "DEFAULT_TENANT_BACKLOG_MAX",
    "EARN_COUNT", "FLAG_ENV", "OverloadGuard", "RSS_SOFT_CAP_ENV",
    "TENANT_BACKLOG_MAX_ENV", "activity", "disabled", "enabled",
    "host_rss_bytes", "note_queue_overflow", "rss_soft_cap_default",
    "set_enabled", "set_simulated_rss", "tenant_backlog_max_default",
]


def activity() -> "dict[str, int]":
    """Flat monotone counters for the chaos strict-noop diff: every
    number here must stay frozen while the plane is disabled (guard
    observations/verdicts/transitions, admission-filter offers, the
    low-water eviction passes)."""
    out: "dict[str, int]" = {}
    out.update(_guard_mod.counters())
    out.update(_eviction_mod.counters())
    return out
