"""OverloadGuard: graduated backpressure from bounded pressure inputs.

The fleet frontend already *measures* everything that matters under
sustained overload — queue depth vs the fairness contract, per-request
deadline budget, the HBM ledger's resident-bytes pressure — but nothing
*acts* on those signals until a request has already burned queue time or
forced a thrashing eviction. The guard folds them (plus host RSS vs a
new soft cap) into one bounded pressure signal and drives a graduated
ladder::

    accept -> defer -> shed -> brownout

Each input is clamped to [0, 1] and the pressure is their max — a
replica one byte from its HBM cap is overloaded no matter how short its
queue is. Levels rise as soon as pressure crosses an entry threshold
and fall ONE level at a time, only after pressure drops below
``threshold - HYSTERESIS`` — the ladder can spike up but recovers
monotonically, so it can never flap across a boundary (the churn
drill's brownout audit and tests/test_overload.py enforce exactly
that edge behavior on FakeClock).

Fairness contract under pressure: the guard only defers/sheds tenants
whose CURRENT backlog already exceeds their registered weight
(``decide(over_rate=True)``); a within-weight tenant is accepted at
every level, so the storm drill's fairness-never-starves invariant
holds while over-rate tenants absorb all sheds.

Brownout drives the existing resilience DegradeLadder (chain
``overload``, rungs ``normal -> brownout``) so the rung is observable
in the same ``karpenter_resilience_degrade_rung`` gauge every other
fallback chain uses, with the ladder's own single-step probe recovery.

Strict-noop contract: every public method checks :func:`state.enabled`
first; disabled, ``observe`` reports level 0, ``decide`` returns
``accept``, and no counter in :func:`counters` moves.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from . import metrics as om
from . import state
from ..utils.clock import Clock

log = logging.getLogger("karpenter.overload.guard")

# -- env knobs (crossover-knob validation idiom: solver/buckets.py) -----------

RSS_SOFT_CAP_ENV = "KARPENTER_TPU_RSS_SOFT_CAP_BYTES"

TENANT_BACKLOG_MAX_ENV = "KARPENTER_TPU_TENANT_BACKLOG_MAX"
DEFAULT_TENANT_BACKLOG_MAX = 64


def rss_soft_cap_default() -> "Optional[int]":
    """The host-RSS soft cap in bytes, validated: unset or garbage means
    the RSS input is disarmed (contributes 0 pressure) — same contract
    as the HBM capacity knob (buckets.hbm_capacity_default)."""
    raw = os.environ.get(RSS_SOFT_CAP_ENV)
    if raw is None or raw.strip() == "":
        return None
    try:
        cap = int(raw)
    except ValueError:
        log.warning("%s=%r is not an integer; RSS pressure disarmed",
                    RSS_SOFT_CAP_ENV, raw)
        return None
    if cap <= 0:
        log.warning("%s=%d is <= 0; RSS pressure disarmed",
                    RSS_SOFT_CAP_ENV, cap)
        return None
    return cap


def tenant_backlog_max_default() -> int:
    """The per-tenant frontend backlog bound, validated: a garbage value
    warns and falls back, < 1 clamps to 1 (a zero-depth queue could
    never admit anything)."""
    raw = os.environ.get(TENANT_BACKLOG_MAX_ENV)
    if raw is None:
        return DEFAULT_TENANT_BACKLOG_MAX
    try:
        bound = int(raw)
    except ValueError:
        log.warning("%s=%r is not an integer; falling back to %d",
                    TENANT_BACKLOG_MAX_ENV, raw,
                    DEFAULT_TENANT_BACKLOG_MAX)
        return DEFAULT_TENANT_BACKLOG_MAX
    if bound < 1:
        log.warning("%s=%d is < 1; clamping to 1",
                    TENANT_BACKLOG_MAX_ENV, bound)
        return 1
    return bound


# -- host RSS (real or chaos-simulated) ---------------------------------------

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

_sim_lock = threading.Lock()
_simulated_rss: "Optional[int]" = None


def set_simulated_rss(nbytes: "Optional[int]") -> None:
    """Chaos hook (fault kind ``host-memory-pressure``): override what
    :func:`host_rss_bytes` reports until cleared with None. Deterministic
    where real RSS is not — the drill and tests use it exclusively."""
    global _simulated_rss
    with _sim_lock:
        _simulated_rss = None if nbytes is None else int(nbytes)
    if state.enabled():
        _count("rss_simulated_sets")


def host_rss_bytes() -> int:
    """Current resident set size: the chaos-simulated value when one is
    armed, else /proc/self/statm (0 where unreadable — RSS pressure is
    advisory, never load-bearing)."""
    with _sim_lock:
        if _simulated_rss is not None:
            return _simulated_rss
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return 0


# -- plane-gated monotone counters (overload.activity()) ----------------------

_counters_lock = threading.Lock()
_counters = {
    "guard_observations": 0,
    "guard_transitions_up": 0,
    "guard_transitions_down": 0,
    "guard_accepts": 0,
    "guard_defers": 0,
    "guard_sheds": 0,
    "guard_brownout_sheds": 0,
    "rss_simulated_sets": 0,
    "queue_overflow_sheds": 0,
}


def _count(key: str, amount: int = 1) -> None:
    with _counters_lock:
        _counters[key] += amount


def counters() -> "dict[str, int]":
    with _counters_lock:
        return dict(_counters)


def note_queue_overflow(n: int = 1) -> None:
    """The frontend's per-tenant backlog bound dropped `n` oldest queued
    tickets (callers gate on :func:`state.enabled`; counted here so the
    overflow sheds show up in overload.activity())."""
    _count("queue_overflow_sheds", n)


class OverloadGuard:
    """Per-replica graduated backpressure (module docstring)."""

    LEVELS = ("accept", "defer", "shed", "brownout")
    # entry thresholds per level (index aligned with LEVELS; accept is
    # the floor). Pressure >= ENTER[i] raises the level to i.
    ENTER = (0.0, 0.50, 0.75, 0.90)
    # a level is left (one step down) only once pressure has dropped
    # below its OWN entry threshold minus this margin — spike up,
    # recover monotonically, never flap on a boundary
    HYSTERESIS = 0.15

    def __init__(self, clock: "Optional[Clock]" = None, ladder=None,
                 rss_soft_cap: "Optional[int]" = None):
        self.clock = clock or Clock()
        self.rss_soft_cap = (rss_soft_cap if rss_soft_cap is not None
                             else rss_soft_cap_default())
        self._lock = threading.Lock()
        self._level = 0
        self._pressure = 0.0
        self._inputs: "dict[str, float]" = {}
        self.transitions: "list[dict]" = []
        # brownout rides the existing resilience DegradeLadder so the
        # rung shows up in the same gauge as every other fallback chain;
        # callers may inject their own (the frontend wires the hub's)
        self.ladder = ladder

    # -- the pressure signal ---------------------------------------------------

    @staticmethod
    def _clamp(x: "Optional[float]") -> float:
        if x is None:
            return 0.0
        return 0.0 if x < 0.0 else (1.0 if x > 1.0 else float(x))

    def _hbm_input(self) -> float:
        # lazy import: the guard must stay importable without the solver
        # stack (same reason statusz's hbm section imports lazily)
        try:
            from ..solver.buckets import HBM
            return self._clamp(HBM.pressure())
        except Exception:  # noqa: BLE001 — advisory input, never raises
            return 0.0

    def _rss_input(self) -> float:
        if self.rss_soft_cap is None:
            return 0.0
        return self._clamp(host_rss_bytes() / self.rss_soft_cap)

    def observe(self, *, backlog: float = 0.0,
                deadline: float = 0.0) -> int:
        """Recompute pressure from the caller's bounded inputs (backlog:
        queued / fairness capacity; deadline: consumed share of the cycle
        budget) plus the live HBM ledger and host RSS. Returns the
        (possibly transitioned) ladder level index."""
        if not state.enabled():
            return 0
        inputs = {
            "backlog": self._clamp(backlog),
            "deadline": self._clamp(deadline),
            "hbm": self._hbm_input(),
            "rss": self._rss_input(),
        }
        pressure = max(inputs.values())
        with self._lock:
            self._inputs = inputs
            self._pressure = pressure
            level = self._level
            # rise: straight to the highest level whose threshold the
            # pressure meets (a spike to 0.95 must brown out NOW, not
            # three observes from now)
            target = max(i for i, t in enumerate(self.ENTER)
                         if pressure >= t)
            if target > level:
                self._move(level, target, pressure)
            elif level > 0 and pressure < self.ENTER[level] - self.HYSTERESIS:
                # fall: one step per observe — monotone recovery
                self._move(level, level - 1, pressure)
            level = self._level
        _count("guard_observations")
        for name, v in inputs.items():
            om.PRESSURE.set(v, input=name)
        om.PRESSURE.set(pressure, input="overall")
        om.LEVEL.set(level)
        self._drive_ladder(level)
        return level

    def _move(self, frm: int, to: int, pressure: float) -> None:
        """Callers hold self._lock."""
        self._level = to
        self.transitions.append({
            "ts": round(self.clock.now(), 3), "from": frm, "to": to,
            "pressure": round(pressure, 4)})
        _count("guard_transitions_up" if to > frm
               else "guard_transitions_down")
        om.TRANSITIONS.inc(direction="up" if to > frm else "down")

    def _drive_ladder(self, level: int) -> None:
        """Keep the DegradeLadder's rung in lockstep with brownout
        through its OWN protocol: fail the current rung while browned
        out (a due probe fails too — staying down is correct), succeed
        the start rung otherwise (a due probe's success is what climbs
        back to rung 0, single-step, exactly like every other chain)."""
        ladder = self.ladder
        if ladder is None:
            return
        rung = ladder.start_rung()
        if level >= 3:
            ladder.record_failure(rung)
        else:
            ladder.record_success(rung)

    # -- per-submission decisions ----------------------------------------------

    def decide(self, *, over_rate: bool) -> str:
        """The verdict for ONE submission at the current level: "accept",
        "defer" (requeue within the starvation bound), "shed", or
        "brownout" (shed, attributed to the brownout). Within-weight
        tenants (over_rate=False) are accepted at EVERY level — the
        fairness contract is the one thing pressure never buys."""
        if not state.enabled():
            return "accept"
        with self._lock:
            level = self._level
        if not over_rate or level == 0:
            _count("guard_accepts")
            om.DECISIONS.inc(decision="accept")
            return "accept"
        if level == 1:
            _count("guard_defers")
            om.DECISIONS.inc(decision="defer")
            return "defer"
        if level == 2:
            _count("guard_sheds")
            om.DECISIONS.inc(decision="shed")
            return "shed"
        _count("guard_brownout_sheds")
        om.DECISIONS.inc(decision="brownout")
        return "brownout"

    # -- observability ---------------------------------------------------------

    def level(self) -> int:
        with self._lock:
            return self._level

    def level_name(self) -> str:
        with self._lock:
            return self.LEVELS[self._level]

    def pressure(self) -> float:
        with self._lock:
            return self._pressure

    def snapshot(self) -> dict:
        with self._lock:
            return {"level": self._level,
                    "level_name": self.LEVELS[self._level],
                    "pressure": round(self._pressure, 4),
                    "inputs": {k: round(v, 4)
                               for k, v in sorted(self._inputs.items())},
                    "rss_soft_cap_bytes": self.rss_soft_cap,
                    "transitions": len(self.transitions)}

    def evidence(self) -> dict:
        """The drill-auditable transition ledger (brownout monotone
        hysteresis: every down-move steps exactly one level)."""
        with self._lock:
            return {"levels": list(self.LEVELS),
                    "enter": list(self.ENTER),
                    "hysteresis": self.HYSTERESIS,
                    "final_level": self._level,
                    "transitions": [dict(t) for t in self.transitions]}
