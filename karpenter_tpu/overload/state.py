"""Global on/off switch for the overload-control plane.

The overload plane is advisory-never-load-bearing (same contract as the
profiling/explain/membership/incremental/spot planes): every producer —
the pressure guard, the admission frequency filter, the low-water
eviction pass, the backlog bound — checks :func:`enabled` before doing
ANY work, so disabling the plane is a strict no-op (zero counters, no
deferred or shed tickets, the resident-solver LRU behaves exactly like
the plain pre-plane eviction loop). The chaos drill enforces exactly
that invariant (``overload-strict-noop``) with two-window evidence:
activity counters frozen while disabled AND the frontend's admission
decisions identical to the baseline.

Default is ON (the guard is cheap: a handful of bounded ratios per
submission); ``KARPENTER_TPU_OVERLOAD=0`` (or ``false``/``off``/``no``)
disables it at process start, and :func:`set_enabled` /
:func:`disabled` flip it at runtime (chaos drills, the churn drill's
admission-filter A/B window).
"""
from __future__ import annotations

import contextlib
import os
import threading

FLAG_ENV = "KARPENTER_TPU_OVERLOAD"
_FALSY = ("0", "false", "off", "no")

_lock = threading.Lock()
_enabled = os.environ.get(FLAG_ENV, "1").strip().lower() not in _FALSY


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the plane; returns the previous state (restore token)."""
    global _enabled
    with _lock:
        prev = _enabled
        _enabled = bool(on)
        return prev


@contextlib.contextmanager
def disabled():
    """Scoped hard-off: A/B baselines and the chaos strict-noop drill."""
    prev = set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)
