"""Client-side fleet failover: re-route, hedge, quarantine.

The membership manager (membership.py) heals the router on the seconds
scale; this module is the request-scale complement. A solve in flight
when its home replica dies must not wait K missed beats to be told —
the client re-routes it to the tenant's NEXT rendezvous choice
(``FleetRouter.ranked``), which is by construction the replica the
tenant would remap to anyway, so client failover and membership remap
always land the tenant in the same place.

Discipline, not heroics:

* every extra attempt — failover hop or hedge — flows through the
  existing resilience primitives: one shared ``RetryBudget`` bounds the
  client's total retry amplification, per-replica ``CircuitBreaker``s
  fail known-dead replicas fast, and the ``check_no_adhoc_retry`` lint
  stays green because there is no sleep-in-except loop here at all
  (failover re-routes immediately; waiting out a dead replica is the
  membership plane's job).
* **bounded tail hedging** — the home-replica attempt carries a hedge
  horizon (``HEDGE_HORIZON_S``): if the primary is merely SLOW (times
  out at the horizon rather than failing), the client fires exactly one
  hedge to the next choice, charged to the retry budget like any retry.
  At most one hedge per request, ever — hedging is a tail-latency tool,
  not a second retry channel.
* **explicit cold remaps** — serving a tenant from a replica other than
  its last home means the new home has no synced catalog and no warm
  compiled programs: the client counts the warm-state loss, and the
  ``on_remap`` hook re-Syncs the tenant's catalog before the solve is
  handed over (the drill ledgers the loss; ~1/R of tenants per replica
  death, the rendezvous contract).
* **poison-pill quarantine** — a request implicated in crashing or
  timing out ``VICTIM_LIMIT`` (two) distinct replicas is quarantined:
  shed with the vocabulary reason ``"poison-quarantine"`` as a ``shed``
  DecisionRecord in the explain plane, instead of hunting a third
  victim. The chaos partition drill's ``quarantine-bounds-cascade``
  invariant enforces the blast radius.

Transports are callables ``transport(tenant_id, request, timeout_s)``
raising :class:`ReplicaUnavailable` (connection refused — the replica is
already down), :class:`ReplicaTimeout` (slow or blackholed past the
deadline), or :class:`ReplicaCrashed` (the request killed its server).
Only the latter two count the request a victim: a refused connection
indicts the replica, not the request.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from typing import Callable, Optional

from ..explain import note_shed
from ..resilience import CircuitBreaker, RetryBudget, RetryPolicy
from ..utils.clock import Clock
from . import metrics as fleet_metrics
from .metrics import tenant_label

# a request is quarantined once this many DISTINCT replicas fell to it
VICTIM_LIMIT = 2


class ReplicaUnavailable(RuntimeError):
    """The replica refused/reset the connection — it is down or
    unreachable; the request is innocent."""

    fault_kind = "unavailable"  # metrics/ledger vocabulary for the cause

    def __init__(self, replica: str, detail: str = ""):
        super().__init__(
            f"replica {replica} unavailable{': ' + detail if detail else ''}")
        self.replica = replica


class ReplicaTimeout(ReplicaUnavailable):
    """The replica did not answer within the deadline (slow, or
    blackholed by a partition)."""

    fault_kind = "timeout"


class ReplicaCrashed(ReplicaUnavailable):
    """The replica died WHILE serving this request — the request is a
    suspect."""

    fault_kind = "crash"


class RequestQuarantined(RuntimeError):
    """The request is in the poison quarantine ring: shed, not served."""

    def __init__(self, tenant_id: str, fingerprint: str):
        super().__init__(
            f"request {fingerprint} from tenant {tenant_id} is quarantined "
            f"(implicated in {VICTIM_LIMIT} replica failures)")
        self.tenant_id = tenant_id
        self.fingerprint = fingerprint


class FailoverExhausted(RuntimeError):
    """Every eligible replica was tried (or the retry budget ran dry)."""

    def __init__(self, tenant_id: str, detail: str):
        super().__init__(f"failover exhausted for tenant {tenant_id}: "
                         f"{detail}")
        self.tenant_id = tenant_id


def request_fingerprint(request) -> str:
    """Content-addressed identity for the quarantine ring: the same
    poison payload resubmitted by any tenant hits the same ring entry.
    blake2b over canonical JSON (the repo's content-hash primitive) —
    never id() or hash(), which are per-process."""
    try:
        blob = json.dumps(request, sort_keys=True, default=repr)
    except (TypeError, ValueError):
        blob = repr(request)
    return hashlib.blake2b(blob.encode("utf-8"), digest_size=8).hexdigest()


class QuarantineRing:
    """Bounded LRU of suspect request fingerprints and their victim
    replicas. ``note_victim`` returns True exactly once per fingerprint
    — on the observation that trips quarantine — so callers can fire the
    edge (shed record, metric) without double counting."""

    def __init__(self, capacity: int = 64,
                 victim_limit: int = VICTIM_LIMIT):
        self.capacity = max(1, capacity)
        self.victim_limit = max(1, victim_limit)
        self._lock = threading.Lock()
        self._victims: "OrderedDict[str, set]" = OrderedDict()
        self._quarantined: "OrderedDict[str, bool]" = OrderedDict()

    def note_victim(self, fingerprint: str, replica: str) -> bool:
        with self._lock:
            victims = self._victims.get(fingerprint)
            if victims is None:
                victims = set()
                self._victims[fingerprint] = victims
                while len(self._victims) > self.capacity:
                    self._victims.popitem(last=False)
            self._victims.move_to_end(fingerprint)
            victims.add(replica)
            if len(victims) >= self.victim_limit \
                    and fingerprint not in self._quarantined:
                self._quarantined[fingerprint] = True
                while len(self._quarantined) > self.capacity:
                    self._quarantined.popitem(last=False)
                return True
            return False

    def is_quarantined(self, fingerprint: str) -> bool:
        with self._lock:
            return fingerprint in self._quarantined

    def victims(self, fingerprint: str) -> "list[str]":
        with self._lock:
            return sorted(self._victims.get(fingerprint, ()))

    def evidence(self) -> dict:
        """Deterministic state for the chaos artifact and statusz."""
        with self._lock:
            return {
                "victim_limit": self.victim_limit,
                "quarantined": sorted(self._quarantined),
                "victims": {fp: sorted(v)
                            for fp, v in sorted(self._victims.items())},
            }


class FailoverClient:
    """Routes one tenant's solve to its rendezvous home with failover,
    hedging and quarantine. Shares ONE retry budget across every replica
    (amplification is a client-wide resource) and one breaker per
    replica (health is per-replica)."""

    HEDGE_HORIZON_S = 0.25    # slow-primary deadline before the one hedge
    BREAKER_THRESHOLD = 3     # consecutive failures before fail-fast
    BREAKER_RECOVERY_S = 10.0

    def __init__(self, router, transports: "dict[str, Callable]",
                 clock: "Optional[Clock]" = None, *,
                 quarantine: "Optional[QuarantineRing]" = None,
                 on_remap: "Optional[Callable[[str, str], None]]" = None,
                 recorder=None, seed: int = 0,
                 hedge_horizon_s: "Optional[float]" = None,
                 budget: "Optional[RetryBudget]" = None):
        self.router = router
        self.transports = transports
        self.clock = clock or Clock()
        self.quarantine = quarantine or QuarantineRing()
        # on_remap(tenant_id, new_replica): re-Sync the tenant's catalog
        # on its new home before the solve proceeds (cold-start handling)
        self.on_remap = on_remap
        self.recorder = recorder
        self.seed = seed
        self.hedge_horizon_s = (hedge_horizon_s if hedge_horizon_s
                                is not None else self.HEDGE_HORIZON_S)
        self.budget = budget or RetryBudget()
        self._lock = threading.Lock()
        self._policies: "dict[str, RetryPolicy]" = {}
        self._home: "dict[str, str]" = {}   # tenant -> last served replica
        self.warm_state_losses = 0          # cold remaps observed

    def _policy(self, replica: str) -> RetryPolicy:
        """Per-replica resilience edge, built lazily: one breaker per
        replica, the client-wide shared budget, FakeClock-safe (no real
        sleeps are ever issued — failover re-routes, it never waits)."""
        with self._lock:
            policy = self._policies.get(replica)
            if policy is None:
                breaker = CircuitBreaker(
                    f"replica:{replica}", clock=self.clock,
                    failure_threshold=self.BREAKER_THRESHOLD,
                    recovery_time=self.BREAKER_RECOVERY_S,
                    recorder=self.recorder)
                policy = RetryPolicy(
                    f"replica:{replica}", clock=self.clock,
                    seed=self.seed, budget=self.budget, breaker=breaker,
                    sleep=lambda _delay: None)
                self._policies[replica] = policy
            return policy

    # -- the solve path -----------------------------------------------------

    def solve(self, tenant_id: str, request, timeout_s:
              "Optional[float]" = None):
        """One solve with failover. Raises RequestQuarantined (the shed),
        FailoverExhausted, or LookupError on an empty fleet."""
        fp = request_fingerprint(request)
        if self.quarantine.is_quarantined(fp):
            self._shed_quarantined(tenant_id, fp)
        candidates = self.router.ranked(tenant_id)
        if not candidates:
            raise LookupError("fleet has no replicas")
        hedge_spent = False
        last_detail = "no replica attempted"
        for i, replica in enumerate(candidates):
            policy = self._policy(replica)
            if i > 0 and not policy.try_retry():
                # budget dry: give up NOW (overload control beats heroics)
                raise FailoverExhausted(
                    tenant_id, f"retry budget exhausted after {last_detail}")
            breaker = policy.breaker
            if not breaker.allow():
                fleet_metrics.FAILOVER_REROUTES.inc(cause="breaker-open")
                last_detail = f"replica {replica} breaker open"
                continue
            # the home attempt runs under the hedge horizon: a slow (not
            # dead) primary times out there and the one hedge fires; the
            # tighter of (caller deadline, horizon) applies
            attempt_timeout = timeout_s
            if i == 0 and not hedge_spent:
                attempt_timeout = (self.hedge_horizon_s if timeout_s is None
                                   else min(timeout_s, self.hedge_horizon_s))
            try:
                result = self.transports[replica](
                    tenant_id, request, attempt_timeout)
            except ReplicaCrashed as e:
                policy.note_failure()
                last_detail = str(e)
                fleet_metrics.FAILOVER_REROUTES.inc(cause="crash")
                if self._note_victim(tenant_id, fp, replica):
                    self._shed_quarantined(tenant_id, fp)
            except ReplicaTimeout as e:
                policy.note_failure()
                last_detail = str(e)
                fleet_metrics.FAILOVER_REROUTES.inc(cause="timeout")
                if i == 0 and not hedge_spent:
                    # the tail hedge: one budgeted backup attempt, fired
                    # only for the slow-primary case (metrics outcome is
                    # judged when the backup resolves below)
                    hedge_spent = True
                    fleet_metrics.FAILOVER_HEDGES.inc(outcome="fired")
                if self._note_victim(tenant_id, fp, replica):
                    self._shed_quarantined(tenant_id, fp)
            except ReplicaUnavailable as e:
                # refused outright: the replica is down, the request is
                # innocent — no victim note
                policy.note_failure()
                last_detail = str(e)
                fleet_metrics.FAILOVER_REROUTES.inc(cause="unavailable")
            else:
                policy.note_success()
                if hedge_spent and i == 1:
                    fleet_metrics.FAILOVER_HEDGES.inc(outcome="win")
                self._note_served(tenant_id, replica)
                return result
        raise FailoverExhausted(tenant_id, last_detail)

    # -- internals ----------------------------------------------------------

    def _note_victim(self, tenant_id: str, fp: str, replica: str) -> bool:
        tripped = self.quarantine.note_victim(fp, replica)
        if tripped:
            fleet_metrics.FAILOVER_QUARANTINED.inc()
            if self.recorder is not None:
                self.recorder.warning(
                    f"fleet/tenant/{tenant_id}", "RequestQuarantined",
                    f"request {fp} quarantined after crashing/timing out "
                    f"{self.quarantine.victim_limit} replicas: "
                    f"{self.quarantine.victims(fp)}")
        return tripped

    def _shed_quarantined(self, tenant_id: str, fp: str) -> None:
        """The quarantine shed: a DecisionRecord with a vocabulary
        reason (explain plane), the fleet shed counters, then the
        raise — the caller gets an explicit refusal, never a third
        victim."""
        now = self.clock.now()
        note_shed(tenant_id, "failover", "poison-quarantine", ts=now)
        tlabel = tenant_label(tenant_id)
        fleet_metrics.SHED.inc(tenant=tlabel, where="failover")
        fleet_metrics.TENANT_SHED.inc(tenant=tlabel, where="failover",
                                      reason="poison-quarantine")
        raise RequestQuarantined(tenant_id, fp)

    def _note_served(self, tenant_id: str, replica: str) -> None:
        prev = self._home.get(tenant_id)
        if prev is not None and prev != replica:
            # cold remap: the new home has neither the synced catalog nor
            # the warm compiled programs — count the loss, re-Sync first
            self.warm_state_losses += 1
            fleet_metrics.FAILOVER_COLD_REMAPS.inc()
            if self.on_remap is not None:
                self.on_remap(tenant_id, replica)
        self._home[tenant_id] = replica

    def evidence(self) -> dict:
        """Deterministic client state for the chaos artifact."""
        with self._lock:
            deps = sorted(self._policies)
            budget = self.budget.evidence()
            breakers = {d: self._policies[d].breaker.evidence()
                        for d in deps}
        return {
            "budget": budget,
            "breakers": breakers,
            "warm_state_losses": self.warm_state_losses,
            "quarantine": self.quarantine.evidence(),
        }
