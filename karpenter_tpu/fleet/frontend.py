"""FleetFrontend: continuously-batching multi-tenant admission for the
solver service.

The single-tenant `SolverService` serves one Solve per RPC; the fleet
frontend turns it into a batched service for thousands of clusters
(ROADMAP item 2, CvxCluster direction: many small problems as one
structured batch). Requests arrive tagged with a `tenant_id`
(SolveRequest field 9), are admitted into per-bucket queues keyed by the
SAME `BucketPlan` rung table that keys the jit cache (solver/buckets.py),
and a tick loop coalesces same-bucket requests from different tenants
into ONE vmapped mega-solve (`TPUSolver.solve_many` — the wave-pipelined
device path PR 7 built, whose batch axis here is tenants, not pods), then
demuxes the results back to each caller.

Admission discipline, in order:

* deadline shed at ADMISSION — a request whose remaining budget
  (`deadline_ms`, resilience/deadline.py semantics) cannot survive the
  next tick plus the service's shed floor is refused before it ever
  queues. Shedding after compute would burn device time every other
  tenant is queued behind; the whole point of the budget is that the
  caller has already given up by then.
* deadline shed in QUEUE — budgets keep draining while queued; the tick
  loop re-checks at dispatch and sheds expired tickets without compute.
* weighted round-robin fairness with a starvation bound — each tick runs
  a fair-share pass first (one rotation over the tenant queues, each
  granted up to `weight` slots), then gives spare capacity to the oldest
  queued admissions. A hot tenant's backlog can fill the spare but never
  a light tenant's guaranteed share, so a within-weight tenant's wait is
  bounded by the rotation reach time and never exceeds
  `starvation_bound` ticks (the chaos `tenant storm` drill asserts
  exactly this).

Tenants sharing identical catalog+provisioner CONTENT dedupe onto one
resident solver (the service LRU is content-hash keyed), so the common
fleet case — thousands of clusters on the same generated catalog —
batches across tenants with zero extra device residency.

Determinism: the tick loop takes time ONLY from the injected clock and
sequence numbers, so under FakeClock a submission schedule replays to the
identical batch composition — the property the chaos storm scenario's
replay contract leans on.
"""

from __future__ import annotations

import itertools
import logging
import threading
import weakref
from collections import OrderedDict, deque
from typing import Callable, Optional, Sequence

from .. import overload
from ..explain import note_shed
from ..models.pod import group_pods
from ..resilience.degrade import DegradeLadder
from ..tracing import TRACER
from ..utils.clock import Clock
from . import metrics as fm
from ..solver import buckets
from ..solver import solver_pb2 as pb
from ..solver import wire
from ..solver.service import SHED_MIN_BUDGET_MS, result_to_response

log = logging.getLogger("karpenter.fleet")

DEFAULT_TENANT = "default"

# module registry of live frontends for /debug/statusz (weak: a frontend's
# lifetime is owned by whoever built it, the diagnostic surface just peeks)
_ACTIVE: "weakref.WeakSet[FleetFrontend]" = weakref.WeakSet()


def active_frontends() -> "list[FleetFrontend]":
    return sorted(_ACTIVE, key=lambda f: f.name)


class FleetShed(RuntimeError):
    """Request refused without compute; `where` is "admission" or "queue"."""

    def __init__(self, where: str, message: str):
        super().__init__(message)
        self.where = where


class TenantNotSynced(RuntimeError):
    """The tenant's (catalog, provisioner) content is not resident on the
    backing service — the fleet analogue of Solve's FAILED_PRECONDITION."""


class _Ticket:
    """One admitted request: the demux handle the submitting caller blocks
    on. Resolution is exactly-once (result or error, never both)."""

    __slots__ = ("tenant_id", "pods", "existing", "daemon_overhead", "key",
                 "plan", "deadline_ms", "admitted_tick", "admitted_at",
                 "served_tick", "latency_s", "result", "error", "_event",
                 "seq", "trace_ctx", "deferred")

    def __init__(self, tenant_id, pods, existing, daemon_overhead, key,
                 plan, deadline_ms, admitted_tick, admitted_at, seq,
                 trace_ctx=None):
        self.tenant_id = tenant_id
        self.pods = pods
        self.existing = existing
        self.daemon_overhead = daemon_overhead
        self.key = key
        self.plan = plan
        self.deadline_ms = deadline_ms
        self.admitted_tick = admitted_tick
        self.admitted_at = admitted_at
        self.served_tick = None
        self.latency_s = None
        self.result = None
        self.error = None
        self._event = threading.Event()
        self.seq = seq
        # overload "defer" verdict: the ticket keeps its fair-share slots
        # but is excluded from the spare-capacity backlog drain
        self.deferred = False
        # the caller's SpanContext when it sent one over the wire: the
        # queue-wait span joins ITS trace, so a federated trace shows the
        # wait inside this replica's lane, not as an orphan trace
        self.trace_ctx = trace_ctx

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: "Optional[float]" = None):
        """Block for the demuxed result; raises the ticket's error (a shed
        raises FleetShed). Returns the SolveResult."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fleet ticket for tenant {self.tenant_id!r} not served "
                f"within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result

    def _resolve(self, result=None, error=None) -> None:
        if self._event.is_set():
            return
        self.result = result
        self.error = error
        self._event.set()


class _TenantState:
    __slots__ = ("key", "weight", "submitted", "served", "shed_admission",
                 "shed_queue", "errors", "max_wait_ticks", "reasons")

    def __init__(self, key, weight: int):
        self.key = key
        self.weight = max(1, int(weight))
        self.submitted = 0
        self.served = 0
        self.shed_admission = 0
        self.shed_queue = 0
        self.errors = 0
        self.max_wait_ticks = 0
        # where -> reason -> count, updated in lockstep with the totals
        # above so shed_attribution() sums reconcile against them
        self.reasons: "dict[str, dict[str, int]]" = {}

    def record_shed(self, where: str, reason: str) -> None:
        if where == "admission":
            self.shed_admission += 1
        else:
            self.shed_queue += 1
        per = self.reasons.setdefault(where, {})
        per[reason] = per.get(reason, 0) + 1

    def as_dict(self) -> dict:
        return {"weight": self.weight, "submitted": self.submitted,
                "served": self.served,
                "shed_admission": self.shed_admission,
                "shed_queue": self.shed_queue, "errors": self.errors,
                "max_wait_ticks": self.max_wait_ticks,
                "shed_reasons": {w: dict(rs)
                                 for w, rs in sorted(self.reasons.items())}}


class FleetFrontend:
    """Batched multi-tenant admission in front of a `SolverService` (or any
    `solve_batch` callable — the chaos drill injects a deterministic stub).

    Queue topology: queues[(solver_key, plan)][tenant_id] -> deque of
    tickets. solver_key is the service's LRU identity
    (catalog_hash, provisioner_hash) — requests can only batch when they
    run against the same resident device state; plan is the padded
    `BucketPlan` rung, so everything in one queue folds into one vmapped
    program."""

    def __init__(self, service=None, clock: "Optional[Clock]" = None,
                 tick_interval_s: float = 0.02, max_wave: int = 16,
                 starvation_bound: int = 4,
                 solve_batch: "Optional[Callable]" = None,
                 name: str = "fleet"):
        if service is None and solve_batch is None:
            raise ValueError("FleetFrontend needs a service or solve_batch")
        self.service = service
        self.clock = clock or Clock()
        self.tick_interval_s = float(tick_interval_s)
        self.max_wave = max(1, int(max_wave))
        self.starvation_bound = max(1, int(starvation_bound))
        self.name = name
        self._solve_batch = solve_batch or self._service_solve_batch
        self._lock = threading.Lock()
        self._tenants: "OrderedDict[str, _TenantState]" = OrderedDict()
        # (key, plan) -> tenant_id -> deque[_Ticket]; OrderedDict keeps
        # tenant iteration order deterministic (registration order)
        self._queues: "OrderedDict[tuple, OrderedDict[str, deque]]" = \
            OrderedDict()
        self._rr: "dict[tuple, int]" = {}   # per-bucket rotation offset
        self._tick = 0
        self._seq = itertools.count()
        self._thread: "Optional[threading.Thread]" = None
        self._stop = threading.Event()
        self.ticks_run = 0
        self.mega_solves = 0
        self._depth_labels: "set[str]" = set()
        # overload-control plane (strict noop while KARPENTER_TPU_OVERLOAD
        # is falsy): the guard recomputes pressure per submission and its
        # brownout level rides a resilience DegradeLadder; the backlog
        # bound caps any one tenant's queue depth (oldest-drop overflow).
        # probe_interval short: brownout should re-probe within a few
        # ticks of pressure clearing, not the kube-chain's two minutes.
        self.tenant_backlog_max = overload.tenant_backlog_max_default()
        self.guard = overload.OverloadGuard(
            clock=self.clock,
            ladder=DegradeLadder("overload", ("normal", "brownout"),
                                 clock=self.clock, probe_interval_s=1.0))
        _ACTIVE.add(self)

    # -- tenant registration ---------------------------------------------------

    def register(self, tenant_id: str, catalog, provisioners: Sequence,
                 weight: int = 1) -> "tuple[int, int]":
        """Sync the tenant's catalog+provisioners into the backing service
        and admit the tenant. Content-identical tenants share one resident
        solver (the LRU key is the content hash), which is what makes
        cross-tenant mega-solves possible. Returns the solver key."""
        key = (wire.catalog_hash(catalog),
               wire.provisioners_hash(list(provisioners)))
        if self.service is not None:
            self.service.Sync(pb.SyncRequest(
                catalog=wire.catalog_to_wire(catalog),
                provisioners=[wire.provisioner_to_wire(p)
                              for p in provisioners]), None)
        self.register_key(tenant_id, key, weight=weight)
        return key

    def register_key(self, tenant_id: str, key: "tuple[int, int]",
                     weight: int = 1) -> None:
        """Admit a tenant whose catalog is ALREADY synced (the wire path:
        the client Sync'd through the fleet's delegated Sync RPC)."""
        with self._lock:
            st = self._tenants.get(tenant_id)
            if st is None:
                self._tenants[tenant_id] = _TenantState(key, weight)
            else:
                st.key = key
                st.weight = max(1, int(weight))

    # -- admission -------------------------------------------------------------

    def _plan_of(self, pods, existing) -> buckets.BucketPlan:
        # Admission-queue key only — NOT the jit key (build_pack_inputs
        # re-derives the exact padded shape at encode time). The group/slot
        # estimate mirrors service._hint_shape's doctrine: the ladder's
        # coarse rungs absorb estimate error, so same-shaped tenant traffic
        # reliably lands in the same queue.
        g = max(1, len(group_pods(list(pods))))
        return buckets.plan_for(g, max(8, g), len(existing))

    def submit(self, tenant_id: str, pods, existing=(),
               daemon_overhead=None, deadline_ms: int = 0,
               weight: "Optional[int]" = None,
               trace_context=None) -> _Ticket:
        """Admit one solve request; returns its ticket (already resolved
        with a FleetShed error when admission shed it). deadline_ms is the
        caller's REMAINING cycle budget, wire semantics (0 = none).
        trace_context joins the caller's distributed trace (SpanContext)."""
        tenant_id = tenant_id or DEFAULT_TENANT
        with self._lock:
            st = self._tenants.get(tenant_id)
            if st is None:
                raise TenantNotSynced(
                    f"tenant {tenant_id!r} not registered with the fleet")
            if weight is not None:
                st.weight = max(1, int(weight))
            st.submitted += 1
            plan = self._plan_of(pods, existing)
            ticket = _Ticket(tenant_id, list(pods), list(existing),
                             daemon_overhead, st.key, plan, int(deadline_ms),
                             self._tick, self.clock.now(), next(self._seq),
                             trace_ctx=trace_context)
            # the guard offers the tenant to the top-K sketch exactly once
            # per submission; every other family this submission touches
            # reuses the same guarded label (peek) so sketch counts track
            # submissions, not metric fan-out
            tlabel = fm.tenant_label(tenant_id)
            fm.REQUESTS.inc(tenant=tlabel)
            # shed at ADMISSION: the request must survive at least one full
            # tick of queueing plus the service's own shed floor, or the
            # answer would arrive after the caller's cycle gave up on it
            min_budget = self.tick_interval_s * 1000.0 + SHED_MIN_BUDGET_MS
            if ticket.deadline_ms and ticket.deadline_ms < min_budget:
                st.record_shed("admission", "deadline")
                fm.SHED.inc(tenant=tlabel, where="admission")
                fm.TENANT_SHED.inc(tenant=tlabel, where="admission",
                                   reason="deadline")
                note_shed(tenant_id, "admission", "deadline",
                          ts=self.clock.now())
                ticket._resolve(error=FleetShed(
                    "admission",
                    f"{ticket.deadline_ms}ms of budget cannot survive the "
                    f"next {self.tick_interval_s * 1000:.0f}ms tick; "
                    f"shedding at admission"))
                return ticket
            # overload plane (strict noop while disabled: observe returns
            # 0 and decide returns "accept" without touching a counter).
            # backlog input: total queue depth vs the fairness plane's
            # drain capacity (starvation_bound ticks of full waves);
            # deadline input: how close this budget sits to the shed floor
            queued_total = sum(len(q) for per in self._queues.values()
                               for q in per.values())
            capacity = float(self.starvation_bound * self.max_wave)
            deadline_input = (min_budget / float(ticket.deadline_ms)
                              if ticket.deadline_ms else 0.0)
            level = self.guard.observe(
                backlog=queued_total / capacity if capacity else 0.0,
                deadline=deadline_input)
            if level > 0:
                # only tenants over their weighted share absorb pressure:
                # the fairness contract is the one thing overload never buys
                tenant_queued = sum(len(per.get(tenant_id, ()))
                                    for per in self._queues.values())
                verdict = self.guard.decide(
                    over_rate=tenant_queued >= st.weight)
                if verdict == "brownout":
                    st.record_shed("admission", "overload-brownout")
                    fm.SHED.inc(tenant=tlabel, where="admission")
                    fm.TENANT_SHED.inc(tenant=tlabel, where="admission",
                                       reason="overload-brownout")
                    note_shed(tenant_id, "admission", "overload-brownout",
                              ts=self.clock.now())
                    ticket._resolve(error=FleetShed(
                        "admission",
                        f"replica browned out (pressure "
                        f"{self.guard.pressure():.2f}) and tenant "
                        f"{tenant_id!r} is over its weighted share"))
                    return ticket
                if verdict == "shed":
                    st.record_shed("admission", "overload-pressure")
                    fm.SHED.inc(tenant=tlabel, where="admission")
                    fm.TENANT_SHED.inc(tenant=tlabel, where="admission",
                                       reason="overload-pressure")
                    note_shed(tenant_id, "admission", "overload-pressure",
                              ts=self.clock.now())
                    ticket._resolve(error=FleetShed(
                        "admission",
                        f"overload pressure {self.guard.pressure():.2f} "
                        f"and tenant {tenant_id!r} is over its weighted "
                        f"share; shedding at admission"))
                    return ticket
                if verdict == "defer":
                    ticket.deferred = True
            bucket = (st.key, plan)
            per_tenant = self._queues.setdefault(bucket, OrderedDict())
            q = per_tenant.setdefault(tenant_id, deque())
            q.append(ticket)
            if overload.enabled() and len(q) > self.tenant_backlog_max:
                # bounded per-tenant backlog, deterministic oldest-drop:
                # the aged ticket has the least budget left, so it is the
                # one a bounded queue sheds
                oldest = q.popleft()
                st.record_shed("queue", "overload-queue-overflow")
                fm.SHED.inc(tenant=tlabel, where="queue")
                fm.TENANT_SHED.inc(tenant=tlabel, where="queue",
                                   reason="overload-queue-overflow")
                note_shed(tenant_id, "queue", "overload-queue-overflow",
                          ts=self.clock.now())
                overload.note_queue_overflow()
                oldest._resolve(error=FleetShed(
                    "queue",
                    f"tenant backlog exceeded the bound "
                    f"{self.tenant_backlog_max}; dropping the oldest "
                    f"queued ticket"))
            self._observe_depths_locked()
        return ticket

    def solve(self, tenant_id: str, pods, existing=(), daemon_overhead=None,
              deadline_ms: int = 0, timeout: "Optional[float]" = 30.0):
        """Synchronous convenience: submit + wait (the tick thread must be
        running, or the caller must tick from another thread)."""
        return self.submit(tenant_id, pods, existing, daemon_overhead,
                           deadline_ms).wait(timeout)

    # -- the tick loop ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"{self.name}-tick", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.clock.sleep(self.tick_interval_s)
            if self._stop.is_set():
                break
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("fleet tick failed")

    def tick(self) -> int:
        """One batching round over every bucket: shed expired tickets,
        select up to max_wave per bucket (fair + starvation-bounded), run
        each selection as ONE mega-solve, demux. Returns requests served.
        Deterministic given the clock and the submission sequence."""
        with self._lock:
            self._tick += 1
            self.ticks_run += 1
            now = self.clock.now()
            batches: "list[tuple[tuple, list[_Ticket]]]" = []
            for bucket in list(self._queues):
                self._shed_expired_locked(bucket, now)
                batch = self._select_locked(bucket)
                if batch:
                    batches.append((bucket, batch))
                if not any(self._queues.get(bucket, {}).values()):
                    self._queues.pop(bucket, None)
            self._observe_depths_locked()
        served = 0
        for (key, plan), batch in batches:
            served += self._dispatch(key, plan, batch)
        return served

    def _shed_expired_locked(self, bucket, now: float) -> None:
        for tenant_id, q in self._queues.get(bucket, {}).items():
            kept: "deque[_Ticket]" = deque()
            for t in q:
                if t.deadline_ms:
                    remaining = t.deadline_ms - (now - t.admitted_at) * 1000.0
                    if remaining < SHED_MIN_BUDGET_MS:
                        st = self._tenants[tenant_id]
                        st.record_shed("queue", "deadline")
                        tlabel = fm.tenant_peek(tenant_id)
                        fm.SHED.inc(tenant=tlabel, where="queue")
                        fm.TENANT_SHED.inc(tenant=tlabel, where="queue",
                                           reason="deadline")
                        note_shed(tenant_id, "queue", "deadline", ts=now)
                        t._resolve(error=FleetShed(
                            "queue",
                            f"budget expired after "
                            f"{self._tick - t.admitted_tick} tick(s) in "
                            f"queue; shedding before compute"))
                        continue
                kept.append(t)
            q.clear()
            q.extend(kept)

    def _select_locked(self, bucket) -> "list[_Ticket]":
        """Up to max_wave tickets in two passes: a FAIR-SHARE pass first —
        one rotation over the tenant queues, each granted up to `weight` —
        then spare capacity to the oldest admissions overall. Order
        matters: running the fair pass before any backlog drain is what
        bounds a light tenant's wait (a backlog-first policy hands every
        slot to a hot tenant's aged queue under sustained overload — FIFO
        over an unbounded backlog IS starvation for everyone behind it).
        The rotation start advances by the number of tenants granted, so
        when one pass cannot reach every tenant the window tiles the
        tenant list across ticks: any within-weight tenant is reached
        within ceil(tenants*weight / max_wave) ticks, the floor the
        starvation bound must sit above."""
        per_tenant = self._queues.get(bucket)
        if not per_tenant:
            return []
        budget = self.max_wave
        picked: "list[_Ticket]" = []
        tenants = [tid for tid in per_tenant if per_tenant[tid]]
        if tenants:
            start = self._rr.get(bucket, 0) % len(tenants)
            granted = 0
            for tid in tenants[start:] + tenants[:start]:
                if budget <= 0:
                    break
                q = per_tenant[tid]
                take = min(self._tenants[tid].weight, budget, len(q))
                for _ in range(take):
                    picked.append(q.popleft())
                if take:
                    granted += 1
                budget -= take
            self._rr[bucket] = self._rr.get(bucket, 0) + max(1, granted)
        # spare capacity drains backlog: oldest admission first, across
        # every tenant (a hot tenant may fill this, never the fair pass).
        # Overload-deferred tickets sit the spare pass out until their age
        # nears the starvation bound — "defer" requeues WITHIN the bound:
        # fair-share slots still drain the tenant, spare yields to fresher
        # within-weight traffic, and the wait-bound contract still holds
        # (aged tickets sort oldest-first, so they reclaim spare first)
        if budget > 0:
            spare_age = max(0, self.starvation_bound - 1)
            backlog = sorted(
                (t for q in per_tenant.values() for t in q
                 if not t.deferred
                 or self._tick - t.admitted_tick >= spare_age),
                key=lambda t: (t.admitted_tick, t.seq))
            for t in backlog[:budget]:
                per_tenant[t.tenant_id].remove(t)
                picked.append(t)
        return picked

    # -- dispatch / demux ------------------------------------------------------

    def _service_solve_batch(self, key, problems: "list[dict]"):
        """Default backend: the mega-solve. Resolve the resident solver for
        the content key and run the whole batch through solve_many — one
        vmapped dispatch per padded shape, one device->host read for all
        tenants (solver/core.py)."""
        svc = self.service
        # checkout pins the resident entry: a concurrent Sync's eviction
        # pass (capacity, HBM pressure, or low-water) can never release
        # this solver's device grid while the mega-solve is in flight
        entry = svc.checkout(key)
        if entry is None:
            raise TenantNotSynced(
                f"catalog hash={key[0]:x} not synced; re-Sync required")
        try:
            solver, _seqnum = entry
            return solver.solve_many(problems)
        finally:
            svc.checkin(key)

    def _dispatch(self, key, plan, batch: "list[_Ticket]") -> int:
        fm.BATCH_OCCUPANCY.observe(len(batch) / self.max_wave)
        fm.MEGA_SOLVES.inc(bucket=plan.label())
        self.mega_solves += 1
        # queue-wait attribution (docs/designs/slo.md): admission-to-
        # dispatch wall time, captured BEFORE the solve so the wait phase
        # excludes solve cost; filed per ticket as a synthesized span at
        # resolution below (fleet.queue_wait in the phase histogram)
        dispatch_started = self.clock.now()
        problems = [{"pods": t.pods, "existing": t.existing,
                     "daemon_overhead": t.daemon_overhead} for t in batch]
        try:
            # gap-ledger wall bracket for the mega-solve: the wave path's
            # phase notes (solver.solve_many) file against this wall, so
            # routed-fleet attribution rows carry the batch size
            from ..profiling import GAP_LEDGER
            with GAP_LEDGER.solve_scope("fleet"):
                GAP_LEDGER.annotate(bucket=plan.label(), batch=len(batch))
                # explicit cross-thread wait: each ticket's admission->
                # dispatch queue time happened on OTHER threads before
                # this scope opened, so lane-gap classification cannot
                # see it — file it as queue_wait on the tick lane (the
                # critical plane's wait vocabulary, ISSUE 18)
                for t in batch:
                    GAP_LEDGER.note_wait(
                        "queue_wait",
                        max(0.0, dispatch_started - t.admitted_at),
                        lane="tick")
                results = self._solve_batch(key, problems)
        except Exception as e:  # noqa: BLE001 — resolve, never wedge callers
            with self._lock:
                for t in batch:
                    self._tenants[t.tenant_id].errors += 1
                    t._resolve(error=e)
            return 0
        now = self.clock.now()
        with self._lock:
            for t, res in zip(batch, results):
                st = self._tenants[t.tenant_id]
                st.served += 1
                t.served_tick = self._tick
                wait = t.served_tick - t.admitted_tick
                st.max_wait_ticks = max(st.max_wait_ticks, wait)
                t.latency_s = max(0.0, now - t.admitted_at)
                tlabel = fm.tenant_peek(t.tenant_id)
                fm.WAIT_TICKS.observe(wait, tenant=tlabel)
                fm.TENANT_SOLVE_SECONDS.observe(t.latency_s, tenant=tlabel)
                TRACER.record_span(
                    "fleet.queue_wait",
                    max(0.0, dispatch_started - t.admitted_at),
                    context=t.trace_ctx,
                    tenant=t.tenant_id, bucket=plan.label(),
                    wait_ticks=wait)
                t._resolve(result=res)
        return len(batch)

    def _observe_depths_locked(self) -> None:
        for (key, plan), per_tenant in self._queues.items():
            fm.QUEUE_DEPTH.set(
                float(sum(len(q) for q in per_tenant.values())),
                bucket=plan.label())
        # per-tenant depth + fair-share deficit, guarded (peek: a gauge
        # sweep is not traffic and must not inflate sketch counts). The
        # rollup label aggregates every untracked tenant's depth; labels
        # set last sweep but absent now are zeroed so a drained tenant
        # doesn't report a stale depth forever.
        depths: "dict[str, float]" = {}
        deficits: "dict[str, float]" = {}
        for per_tenant in self._queues.values():
            for tid, q in per_tenant.items():
                if not q:
                    continue
                tlabel = fm.tenant_peek(tid)
                depths[tlabel] = depths.get(tlabel, 0.0) + len(q)
                share = float(self._tenants[tid].weight)
                deficits[tlabel] = deficits.get(tlabel, 0.0) + \
                    max(0.0, len(q) - share)
        for tlabel in self._depth_labels - set(depths):
            # zero only labels still live in the sketch: re-setting an
            # evicted label would resurrect the series its fold deleted
            if not fm.TENANT_GUARD.is_tracked_label(tlabel):
                continue
            fm.TENANT_QUEUE_DEPTH.set(0.0, tenant=tlabel)
            fm.TENANT_FAIR_SHARE_DEFICIT.set(0.0, tenant=tlabel)
        for tlabel, depth in depths.items():
            fm.TENANT_QUEUE_DEPTH.set(depth, tenant=tlabel)
            fm.TENANT_FAIR_SHARE_DEFICIT.set(
                deficits.get(tlabel, 0.0), tenant=tlabel)
        self._depth_labels = set(depths)

    # -- observability ---------------------------------------------------------

    def reset_stats(self) -> None:
        """Zero the ledgers (tenant counters, tick/mega-solve totals) while
        keeping registrations and queues. For benchmarks: the measured
        window must not inherit the warmup phase's compile-stall waits."""
        with self._lock:
            self.ticks_run = 0
            self.mega_solves = 0
            for st in self._tenants.values():
                st.submitted = st.served = 0
                st.shed_admission = st.shed_queue = st.errors = 0
                st.max_wait_ticks = 0

    def queued(self) -> int:
        with self._lock:
            return sum(len(q) for per in self._queues.values()
                       for q in per.values())

    def stats(self) -> dict:
        """statusz section payload (introspect/statusz.py "fleet")."""
        with self._lock:
            return {
                "name": self.name,
                "tick_interval_s": self.tick_interval_s,
                "max_wave": self.max_wave,
                "starvation_bound": self.starvation_bound,
                "ticks": self.ticks_run,
                "mega_solves": self.mega_solves,
                # fleet-wide totals so a federated scraper computes
                # per-replica solves/s from ONE row instead of summing
                # the (possibly top-K-guarded) per-tenant table
                "served": sum(st.served for st in self._tenants.values()),
                "submitted": sum(st.submitted
                                 for st in self._tenants.values()),
                "queued": sum(len(q) for per in self._queues.values()
                              for q in per.values()),
                "buckets": {plan.label(): sum(len(q) for q in per.values())
                            for (_k, plan), per in self._queues.items()},
                "tenants": {tid: st.as_dict()
                            for tid, st in self._tenants.items()},
                "tenant_telemetry": fm.TENANT_GUARD.snapshot(),
                "overload": {
                    "enabled": overload.enabled(),
                    "tenant_backlog_max": self.tenant_backlog_max,
                    "guard": self.guard.snapshot(),
                },
            }

    def evidence(self) -> dict:
        """The fairness-invariant input (chaos/invariants.py
        check_fairness_never_starves): per-tenant ledger + the bound."""
        s = self.stats()
        return {"starvation_bound": self.starvation_bound,
                "queued": s["queued"], "tenants": s["tenants"],
                "overload": self.guard.evidence()}

    def shed_attribution(self) -> dict:
        """Per-tenant shed attribution (tenant -> where -> reason -> count)
        for the chaos storm and churn artifacts. Built from the frontend's
        own exact ledgers — NOT the guarded metric families — so every
        tenant is named even past the top-K, and the sums reconcile
        against totals (the shed-attribution-sums-match-totals invariant).
        Reasons are SHED_REASONS rows: "deadline" plus the overload
        plane's "overload-pressure" / "overload-queue-overflow" /
        "overload-brownout"."""
        with self._lock:
            out: "dict[str, dict]" = {}
            for tid, st in sorted(self._tenants.items()):
                entry = {where: dict(rs)
                         for where, rs in sorted(st.reasons.items()) if rs}
                if entry:
                    out[tid] = entry
            return out


class FleetService:
    """Wire adapter: a drop-in for `SolverService` in `serve()` whose Solve
    queues through the fleet frontend (tenant-tagged, batched, fair, shed)
    while Sync/Consolidate/Health delegate straight to the backing
    service. A Sync through this adapter also admits the requesting tenant
    — the wire client never needs a separate registration RPC."""

    def __init__(self, frontend: FleetFrontend,
                 solve_timeout_s: float = 30.0):
        if frontend.service is None:
            raise ValueError("FleetService needs a service-backed frontend")
        self.frontend = frontend
        self.service = frontend.service
        self.solve_timeout_s = solve_timeout_s

    def Sync(self, request, context):
        resp = self.service.Sync(request, context)
        # the synced content IS the tenant's solver key; tenants announce
        # themselves on their first Solve (tenant_id), so admission here is
        # keyed for everyone sharing this content
        return resp

    def Consolidate(self, request, context):
        return self.service.Consolidate(request, context)

    def Health(self, request, context):
        return self.service.Health(request, context)

    def Solve(self, request, context):
        import grpc

        tenant = request.tenant_id or DEFAULT_TENANT
        key = (request.catalog_hash, request.provisioner_hash)
        svc = self.service
        # checkout is probation-aware (a tenant whose content the
        # admission filter is still holding on probation is synced too);
        # only the seqnum is needed here, so check right back in
        entry = svc.checkout(key)
        if entry is not None:
            svc.checkin(key)
        if entry is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"catalog hash={request.catalog_hash:x} not synced; "
                f"re-Sync required")
        _solver, seqnum = entry
        self.frontend.register_key(tenant, key)
        import time as _time

        # join the caller's trace (wire trace_context) exactly like the
        # direct SolverService.Solve path does — this is what makes a
        # FEDERATED trace work across real processes: the client's trace
        # id crosses the wire, this replica's queue-wait + Solve spans
        # land in its own ring under the same id, and fleetview stitches
        # the rings into one Perfetto file with one lane per pid
        ctx = wire.trace_context_from_wire(request.trace_context)
        with TRACER.start_span("solver.service.Solve", context=ctx,
                               pods=len(request.pods), tenant=tenant,
                               transport="fleet") as span:
            t0 = _time.perf_counter()
            ticket = self.frontend.submit(
                tenant,
                [wire.pod_from_wire(m) for m in request.pods],
                [wire.existing_from_wire(m) for m in request.existing],
                list(request.daemon_overhead) or None,
                deadline_ms=int(request.deadline_ms),
                trace_context=span.context())
            timeout = self.solve_timeout_s
            if request.deadline_ms:
                timeout = min(timeout, request.deadline_ms / 1000.0 + 1.0)
            try:
                result = ticket.wait(timeout)
            except FleetShed as e:
                span.set_attribute("outcome", "shed")
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
            except TenantNotSynced as e:
                span.set_attribute("outcome", "not-synced")
                context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
            except TimeoutError as e:
                span.set_attribute("outcome", "timeout")
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
            solve_ms = (_time.perf_counter() - t0) * 1000
            resp = result_to_response(result, solve_ms, seqnum)
            resp.routing = "fleet"
            resp.bucket = ticket.plan.label()
            return resp
