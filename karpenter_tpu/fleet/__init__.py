"""Solver fleet layer: batched multi-tenant serving for thousands of
clusters (docs/designs/fleet.md).

The pieces:

* `FleetFrontend` (frontend.py) — tenant-tagged admission queues keyed by
  `BucketPlan` rungs, a tick loop that coalesces same-bucket requests
  from different tenants into one vmapped mega-solve, weighted
  round-robin fairness with a starvation bound, and deadline-budget
  shedding at admission (never after compute).
* `FleetService` (frontend.py) — the gRPC adapter: drop it into
  `solver.service.serve()` and the wire Solve path batches.
* `FleetRouter` (router.py) — rendezvous-hash tenant -> replica mapping
  across N fleet replicas; rebalance-safe by construction.
* metrics.py — queue depth, batch occupancy, shed counts, per-tenant
  latency (surfaced in /debug/statusz and docs/metrics.md "Fleet").
"""

from .frontend import (DEFAULT_TENANT, FleetFrontend, FleetService,
                       FleetShed, TenantNotSynced, active_frontends)
from .router import FleetRouter

__all__ = [
    "DEFAULT_TENANT",
    "FleetFrontend",
    "FleetRouter",
    "FleetService",
    "FleetShed",
    "TenantNotSynced",
    "active_frontends",
]
