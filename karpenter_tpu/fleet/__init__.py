"""Solver fleet layer: batched multi-tenant serving for thousands of
clusters (docs/designs/fleet.md).

The pieces:

* `FleetFrontend` (frontend.py) — tenant-tagged admission queues keyed by
  `BucketPlan` rungs, a tick loop that coalesces same-bucket requests
  from different tenants into one vmapped mega-solve, weighted
  round-robin fairness with a starvation bound, and deadline-budget
  shedding at admission (never after compute).
* `FleetService` (frontend.py) — the gRPC adapter: drop it into
  `solver.service.serve()` and the wire Solve path batches.
* `FleetRouter` (router.py) — rendezvous-hash tenant -> replica mapping
  across N fleet replicas; rebalance-safe by construction.
* `MembershipManager` (membership.py) — health-gated membership: probe
  evidence (K-missed-beats + latency-quantile gray-failure detectors)
  drives the router's member set; monotone epochs, edge-triggered
  Replica{Joined,Ejected,Recovered} events; strict no-op when disabled.
* `FailoverClient` (failover.py) — client-side re-route to the next
  rendezvous choice through per-replica breakers and one shared retry
  budget, bounded tail hedging, cold-remap re-Sync, and the poison-pill
  `QuarantineRing`.
* metrics.py — queue depth, batch occupancy, shed counts, per-tenant
  latency, membership/failover families (surfaced in /debug/statusz and
  docs/metrics.md "Fleet").
"""

from .failover import (FailoverClient, FailoverExhausted, QuarantineRing,
                       ReplicaCrashed, ReplicaTimeout, ReplicaUnavailable,
                       RequestQuarantined, request_fingerprint)
from .frontend import (DEFAULT_TENANT, FleetFrontend, FleetService,
                       FleetShed, TenantNotSynced, active_frontends)
from .membership import MembershipManager
from .router import FleetRouter

__all__ = [
    "DEFAULT_TENANT",
    "FailoverClient",
    "FailoverExhausted",
    "FleetFrontend",
    "FleetRouter",
    "FleetService",
    "FleetShed",
    "MembershipManager",
    "QuarantineRing",
    "ReplicaCrashed",
    "ReplicaTimeout",
    "ReplicaUnavailable",
    "RequestQuarantined",
    "TenantNotSynced",
    "active_frontends",
    "request_fingerprint",
]
