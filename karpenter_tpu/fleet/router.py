"""Tenant -> replica routing for the solver fleet (rendezvous hashing).

With thousands of clusters behind N fleet replicas, the router must keep a
tenant pinned to one replica (its synced catalog and compiled programs are
resident THERE) while surviving replica churn gracefully. Rendezvous
(highest-random-weight) hashing gives both for free:

* stability — a tenant moves only when its own top-scoring replica leaves
  the set (or a new replica out-scores every incumbent). Removing one of R
  replicas remaps exactly the tenants that lived on it (~1/R of traffic);
  adding one steals only the tenants the newcomer now wins (~1/(R+1)).
  A modulo hash would remap almost everything on any membership change,
  invalidating device-resident state fleet-wide.
* no token ring to persist — the score is a pure function of
  (tenant, replica), so every controller computes the same answer with no
  coordination and no shared state to journal/recover.

Scores come from blake2b (the repo's content-hash primitive, wire.py):
python's hash() is per-process salted and MUST NOT be used here — two
controllers would route the same tenant to different replicas.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Optional


def _score(tenant_id: str, replica: str) -> int:
    h = hashlib.blake2b(digest_size=8)
    h.update(tenant_id.encode("utf-8"))
    h.update(b"\x00")  # unambiguous boundary: ("ab","c") != ("a","bc")
    h.update(replica.encode("utf-8"))
    return int.from_bytes(h.digest(), "little")


class FleetRouter:
    """Rendezvous-hash map from tenant_id to a replica name. Replicas are
    opaque strings (typically "host:port" solver-service targets); ties —
    cryptographically negligible but not impossible — break by replica
    name so the choice stays deterministic across processes."""

    def __init__(self, replicas: Iterable[str] = ()):
        # kept sorted at mutation time (bisect.insort): route() runs once
        # per request, membership changes run once per epoch — sorting on
        # the hot path was pure waste, and the scan order doesn't affect
        # the winner anyway (max with a total-order key)
        self._replicas: "list[str]" = []
        for r in replicas:
            self.add_replica(r)

    @property
    def replicas(self) -> "tuple[str, ...]":
        return tuple(self._replicas)

    def add_replica(self, replica: str) -> None:
        if not replica:
            raise ValueError("replica name must be non-empty")
        if replica not in self._replicas:
            bisect.insort(self._replicas, replica)

    def remove_replica(self, replica: str) -> None:
        if replica in self._replicas:
            self._replicas.remove(replica)

    def route(self, tenant_id: str) -> str:
        """The tenant's home replica. Raises if the fleet is empty —
        routing nowhere is a caller decision, not a silent default."""
        if not self._replicas:
            raise LookupError("fleet has no replicas")
        return max(self._replicas,
                   key=lambda r: (_score(tenant_id, r), r))

    def ranked(self, tenant_id: str) -> "list[str]":
        """Every replica in descending rendezvous preference for the
        tenant. ranked()[0] == route(); ranked()[1] is the failover
        client's next choice when the home replica is down — exactly the
        replica the tenant would remap to if the home left the set, so a
        client-side reroute and a membership-driven remap always agree."""
        return sorted(self._replicas,
                      key=lambda r: (_score(tenant_id, r), r),
                      reverse=True)

    def route_or_none(self, tenant_id: str) -> Optional[str]:
        return self.route(tenant_id) if self._replicas else None

    def assignment(self, tenant_ids: Iterable[str]) -> "dict[str, str]":
        """tenant -> replica for a whole tenant set (rebalance previews)."""
        return {t: self.route(t) for t in tenant_ids}
