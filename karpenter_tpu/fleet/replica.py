"""Replica runtime: one solver replica as its own OS process.

Everything the fleet layer proved in-process (rendezvous routing,
health-gated membership, client-side failover, federated observability)
meets real process boundaries here. A replica subprocess runs

    python -m karpenter_tpu fleet-replica --name r0 --rendezvous DIR

which boots a SolverService behind a FleetFrontend + FleetService on an
EPHEMERAL gRPC port, starts the standard ServingPlane debug listeners
(also port 0 — N replicas on one host never collide), and then announces
its resolved addresses through a filesystem rendezvous: one atomically
renamed `<name>.json` per replica in a shared directory. The parent
(benchmarks/fleet_drill.py, tests) waits on those files and wires the
REAL endpoints into the same client objects the in-process drills use:

* `HttpReplica(debug_url)` -> FleetView federates live `/debug/statusz`
  and `/debug/traces` over HTTP (introspect/fleetview.py);
* `http_probe(health_url)` -> MembershipManager heartbeats measure real
  HTTP round-trips, so the gray-failure latency detector sees genuine
  tail inflation, not a FakeClock script;
* `GrpcReplicaTransport(grpc_target)` -> FailoverClient's per-replica
  transport table speaks the real solver wire protocol, with gRPC
  status codes mapped onto the failover taxonomy (UNAVAILABLE ->
  ReplicaUnavailable, DEADLINE_EXCEEDED -> ReplicaTimeout, anything
  else -> ReplicaCrashed).

The serving side reuses ServingPlane + statusz verbatim: the replica's
"operator" is a shim that carries exactly the surfaces a solver replica
has (metrics registry, event recorder, wall clock, flight recorder) and
lets the op-scoped statusz sections degrade through their fences. The
sections federation actually reads — fleet frontends, the HBM ledger,
profiling's gap ledger, the decision ring, metrics — are all op-free and
therefore REAL in the subprocess.

Clocks: rendezvous records and the shim's statusz `ts` use wall time
(utils.clock.WallClock), because these timestamps are compared ACROSS
processes (fleetz staleness_s); monotonic clocks are per-process.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Optional

from ..utils.clock import WallClock

log = logging.getLogger("karpenter.fleet.replica")

RENDEZVOUS_SCHEMA = 1

# parent-side default: how long to wait for a spawned replica's
# rendezvous file before declaring the boot failed (cold JAX import on a
# busy single-core host takes tens of seconds)
DEFAULT_BOOT_TIMEOUT_S = 180.0


# -- rendezvous (filesystem handshake) --------------------------------------


def registration_path(rendezvous_dir: str, name: str) -> str:
    return os.path.join(rendezvous_dir, f"{name}.json")


def write_registration(rendezvous_dir: str, record: dict) -> str:
    """Atomically publish one replica's resolved addresses: write to a
    tmp file, fsync, rename. A reader either sees no file or a COMPLETE
    record — never a torn JSON body (the HttpReplica invalid-json
    hardening exists for the network path, not for the handshake)."""
    os.makedirs(rendezvous_dir, exist_ok=True)
    path = registration_path(rendezvous_dir, record["name"])
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_registrations(rendezvous_dir: str) -> "dict[str, dict]":
    """All complete registrations in the directory, by replica name.
    Unreadable/partial files are skipped (the writer is mid-rename)."""
    out: "dict[str, dict]" = {}
    if not os.path.isdir(rendezvous_dir):
        return out
    for fn in sorted(os.listdir(rendezvous_dir)):
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(rendezvous_dir, fn)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and rec.get("name"):
            out[rec["name"]] = rec
    return out


def wait_for_registrations(rendezvous_dir: str, names,
                           timeout_s: float = DEFAULT_BOOT_TIMEOUT_S,
                           poll_s: float = 0.25) -> "dict[str, dict]":
    """Block until every named replica has published its registration;
    raises TimeoutError naming the stragglers."""
    names = set(names)
    deadline = time.monotonic() + timeout_s
    while True:
        regs = read_registrations(rendezvous_dir)
        if names <= set(regs):
            return {n: regs[n] for n in sorted(names)}
        if time.monotonic() >= deadline:
            missing = sorted(names - set(regs))
            raise TimeoutError(
                f"replicas never registered in {rendezvous_dir} within "
                f"{timeout_s:.0f}s: {missing}")
        time.sleep(poll_s)


# -- the serving side (runs inside the subprocess) --------------------------


class _ReplicaShim:
    """The minimal "operator" a solver replica has. statusz(op) walks
    this: the sections a replica genuinely owns (metrics, events, fleet
    frontends, HBM, profiling, decisions, serving ports) are real; the
    controller-plane sections (cluster, watchdog, queues, caches) degrade
    through their per-section fences — statusz was built to stay up with
    subsystems missing, and a replica is exactly that."""

    def __init__(self, name: str):
        from ..events import EventRecorder
        from ..introspect.flightrecorder import FlightRecorder
        from ..metrics import REGISTRY

        self.name = name
        self.clock = WallClock()
        self.recorder = EventRecorder(clock=self.clock)
        self.flightrecorder = FlightRecorder(self, clock=self.clock)
        self.fleetview = None  # replicas are federated, they don't federate
        self.serving = None    # set once the plane is started
        self._registry = REGISTRY

    def metrics_text(self) -> str:
        return self._registry.expose()

    def healthz(self) -> bool:
        return True

    def livez(self) -> bool:
        return True

    class _Resilience:
        @staticmethod
        def snapshot() -> dict:
            return {"watchdog": {"healthy": True}}

    resilience = _Resilience()


class ReplicaRuntime:
    """Boots and owns one replica's serving stack inside the current
    process: SolverService -> FleetFrontend -> FleetService on gRPC,
    plus the ServingPlane debug listeners, plus the rendezvous
    announcement. `start()` returns the published registration record."""

    def __init__(self, name: str, rendezvous_dir: str,
                 grpc_port: int = 0, debug_port: int = 0,
                 max_wave: int = 16, tick_interval_s: float = 0.01,
                 solve_timeout_s: float = 60.0,
                 starvation_bound: int = 4):
        self.name = name
        self.rendezvous_dir = rendezvous_dir
        self.grpc_port = grpc_port
        self.debug_port = debug_port
        self.max_wave = max_wave
        self.tick_interval_s = tick_interval_s
        self.solve_timeout_s = solve_timeout_s
        self.starvation_bound = starvation_bound
        self.registration: "Optional[dict]" = None
        self.frontend = None
        self.service = None
        self._grpc_server = None
        self._plane = None
        self._op: "Optional[_ReplicaShim]" = None

    def start(self) -> dict:
        from ..serving import ServingPlane
        from ..solver.service import SolverService, serve
        from .frontend import FleetFrontend, FleetService

        self.service = SolverService()
        self.frontend = FleetFrontend(
            self.service, tick_interval_s=self.tick_interval_s,
            max_wave=self.max_wave, name=self.name,
            starvation_bound=self.starvation_bound)
        self.frontend.start()
        fleet_service = FleetService(self.frontend,
                                     solve_timeout_s=self.solve_timeout_s)
        self._grpc_server, grpc_port, _svc = serve(
            f"127.0.0.1:{self.grpc_port}", max_workers=8,
            service=fleet_service)
        self._op = _ReplicaShim(self.name)
        self._plane = ServingPlane(self._op, metrics_port=self.debug_port,
                                   health_port=0, webhook_port=-1)
        bound = self._plane.start()
        self._op.serving = self._plane
        self.registration = {
            "schema": RENDEZVOUS_SCHEMA,
            "name": self.name,
            "pid": os.getpid(),
            "ts": time.time(),
            "grpc": f"127.0.0.1:{grpc_port}",
            "debug": f"http://127.0.0.1:{bound['metrics']}",
            "health": f"http://127.0.0.1:{bound['health']}",
        }
        write_registration(self.rendezvous_dir, self.registration)
        log.info("replica %s registered: %s", self.name, self.registration)
        return self.registration

    def stop(self) -> None:
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1.0)
            self._grpc_server = None
        if self.frontend is not None:
            self.frontend.stop()
        if self._plane is not None:
            self._plane.stop()
            self._plane = None
        # withdraw the registration so a rendezvous reader doesn't keep
        # discovering a gone replica (a SIGKILLed replica can't — its
        # stale record is exactly what the membership probes then eject)
        try:
            os.unlink(registration_path(self.rendezvous_dir, self.name))
        except OSError:
            pass


def run_replica_main(args) -> int:
    """`python -m karpenter_tpu fleet-replica` body: boot, announce,
    serve until SIGTERM/SIGINT."""
    import signal

    rt = ReplicaRuntime(
        args.name, args.rendezvous, grpc_port=args.grpc_port,
        debug_port=args.debug_port, max_wave=args.max_wave,
        tick_interval_s=args.tick_interval,
        starvation_bound=getattr(args, "starvation_bound", 4))
    reg = rt.start()
    # one parseable ready line for humans/logs; the rendezvous FILE is
    # the machine-readable handshake
    print("REPLICA_READY " + json.dumps(reg, sort_keys=True), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda signum, frame: stop.set())
    while not stop.is_set():
        stop.wait(0.2)
    rt.stop()
    return 0


# -- the client side (runs in the parent / drill process) -------------------


def subprocess_env(name: "Optional[str]" = None) -> dict:
    """The hygienic environment every drill subprocess launches with:
    force the CPU backend with ONE XLA host device (N subprocesses
    timesharing one core must not each fan out eight device threads) and
    drop any inherited accelerator-pool pointers. Shared by
    `spawn_replica` and the subprocess-spawning tests so there is one
    harness, not several half-copies of it."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if name:
        env["KARPENTER_TPU_REPLICA_NAME"] = name
    return env


def spawn_replica(name: str, rendezvous_dir: str, *, grpc_port: int = 0,
                  debug_port: int = 0, max_wave: int = 16,
                  tick_interval_s: float = 0.01,
                  starvation_bound: int = 4,
                  log_dir: "Optional[str]" = None) -> subprocess.Popen:
    """Launch one replica subprocess (env hygiene: `subprocess_env`).
    stdout/stderr land in `<log_dir>/<name>.log` for post-mortems."""
    env = subprocess_env(name)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    cmd = [sys.executable, "-m", "karpenter_tpu", "fleet-replica",
           "--name", name, "--rendezvous", rendezvous_dir,
           "--grpc-port", str(grpc_port), "--debug-port", str(debug_port),
           "--max-wave", str(max_wave),
           "--tick-interval", str(tick_interval_s),
           "--starvation-bound", str(starvation_bound)]
    os.makedirs(log_dir or rendezvous_dir, exist_ok=True)
    logf = open(os.path.join(log_dir or rendezvous_dir, f"{name}.log"),
                "wb")
    try:
        return subprocess.Popen(cmd, stdout=logf, stderr=subprocess.STDOUT,
                                env=env, cwd=repo_root)
    finally:
        logf.close()  # the child holds its own fd


def http_probe(health_url: str, timeout_s: float = 2.0):
    """A MembershipManager probe against a live replica's /healthz:
    returns the measured round-trip LATENCY in seconds (feeding the
    gray-failure quantile detector with real numbers), raises on any
    failure (feeding the K-missed-beats detector)."""
    url = health_url.rstrip("/") + "/healthz"

    def probe() -> float:
        t0 = time.perf_counter()
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            body = resp.read(64)
            if resp.status != 200:
                raise RuntimeError(
                    f"{url}: HTTP {resp.status} {body[:32]!r}")
        return time.perf_counter() - t0

    return probe


class GrpcReplicaTransport:
    """One replica's solve transport, shaped for FailoverClient's
    transports table: `transport(tenant_id, request, timeout_s)`.

    `request` is a pb.SolveRequest TEMPLATE; each call sends a copy with
    the tenant stamped, so hedges (two replicas racing one logical
    request from two threads) never serialize a message being mutated.
    gRPC status codes map onto the failover taxonomy the in-process
    drills established; trace_context on the template rides through
    unchanged, which is how a drill's client span federates with the
    serving replica's `solver.service.Solve` span."""

    def __init__(self, name: str, target: str):
        import grpc

        from ..solver.service import METHODS, SERVICE_NAME

        self.name = name
        self.target = target
        self._grpc = grpc
        self._channel = grpc.insecure_channel(target)
        self._stubs = {
            method: self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )
            for method, (_req_cls, resp_cls) in METHODS.items()
        }

    def sync(self, catalog, provisioners, timeout_s: float = 120.0):
        """Push (catalog, provisioners) content to the replica; the fleet
        frontend admits tenants against the returned content hashes."""
        from ..solver import solver_pb2 as pb
        from ..solver import wire

        req = pb.SyncRequest(
            catalog=wire.catalog_to_wire(catalog),
            provisioners=[wire.provisioner_to_wire(p)
                          for p in provisioners])
        return self._stubs["Sync"](req, timeout=timeout_s)

    def __call__(self, tenant_id: str, request, timeout_s: float):
        from ..solver import solver_pb2 as pb
        from .failover import (ReplicaCrashed, ReplicaTimeout,
                               ReplicaUnavailable)

        msg = pb.SolveRequest()
        msg.CopyFrom(request)
        msg.tenant_id = tenant_id
        grpc = self._grpc
        try:
            return self._stubs["Solve"](msg, timeout=timeout_s)
        except grpc.RpcError as e:
            code = e.code()
            if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                raise ReplicaTimeout(
                    f"{self.name}: {e.details()}") from e
            if code == grpc.StatusCode.UNAVAILABLE:
                raise ReplicaUnavailable(
                    f"{self.name}: {e.details()}") from e
            # INTERNAL/UNKNOWN/CANCELLED: the replica broke while holding
            # this request — the failover layer treats it as a suspect
            raise ReplicaCrashed(
                f"{self.name}: {code.name}: {e.details()}") from e

    def close(self) -> None:
        self._channel.close()
