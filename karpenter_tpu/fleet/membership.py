"""Health-gated fleet membership: evidence drives the router, not config.

The rendezvous router (router.py) gives stable tenant pinning, but a
static member list means a dead replica keeps winning its tenants'
hashes forever and a merely-slow replica quietly poisons fleet p99. The
membership manager closes that gap: it owns the router's member set and
mutates it only on detector evidence —

* **K-missed-beats failure detector** — every `tick()` probes each
  registered replica's health surface (a callable: `/healthz`, a gRPC
  ping, or an in-process stub under FakeClock); ``MISSED_BEATS_K``
  consecutive probe failures eject the replica from the router.
* **latency-quantile gray-failure detector** — a replica that still
  answers probes but whose recent ``GRAY_QUANTILE`` latency exceeds
  ``GRAY_FACTOR`` x the median of its peers is ejected *before* it
  drags fleet p99 up (gray failures kill tail latency long before they
  kill health checks). Needs ``GRAY_MIN_SAMPLES`` observations and at
  least one peer with samples — "slow" is relative, a fleet of one has
  no baseline.
* **monotone membership epochs** — every join/eject/recover bumps one
  counter that never regresses; `/debug/fleetz` stamps it
  (``FleetView.set_epoch_source``) so observers can order membership
  views, and the chaos partition drill's ``membership-epoch-monotone``
  invariant audits the full observed sequence.
* **edge-triggered events** — ``ReplicaJoined`` / ``ReplicaEjected`` /
  ``ReplicaRecovered`` through the shared EventRecorder, plus a
  flight-recorder bundle at the ejection edge (the cycles that led to
  an ejection are exactly the forensics a 3am page needs).

An ejected replica keeps being probed (cheaply — probing is the
manager's job precisely so the router never routes to test a corpse);
``RECOVERY_PROBES`` consecutive successes re-admit it
(``ReplicaRecovered``), and rendezvous hashing guarantees its old
tenants — and only those — come home. A gray-ejected replica clears a
higher bar: its recovery probes only count while the observed latency
is back under the gray threshold — a slow replica still ANSWERS, so
plain success-counting would flap it in and out forever.

Strict no-op contract (chaos-invariant-enforced, like the profiling and
explain planes): with the plane disabled (``KARPENTER_TPU_MEMBERSHIP=0``
or :func:`set_enabled`), ``register()`` and ``tick()`` do NOTHING — no
probes, no router mutation, no epoch movement, no metrics — so routing
is bit-identical to the static-membership behavior and
:func:`activity` counters stay frozen (invariants.check_membership_noop).
"""

from __future__ import annotations

import contextlib
import os
import threading
from collections import deque
from typing import Callable, Optional

from ..utils.clock import Clock
from . import metrics as fleet_metrics

# -- plane switch (explain/state.py idiom) ---------------------------------

FLAG_ENV = "KARPENTER_TPU_MEMBERSHIP"
_FALSY = ("0", "false", "off", "no")

_state_lock = threading.Lock()
_enabled = os.environ.get(FLAG_ENV, "1").strip().lower() not in _FALSY


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the plane; returns the previous state (restore token)."""
    global _enabled
    with _state_lock:
        prev = _enabled
        _enabled = bool(on)
        return prev


@contextlib.contextmanager
def disabled():
    """Scoped hard-off: the chaos strict-noop drill."""
    prev = set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)


# -- activity counters (the strict-noop evidence) --------------------------

_activity_lock = threading.Lock()
_ACTIVITY = {
    "probes_total": 0,
    "probe_failures_total": 0,
    "transitions_total": 0,
    "epoch_bumps_total": 0,
}


def activity() -> dict:
    """Monotonic process-wide activity counters — the chaos
    ``membership-strict-noop`` invariant diffs two of these."""
    with _activity_lock:
        return dict(_ACTIVITY)


def _count(key: str, n: int = 1) -> None:
    with _activity_lock:
        _ACTIVITY[key] += n


def _quantile(values: "list[float]", q: float) -> float:
    """Nearest-rank quantile over a small latency window (no numpy: the
    detector runs per heartbeat, the windows hold <= LATENCY_WINDOW
    floats)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(q * len(ordered))))
    return ordered[idx]


class _ReplicaHealth:
    """Per-replica detector state. `probe` is the replica's health
    surface: a callable returning the observed probe latency in seconds
    (or any truthy/None success) and raising on failure — `/healthz`
    over HTTP, a gRPC ping, or an in-process stub under FakeClock all
    fit."""

    __slots__ = ("name", "probe", "endpoint", "member", "ever_joined",
                 "gray_ejected", "consecutive_misses",
                 "consecutive_successes", "latencies")

    def __init__(self, name: str, probe: "Callable[[], object]",
                 endpoint=None, window: int = 16):
        self.name = name
        self.probe = probe
        self.endpoint = endpoint  # optional FleetView replica duck object
        self.member = False
        self.ever_joined = False
        self.gray_ejected = False  # last ejection was the gray detector's
        self.consecutive_misses = 0
        self.consecutive_successes = 0
        self.latencies: "deque[float]" = deque(maxlen=window)


class MembershipManager:
    """Drives a FleetRouter's member set from probe evidence. All state
    transitions happen inside `tick()` — callers (the operator's
    reconcile loop, the chaos drill) decide the heartbeat cadence, the
    manager decides membership."""

    MISSED_BEATS_K = 3        # consecutive probe failures before ejection
    RECOVERY_PROBES = 2       # consecutive successes before re-admission
    LATENCY_WINDOW = 16       # recent probe latencies kept per replica
    GRAY_QUANTILE = 0.9       # the replica-side tail the detector inspects
    GRAY_FACTOR = 4.0         # ...ejected when > GRAY_FACTOR x peer median
    GRAY_MIN_SAMPLES = 8      # observations before "slow" is believable

    def __init__(self, router, clock: "Optional[Clock]" = None, *,
                 view=None, recorder=None, flight_trigger=None,
                 missed_beats_k: "Optional[int]" = None,
                 recovery_probes: "Optional[int]" = None,
                 gray_factor: "Optional[float]" = None,
                 gray_min_samples: "Optional[int]" = None):
        self.router = router
        self.clock = clock or Clock()
        # optional FleetView kept in lockstep: when both are wired, the
        # view mirrors into the SAME router, so fleetz pinning and live
        # routing can never disagree (fleetview.py docstring contract)
        self.view = view
        self.recorder = recorder
        # flight_trigger(reason, detail) -> path|None; the operator wires
        # flightrecorder.trigger so the ejection edge dumps a bundle
        self.flight_trigger = flight_trigger
        self.missed_beats_k = missed_beats_k or self.MISSED_BEATS_K
        self.recovery_probes = recovery_probes or self.RECOVERY_PROBES
        self.gray_factor = gray_factor or self.GRAY_FACTOR
        self.gray_min_samples = gray_min_samples or self.GRAY_MIN_SAMPLES
        self._lock = threading.Lock()
        self._replicas: "dict[str, _ReplicaHealth]" = {}
        self._epoch = 0
        if self.view is not None:
            self.view.set_epoch_source(self.epoch)

    # -- registration -------------------------------------------------------

    def register(self, name: str, probe: "Callable[[], object]",
                 endpoint=None) -> None:
        """Track a replica. It joins the router only after its FIRST
        successful probe round (evidence-gated even at birth — a replica
        that never answered a heartbeat never owned a tenant). With the
        plane disabled this is a strict no-op: membership stays whatever
        configuration put in the router."""
        if not enabled():
            return
        with self._lock:
            if name in self._replicas:
                return
            self._replicas[name] = _ReplicaHealth(
                name, probe, endpoint=endpoint, window=self.LATENCY_WINDOW)

    def forget(self, name: str) -> None:
        """Administratively drop a replica (scale-in, not failure)."""
        if not enabled():
            return
        with self._lock:
            h = self._replicas.pop(name, None)
        if h is not None and h.member:
            self._transition_out(h, "forgotten", "administrative removal")

    # -- the heartbeat ------------------------------------------------------

    def tick(self) -> "list[dict]":
        """One heartbeat round: probe every tracked replica, run both
        detectors, mutate membership on edges. Returns the edge events
        fired this round (drill ledger food); [] when disabled."""
        if not enabled():
            return []
        with self._lock:
            handles = [self._replicas[n] for n in sorted(self._replicas)]
        # recovery bar for gray-ejected replicas: a gray casualty still
        # ANSWERS probes — that is what made it gray — so successes only
        # count toward re-admission once its probe latency is back under
        # the same threshold that ejected it (else eject/rejoin flaps and
        # the slow replica re-poisons p99 every RECOVERY_PROBES beats)
        member_medians = [
            _quantile(list(h.latencies), 0.5) for h in handles
            if h.member and h.latencies]
        gray_bar = (self.gray_factor * _quantile(member_medians, 0.5)
                    if member_medians else None)
        events: "list[dict]" = []
        for h in handles:
            _count("probes_total")
            try:
                latency = h.probe()
            except Exception as e:  # noqa: BLE001 — a probe failure IS the signal
                _count("probe_failures_total")
                fleet_metrics.MEMBERSHIP_PROBES.inc(outcome="fail")
                h.consecutive_misses += 1
                h.consecutive_successes = 0
                if h.member and h.consecutive_misses >= self.missed_beats_k:
                    events.append(self._transition_out(
                        h, "k-missed-beats",
                        f"{h.consecutive_misses} consecutive missed "
                        f"beats (K={self.missed_beats_k}): "
                        f"{type(e).__name__}: {e}"))
            else:
                fleet_metrics.MEMBERSHIP_PROBES.inc(outcome="ok")
                h.consecutive_misses = 0
                if isinstance(latency, (int, float)):
                    h.latencies.append(float(latency))
                if not h.member:
                    if h.gray_ejected and gray_bar is not None \
                            and isinstance(latency, (int, float)) \
                            and float(latency) > gray_bar:
                        h.consecutive_successes = 0  # answering, still slow
                    else:
                        h.consecutive_successes += 1
                        if h.consecutive_successes >= self.recovery_probes:
                            events.append(self._transition_in(h))
        events.extend(self._gray_pass())
        self._sweep_gauges()
        return events

    def _gray_pass(self) -> "list[dict]":
        """Eject at most ONE gray replica per tick (the worst offender):
        mass ejection on a shared blip would trade a slow fleet for no
        fleet."""
        with self._lock:
            members = [h for h in self._replicas.values() if h.member]
        worst = None
        worst_ratio = 0.0
        for h in members:
            if len(h.latencies) < self.gray_min_samples:
                continue
            peer_medians = [
                _quantile(list(p.latencies), 0.5) for p in members
                if p is not h and len(p.latencies) >= self.gray_min_samples]
            if not peer_medians:
                continue
            peer_median = _quantile(peer_medians, 0.5)
            if peer_median <= 0.0:
                continue
            tail = _quantile(list(h.latencies), self.GRAY_QUANTILE)
            ratio = tail / peer_median
            if ratio > self.gray_factor and ratio > worst_ratio:
                worst, worst_ratio = h, ratio
        if worst is None:
            return []
        tail = _quantile(list(worst.latencies), self.GRAY_QUANTILE)
        return [self._transition_out(
            worst, "gray-failure",
            f"p{int(self.GRAY_QUANTILE * 100)} probe latency {tail:.4f}s "
            f"is {worst_ratio:.1f}x the peer median "
            f"(threshold {self.gray_factor:.1f}x)")]

    # -- transitions (edge-triggered) ---------------------------------------

    def _bump_epoch(self) -> int:
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
        _count("epoch_bumps_total")
        _count("transitions_total")
        return epoch

    def _transition_in(self, h: _ReplicaHealth) -> dict:
        event = "ReplicaRecovered" if h.ever_joined else "ReplicaJoined"
        h.member = True
        h.ever_joined = True
        h.gray_ejected = False
        h.consecutive_successes = 0
        # fresh member, fresh evidence: latencies observed while ejected
        # (e.g. the slow tail that caused a gray ejection) must not
        # instantly re-trip the detector on the replica's first beat back
        h.latencies.clear()
        epoch = self._bump_epoch()
        if self.view is not None and h.endpoint is not None:
            self.view.add_replica(h.endpoint)  # mirrors into the router
        else:
            self.router.add_replica(h.name)
        fleet_metrics.MEMBERSHIP_TRANSITIONS.inc(
            event="recovered" if event == "ReplicaRecovered" else "joined")
        if self.recorder is not None:
            self.recorder.normal(
                f"fleet/{h.name}", event,
                f"replica {h.name} admitted at membership epoch {epoch}")
        return {"event": event, "replica": h.name, "epoch": epoch}

    def _transition_out(self, h: _ReplicaHealth, reason: str,
                        detail: str) -> dict:
        h.member = False
        h.gray_ejected = reason == "gray-failure"
        h.consecutive_successes = 0
        h.latencies.clear()  # stale latencies must not re-trip detectors
        epoch = self._bump_epoch()
        if self.view is not None:
            self.view.remove_replica(h.name)  # mirrors into the router
        else:
            self.router.remove_replica(h.name)
        fleet_metrics.MEMBERSHIP_TRANSITIONS.inc(event="ejected")
        if self.recorder is not None:
            self.recorder.warning(
                f"fleet/{h.name}", "ReplicaEjected",
                f"replica {h.name} ejected ({reason}) at membership "
                f"epoch {epoch}: {detail}")
        if self.flight_trigger is not None:
            try:  # forensics must never break the ejection itself
                self.flight_trigger(
                    "fleet_replica_ejected", f"{h.name}: {reason}: {detail}")
            except Exception:  # noqa: BLE001
                pass
        return {"event": "ReplicaEjected", "replica": h.name,
                "reason": reason, "epoch": epoch}

    def _sweep_gauges(self) -> None:
        with self._lock:
            member = sum(1 for h in self._replicas.values() if h.member)
            total = len(self._replicas)
            epoch = self._epoch
        fleet_metrics.MEMBERSHIP_EPOCH.set(epoch)
        fleet_metrics.MEMBERSHIP_REPLICAS.set(member, state="member")
        fleet_metrics.MEMBERSHIP_REPLICAS.set(total - member, state="ejected")

    # -- read side ----------------------------------------------------------

    def epoch(self) -> int:
        """The monotone membership epoch (FleetView's epoch source)."""
        with self._lock:
            return self._epoch

    def members(self) -> "list[str]":
        with self._lock:
            return sorted(n for n, h in self._replicas.items() if h.member)

    def snapshot(self) -> dict:
        """Deterministic detector state for statusz/fleetz and the chaos
        drill artifact."""
        with self._lock:
            rows = {
                n: {
                    "member": h.member,
                    "consecutive_misses": h.consecutive_misses,
                    "latency_p50": round(
                        _quantile(list(h.latencies), 0.5), 6),
                    "latency_p90": round(
                        _quantile(list(h.latencies), 0.9), 6),
                    "samples": len(h.latencies),
                }
                for n, h in sorted(self._replicas.items())
            }
            return {
                "enabled": enabled(),
                "epoch": self._epoch,
                "missed_beats_k": self.missed_beats_k,
                "gray_factor": self.gray_factor,
                "replicas": rows,
            }
