"""Fleet-serving metrics (REGISTRY-registered so gen_docs and statusz pick
them up). The fleet is the first layer whose batch axis is TENANTS, so the
families here answer the multi-tenant triage questions the solver metrics
can't: who is queued, how full the mega-solves run, who is being shed and
why, and what latency each tenant actually sees through the queue."""

from __future__ import annotations

from ..metrics import REGISTRY

QUEUE_DEPTH = REGISTRY.gauge(
    "karpenter_fleet_queue_depth",
    "Requests waiting in a fleet admission queue, by bucket-plan label. "
    "Sustained depth means ticks are under-provisioned for the offered "
    "load (raise max_wave or add replicas).",
    ("bucket",))

REQUESTS = REGISTRY.counter(
    "karpenter_fleet_requests_total",
    "Solve requests admitted to the fleet frontend, by tenant.",
    ("tenant",))

SHED = REGISTRY.counter(
    "karpenter_fleet_shed_total",
    "Requests shed without compute, by tenant and where the shed happened "
    "(admission = remaining deadline budget could not survive the next "
    "tick; queue = the budget expired while enqueued).",
    ("tenant", "where"))

MEGA_SOLVES = REGISTRY.counter(
    "karpenter_fleet_mega_solves_total",
    "Coalesced multi-tenant dispatches, by bucket-plan label. One count "
    "here covers every request in the batch (see batch occupancy).",
    ("bucket",))

BATCH_OCCUPANCY = REGISTRY.histogram(
    "karpenter_fleet_batch_occupancy_ratio",
    "Mega-solve batch size / max_wave per tick dispatch. Persistently low "
    "occupancy means the tick interval is too short (batches never fill); "
    "pinned at 1.0 means the wave cap is the throughput ceiling.",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))

TENANT_SOLVE_SECONDS = REGISTRY.histogram(
    "karpenter_fleet_tenant_solve_seconds",
    "End-to-end fleet latency per served request (admission to demuxed "
    "result), by tenant — queue wait included, which is the point.",
    ("tenant",))

WAIT_TICKS = REGISTRY.histogram(
    "karpenter_fleet_wait_ticks",
    "Ticks a served request spent queued before dispatch, by tenant. The "
    "fairness invariant bounds this at the frontend's starvation bound.",
    ("tenant",),
    buckets=(0, 1, 2, 4, 8, 16, 32))
