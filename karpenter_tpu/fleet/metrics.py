"""Fleet-serving metrics (REGISTRY-registered so gen_docs and statusz pick
them up). The fleet is the first layer whose batch axis is TENANTS, so the
families here answer the multi-tenant triage questions the solver metrics
can't: who is queued, how full the mega-solves run, who is being shed and
why, and what latency each tenant actually sees through the queue."""

from __future__ import annotations

from ..metrics import REGISTRY
from ..metrics.cardinality import OTHER, CardinalityGuard

# Every tenant-labeled family below routes its label values through this
# guard: exact series for the top-K heaviest tenants, everything else in
# one `tenant="_other"` rollup, so series stay O(K) at 1000+ tenants.
TENANT_GUARD = CardinalityGuard()


def tenant_label(tenant_id: str, amount: float = 1.0) -> str:
    """The guarded label value for one tenant observation (offers to the
    top-K sketch; an eviction folds the loser's series into the rollup)."""
    return TENANT_GUARD.label(tenant_id, amount)


def tenant_peek(tenant_id: str) -> str:
    """Read-only guarded label (for gauge sweeps: tracked id or _other)."""
    return TENANT_GUARD.peek(tenant_id)


QUEUE_DEPTH = REGISTRY.gauge(
    "karpenter_fleet_queue_depth",
    "Requests waiting in a fleet admission queue, by bucket-plan label. "
    "Sustained depth means ticks are under-provisioned for the offered "
    "load (raise max_wave or add replicas).",
    ("bucket",))

REQUESTS = REGISTRY.counter(
    "karpenter_fleet_requests_total",
    "Solve requests admitted to the fleet frontend, by tenant.",
    ("tenant",))

SHED = REGISTRY.counter(
    "karpenter_fleet_shed_total",
    "Requests shed without compute, by tenant and where the shed happened "
    "(admission = remaining deadline budget could not survive the next "
    "tick; queue = the budget expired while enqueued; failover = the "
    "request is poison-quarantined).",
    ("tenant", "where"))

MEGA_SOLVES = REGISTRY.counter(
    "karpenter_fleet_mega_solves_total",
    "Coalesced multi-tenant dispatches, by bucket-plan label. One count "
    "here covers every request in the batch (see batch occupancy).",
    ("bucket",))

BATCH_OCCUPANCY = REGISTRY.histogram(
    "karpenter_fleet_batch_occupancy_ratio",
    "Mega-solve batch size / max_wave per tick dispatch. Persistently low "
    "occupancy means the tick interval is too short (batches never fill); "
    "pinned at 1.0 means the wave cap is the throughput ceiling.",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))

TENANT_SOLVE_SECONDS = REGISTRY.histogram(
    "karpenter_fleet_tenant_solve_seconds",
    "End-to-end fleet latency per served request (admission to demuxed "
    "result), by tenant — queue wait included, which is the point.",
    ("tenant",))

WAIT_TICKS = REGISTRY.histogram(
    "karpenter_fleet_wait_ticks",
    "Ticks a served request spent queued before dispatch, by tenant. The "
    "fairness invariant bounds this at the frontend's starvation bound.",
    ("tenant",),
    buckets=(0, 1, 2, 4, 8, 16, 32))

TENANT_QUEUE_DEPTH = REGISTRY.gauge(
    "karpenter_fleet_tenant_queue_depth",
    "Requests waiting in fleet queues per tracked tenant (top-K exact; "
    f"everything else rolls up under tenant=\"{OTHER}\"). A tenant pinned "
    "high here while others drain is the fairness-triage entry point.",
    ("tenant",))

TENANT_FAIR_SHARE_DEFICIT = REGISTRY.gauge(
    "karpenter_fleet_tenant_fair_share_deficit",
    "Queued requests beyond the tenant's per-tick fair share (depth minus "
    "weighted share, floored at 0), per tracked tenant. Persistent "
    "deficit means the tenant offers more than its share and is the one "
    "paying queue latency for it.",
    ("tenant",))

TENANT_SHED = REGISTRY.counter(
    "karpenter_fleet_tenant_shed_total",
    "Shed requests per tracked tenant, split by where the shed happened "
    "(admission/queue) and reason. The chaos storm's shed-attribution "
    "invariant reconciles this family against frontend totals.",
    ("tenant", "where", "reason"))

# -- membership plane (fleet/membership.py) --------------------------------
# All labels below are code-enumerable or bounded by fleet size (replica
# counts are deployment config, not tenant-scale), so none need the guard.

MEMBERSHIP_EPOCH = REGISTRY.gauge(
    "karpenter_fleet_membership_epoch",
    "The monotone membership epoch: bumped on every evidence-driven "
    "join/eject/recover. Observers order membership views by it "
    "(/debug/fleetz stamps the same source); it NEVER regresses — the "
    "chaos partition drill's membership-epoch-monotone invariant.")

MEMBERSHIP_REPLICAS = REGISTRY.gauge(
    "karpenter_fleet_membership_replicas",
    "Tracked replicas by membership state (member = in the router's "
    "rendezvous set; ejected = failed a detector, still probed for "
    "recovery).",
    ("state",))

MEMBERSHIP_PROBES = REGISTRY.counter(
    "karpenter_fleet_membership_probes_total",
    "Heartbeat probes by outcome (ok/fail). The K-missed-beats detector "
    "ejects a replica after MISSED_BEATS_K consecutive failures; a "
    "sustained fail rate with no ejection means detection is wedged.",
    ("outcome",))

MEMBERSHIP_TRANSITIONS = REGISTRY.counter(
    "karpenter_fleet_membership_transitions_total",
    "Edge-triggered membership transitions (joined/ejected/recovered). "
    "Ejections fire a ReplicaEjected event and a flight-recorder bundle; "
    "a joined/recovered edge means rendezvous routing just remapped "
    "~1/R of tenants.",
    ("event",))

# -- federation scrape plane (introspect/fleetview.py) ---------------------
# `kind` is the closed ScrapeError vocabulary (timeout/connect/http-NNN/
# invalid-json/oversized-response) — bounded, so no guard.

SCRAPE_ERRORS = REGISTRY.counter(
    "karpenter_fleet_scrape_errors_total",
    "Federated statusz scrapes that degraded to a named error row, by "
    "failure kind (HttpReplica hardening: timeout, connect, http-<code>, "
    "invalid-json, oversized-response). Each failure also feeds the "
    "per-replica probe breaker, so a corpse backs off instead of "
    "costing every fleetz snapshot a timeout.",
    ("kind",))

SCRAPE_LATENCY = REGISTRY.histogram(
    "karpenter_fleet_scrape_latency_seconds",
    "Wall-clock cost of one successful per-replica statusz scrape over "
    "HTTP (the same number surfaced per row as scrape_ms in "
    "/debug/fleetz). Rising scrape latency is the gray-failure smell "
    "at the observability layer.")

# -- failover plane (fleet/failover.py) ------------------------------------

FAILOVER_REROUTES = REGISTRY.counter(
    "karpenter_fleet_failover_reroutes_total",
    "Client-side failover hops past a replica, by cause (unavailable = "
    "connection refused; timeout = deadline/blackhole; crash = the "
    "request killed its server; breaker-open = failed fast without a "
    "socket). Every hop beyond the first attempt is charged to the "
    "shared retry budget.",
    ("cause",))

FAILOVER_HEDGES = REGISTRY.counter(
    "karpenter_fleet_failover_hedges_total",
    "Tail hedges by outcome (fired = the home replica timed out at the "
    "hedge horizon and the one budgeted backup launched; win = that "
    "backup served the request). At most one hedge per request.",
    ("outcome",))

FAILOVER_QUARANTINED = REGISTRY.counter(
    "karpenter_fleet_failover_quarantined_total",
    "Requests quarantined as poison pills: implicated in crashing or "
    "timing out VICTIM_LIMIT distinct replicas. Each is shed with "
    "reason \"poison-quarantine\" (a shed DecisionRecord in the explain "
    "plane) instead of hunting further victims.")

FAILOVER_COLD_REMAPS = REGISTRY.counter(
    "karpenter_fleet_failover_cold_remaps_total",
    "Tenants served by a replica other than their previous home: the "
    "new home held neither the synced catalog nor warm compiled "
    "programs (warm-state loss; the on_remap hook re-Syncs before the "
    "solve). Expect ~1/R of active tenants per replica death.")

# Guarded tenant families: an eviction from the top-K folds each of these
# families' evicted series into the rollup (counters/histograms merge,
# gauges drop and re-set on the next sweep).
for _m in (REQUESTS, SHED, TENANT_SOLVE_SECONDS, WAIT_TICKS,
           TENANT_QUEUE_DEPTH, TENANT_FAIR_SHARE_DEFICIT, TENANT_SHED):
    TENANT_GUARD.watch(_m, label="tenant")
del _m
