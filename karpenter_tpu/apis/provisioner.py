"""Provisioner API object: the user-facing capacity policy.

Parity target: the v1alpha5 Provisioner CRD whose full schema is snapshotted at
/root/reference/pkg/apis/crds/karpenter.sh_provisioners.yaml:24-305 (fields:
requirements, limits, taints, startupTaints, ttlSecondsAfterEmpty,
ttlSecondsUntilExpired, consolidation, weight, kubeletConfiguration, labels,
provider/providerRef) plus the AWS defaulting/validation alias at
/root/reference/pkg/apis/v1alpha5/provisioner.go:30-60 (defaults: linux OS,
amd64 arch, on-demand capacity type).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..models.pod import Taint
from ..models.requirements import Requirement, Requirements, OP_IN
from . import wellknown as wk


class ValidationError(ValueError):
    pass


@dataclasses.dataclass
class Limits:
    """Provisioner.spec.limits.resources — cluster-wide caps per provisioner
    (designs/limits.md; crds yaml `limits`)."""

    cpu_millis: Optional[int] = None
    memory_bytes: Optional[int] = None

    def exceeded_by(self, used_cpu_millis: int, used_memory_bytes: int) -> "Optional[str]":
        if self.cpu_millis is not None and used_cpu_millis > self.cpu_millis:
            return f"cpu limit exceeded: {used_cpu_millis}m > {self.cpu_millis}m"
        if self.memory_bytes is not None and used_memory_bytes > self.memory_bytes:
            return f"memory limit exceeded: {used_memory_bytes} > {self.memory_bytes}"
        return None


@dataclasses.dataclass
class KubeletConfiguration:
    """Provisioner.spec.kubeletConfiguration subset that affects scheduling
    (maxPods, podsPerCore, reserved resources; settings.md + instancetype.go
    overhead math)."""

    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    system_reserved_cpu_millis: int = 0
    system_reserved_memory_bytes: int = 0
    kube_reserved_cpu_millis: Optional[int] = None
    kube_reserved_memory_bytes: Optional[int] = None
    eviction_hard_memory_bytes: int = 100 * 2**20  # 100Mi default
    # bootstrap passthrough (no scheduling impact — rendered into the
    # node's kubelet flags by the image family; reference CRD
    # karpenter.sh_provisioners.yaml kubeletConfiguration properties)
    cluster_dns: "tuple[str, ...]" = ()
    container_runtime: Optional[str] = None
    cpu_cfs_quota: Optional[bool] = None
    eviction_soft: "tuple[tuple[str, str], ...]" = ()
    eviction_soft_grace_period: "tuple[tuple[str, str], ...]" = ()
    eviction_max_pod_grace_period: Optional[int] = None
    image_gc_high_threshold_percent: Optional[int] = None
    image_gc_low_threshold_percent: Optional[int] = None


@dataclasses.dataclass
class Provisioner:
    name: str
    requirements: Requirements = dataclasses.field(default_factory=Requirements)
    taints: "tuple[Taint, ...]" = ()
    startup_taints: "tuple[Taint, ...]" = ()
    labels: "tuple[tuple[str, str], ...]" = ()
    # applied to every node this provisioner launches (CRD spec.annotations)
    annotations: "tuple[tuple[str, str], ...]" = ()
    limits: Limits = dataclasses.field(default_factory=Limits)
    weight: int = 0  # higher wins when multiple provisioners match (core semantics)
    ttl_seconds_after_empty: Optional[int] = None
    ttl_seconds_until_expired: Optional[int] = None
    consolidation_enabled: bool = False
    kubelet: KubeletConfiguration = dataclasses.field(default_factory=KubeletConfiguration)
    provider_ref: Optional[str] = None  # NodeTemplate name
    # status.resources maintained by the counters controller
    # (controllers/counters.py) — NOT part of the spec: excluded from the
    # solver wire mapping, so status churn never invalidates solver caches
    status_resources: "dict[str, str]" = dataclasses.field(default_factory=dict)

    def set_defaults(self) -> None:
        """Reference defaulting (v1alpha5/provisioner.go:45-60): default OS
        linux, arch amd64, capacity-type on-demand when unconstrained."""
        defaults = (
            (wk.LABEL_OS, "linux"),
            (wk.LABEL_ARCH, "amd64"),
            (wk.LABEL_CAPACITY_TYPE, wk.CAPACITY_TYPE_ON_DEMAND),
        )
        for key, value in defaults:
            if self.requirements.get(key) is None:
                self.requirements.add(Requirement.create(key, OP_IN, [value]))

    def validate(self) -> None:
        """Reference validation (v1alpha5/provisioner.go:34-43 + core):
        restricted labels, consolidation/ttlSecondsAfterEmpty mutual
        exclusion, non-negative TTLs/weight."""
        for req in self.requirements:
            if req.key in wk.RESTRICTED_LABELS:
                raise ValidationError(f"restricted label in requirements: {req.key}")
        for key, _ in self.labels:
            if key in wk.RESTRICTED_LABELS:
                raise ValidationError(f"restricted label: {key}")
        if self.consolidation_enabled and self.ttl_seconds_after_empty is not None:
            raise ValidationError(
                "consolidation and ttlSecondsAfterEmpty are mutually exclusive"
            )
        for ttl in (self.ttl_seconds_after_empty, self.ttl_seconds_until_expired):
            if ttl is not None and ttl < 0:
                raise ValidationError("TTLs must be non-negative")
        if self.weight < 0 or self.weight > 100:
            raise ValidationError("weight must be in [0, 100]")

    def scheduling_requirements(self) -> Requirements:
        """requirements ∪ static labels, the constraint set a node of this
        provisioner will carry."""
        reqs = self.requirements.copy()
        for k, v in self.labels:
            reqs.add(Requirement.create(k, OP_IN, [v]))
        return reqs
