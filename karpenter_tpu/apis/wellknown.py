"""Well-known scheduling labels and resource names.

Parity target: /root/reference/pkg/apis/v1alpha1/register.go:30-115 (AWS label
set: instance-category/family/generation/size/cpu/memory/gpu-*/local-nvme/
ami-id/instance-pods + extended resources nvidia.com/gpu, amd.com/gpu,
aws.amazon.com/neuron, habana.ai/gaudi, vpc.amazonaws.com/pod-eni) and the
karpenter-core well-known set consumed at
/root/reference/pkg/cloudprovider/instancetype.go:67-117 (arch, os, zone,
capacity-type, instance-type).

This build is cloud-agnostic with a TPU-cloud flavor: the well-known label
vocabulary keeps the reference's keys (so reference workloads schedule
unchanged) and adds TPU accelerator labels/resources.
"""

from __future__ import annotations

# -- core k8s / karpenter labels -------------------------------------------------
LABEL_ARCH = "kubernetes.io/arch"
LABEL_OS = "kubernetes.io/os"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_CAPACITY_TYPE = "karpenter.sh/capacity-type"
LABEL_PROVISIONER = "karpenter.sh/provisioner-name"

# -- provider instance-shape labels (reference: register.go:30-115) --------------
LABEL_INSTANCE_CATEGORY = "karpenter.k8s.tpu/instance-category"
LABEL_INSTANCE_FAMILY = "karpenter.k8s.tpu/instance-family"
LABEL_INSTANCE_GENERATION = "karpenter.k8s.tpu/instance-generation"
LABEL_INSTANCE_SIZE = "karpenter.k8s.tpu/instance-size"
LABEL_INSTANCE_CPU = "karpenter.k8s.tpu/instance-cpu"
LABEL_INSTANCE_MEMORY = "karpenter.k8s.tpu/instance-memory"
LABEL_INSTANCE_PODS = "karpenter.k8s.tpu/instance-pods"
LABEL_INSTANCE_GPU_NAME = "karpenter.k8s.tpu/instance-gpu-name"
LABEL_INSTANCE_GPU_COUNT = "karpenter.k8s.tpu/instance-gpu-count"
LABEL_INSTANCE_GPU_MEMORY = "karpenter.k8s.tpu/instance-gpu-memory"
LABEL_INSTANCE_ACCEL_NAME = "karpenter.k8s.tpu/instance-accelerator-name"
LABEL_INSTANCE_ACCEL_COUNT = "karpenter.k8s.tpu/instance-accelerator-count"
LABEL_INSTANCE_LOCAL_NVME = "karpenter.k8s.tpu/instance-local-nvme"
LABEL_INSTANCE_HYPERVISOR = "karpenter.k8s.tpu/instance-hypervisor"
LABEL_AMI_ID = "karpenter.k8s.tpu/instance-ami-id"

# Numeric labels support Gt/Lt operators (reference: core scheduling algebra,
# consumed at instancetype.go:67-117 for instance-cpu/-memory/-gpu-count).
NUMERIC_LABELS = frozenset({
    LABEL_INSTANCE_CPU,
    LABEL_INSTANCE_MEMORY,
    LABEL_INSTANCE_PODS,
    LABEL_INSTANCE_GPU_COUNT,
    LABEL_INSTANCE_GPU_MEMORY,
    LABEL_INSTANCE_ACCEL_COUNT,
    LABEL_INSTANCE_GENERATION,
    LABEL_INSTANCE_LOCAL_NVME,
})

# Restricted labels: users may not set these on Provisioners directly
# (reference: core v1alpha5 restricted set + tags.go:29+ restricted tags).
RESTRICTED_LABELS = frozenset({
    LABEL_PROVISIONER,
    "kubernetes.io/cluster",
})

WELL_KNOWN_LABELS = frozenset({
    LABEL_ARCH, LABEL_OS, LABEL_ZONE, LABEL_REGION, LABEL_HOSTNAME,
    LABEL_INSTANCE_TYPE, LABEL_CAPACITY_TYPE, LABEL_PROVISIONER,
    LABEL_INSTANCE_CATEGORY, LABEL_INSTANCE_FAMILY, LABEL_INSTANCE_GENERATION,
    LABEL_INSTANCE_SIZE, LABEL_INSTANCE_CPU, LABEL_INSTANCE_MEMORY,
    LABEL_INSTANCE_PODS, LABEL_INSTANCE_GPU_NAME, LABEL_INSTANCE_GPU_COUNT,
    LABEL_INSTANCE_GPU_MEMORY, LABEL_INSTANCE_ACCEL_NAME,
    LABEL_INSTANCE_ACCEL_COUNT, LABEL_INSTANCE_LOCAL_NVME,
    LABEL_INSTANCE_HYPERVISOR, LABEL_AMI_ID,
})

# -- capacity types (reference: v1alpha5 CapacityTypeSpot/OnDemand) --------------
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPES = (CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT)

# -- resource names ---------------------------------------------------------------
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
RESOURCE_EPHEMERAL = "ephemeral-storage"
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"
RESOURCE_AMD_GPU = "amd.com/gpu"
RESOURCE_TPU = "google.com/tpu"
RESOURCE_NEURON = "aws.amazon.com/neuron"
RESOURCE_GAUDI = "habana.ai/gaudi"
RESOURCE_POD_ENI = "vpc.amazonaws.com/pod-eni"

# Requests for resource names outside the axis land on this sentinel slot; no
# instance type ever advertises capacity for it, so such pods are correctly
# unschedulable rather than silently zero-demand.
RESOURCE_UNKNOWN = "__unknown__"

# Canonical resource axis for array encodings. Order is load-bearing: it is the
# R axis of every capacity/request tensor. (Reference analogue: the resource
# union built at instancetype.go:128-163.)
RESOURCE_AXIS = (
    RESOURCE_CPU,          # millicores
    RESOURCE_MEMORY,       # MiB
    RESOURCE_PODS,         # count
    RESOURCE_EPHEMERAL,    # GiB
    RESOURCE_NVIDIA_GPU,   # count
    RESOURCE_AMD_GPU,      # count
    RESOURCE_TPU,          # count
    RESOURCE_NEURON,       # count
    RESOURCE_GAUDI,        # count
    RESOURCE_POD_ENI,      # count
    RESOURCE_UNKNOWN,      # sentinel: capacity always 0
)
RESOURCE_INDEX = {name: i for i, name in enumerate(RESOURCE_AXIS)}
NUM_RESOURCES = len(RESOURCE_AXIS)

EXTENDED_RESOURCES = frozenset(RESOURCE_AXIS[4:-1])

# Per-resource canonical unit scale: raw-unit value / scale = axis value.
# cpu: millicores stay exact; memory: bytes -> MiB; ephemeral: bytes -> GiB.
# Chosen so realistic magnitudes stay < 2**24 and are exact in float32.
_MEM_SCALE = 2**20
_EPH_SCALE = 2**30


def resource_vector(requests: "dict[str, int]") -> "list[int]":
    """dict of canonical-unit ints (cpu millis, memory bytes, counts) -> R-axis list."""
    vec = [0] * NUM_RESOURCES
    for name, val in requests.items():
        idx = RESOURCE_INDEX.get(name)
        if idx is None:
            # unknown resource: demand lands on the sentinel slot, which no
            # capacity ever satisfies -> pod is unschedulable, as in the
            # reference (unknown extended resources never fit).
            if val > 0:
                vec[RESOURCE_INDEX[RESOURCE_UNKNOWN]] += val
            continue
        if name == RESOURCE_MEMORY:
            val = -(-val // _MEM_SCALE)  # ceil to MiB: request rounds up
        elif name == RESOURCE_EPHEMERAL:
            val = -(-val // _EPH_SCALE)
        vec[idx] = val
    return vec


def raw_resources_from_vector(vec: "list[int]") -> "dict[str, int]":
    """Inverse of capacity_vector: axis-unit vector -> raw-unit dict
    (cpu millis, memory BYTES, ephemeral BYTES, counts). Zero entries and the
    unknown sentinel are omitted."""
    out: "dict[str, int]" = {}
    for name, val in zip(RESOURCE_AXIS, vec):
        if val <= 0 or name == RESOURCE_UNKNOWN:
            continue
        if name == RESOURCE_MEMORY:
            val = val * _MEM_SCALE
        elif name == RESOURCE_EPHEMERAL:
            val = val * _EPH_SCALE
        out[name] = int(val)
    return out


def capacity_vector(capacity: "dict[str, int]") -> "list[int]":
    """Like resource_vector but rounds memory/storage DOWN (capacity is floor)."""
    vec = [0] * NUM_RESOURCES
    for name, val in capacity.items():
        idx = RESOURCE_INDEX.get(name)
        if idx is None:
            continue
        if name == RESOURCE_MEMORY:
            val = val // _MEM_SCALE
        elif name == RESOURCE_EPHEMERAL:
            val = val // _EPH_SCALE
        vec[idx] = val
    return vec
