"""NodeTemplate: provider-specific node configuration CRD-equivalent.

Parity target: the `AWSNodeTemplate` v1alpha1 API —
/root/reference/pkg/apis/v1alpha1/awsnodetemplate.go:21-79 (spec: provider
fields + userData/imageSelector/detailedMonitoring; status: resolved
subnets/security groups) and provider.go:24-186 (imageFamily,
instanceProfile, subnetSelector, securityGroupSelector, tags, launchTemplate
name, metadataOptions, blockDeviceMappings), with validation per
awsnodetemplate_validation.go / provider_validation.go:46+ and restricted
tags per tags.go:29+.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .provisioner import ValidationError

IMAGE_FAMILIES = ("ubuntu-k8s", "flatboat", "custom")
RESTRICTED_TAG_PREFIXES = ("karpenter.sh/", "kubernetes.io/cluster")


@dataclasses.dataclass
class MetadataOptions:
    http_endpoint: str = "enabled"
    http_tokens: str = "required"
    http_put_response_hop_limit: int = 2

    def validate(self):
        if self.http_endpoint not in ("enabled", "disabled"):
            raise ValidationError("metadataOptions.httpEndpoint must be enabled|disabled")
        if self.http_tokens not in ("required", "optional"):
            raise ValidationError("metadataOptions.httpTokens must be required|optional")


@dataclasses.dataclass
class BlockDeviceMapping:
    device_name: str = "/dev/sda1"
    volume_size_gib: int = 20
    volume_type: str = "ssd"
    encrypted: bool = True
    iops: Optional[int] = None

    def validate(self):
        if self.volume_size_gib < 1:
            raise ValidationError("blockDeviceMapping.volumeSize must be >= 1GiB")
        if self.volume_type not in ("ssd", "balanced", "throughput"):
            raise ValidationError(f"unknown volume type {self.volume_type}")


@dataclasses.dataclass
class NodeTemplateStatus:
    subnets: "list[dict]" = dataclasses.field(default_factory=list)
    security_groups: "list[str]" = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class NodeTemplate:
    name: str
    image_family: str = "ubuntu-k8s"
    instance_profile: str = ""
    subnet_selector: "dict[str, str]" = dataclasses.field(default_factory=dict)
    security_group_selector: "dict[str, str]" = dataclasses.field(default_factory=dict)
    image_selector: "dict[str, str]" = dataclasses.field(default_factory=dict)
    userdata: str = ""
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)
    launch_template_name: str = ""  # static LT passthrough (launchtemplate.go:93-96)
    metadata_options: MetadataOptions = dataclasses.field(default_factory=MetadataOptions)
    block_device_mappings: "tuple[BlockDeviceMapping, ...]" = ()
    detailed_monitoring: bool = False
    generation: int = 1
    status: NodeTemplateStatus = dataclasses.field(default_factory=NodeTemplateStatus)

    def validate(self) -> None:
        if self.image_family not in IMAGE_FAMILIES:
            raise ValidationError(
                f"imageFamily must be one of {IMAGE_FAMILIES}, got {self.image_family!r}")
        if self.image_family == "custom" and not self.image_selector:
            raise ValidationError("imageFamily=custom requires imageSelector")
        if self.launch_template_name and (
                self.userdata or self.image_selector or self.block_device_mappings):
            raise ValidationError(
                "launchTemplateName is mutually exclusive with userData/"
                "imageSelector/blockDeviceMappings")
        if not self.subnet_selector:
            # launch always needs subnets for the zonal overrides, static LT
            # or not (instance.go:325-373)
            raise ValidationError("subnetSelector is required")
        for key in self.tags:
            if any(key.startswith(p) for p in RESTRICTED_TAG_PREFIXES):
                raise ValidationError(f"restricted tag key: {key}")
        self.metadata_options.validate()
        for bdm in self.block_device_mappings:
            bdm.validate()

    def set_defaults(self) -> None:
        if not self.image_family:
            self.image_family = "ubuntu-k8s"
