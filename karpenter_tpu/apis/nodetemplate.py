"""NodeTemplate: provider-specific node configuration CRD-equivalent.

Parity target: the `AWSNodeTemplate` v1alpha1 API —
/root/reference/pkg/apis/v1alpha1/awsnodetemplate.go:21-79 (spec: provider
fields + userData/imageSelector/detailedMonitoring; status: resolved
subnets/security groups) and provider.go:24-186 (imageFamily,
instanceProfile, subnetSelector, securityGroupSelector, tags, launchTemplate
name, metadataOptions, blockDeviceMappings), with validation per
awsnodetemplate_validation.go / provider_validation.go:46+ and restricted
tags per tags.go:29+.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from .provisioner import ValidationError

IMAGE_FAMILIES = ("ubuntu-k8s", "flatboat", "custom")
RESTRICTED_TAG_PREFIXES = ("karpenter.sh/", "kubernetes.io/cluster")

# resource-id shapes for "id"/"ids" selector values (reference:
# provider_validation.go:40-44 subnetRegex/securityGroupRegex, and
# awsnodetemplate_validation.go amiRegex)
_ID_RES = {
    "subnet": re.compile(r"^subnet-[0-9a-z-]+$"),
    "sg": re.compile(r"^sg-[0-9a-z-]+$"),
    "img": re.compile(r"^img-[0-9a-z-]+$"),
}
MAX_VOLUME_GIB = 64 * 1024  # 64 TiB (provider_validation.go maxVolumeSize)


def _validate_selector(field: str, selector: "dict[str, str]",
                       id_kind: Optional[str] = None) -> None:
    """Selector hygiene (provider_validation.go:86-100): no empty keys or
    values; explicit "id"/"ids" values must be well-formed resource ids."""
    for key, value in selector.items():
        if key == "" or value == "":
            raise ValidationError(
                f"{field}[{key!r}] must have a non-empty key and value")
        if id_kind is not None and key in ("id", "ids"):
            regex = _ID_RES[id_kind]
            for item in value.split(","):
                if not regex.match(item.strip()):
                    raise ValidationError(
                        f"{field}[{key!r}]: {item.strip()!r} is not a valid "
                        f"{id_kind} id ({regex.pattern})")


@dataclasses.dataclass
class MetadataOptions:
    http_endpoint: str = "enabled"
    http_tokens: str = "required"
    http_put_response_hop_limit: int = 2
    http_protocol_ipv6: str = "disabled"  # dual-stack metadata endpoint

    def validate(self):
        if self.http_endpoint not in ("enabled", "disabled"):
            raise ValidationError("metadataOptions.httpEndpoint must be enabled|disabled")
        if self.http_tokens not in ("required", "optional"):
            raise ValidationError("metadataOptions.httpTokens must be required|optional")
        if self.http_protocol_ipv6 not in ("enabled", "disabled"):
            raise ValidationError(
                "metadataOptions.httpProtocolIPv6 must be enabled|disabled")
        if not 1 <= self.http_put_response_hop_limit <= 64:
            # provider_validation.go:169-177 bounds
            raise ValidationError(
                "metadataOptions.httpPutResponseHopLimit must be in [1, 64]")

    def is_default(self) -> bool:
        return self == MetadataOptions()


@dataclasses.dataclass
class BlockDeviceMapping:
    device_name: str = "/dev/sda1"
    volume_size_gib: int = 20
    volume_type: str = "ssd"
    encrypted: bool = True
    iops: Optional[int] = None

    def validate(self):
        if not self.device_name:
            raise ValidationError("blockDeviceMapping.deviceName is required")
        if not 1 <= self.volume_size_gib <= MAX_VOLUME_GIB:
            raise ValidationError(
                f"blockDeviceMapping.volumeSize must be in [1GiB, 64TiB], "
                f"got {self.volume_size_gib}GiB")
        if self.volume_type not in ("ssd", "balanced", "throughput"):
            raise ValidationError(f"unknown volume type {self.volume_type}")
        if self.iops is not None and self.volume_type != "ssd":
            raise ValidationError("iops is only configurable for ssd volumes")


@dataclasses.dataclass
class NodeTemplateStatus:
    subnets: "list[dict]" = dataclasses.field(default_factory=list)
    security_groups: "list[str]" = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class NodeTemplate:
    name: str
    image_family: str = "ubuntu-k8s"
    instance_profile: str = ""
    subnet_selector: "dict[str, str]" = dataclasses.field(default_factory=dict)
    security_group_selector: "dict[str, str]" = dataclasses.field(default_factory=dict)
    image_selector: "dict[str, str]" = dataclasses.field(default_factory=dict)
    userdata: str = ""
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)
    launch_template_name: str = ""  # static LT passthrough (launchtemplate.go:93-96)
    # fleet "context" (reserved-capacity targeting) passed verbatim to the
    # launch API (reference instance.go:228 Context: nodeTemplate.Spec.Context)
    fleet_context: str = ""
    metadata_options: MetadataOptions = dataclasses.field(default_factory=MetadataOptions)
    block_device_mappings: "tuple[BlockDeviceMapping, ...]" = ()
    detailed_monitoring: bool = False
    generation: int = 1
    status: NodeTemplateStatus = dataclasses.field(default_factory=NodeTemplateStatus)

    def validate(self, cluster_name: Optional[str] = None) -> None:
        """Full v1alpha1 validation (awsnodetemplate_validation.go +
        provider_validation.go:46+ + restricted tags per tags.go:29+;
        per-cluster ownership tag restriction when `cluster_name` given)."""
        if self.image_family not in IMAGE_FAMILIES:
            raise ValidationError(
                f"imageFamily must be one of {IMAGE_FAMILIES}, got {self.image_family!r}")
        if self.image_family == "custom" and not self.image_selector:
            raise ValidationError("imageFamily=custom requires imageSelector")
        if self.launch_template_name:
            # static LT owns bootstrap, networking, devices AND identity:
            # every field it subsumes is mutually exclusive with it
            # (provider_validation.go:64-84 + validateUserData/validateAMISelector)
            conflicts = [
                ("userData", self.userdata),
                ("imageSelector", self.image_selector),
                ("blockDeviceMappings", self.block_device_mappings),
                ("securityGroupSelector", self.security_group_selector),
                ("instanceProfile", self.instance_profile),
                ("metadataOptions", not self.metadata_options.is_default()),
            ]
            for field, present in conflicts:
                if present:
                    raise ValidationError(
                        f"launchTemplateName is mutually exclusive with {field}")
        if not self.subnet_selector:
            # launch always needs subnets for the zonal overrides, static LT
            # or not (instance.go:325-373)
            raise ValidationError("subnetSelector is required")
        if not self.launch_template_name and not self.security_group_selector:
            # matches validateSecurityGroups: SGs required unless the static
            # LT carries them
            raise ValidationError(
                "securityGroupSelector is required without launchTemplateName")
        _validate_selector("subnetSelector", self.subnet_selector, "subnet")
        _validate_selector("securityGroupSelector",
                           self.security_group_selector, "sg")
        _validate_selector("imageSelector", self.image_selector, "img")
        for key, value in self.tags.items():
            if key == "":
                raise ValidationError(
                    f"empty tag keys are not supported (value {value!r})")
            if key.startswith("karpenter.sh/"):
                raise ValidationError(f"restricted tag key: {key}")
            if key.startswith("kubernetes.io/cluster"):
                # With the cluster context, only THIS cluster's ownership tag
                # is karpenter-owned (instance.go:224 stamps it); tagging for
                # other clusters is legitimate shared-infra practice. Without
                # context (direct validate() calls) stay conservative.
                if not cluster_name \
                        or key == f"kubernetes.io/cluster/{cluster_name}":
                    raise ValidationError(
                        f"tag {key} is reserved for cluster ownership")
        self.metadata_options.validate()
        for bdm in self.block_device_mappings:
            bdm.validate()

    def set_defaults(self) -> None:
        if not self.image_family:
            self.image_family = "ubuntu-k8s"
