"""Global settings.

Parity target: /root/reference/pkg/apis/settings/settings.go:40-93 — the
`karpenter-global-settings` ConfigMap schema: required clusterName /
clusterEndpoint (URL-validated), defaultInstanceProfile, vmMemoryOverheadPercent
(default 0.075, min 0), enablePodENI, enableENILimitedPodDensity, isolatedVPC,
interruptionQueueName, tags; plus core batching windows
(batchIdleDuration=1s / batchMaxDuration=10s, website settings.md:43-47) and
feature gates (driftEnabled, settings.md:73-78).
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Optional


class SettingsError(ValueError):
    pass


# module-level: Settings is a dataclass with mutable default-containing
# instances; a per-instance lock would complicate dataclasses.replace
_apply_lock = threading.Lock()


@dataclasses.dataclass
class FeatureGates:
    drift_enabled: bool = False


@dataclasses.dataclass
class Settings:
    cluster_name: str = ""
    cluster_endpoint: str = ""
    default_instance_profile: str = ""
    vm_memory_overhead_percent: float = 0.075
    enable_pod_eni: bool = False
    enable_eni_limited_pod_density: bool = True
    isolated_vpc: bool = False
    interruption_queue_name: str = ""
    # how nodes are named at registration (settings.go:29-47): "ip-name"
    # (default) = the instance's private DNS name; "resource-name" = the
    # cloud instance id
    node_name_convention: str = "ip-name"
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)
    # core provisioning batch windows (settings.md:43-47,81-99)
    batch_idle_duration: float = 1.0
    batch_max_duration: float = 10.0
    feature_gates: FeatureGates = dataclasses.field(default_factory=FeatureGates)
    # solver service endpoint; empty => in-process oracle fallback only
    solver_endpoint: str = ""

    def validate(self) -> None:
        if not self.cluster_name:
            raise SettingsError("clusterName is required")
        if self.cluster_endpoint and not re.match(r"^https://", self.cluster_endpoint):
            raise SettingsError("clusterEndpoint must be a https:// URL")
        if self.vm_memory_overhead_percent < 0:
            raise SettingsError("vmMemoryOverheadPercent must be >= 0")
        if self.batch_idle_duration < 0 or self.batch_max_duration < self.batch_idle_duration:
            raise SettingsError("batchMaxDuration must be >= batchIdleDuration >= 0")
        if self.node_name_convention not in ("ip-name", "resource-name"):
            raise SettingsError(
                "nodeNameConvention must be ip-name or resource-name")
        for key in self.tags:
            if key.startswith("karpenter.sh/") or key.startswith("kubernetes.io/cluster"):
                raise SettingsError(f"restricted tag key: {key}")

    def apply(self, other: "Settings") -> "list[str]":
        """In-place update from a freshly parsed Settings; every component
        holding this object by reference observes the change (the reference's
        live-watched ConfigMap injection, settings.go Inject). Returns the
        names of changed fields.

        Controller threads read fields concurrently; single-field reads are
        atomic under the GIL, and multi-field readers that need a mutually
        consistent view take snapshot(). The lock makes apply+snapshot
        linearize so no snapshot observes a half-applied update."""
        changed = []
        with _apply_lock:
            for f in dataclasses.fields(Settings):
                new = getattr(other, f.name)
                if getattr(self, f.name) != new:
                    setattr(self, f.name, new)
                    changed.append(f.name)
        return changed

    def snapshot(self) -> "Settings":
        """Consistent point-in-time copy for multi-field readers (e.g. the
        batcher reading both batch windows together)."""
        with _apply_lock:
            return dataclasses.replace(
                self, tags=dict(self.tags),
                feature_gates=dataclasses.replace(self.feature_gates))

    @staticmethod
    def from_dict(data: "dict[str, str]") -> "Settings":
        """Parse the ConfigMap-style flat key space (settings.go Inject)."""

        def flag(key, default=False):
            v = data.get(key)
            return default if v is None else str(v).lower() in ("1", "true", "yes")

        def dur(key, default):
            v = data.get(key)
            if v is None:
                return default
            m = re.match(r"^([0-9.]+)(ms|s|m)?$", str(v))
            if not m:
                raise SettingsError(f"invalid duration for {key}: {v!r}")
            mult = {"ms": 0.001, "s": 1.0, "m": 60.0, None: 1.0}[m.group(2)]
            return float(m.group(1)) * mult

        tags = {k[len("tags."):]: v for k, v in data.items() if k.startswith("tags.")}
        s = Settings(
            cluster_name=data.get("clusterName", ""),
            cluster_endpoint=data.get("clusterEndpoint", ""),
            default_instance_profile=data.get("defaultInstanceProfile", ""),
            vm_memory_overhead_percent=float(data.get("vmMemoryOverheadPercent", 0.075)),
            enable_pod_eni=flag("enablePodENI"),
            enable_eni_limited_pod_density=flag("enableENILimitedPodDensity", True),
            isolated_vpc=flag("isolatedVPC"),
            interruption_queue_name=data.get("interruptionQueueName", ""),
            node_name_convention=data.get("nodeNameConvention", "ip-name"),
            tags=tags,
            batch_idle_duration=dur("batchIdleDuration", 1.0),
            batch_max_duration=dur("batchMaxDuration", 10.0),
            feature_gates=FeatureGates(drift_enabled=flag("featureGates.driftEnabled")),
            solver_endpoint=data.get("solverEndpoint", ""),
        )
        s.validate()
        return s
