"""Karpenter-manifest compatibility loader.

Parses the REFERENCE's own YAML kinds — unchanged files from
/root/reference/examples/ work directly (the switch-over contract: a user of
the reference brings their manifests as-is):

- karpenter.sh/v1alpha5 Provisioner  -> apis.provisioner.Provisioner
- karpenter.k8s.aws/v1alpha1 AWSNodeTemplate (and the native
  karpenter.k8s.tpu NodeTemplate) -> apis.nodetemplate.NodeTemplate
- apps/v1 Deployment -> replicas x models.pod.PodSpec
- v1 Pod -> PodSpec
- policy/v1 PodDisruptionBudget -> models.cluster.PodDisruptionBudget

`preferredDuringScheduling` node affinities parse to ordered preference terms
(weight desc) that the scheduler relaxes iteratively, dropping lowest-weight
first — the reference core's progressive preference relaxation. Percentage
PDBs resolve against the workload's replica count when a matching Deployment
is in the same bundle.
Replay parity with the reference's examples is tested in
tests/test_yaml_compat.py (SURVEY.md §7.2 step 1's replay harness).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import yaml

from ..models.cluster import PodDisruptionBudget
from ..models.pod import (PodAffinityTerm, PodSpec, Taint, Toleration,
                          TopologySpreadConstraint)
from ..models.requirements import OP_IN, Requirement, Requirements
from ..utils.quantity import cpu_millis, mem_bytes, count as count_qty
from . import wellknown as wk
from .nodetemplate import BlockDeviceMapping, MetadataOptions, NodeTemplate
from .provisioner import (KubeletConfiguration, Limits, Provisioner,
                          ValidationError)

# reference AMI families -> our image families (providers/images.py)
FAMILY_MAP = {
    "AL2": "ubuntu-k8s",
    "Ubuntu": "ubuntu-k8s",
    "Bottlerocket": "flatboat",
    "Custom": "custom",
    # native names round-trip as themselves (serde emits these; users may
    # also write them directly)
    "ubuntu-k8s": "ubuntu-k8s",
    "Flatboat": "flatboat",
    "flatboat": "flatboat",
    "custom": "custom",
}
# EBS volume types -> our volume classes
VOLUME_MAP = {"gp2": "ssd", "gp3": "ssd", "io1": "ssd", "io2": "ssd",
              "st1": "throughput", "sc1": "throughput", "standard": "balanced",
              # native classes round-trip as themselves
              "ssd": "ssd", "throughput": "throughput", "balanced": "balanced"}

# the reference's provider label namespace -> ours (same suffixes:
# instance-family/-size/-cpu/..., apis/wellknown.py)
_AWS_LABEL_PREFIX = "karpenter.k8s.aws/"
_OUR_LABEL_PREFIX = "karpenter.k8s.tpu/"


def _map_key(key: str) -> str:
    if key.startswith(_AWS_LABEL_PREFIX):
        return _OUR_LABEL_PREFIX + key[len(_AWS_LABEL_PREFIX):]
    return key


@dataclasses.dataclass
class LoadedManifests:
    provisioners: "list[Provisioner]"
    templates: "list[NodeTemplate]"
    pods: "list[PodSpec]"
    pdbs: "list[PodDisruptionBudget]"


def load_manifests(text: str, env: "Optional[dict[str, str]]" = None,
                   replicas_override: "Optional[int]" = None) -> LoadedManifests:
    """Parse a multi-document YAML bundle. `${VAR}` placeholders substitute
    from `env` (the reference's examples use ${CLUSTER_NAME})."""
    for key, value in (env or {}).items():
        text = text.replace("${" + key + "}", value)
    out = LoadedManifests([], [], [], [])
    synthesized: "set[str]" = set()  # templates minted from inline provider
    docs = [d for d in yaml.safe_load_all(text) if d]
    for doc in docs:
        kind = doc.get("kind", "")
        if kind == "Provisioner":
            prov = _provisioner(doc)
            inline = (doc.get("spec") or {}).get("provider")
            if inline:
                # v1alpha5 still accepts the inline vendor block that
                # v1alpha4 introduced (provisioner.go:38 DeserializeProvider)
                # — mutually exclusive with providerRef, loaded as an
                # anonymous NodeTemplate owned by this provisioner
                # (docs/designs/api-evolution.md).
                if prov.provider_ref:
                    raise ValidationError(
                        f"provisioner {prov.name}: spec.provider and "
                        f"spec.providerRef are mutually exclusive")
                out.templates.append(_nodetemplate(
                    {"metadata": {"name": prov.name}, "spec": inline}))
                synthesized.add(prov.name)
                prov.provider_ref = prov.name
            out.provisioners.append(prov)
        elif kind in ("AWSNodeTemplate", "NodeTemplate"):
            out.templates.append(_nodetemplate(doc))
        elif kind == "Deployment":
            out.pods.extend(_deployment_pods(doc, replicas_override))
        elif kind == "Pod":
            out.pods.append(_pod(doc.get("metadata", {}),
                                 doc.get("spec") or {}))
        elif kind == "PodDisruptionBudget":
            out.pdbs.append(_pdb(doc, docs))
    counts: "dict[str, int]" = {}
    for t in out.templates:
        counts[t.name] = counts.get(t.name, 0) + 1
    clash = {n for n in synthesized if counts[n] > 1}
    if clash:
        raise ValidationError(
            f"inline spec.provider synthesizes a NodeTemplate named after "
            f"its provisioner, which collides with an explicit template: "
            f"{sorted(clash)} — rename the provisioner or use providerRef")
    return out


def load_files(*paths, env=None, replicas_override=None) -> LoadedManifests:
    text = "\n---\n".join(open(p).read() for p in paths)
    return load_manifests(text, env=env, replicas_override=replicas_override)


# -- provisioner -------------------------------------------------------------------

def _requirements(items) -> Requirements:
    reqs = Requirements()
    for item in items or ():
        reqs.add(Requirement.create(
            _map_key(item["key"]), item["operator"],
            [str(v) for v in item.get("values", [])]))
    return reqs


def _taints(items) -> "tuple[Taint, ...]":
    return tuple(
        Taint(key=t["key"], value=str(t.get("value", "")),
              effect=t.get("effect", "NoSchedule"))
        for t in items or ())


def _provisioner(doc) -> Provisioner:
    spec_keys = doc.get("spec") or {}
    for removed, instead in (
            ("architecture", "a kubernetes.io/arch requirement"),
            ("operatingSystem", "a kubernetes.io/os requirement"),
            ("cluster", "settings (apis/settings.py)")):
        # scalars the reference removed in v1alpha4 (designs/v1alpha4-api.md)
        # fail loudly instead of silently narrowing the pool
        if removed in spec_keys:
            raise ValidationError(
                f"spec.{removed} was removed in v1alpha4; use {instead}")
    spec = spec_keys  # same fetch, None-safe (explicit `spec:` null)
    limits_spec = (spec.get("limits") or {}).get("resources", {})
    limits = Limits(
        cpu_millis=cpu_millis(limits_spec["cpu"]) if "cpu" in limits_spec else None,
        memory_bytes=mem_bytes(limits_spec["memory"]) if "memory" in limits_spec else None,
    )
    kube = spec.get("kubeletConfiguration") or {}
    sys_res = kube.get("systemReserved") or {}
    kube_res = kube.get("kubeReserved") or {}
    evict = kube.get("evictionHard") or {}
    evict_mem = evict.get("memory.available")
    kubelet = KubeletConfiguration(
        max_pods=kube.get("maxPods"),
        pods_per_core=kube.get("podsPerCore"),
        system_reserved_cpu_millis=cpu_millis(sys_res["cpu"]) if "cpu" in sys_res else 0,
        system_reserved_memory_bytes=mem_bytes(sys_res["memory"]) if "memory" in sys_res else 0,
        kube_reserved_cpu_millis=cpu_millis(kube_res["cpu"]) if "cpu" in kube_res else None,
        kube_reserved_memory_bytes=mem_bytes(kube_res["memory"]) if "memory" in kube_res else None,
        eviction_hard_memory_bytes=mem_bytes(evict_mem) if evict_mem else 100 * 2**20,
        # bootstrap passthrough (reference CRD kubeletConfiguration)
        cluster_dns=tuple(kube.get("clusterDNS") or ()),
        container_runtime=kube.get("containerRuntime"),
        cpu_cfs_quota=kube.get("cpuCFSQuota"),
        eviction_soft=tuple(sorted((kube.get("evictionSoft") or {}).items())),
        eviction_soft_grace_period=tuple(sorted(
            (kube.get("evictionSoftGracePeriod") or {}).items())),
        eviction_max_pod_grace_period=kube.get("evictionMaxPodGracePeriod"),
        image_gc_high_threshold_percent=kube.get("imageGCHighThresholdPercent"),
        image_gc_low_threshold_percent=kube.get("imageGCLowThresholdPercent"),
    )
    p = Provisioner(
        name=doc.get("metadata", {}).get("name", "default"),
        requirements=_requirements(spec.get("requirements")),
        taints=_taints(spec.get("taints")),
        startup_taints=_taints(spec.get("startupTaints")),
        labels=tuple(sorted((spec.get("labels") or {}).items())),
        annotations=tuple(sorted((spec.get("annotations") or {}).items())),
        limits=limits,
        weight=int(spec.get("weight", 0)),
        ttl_seconds_after_empty=spec.get("ttlSecondsAfterEmpty"),
        ttl_seconds_until_expired=spec.get("ttlSecondsUntilExpired"),
        consolidation_enabled=bool((spec.get("consolidation") or {}).get("enabled", False)),
        kubelet=kubelet,
        provider_ref=(spec.get("providerRef") or {}).get("name"),
    )
    p.set_defaults()
    p.validate()
    return p


# -- node template -----------------------------------------------------------------

def _nodetemplate(doc) -> NodeTemplate:
    spec = doc.get("spec") or {}  # None-safe (explicit `spec:` null)
    bdms = []
    for m in spec.get("blockDeviceMappings") or ():
        ebs = m.get("ebs") or {}
        size = ebs.get("volumeSize", "20Gi")
        size_gib = max(1, mem_bytes(str(size)) // 2**30) if not isinstance(size, int) else size
        bdms.append(BlockDeviceMapping(
            device_name=m.get("deviceName", "/dev/sda1"),
            volume_size_gib=int(size_gib),
            volume_type=VOLUME_MAP.get(ebs.get("volumeType", "gp3"), "ssd"),
            encrypted=bool(ebs.get("encrypted", True)),
            iops=ebs.get("iops"),
        ))
    md = spec.get("metadataOptions") or {}
    template = NodeTemplate(
        name=doc.get("metadata", {}).get("name", "default"),
        image_family=FAMILY_MAP.get(spec.get("amiFamily", "AL2"), "ubuntu-k8s"),
        instance_profile=spec.get("instanceProfile", ""),
        subnet_selector=dict(spec.get("subnetSelector") or {}),
        security_group_selector=dict(spec.get("securityGroupSelector") or {}),
        image_selector=dict(spec.get("amiSelector") or {}),
        userdata=spec.get("userData", ""),
        tags=dict(spec.get("tags") or {}),
        launch_template_name=spec.get("launchTemplate", ""),
        fleet_context=spec.get("context", ""),
        metadata_options=MetadataOptions(
            http_endpoint=md.get("httpEndpoint", "enabled"),
            http_tokens=md.get("httpTokens", "required"),
            http_put_response_hop_limit=int(md.get("httpPutResponseHopLimit", 2)),
            http_protocol_ipv6=md.get("httpProtocolIPv6", "disabled"),
        ),
        block_device_mappings=tuple(bdms),
        detailed_monitoring=bool(spec.get("detailedMonitoring", False)),
    )
    template.set_defaults()
    return template


# -- workloads ---------------------------------------------------------------------

def _container_requests(c) -> "dict[str, int]":
    resources = c.get("resources") or {}
    limits = resources.get("limits") or {}
    requests = dict(limits)  # limits imply requests (k8s defaulting rule)
    requests.update(resources.get("requests") or {})
    out: "dict[str, int]" = {}
    for name, qty in requests.items():
        if name == "cpu":
            out["cpu"] = cpu_millis(str(qty))
        elif name in ("memory", "ephemeral-storage"):
            out[name] = mem_bytes(str(qty))
        else:
            out[name] = count_qty(qty)
    return out


def _pod_requests(containers, init_containers=()) -> "dict[str, int]":
    """k8s effective pod requests: max(sum(containers), max(initContainers))
    per resource — init containers run serially before the main set, so the
    node must fit whichever phase is larger (the rule the reference inherits
    from scheduler resource accounting)."""
    total: "dict[str, int]" = {}
    for c in containers or ():
        for name, v in _container_requests(c).items():
            total[name] = total.get(name, 0) + v
    for c in init_containers or ():
        for name, v in _container_requests(c).items():
            if v > total.get(name, 0):
                total[name] = v
    return total


def _pod(metadata, spec, name: str = "", labels=None) -> PodSpec:
    labels = labels if labels is not None else (metadata.get("labels") or {})
    requests = _pod_requests(spec.get("containers"), spec.get("initContainers"))
    reqs = Requirements()
    for k, v in (spec.get("nodeSelector") or {}).items():
        reqs.add(Requirement.create(_map_key(k), OP_IN, [str(v)]))
    affinity = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required = affinity.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in required.get("nodeSelectorTerms") or ():
        for expr in term.get("matchExpressions") or ():
            reqs.add(Requirement.create(
                _map_key(expr["key"]), expr["operator"],
                [str(v) for v in expr.get("values", [])]))
    # preferredDuringScheduling: every term becomes an ordered preference
    # (weight desc); the scheduler relaxes them iteratively, dropping the
    # lowest-weight term first (k8s's weighted scoring, approximated as a
    # lexicographic prefix preference — the reference core's relaxation)
    pref_terms: "list[Requirements]" = []
    preferred = sorted(
        affinity.get("preferredDuringSchedulingIgnoredDuringExecution") or (),
        key=lambda t: -int(t.get("weight", 0)))
    for term in preferred:
        tr = Requirements()
        for expr in (term.get("preference") or {}).get("matchExpressions") or ():
            tr.add(Requirement.create(
                _map_key(expr["key"]), expr["operator"],
                [str(v) for v in expr.get("values", [])]))
        if len(tr):
            pref_terms.append(tr)
    prefs = tuple(pref_terms)
    tolerations = tuple(
        Toleration(key=t.get("key", ""), operator=t.get("operator", "Equal"),
                   value=str(t.get("value", "")), effect=t.get("effect", ""))
        for t in spec.get("tolerations") or ())
    topology = tuple(
        TopologySpreadConstraint(
            max_skew=int(t.get("maxSkew", 1)),
            topology_key=t["topologyKey"],
            when_unsatisfiable=t.get("whenUnsatisfiable", "DoNotSchedule"))
        for t in spec.get("topologySpreadConstraints") or ())
    label_items = {str(k): str(v) for k, v in labels.items()}

    def _term_selector(term) -> "tuple[tuple[str, str], ...]":
        """labelSelector -> conjunctive matchLabels pairs (matchExpressions
        with op In and a single value fold in; other operators are dropped —
        documented approximation, they cannot narrow a conjunctive form)."""
        sel = term.get("labelSelector") or {}
        pairs = {str(k): str(v) for k, v in (sel.get("matchLabels") or {}).items()}
        for expr in sel.get("matchExpressions") or ():
            if expr.get("operator") == "In" and len(expr.get("values", [])) == 1:
                pairs[str(expr["key"])] = str(expr["values"][0])
        return tuple(sorted(pairs.items()))

    def _is_self(sel_pairs) -> bool:
        return all(label_items.get(k) == v for k, v in sel_pairs)

    anti = (spec.get("affinity") or {}).get("podAntiAffinity") or {}
    anti_host = anti_zone = False
    anti_terms: "list[PodAffinityTerm]" = []
    for term in anti.get("requiredDuringSchedulingIgnoredDuringExecution") or ():
        key = term.get("topologyKey", "")
        if key not in (wk.LABEL_HOSTNAME, wk.LABEL_ZONE):
            continue
        sel = _term_selector(term)
        if _is_self(sel):
            # selector matches this pod's own labels: self anti-affinity.
            # An empty/absent labelSelector lands here too (k8s: matches ALL
            # pods) — the cross-group term below then carries the
            # exclude-every-occupied-domain half of that semantics.
            anti_host |= key == wk.LABEL_HOSTNAME
            anti_zone |= key == wk.LABEL_ZONE
        # self-spread and cross-group exclusion are NOT mutually exclusive:
        # the same selector can also match other deployments' pods (e.g.
        # {app: x} with foreign app=x residents), so the term always joins
        # the cross-group exclusion list (resolve_pod_affinity); for the
        # matches-self case the resident-count caps make it redundant but
        # never conflicting.
        anti_terms.append(PodAffinityTerm(match_labels=sel, topology_key=key))
    aff = (spec.get("affinity") or {}).get("podAffinity") or {}
    aff_terms: "list[PodAffinityTerm]" = []
    for term in aff.get("requiredDuringSchedulingIgnoredDuringExecution") or ():
        key = term.get("topologyKey", "")
        if key in (wk.LABEL_HOSTNAME, wk.LABEL_ZONE):
            aff_terms.append(PodAffinityTerm(
                match_labels=_term_selector(term), topology_key=key))
    raw = dict(requests)
    raw.setdefault("pods", 1)
    return PodSpec(
        name=name or metadata.get("name", "pod"),
        labels=tuple(sorted((str(k), str(v)) for k, v in labels.items())),
        requests=tuple(sorted(raw.items())),
        requirements=reqs,
        preferences=prefs,
        tolerations=tolerations,
        topology=topology,
        anti_affinity_hostname=anti_host,
        anti_affinity_zone=anti_zone,
        pod_affinity=tuple(aff_terms),
        pod_anti_affinity=tuple(anti_terms),
        do_not_evict=(metadata.get("annotations") or {}).get(
            "karpenter.sh/do-not-evict", "") == "true",
    )


def _deployment_pods(doc, replicas_override: "Optional[int]") -> "list[PodSpec]":
    spec = doc.get("spec") or {}  # None-safe (explicit `spec:` null)
    replicas = replicas_override if replicas_override is not None \
        else int(spec.get("replicas", 1))
    template = spec.get("template") or {}
    metadata = template.get("metadata", {})
    name = doc.get("metadata", {}).get("name", "workload")
    proto = _pod(metadata, template.get("spec") or {}, name=name)
    return [dataclasses.replace(proto, name=f"{name}-{i}")
            for i in range(replicas)]


def _pdb(doc, all_docs) -> PodDisruptionBudget:
    spec = doc.get("spec") or {}  # None-safe (explicit `spec:` null)
    selector = {str(k): str(v) for k, v in
                ((spec.get("selector") or {}).get("matchLabels") or {}).items()}
    min_available = spec.get("minAvailable")
    max_unavailable = spec.get("maxUnavailable")

    def resolve(value):
        if value is None:
            return None
        if isinstance(value, int):
            return value
        m = re.match(r"^(\d+)%$", str(value))
        if not m:
            return int(value)
        # percentage: resolve against a matching Deployment's replicas in the
        # same bundle (k8s resolves against the live replica count)
        pct = int(m.group(1))
        for d in all_docs:
            if d.get("kind") != "Deployment":
                continue
            labels = (d.get("spec", {}).get("template", {})
                      .get("metadata", {}).get("labels") or {})
            if all(labels.get(k) == v for k, v in selector.items()):
                replicas = int(d.get("spec", {}).get("replicas", 1))
                return -(-pct * replicas // 100)  # ceil, k8s rounding
        # resolving silently to 0 would fail OPEN for minAvailable (every pod
        # evictable) or permanently CLOSED for maxUnavailable — refuse instead
        raise ValueError(
            f"percentage PDB {doc.get('metadata', {}).get('name')!r} needs a "
            f"matching Deployment in the same bundle to resolve {value!r}")

    return PodDisruptionBudget(
        name=doc.get("metadata", {}).get("name", "pdb"),
        selector=selector,
        min_available=resolve(min_available),
        max_unavailable=resolve(max_unavailable),
    )
