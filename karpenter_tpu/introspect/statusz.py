"""One consistent JSON snapshot of the whole operator.

`snapshot(op)` walks every subsystem a triage wants to see at once —
cluster state, per-controller watchdog status and cycle latencies, batcher
and interruption queue depths, solver/compile-cache and pricing/
instance-type cache stats, recent events, and current metric values — and
returns one JSON-serializable dict. Served at `GET /debug/statusz` on the
metrics listener and via `python -m karpenter_tpu statusz`; the flight
recorder's snapshot ring is a deque of these.

Every section is individually fenced: statusz is the surface you read when
something is broken, so one wedged subsystem must degrade its own section
to an error string, not take the whole snapshot down. Timestamps come from
the operator's injected clock (deterministic under FakeClock).
"""

from __future__ import annotations

import logging

from .. import __version__
from ..metrics import REGISTRY, Gauge, Histogram

log = logging.getLogger("karpenter.statusz")

# 13: added "overload" (backpressure gate state + activity counters,
# per-frontend guard snapshots, per-service resident/thrash eviction
# ledger)
# (12: "spot" + caches.pricing per-rung staleness; 11: "critical";
# 10: "incremental"; 9: "pid" + "serving"; 8: "decisions";
# 7: "profiling"; 6: "hbm"; 5: "slo")
SCHEMA_VERSION = 13

# hard caps so a pathological operator can't make statusz unbounded
MAX_EVENTS = 50
MAX_SERIES_PER_METRIC = 50


def _fenced(build):
    try:
        return build()
    except Exception as e:  # noqa: BLE001 — a diagnostic surface degrades
        return {"error": f"{type(e).__name__}: {e}"}


def _cluster_section(op) -> dict:
    nodes = dict(op.cluster.nodes)
    pods = op.kube.list("pods")
    by_provisioner: "dict[str, int]" = {}
    for n in nodes.values():
        key = n.provisioner_name or ""
        by_provisioner[key] = by_provisioner.get(key, 0) + 1
    return {
        "nodes": len(nodes),
        "nodes_by_provisioner": dict(sorted(by_provisioner.items())),
        "nodes_marked_for_deletion": sum(
            1 for n in nodes.values() if n.marked_for_deletion),
        "machines": len(op.kube.list("machines")),
        "pods": len(pods),
        "pending_pods": len(op.kube.pending_pods()),
        "provisioners": len(op.kube.list("provisioners")),
        "nodetemplates": len(op.kube.list("nodetemplates")),
        "pdbs": len(op.cluster.pdbs),
    }


def _queue_section(op) -> dict:
    def depth(batcher) -> "int | None":
        fn = getattr(batcher, "depth", None)
        return fn() if callable(fn) else None

    inst = op.cloudprovider.instances
    out = {
        "create_fleet": depth(getattr(inst, "fleet", None)),
        "describe_instances": depth(getattr(inst, "describe", None)),
        "terminate_instances": depth(getattr(inst, "terminate", None)),
    }
    queue = getattr(op, "queue", None)
    out["interruption"] = (queue.approximate_depth()
                           if queue is not None else None)
    return out


def _cache_section(op) -> dict:
    prov = op.provisioning
    cp = op.cloudprovider
    pricing = cp.pricing
    last = pricing._last_update
    return {
        "solver": {
            "rebuilds": prov.solver_rebuilds,
            "resident_primary": len(prov._solver_cache),
            "resident_native": len(prov._native_cache),
            "route_threshold": prov.route_threshold,
            "last_routing": prov.last_solver_kind,
        },
        "instance_types": {
            "memo_entries": len(cp.instance_types._memo),
            "derived_seqnum": cp.instance_types._version,
            "source_seqnum": cp.instance_types.source.seqnum,
        },
        "ice": {"seqnum": cp.ice.seqnum},
        "pricing": {
            "entries": len(pricing._prices),
            "updates": pricing._updates,
            "last_update_age_s": (None if last is None
                                  else round(op.clock.now() - last, 3)),
            "staleness": pricing.observe_staleness(),
        },
        "launch_templates": {"known": len(cp.launch_templates._known)},
    }


def _events_section(op, n: int = MAX_EVENTS) -> "list[dict]":
    return [{"ts": ts, "kind": e.kind, "reason": e.reason,
             "object": e.object_ref, "message": e.message}
            for ts, e in op.recorder.recent(n)]


def _metrics_section(registry=None) -> dict:
    """Current counter/gauge values and histogram count/sum — the numbers,
    not the exposition text (the bundle carries the full text)."""
    reg = registry if registry is not None else REGISTRY
    out = {}
    with reg._lock:
        metrics = dict(reg._metrics)
    for name in sorted(metrics):
        m = metrics[name]
        if isinstance(m, Histogram):
            with m._lock:
                series = [{"labels": dict(zip(m.label_names, key)),
                           "count": m._totals[key],
                           "sum": round(m._sums[key], 6),
                           # last exemplar: the trace id that resolves this
                           # series at /debug/traces?id=
                           **({"exemplar": m._exemplars[key]["trace_id"]}
                              if key in m._exemplars else {})}
                          for key in sorted(m._totals)]
        else:
            series = [{"labels": labels, "value": v}
                      for labels, v in m.collect()]
        if not series:
            continue
        out[name] = {
            "type": ("histogram" if isinstance(m, Histogram)
                     else "gauge" if isinstance(m, Gauge) else "counter"),
            "series": series[:MAX_SERIES_PER_METRIC],
            "series_total": len(series),
        }
    return out


def _fleet_section() -> dict:
    # lazy import: the fleet layer is optional (and imports the solver
    # stack); statusz must stay importable without it
    from ..fleet.frontend import active_frontends

    return {"frontends": [f.stats() for f in active_frontends()]}


def _hbm_section() -> dict:
    # lazy import mirrors _fleet_section (buckets imports no jax at module
    # level, but the solver package is still optional surface area here)
    from ..solver.buckets import HBM

    return HBM.snapshot()


def _incremental_section(op) -> dict:
    # the delta-aware solving plane: gate state, monotone activity
    # counters, and the provisioning controller's last solve (mode,
    # dirty/sub/full node counts, escape reason when one fired)
    from .. import incremental

    out = {"enabled": incremental.enabled(),
           "counters": incremental.activity()}
    inc = getattr(getattr(op, "provisioning", None), "_incremental", None)
    if inc is not None and inc.last is not None:
        out["last_solve"] = dict(inc.last)
    return out


def _profiling_section() -> dict:
    # the attribution plane's own snapshot: sampler health/overhead, device
    # ladder mode, and the gap ledger's phase totals + last rows
    from ..profiling import snapshot as profiling_snapshot

    return profiling_snapshot()


def _critical_section() -> dict:
    # the critical-path plane's snapshot: overlap ratio + chain of the
    # most recent solves, cumulative wait-vocabulary totals, and the
    # measured-roofline rung table with drift flags (full rows live at
    # /debug/criticalz and in flight-recorder bundles)
    from ..profiling import critical

    return critical.snapshot()


def _decisions_section() -> dict:
    # the explain plane's snapshot: ring activity counters, the reason
    # vocabulary, and the most recent DecisionRecord ids (full records
    # live at /debug/decisions and in flight-recorder bundles)
    from ..explain import snapshot as explain_snapshot

    return explain_snapshot()


def _spot_section(op) -> dict:
    # the spot-storm resilience plane: forecaster rung + per-pool rate
    # table, risk-objective/rebalance activity counters, and the
    # rebalance controller's in-flight replace + rate-limiter bank
    from .. import spot as spot_plane

    out = {"enabled": spot_plane.enabled(),
           "counters": spot_plane.activity()}
    forecaster = getattr(op, "spotforecaster", None)
    if forecaster is not None:
        out["forecast"] = forecaster.snapshot()
    rebalance = getattr(op, "spotrebalance", None)
    if rebalance is not None:
        out["rebalance"] = rebalance.snapshot()
    return out


def _overload_section() -> dict:
    # the overload/backpressure plane: gate state, monotone activity
    # counters (guard observations/verdicts, admission-filter offers,
    # low-water passes), plus each live frontend's guard snapshot and
    # its solver service's resident/thrash eviction ledger — the numbers
    # the churn drill's resident-bytes and thrash-ratio audits scrape
    from .. import overload
    from ..fleet.frontend import active_frontends

    out = {"enabled": overload.enabled(),
           "counters": overload.activity(),
           "frontends": []}
    for f in active_frontends():
        # evidence carries the full transition ledger (bounded: hysteresis
        # caps flapping) — the churn drill audits brownout monotonicity
        # from a scrape, so the ledger must cross the process boundary
        row = {"name": f.name, "guard": f.guard.snapshot(),
               "evidence": f.guard.evidence()}
        svc = getattr(f, "service", None)
        if svc is not None and hasattr(svc, "eviction_stats"):
            row["eviction"] = svc.eviction_stats()
        out["frontends"].append(row)
    return out


def _serving_section(op) -> "dict | None":
    """The ACTUAL bound listener ports (serving.py `ServingPlane.bound`):
    with port-0 ephemeral binds this is the only place the resolved
    address is observable from the outside, so federation (fleetview /
    the replica rendezvous handshake) reads it here."""
    serving = getattr(op, "serving", None)
    if serving is None:
        return None
    return {"ports": dict(getattr(serving, "ports", {}) or {}),
            "bound": dict(getattr(serving, "bound", {}) or {})}


def snapshot(op) -> dict:
    """The one consistent operator snapshot (see module docstring)."""
    import os

    return {
        "tool": "karpenter_tpu.statusz",
        "schema": SCHEMA_VERSION,
        "version": __version__,
        "pid": os.getpid(),
        # every accessor is deferred into the fence — `op.watchdog.status`
        # evaluated HERE would escape it on an operator (a replica shim)
        # that doesn't carry the attribute at all
        "ts": _fenced(lambda: op.clock.now()),
        "serving": _fenced(lambda: _serving_section(op)),
        "cluster": _fenced(lambda: _cluster_section(op)),
        "controllers": _fenced(lambda: op.watchdog.status()),
        "queues": _fenced(lambda: _queue_section(op)),
        "caches": _fenced(lambda: _cache_section(op)),
        "events": _fenced(lambda: _events_section(op)),
        "resilience": _fenced(lambda: op.resilience.snapshot()),
        "recovery": _fenced(lambda: op.recovery.snapshot()),
        "fleet": _fenced(_fleet_section),
        "slo": _fenced(lambda: op.slo.snapshot()),
        "hbm": _fenced(_hbm_section),
        "incremental": _fenced(lambda: _incremental_section(op)),
        "profiling": _fenced(_profiling_section),
        "critical": _fenced(_critical_section),
        "spot": _fenced(lambda: _spot_section(op)),
        "overload": _fenced(_overload_section),
        "decisions": _fenced(_decisions_section),
        "metrics": _fenced(_metrics_section),
    }
