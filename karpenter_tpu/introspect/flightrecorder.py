"""Flight recorder: snapshot ring + trigger-based diagnostics bundles.

Two halves:

- a bounded ring of periodic statusz snapshots (`record_snapshot()` on an
  operator loop) — history *leading up to* an incident, since the
  post-mortem question is "what changed", not "what is";
- `trigger(reason)` assembles one JSON diagnostics bundle — triggering
  reason, the snapshot ring, a fresh statusz, the last N logring records,
  recent TRACER traces, the event ring, and the metrics exposition text —
  and writes it to `out_dir` (KARPENTER_TPU_BUNDLE_DIR).

Wired triggers: reconcile exception (watchdog failure listener), watchdog
deadman firing (stall listener), chaos invariant breach (runner calls with
`force=True` and a deterministic path next to the replay artifact). Live
fetch: `GET /debug/bundle` + `python -m karpenter_tpu diagnose`.

Auto-triggers are rate-limited per reason on the injected clock so a
crash-looping controller produces one bundle per window, not one per
cycle; `force=True` bypasses the limiter.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from typing import Optional

from .. import __version__
from ..tracing import TRACER
from ..utils import logring
from ..utils.clock import Clock
from .statusz import snapshot

log = logging.getLogger("karpenter.flightrecorder")

DEFAULT_RING = 32
BUNDLE_LOG_LINES = 200
BUNDLE_TRACES = 10
BUNDLE_EVENTS = 100
# one auto-bundle per reason per window; chaos passes force=True
TRIGGER_MIN_INTERVAL = 60.0

# folded stacks carried per bundle — enough flame to triage, bounded so a
# bundle stays a bundle
BUNDLE_PROFILE_STACKS = 50

# DecisionRecords carried per bundle — the tail of the explain ring, so a
# post-mortem bundle answers "why" for the decisions leading into the
# incident (`/debug/bundle?decisions=` overrides, clamped)
BUNDLE_DECISIONS = 50


def _profile_section() -> dict:
    from ..profiling import PROFILER, snapshot as profiling_snapshot

    return {
        **profiling_snapshot(),
        "folded": [f"{stack} {count}" for stack, count in
                   PROFILER.host.folded(BUNDLE_PROFILE_STACKS)],
    }


# critical rows carried per bundle — the chain/wait view of the solves
# leading into the trigger (full ring at /debug/criticalz)
BUNDLE_CRITICAL_ROWS = 20


def _critical_section() -> dict:
    from ..profiling import critical

    return critical.criticalz(BUNDLE_CRITICAL_ROWS)


def _decisions_section(limit: int = BUNDLE_DECISIONS) -> dict:
    from .. import explain

    return {**explain.snapshot(),
            "records": explain.DECISIONS.records(limit)}


class FlightRecorder:
    def __init__(self, operator, ring_size: int = DEFAULT_RING,
                 out_dir: "Optional[str]" = None,
                 clock: "Optional[Clock]" = None,
                 min_interval: float = TRIGGER_MIN_INTERVAL):
        self.op = operator
        self.clock = clock or getattr(operator, "clock", None) or Clock()
        self.out_dir = out_dir
        self.min_interval = min_interval
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max(1, ring_size))
        self._last_trigger: "dict[str, float]" = {}
        # recent trigger history rides along in every bundle — a bundle
        # that fires while another reason is already hot should say so
        self._triggers: "deque[dict]" = deque(maxlen=50)

    # -- snapshot ring ---------------------------------------------------------

    def record_snapshot(self) -> dict:
        """Take one statusz snapshot into the ring (periodic operator loop,
        and once per chaos cycle)."""
        snap = snapshot(self.op)
        with self._lock:
            self._ring.append(snap)
        return snap

    def ring(self) -> "list[dict]":
        with self._lock:
            return list(self._ring)

    # -- bundles ---------------------------------------------------------------

    def bundle(self, reason: str, detail: str = "",
               decisions: int = BUNDLE_DECISIONS) -> dict:
        """Assemble one diagnostics bundle. Every section is fenced the
        same way statusz sections are — capture must not fail because one
        subsystem is wedged (that subsystem is often WHY we're here)."""
        def fenced(build):
            try:
                return build()
            except Exception as e:  # noqa: BLE001
                return {"error": f"{type(e).__name__}: {e}"}

        return {
            "tool": "karpenter_tpu.diagnostics_bundle",
            "version": __version__,
            "ts": fenced(self.clock.now),
            "trigger": {"reason": reason, "detail": detail},
            "recent_triggers": list(self._triggers),
            "statusz": fenced(lambda: snapshot(self.op)),
            "statusz_ring": self.ring(),
            "logs": fenced(lambda: logring.dump_records(BUNDLE_LOG_LINES)),
            "traces": fenced(lambda: TRACER.traces(BUNDLE_TRACES)),
            "events": fenced(lambda: [
                {"ts": ts, "kind": e.kind, "reason": e.reason,
                 "object": e.object_ref, "message": e.message}
                for ts, e in self.op.recorder.recent(BUNDLE_EVENTS)]),
            "metrics_text": fenced(self.op.metrics_text),
            # profile snapshot rides in every bundle: an SLO-burn trigger's
            # first question is "which phase ate the budget" (gap ledger),
            # and the folded stacks say what the host was doing meanwhile
            "profile": fenced(_profile_section),
            # the critical-path view of the same solves: which phase was
            # on the chain, what the lanes waited on, and whether the
            # measured roofline flagged model drift
            "critical": fenced(_critical_section),
            # the explain ring's tail: every bundle carries the decisions
            # (assignments, unschedulable attributions, consolidation
            # verdicts, sheds) that led into the trigger
            "decisions": fenced(lambda: _decisions_section(decisions)),
        }

    def trigger(self, reason: str, detail: str = "", force: bool = False,
                path: "Optional[str]" = None) -> "Optional[str]":
        """Fire the recorder: assemble a bundle and write it to disk.
        Returns the written path, or None when rate-limited / nowhere to
        write. `path` overrides the destination (chaos puts the bundle
        next to the replay artifact); `force` bypasses the limiter."""
        now = self.clock.now()
        with self._lock:
            last = self._last_trigger.get(reason)
            if not force and last is not None and \
                    now - last < self.min_interval:
                return None
            self._last_trigger[reason] = now
            self._triggers.append(
                {"ts": now, "reason": reason, "detail": detail})
        b = self.bundle(reason, detail)
        out = path
        if out is None:
            if not self.out_dir:
                log.warning("flight recorder triggered (%s: %s) but no "
                            "bundle dir configured; bundle not written "
                            "(fetch via /debug/bundle)", reason, detail)
                return None
            safe = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in reason)
            out = os.path.join(
                self.out_dir, f"bundle_{safe}_{now:.0f}.json")
        try:
            parent = os.path.dirname(out)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = f"{out}.tmp"
            with open(tmp, "w") as f:
                json.dump(b, f, indent=2, sort_keys=True, default=str)
                f.write("\n")
            os.replace(tmp, out)  # readers never see a torn bundle
        except Exception as e:
            log.warning("flight recorder failed to write %s: %s", out, e)
            return None
        log.warning("diagnostics bundle written: %s (%s: %s)",
                    out, reason, detail)
        return out
