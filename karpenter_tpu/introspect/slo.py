"""Live SLOs with multi-window burn rates, evaluated from the metrics
registry (docs/designs/slo.md).

The registry's families are cumulative — counters and histogram buckets
only ever grow — so "how are we doing *lately*" needs a time dimension
the registry doesn't have. The evaluator adds it: on every tick it
snapshots each SLO's (good, total) event counts into a bounded ring and
differences the ring against window horizons (5m / 1h by default) to get
windowed bad-event fractions.

Two SLO shapes cover everything in the table:

- **latency**: a histogram family + threshold. Good events are
  observations at or under the threshold (counted at the nearest bucket
  boundary ≥ threshold, the conservative side); the objective is "≥ N%
  of events under the threshold".
- **share**: a ratio of histogram *sums* (e.g. watch-ingest seconds as
  a share of cycle seconds). The objective is "the windowed ratio stays
  under the threshold".

Burn rate is the standard SRE definition: the rate the error budget is
being consumed, where 1.0 means exactly on budget — bad_fraction /
(1 - objective) for latency SLOs, ratio / threshold for share SLOs. A
short-window burn ≥ BURN_THRESHOLD edge-triggers an `SloBurn` warning
event and a flight-recorder bundle (the statusz snapshot at the moment
of the burn is exactly the evidence a triage needs); dropping back under
triggers `SloRecovered`. Results land in `karpenter_slo_*` gauges and
the statusz `slo` section.

Label-templated SLOs (`per_label`): one declarative row expands into one
evaluated instance per distinct value of that label found in the metric's
series — `fleet_tenant_p99` becomes `fleet_tenant_p99{tenant=hot}`,
`...{tenant=_other}`, etc. Instance count is bounded because tenant
families sit behind the cardinality guard (metrics/cardinality.py):
at most K+1 label values exist, so at most K+1 instances ring up.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from ..metrics import NAMESPACE, REGISTRY, Histogram
from ..utils.clock import Clock

# evaluation windows: (label, seconds). The short window is the paging
# signal (fast burn), the long window the trend (slow burn).
WINDOWS = (("5m", 300.0), ("1h", 3600.0))

# short-window burn at or above this edge-triggers SloBurn
BURN_THRESHOLD = 1.0

PHASE_METRIC = f"{NAMESPACE}_scheduling_phase_duration_seconds"


class Slo:
    """One declarative objective. kind is "latency" (histogram + per-event
    threshold + objective fraction) or "share" (sum-ratio + ceiling)."""

    __slots__ = ("name", "kind", "metric", "labels", "threshold_s",
                 "objective", "num_metric", "num_labels", "den_metric",
                 "den_labels", "threshold", "description", "per_label")

    def __init__(self, name: str, kind: str, description: str = "", *,
                 metric: str = "", labels: "Optional[dict]" = None,
                 threshold_s: float = 0.0, objective: float = 0.99,
                 num_metric: str = "", num_labels: "Optional[dict]" = None,
                 den_metric: str = "", den_labels: "Optional[dict]" = None,
                 threshold: float = 1.0, per_label: str = ""):
        self.name = name
        self.kind = kind
        self.description = description
        self.metric = metric
        self.labels = dict(labels or {})
        self.threshold_s = threshold_s
        self.objective = objective
        self.num_metric = num_metric
        self.num_labels = dict(num_labels or {})
        self.den_metric = den_metric
        self.den_labels = dict(den_labels or {})
        self.threshold = threshold
        # label-templated SLO: evaluate one instance per distinct value of
        # this label found in the metric's series (bounded by the
        # cardinality guard — at most K+1 values for tenant families)
        self.per_label = per_label


# The SLO table (ISSUE 10). Latency thresholds are error-budget lines, not
# aspirations: cycle p99 gets the soak-proven budget (soak artifact p99
# 534 ms at 100k nodes -> 1 s line), the solve p50 gets the paper's
# < 100 ms north star, fleet solves get the bench-proven 1 s tail.
SLO_TABLE = (
    Slo("cycle_p99", "latency",
        "99% of provisioning cycles complete within 1 s",
        metric=PHASE_METRIC, labels={"phase": "provisioning.cycle"},
        threshold_s=1.0, objective=0.99),
    Slo("solve_p50", "latency",
        "50% of solves complete within the 100 ms north star",
        metric=PHASE_METRIC, labels={"phase": "provisioning.solve"},
        threshold_s=0.1, objective=0.50),
    Slo("fleet_p99", "latency",
        "99% of fleet tenant solves complete within 1 s",
        metric=f"{NAMESPACE}_fleet_tenant_solve_seconds", labels={},
        threshold_s=1.0, objective=0.99),
    Slo("fleet_tenant_p99", "latency",
        "99% of each tracked tenant's fleet solves complete within 1 s "
        "(one burn rate per tenant in the top-K, plus the _other rollup)",
        metric=f"{NAMESPACE}_fleet_tenant_solve_seconds", labels={},
        threshold_s=1.0, objective=0.99, per_label="tenant"),
    Slo("fleet_shed_rate", "share",
        "shed fleet requests stay under 5% of submissions",
        num_metric=f"{NAMESPACE}_fleet_shed_total",
        den_metric=f"{NAMESPACE}_fleet_requests_total",
        threshold=0.05),
    Slo("ingest_share", "share",
        "watch-ingest stays under 50% of provisioning-cycle wall clock",
        num_metric=PHASE_METRIC, num_labels={"phase": "ingest."},
        den_metric=PHASE_METRIC,
        den_labels={"phase": "provisioning.cycle"},
        threshold=0.5),
)


def _match(series_labels: dict, want: dict) -> bool:
    """Label filter; a value ending in "." is a prefix match (lets one SLO
    aggregate the ingest.decode/ingest.apply span family)."""
    for k, v in want.items():
        got = series_labels.get(k, "")
        if v.endswith("."):
            if not got.startswith(v[:-1]):
                return False
        elif got != v:
            return False
    return True


class SloEvaluator:
    """Periodic evaluator: metrics registry -> karpenter_slo_* gauges,
    statusz `slo` section, and edge-triggered burn events."""

    def __init__(self, registry=None, clock: "Optional[Clock]" = None,
                 recorder=None, flightrecorder=None,
                 slos: "tuple[Slo, ...]" = SLO_TABLE,
                 windows: "tuple[tuple[str, float], ...]" = WINDOWS,
                 burn_threshold: float = BURN_THRESHOLD):
        self.registry = registry if registry is not None else REGISTRY
        self.clock = clock or Clock()
        self.recorder = recorder
        self.flightrecorder = flightrecorder
        self.slos = slos
        self.windows = windows
        self.burn_threshold = burn_threshold
        self._lock = threading.Lock()
        # per-SLO snapshot ring: (ts, good, total). Ring length bounds
        # memory: long window / min evaluation cadence (1s) is the worst
        # case; 4096 covers 1h at sub-second ticks with slack.
        self._rings: "dict[str, collections.deque]" = {
            s.name: collections.deque(maxlen=4096) for s in slos}
        self._burning: "dict[str, bool]" = {s.name: False for s in slos}
        self._last: "dict[str, dict]" = {}
        reg = self.registry
        self.g_current = reg.gauge(
            f"{NAMESPACE}_slo_current",
            "Current windowed measurement per SLO (bad-event fraction for "
            "latency SLOs, the ratio itself for share SLOs).",
            ("slo", "window"))
        self.g_burn = reg.gauge(
            f"{NAMESPACE}_slo_burn_rate",
            "Error-budget burn rate per SLO and window (1.0 = consuming "
            "budget exactly as fast as allowed).", ("slo", "window"))
        self.g_healthy = reg.gauge(
            f"{NAMESPACE}_slo_healthy",
            "1 when the SLO's short-window burn is under the alert "
            "threshold, else 0.", ("slo",))
        self.g_target = reg.gauge(
            f"{NAMESPACE}_slo_objective",
            "Declared objective per SLO (good-event fraction for latency "
            "SLOs; 1 - threshold for share SLOs).", ("slo",))

    # -- registry reads --------------------------------------------------------

    def _histogram(self, name: str) -> "Optional[Histogram]":
        with self.registry._lock:
            m = self.registry._metrics.get(name)
        return m if isinstance(m, Histogram) else None

    def _latency_counts(self, slo: Slo,
                        want: "Optional[dict]" = None
                        ) -> "tuple[float, float]":
        """(good, total) cumulative events under/at the threshold, counted
        at the first bucket boundary >= threshold (conservative: events in
        the straddling bucket count as good only if the whole bucket is).
        `want` overrides the SLO's label filter (templated instances)."""
        h = self._histogram(slo.metric)
        if h is None:
            return 0.0, 0.0
        if want is None:
            want = slo.labels
        good = total = 0.0
        with h._lock:
            for key, counts in h._counts.items():
                labels = dict(zip(h.label_names, key))
                if not _match(labels, want):
                    continue
                total += h._totals[key]
                cum = 0.0
                for b, c in zip(h.buckets, counts):
                    cum = c  # counts are already cumulative per bucket
                    if b >= slo.threshold_s:
                        break
                else:
                    cum = h._totals[key]
                good += cum
        return good, total

    def _sum(self, name: str, want: dict) -> float:
        h = self._histogram(name)
        if h is not None:
            out = 0.0
            with h._lock:
                for key, s in h._sums.items():
                    if _match(dict(zip(h.label_names, key)), want):
                        out += s
            return out
        with self.registry._lock:
            m = self.registry._metrics.get(name)
        if m is None or isinstance(m, Histogram):
            return 0.0
        return sum(v for labels, v in m.collect() if _match(labels, want))

    def _counts(self, slo: Slo, want: "Optional[dict]" = None
                ) -> "tuple[float, float]":
        """Cumulative (numerator, denominator) for this SLO. For latency:
        (good, total) events. For share: (num_sum, den_sum)."""
        if slo.kind == "latency":
            return self._latency_counts(slo, want)
        return (self._sum(slo.num_metric, slo.num_labels),
                self._sum(slo.den_metric, slo.den_labels))

    def _label_values(self, metric_name: str, label: str) -> "list[str]":
        """Distinct values of `label` across the histogram's series —
        the instance axis for a templated SLO. Bounded in practice: tenant
        families sit behind the cardinality guard (<= K+1 values)."""
        h = self._histogram(metric_name)
        if h is None:
            return []
        try:
            idx = h.label_names.index(label)
        except ValueError:
            return []
        with h._lock:
            return sorted({key[idx] for key in h._totals})

    def _instances(self) -> "list[tuple[str, Slo, Optional[dict]]]":
        """The evaluation list: (instance_name, slo, label_filter). Plain
        SLOs evaluate once under their own name; a per_label SLO expands
        into one instance per discovered label value, named
        `slo{label=value}` (the key for its ring, gauges, and edges)."""
        out: "list[tuple[str, Slo, Optional[dict]]]" = []
        for slo in self.slos:
            if not slo.per_label:
                out.append((slo.name, slo, None))
                continue
            for value in self._label_values(slo.metric, slo.per_label):
                want = dict(slo.labels)
                want[slo.per_label] = value
                out.append((f"{slo.name}{{{slo.per_label}={value}}}",
                            slo, want))
        return out

    # -- evaluation ------------------------------------------------------------

    def _window_delta(self, ring, now: float,
                      horizon: float) -> "tuple[float, float]":
        """(num_delta, den_delta) between now's snapshot (ring[-1]) and the
        oldest snapshot inside the window. Falls back to the full ring when
        history is shorter than the window (cold start: judge what we
        have, never divide the future by zero)."""
        newest = ring[-1]
        base = ring[0]
        for ts, num, den in ring:
            if ts >= now - horizon:
                base = (ts, num, den)
                break
        return max(0.0, newest[1] - base[1]), max(0.0, newest[2] - base[2])

    def evaluate(self) -> "dict[str, dict]":
        """One tick: snapshot cumulative counts, compute windowed burn
        rates, set gauges, edge-trigger burn/recovery events. Returns the
        per-SLO result dict (also cached for statusz)."""
        now = self.clock.now()
        results: "dict[str, dict]" = {}
        # edge transitions collected under the lock, fired after releasing
        # it: the burn bundle captures statusz, which re-enters snapshot()
        edges: "list[tuple[str, str, Slo, dict, str]]" = []
        with self._lock:
            for iname, slo, want in self._instances():
                num, den = self._counts(slo, want)
                # templated instances appear (and ring up) lazily, as
                # their label values first show in the metric series
                ring = self._rings.setdefault(
                    iname, collections.deque(maxlen=4096))
                ring.append((now, num, den))
                res = {"kind": slo.kind, "description": slo.description,
                       "objective": (slo.objective if slo.kind == "latency"
                                     else 1.0 - slo.threshold),
                       "windows": {}}
                if want is not None:
                    res["labels"] = {slo.per_label: want[slo.per_label]}
                budget = (max(1e-9, 1.0 - slo.objective)
                          if slo.kind == "latency"
                          else max(1e-9, slo.threshold))
                for wname, horizon in self.windows:
                    dn, dd = self._window_delta(ring, now, horizon)
                    if slo.kind == "latency":
                        # dn is GOOD events; bad fraction burns the budget
                        value = (1.0 - dn / dd) if dd > 0 else 0.0
                    else:
                        value = dn / dd if dd > 0 else 0.0
                    burn = value / budget
                    res["windows"][wname] = {
                        "value": round(value, 6),
                        "burn_rate": round(burn, 4),
                        "events": dd if slo.kind == "latency" else None,
                    }
                    self.g_current.set(value, slo=iname, window=wname)
                    self.g_burn.set(burn, slo=iname, window=wname)
                short = self.windows[0][0]
                burning = (res["windows"][short]["burn_rate"]
                           >= self.burn_threshold)
                res["burning"] = burning
                self.g_healthy.set(0.0 if burning else 1.0, slo=iname)
                self.g_target.set(res["objective"], slo=iname)
                was = self._burning.get(iname, False)
                self._burning[iname] = burning
                results[iname] = res
                if burning and not was:
                    edges.append(("burn", iname, slo, res, short))
                elif was and not burning:
                    edges.append(("recovered", iname, slo, res, short))
            self._last = results
        for kind, iname, slo, res, short in edges:
            if kind == "burn":
                self._on_burn(iname, slo, res, short)
            else:
                self._on_recovered(iname, slo, res, short)
        return results

    def _on_burn(self, iname: str, slo: Slo, res: dict,
                 window: str) -> None:
        detail = (f"{iname} burn_rate="
                  f"{res['windows'][window]['burn_rate']} over {window} "
                  f"(objective: {slo.description})")
        if self.recorder is not None:
            self.recorder.warning("slo/" + iname, "SloBurn", detail)
        if self.flightrecorder is not None:
            # the bundle captures statusz AT the burn edge — the phase
            # split and queue depths that explain it are still hot
            # (the trigger sanitizes iname's {tenant=...} for the filename)
            try:
                self.flightrecorder.trigger(f"slo_burn_{iname}",
                                            detail=detail)
            except Exception:  # noqa: BLE001 — diagnostics must not cascade
                pass

    def _on_recovered(self, iname: str, slo: Slo, res: dict,
                      window: str) -> None:
        if self.recorder is not None:
            self.recorder.normal(
                "slo/" + iname, "SloRecovered",
                f"{iname} burn back under {self.burn_threshold} "
                f"over {window}")

    # -- read side -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The statusz `slo` section: last evaluation per SLO plus the
        window/threshold configuration (evaluates inline when no tick has
        run yet, so a fresh statusz is never empty)."""
        with self._lock:
            last = dict(self._last)
        if not last:
            last = self.evaluate()
        return {
            "windows": {name: secs for name, secs in self.windows},
            "burn_threshold": self.burn_threshold,
            "slos": last,
        }
