"""Per-controller heartbeat registry with a deadman check.

Every reconcile loop wraps its cycle in `watchdog.cycle(name)` (the
controllers carry an optional `watchdog=` and wrap their own
`reconcile_once`, so beats happen no matter who drives the cycle — the
operator's loops, `reconcile_all_once`, or the chaos runner). A cycle that
raises records a failure WITHOUT refreshing the heartbeat: a controller
stuck in a crash loop goes stale exactly like one hung mid-solve.

`check()` is the deadman: any controller whose last completed cycle is
older than its threshold flips to stalled. Verdicts feed three surfaces:

- gauges `karpenter_controller_healthy{controller}` (1/0) and
  `karpenter_controller_last_cycle_seconds{controller}` (age);
- `/readyz` aggregation (`Operator.readyz` names the stalled controllers);
- deduped Warning/Normal events on stall/recovery TRANSITIONS only, plus
  registered stall listeners (the flight recorder auto-dumps a bundle).

Staleness is measured on the injected clock (FakeClock-driven in tests and
chaos); cycle durations are wall time (they measure real work).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Callable, Optional

from ..metrics import NAMESPACE, REGISTRY
from ..utils.clock import Clock

log = logging.getLogger("karpenter.watchdog")

DEFAULT_THRESHOLD = 120.0

HEALTHY_METRIC = f"{NAMESPACE}_controller_healthy"
LAST_CYCLE_METRIC = f"{NAMESPACE}_controller_last_cycle_seconds"
CYCLE_DURATION_METRIC = f"{NAMESPACE}_controller_cycle_duration_seconds"


class Watchdog:
    def __init__(self, clock: Optional[Clock] = None, registry=None,
                 recorder=None):
        self.clock = clock or Clock()
        reg = registry if registry is not None else REGISTRY
        self.recorder = recorder
        self._lock = threading.Lock()
        self._controllers: "dict[str, dict]" = {}
        self._stall_listeners: "list[Callable]" = []
        self._failure_listeners: "list[Callable]" = []
        self.healthy_gauge = reg.gauge(
            HEALTHY_METRIC,
            "1 when the controller completed a reconcile cycle within its "
            "deadman threshold, 0 when the watchdog flagged it stalled.",
            ("controller",))
        self.last_cycle_gauge = reg.gauge(
            LAST_CYCLE_METRIC,
            "Seconds since the controller last completed a reconcile cycle.",
            ("controller",))
        self.cycle_duration = reg.histogram(
            CYCLE_DURATION_METRIC,
            "Duration of completed reconcile cycles.", ("controller",))

    # -- registration / heartbeats ---------------------------------------------

    def register(self, name: str,
                 threshold: float = DEFAULT_THRESHOLD) -> None:
        """Idempotent; re-registering updates the threshold only. A
        controller that never beats goes stale `threshold` seconds after
        registration (startup grace == one threshold)."""
        now = self.clock.now()
        with self._lock:
            rec = self._controllers.get(name)
            if rec is None:
                rec = self._controllers[name] = {
                    "threshold": threshold, "registered_at": now,
                    "last_beat": None, "beats": 0, "failures": 0,
                    "last_error": None, "last_duration_s": None,
                    "stalled": False,
                }
            else:
                rec["threshold"] = threshold
        self.healthy_gauge.set(1.0, controller=name)
        self.last_cycle_gauge.set(0.0, controller=name)

    def beat(self, name: str, duration_s: "Optional[float]" = None) -> None:
        """Record one COMPLETED cycle (auto-registers unknown names)."""
        now = self.clock.now()
        with self._lock:
            rec = self._controllers.get(name)
            if rec is None:
                rec = self._controllers[name] = {
                    "threshold": DEFAULT_THRESHOLD, "registered_at": now,
                    "last_beat": None, "beats": 0, "failures": 0,
                    "last_error": None, "last_duration_s": None,
                    "stalled": False,
                }
                self.healthy_gauge.set(1.0, controller=name)
            rec["last_beat"] = now
            rec["beats"] += 1
            if duration_s is not None:
                rec["last_duration_s"] = duration_s
        if duration_s is not None:
            self.cycle_duration.observe(duration_s, controller=name)
        self.last_cycle_gauge.set(0.0, controller=name)

    def fail(self, name: str, error: BaseException) -> None:
        """Record a cycle that raised; the heartbeat is NOT refreshed."""
        with self._lock:
            rec = self._controllers.get(name)
            if rec is not None:
                rec["failures"] += 1
                rec["last_error"] = f"{type(error).__name__}: {error}"
        for listener in list(self._failure_listeners):
            try:
                listener(name, error)
            except Exception as e:  # diagnostics must never break the loop
                log.warning("watchdog failure listener raised: %s", e)

    @contextlib.contextmanager
    def cycle(self, name: str):
        """Wrap one reconcile cycle: beat on success, fail (and re-raise)
        on exception."""
        t0 = time.perf_counter()
        try:
            yield
        except Exception as e:
            self.fail(name, e)
            raise
        else:
            self.beat(name, time.perf_counter() - t0)

    # -- deadman ---------------------------------------------------------------

    def names(self) -> "list[str]":
        with self._lock:
            return sorted(self._controllers)

    def _age(self, rec: dict, now: float) -> float:
        anchor = rec["last_beat"]
        if anchor is None:
            anchor = rec["registered_at"]
        return max(0.0, now - anchor)

    def check(self) -> "list[str]":
        """Evaluate every controller against its threshold, update the
        gauges, emit stall/recovery transition events, fire stall
        listeners. Returns the currently stalled names, sorted."""
        now = self.clock.now()
        newly_stalled, recovered, stalled_now = [], [], []
        with self._lock:
            for name in sorted(self._controllers):
                rec = self._controllers[name]
                age = self._age(rec, now)
                stalled = age > rec["threshold"]
                if stalled and not rec["stalled"]:
                    newly_stalled.append((name, age, rec["threshold"]))
                elif rec["stalled"] and not stalled:
                    recovered.append((name, age))
                rec["stalled"] = stalled
                if stalled:
                    stalled_now.append(name)
                self.healthy_gauge.set(0.0 if stalled else 1.0,
                                       controller=name)
                self.last_cycle_gauge.set(age, controller=name)
        for name, age, threshold in newly_stalled:
            log.warning("controller %s stalled: last completed cycle %.1fs "
                        "ago (threshold %.1fs)", name, age, threshold)
            if self.recorder is not None:
                self.recorder.warning(
                    f"controller/{name}", "ControllerStalled",
                    f"last completed reconcile cycle {age:.1f}s ago "
                    f"(threshold {threshold:.1f}s)")
        for name, age in recovered:
            log.info("controller %s recovered (last cycle %.1fs ago)",
                     name, age)
            if self.recorder is not None:
                self.recorder.normal(
                    f"controller/{name}", "ControllerRecovered",
                    "reconcile cycles resumed within the deadman threshold")
        if newly_stalled:
            names = [n for n, _, _ in newly_stalled]
            for listener in list(self._stall_listeners):
                try:
                    listener(names)
                except Exception as e:
                    log.warning("watchdog stall listener raised: %s", e)
        return stalled_now

    def healthy(self) -> bool:
        return not self.check()

    def add_stall_listener(self, fn: Callable) -> None:
        """fn(newly_stalled_names: list[str]) on healthy->stalled
        transitions (the flight recorder's deadman trigger)."""
        self._stall_listeners.append(fn)

    def add_failure_listener(self, fn: Callable) -> None:
        """fn(name, exception) on every failed cycle (the flight
        recorder's reconcile-exception trigger; rate limiting is the
        listener's job)."""
        self._failure_listeners.append(fn)

    # -- read side -------------------------------------------------------------

    def status(self) -> "dict[str, dict]":
        """Read-only per-controller view (no transition side effects) —
        the statusz `controllers` section."""
        now = self.clock.now()
        out = {}
        with self._lock:
            for name in sorted(self._controllers):
                rec = self._controllers[name]
                age = self._age(rec, now)
                dur = rec["last_duration_s"]
                out[name] = {
                    "healthy": age <= rec["threshold"],
                    "last_cycle_age_s": round(age, 3),
                    "threshold_s": rec["threshold"],
                    "beats": rec["beats"],
                    "failures": rec["failures"],
                    "last_error": rec["last_error"],
                    "last_cycle_ms": (None if dur is None
                                      else round(dur * 1e3, 3)),
                }
        return out


@contextlib.contextmanager
def cycle(watchdog: "Optional[Watchdog]", name: str):
    """Controller-side wrapper tolerating standalone construction (no
    watchdog wired): a strict no-op when `watchdog` is None."""
    if watchdog is None:
        yield
        return
    with watchdog.cycle(name):
        yield
