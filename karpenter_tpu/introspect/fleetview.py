"""FleetView: cross-replica observability federation (`/debug/fleetz`).

A multi-replica fleet (fleet/router.py rendezvous pinning, ROADMAP item
2b) has N disjoint trace rings, N statusz snapshots, and N metric
registries — a triage that starts from "tenant X is slow" first has to
guess WHICH replica owns tenant X before any existing surface helps.
FleetView closes the gap without inventing a control plane: it is an
in-process registry of replica endpoints whose membership mirrors the
FleetRouter's, and it answers two questions by fan-out + join over the
debug surfaces every replica already serves:

* `fleetz()` — one schema-versioned snapshot joining per-replica health,
  schema, membership epoch, resident-solver keys (the HBM ledger), and
  per-tenant telemetry, plus the router's tenant->replica pinning and a
  merged fleet-wide top-K tenant table.
* `federated_trace(trace_id)` — ONE Perfetto-loadable trace stitching
  the client-side spans (local tracer) and every replica's server-side
  spans for the id. No new wire protocol: the trace_context already
  crosses the solver wire (solver/wire.py), so both halves share the
  trace id — federation is just collecting the halves into one file,
  with one Perfetto "process" lane per replica.

Replica endpoints come in two transports behind one duck type
(`name`, `statusz()`, `trace_spans(id)`, `trace_index(limit)`):
`LocalReplica` wraps in-process callables (same-process replicas, the
telemetry drill, and the operator's own "self" row); `HttpReplica`
fetches the debug endpoints of a remote serving plane over urllib.
Replica failures degrade to an `"error"` entry in the join — a dead
replica must never take fleetz down with it; naming the corpse is the
feature.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from ..resilience import CircuitBreaker
from ..tracing import TRACER, Tracer
from ..utils.clock import Clock

FLEETZ_SCHEMA_VERSION = 2  # 2: scrape_ms/staleness_s/pid per row

# fan-out budget per replica fetch; a wedged replica costs one timeout,
# not a hung fleetz
DEFAULT_TIMEOUT_S = 2.0

# per-replica probe breaker: after PROBE_FAILURE_THRESHOLD consecutive
# statusz failures the fetch is suppressed for PROBE_BACKOFF_S (then one
# half-open probe at a time) — a dead replica costs fleetz one timeout
# per backoff window, not DEFAULT_TIMEOUT_S on EVERY snapshot forever
PROBE_FAILURE_THRESHOLD = 3
PROBE_BACKOFF_S = 30.0

# oversized-response clamp: a statusz/spans payload past this bound is a
# misbehaving replica (the summary extracts KBs, full snapshots are
# ~100KB) — name it instead of buffering an unbounded body into the join
MAX_SCRAPE_BYTES = 4 << 20


class ScrapeError(RuntimeError):
    """A classified scrape failure. `kind` is the closed vocabulary the
    error row (and the karpenter_fleet_scrape_errors_total counter) is
    named with: timeout | connect | http-<code> | invalid-json |
    oversized-response. Raised (not swallowed) so the caller's probe
    breaker still counts the failure and backs off."""

    def __init__(self, kind: str, detail: str):
        super().__init__(f"{kind}: {detail}")
        self.kind = kind
        self.detail = detail


class LocalReplica:
    """An in-process replica endpoint: callables instead of HTTP. The
    operator registers itself this way (its own statusz is a function
    call), and the telemetry drill builds its 2-replica fleet from
    these."""

    def __init__(self, name: str,
                 statusz: "Optional[Callable[[], dict]]" = None,
                 tracer: "Optional[Tracer]" = None):
        self.name = name
        self._statusz = statusz
        self.tracer = tracer
        # same-process by definition; federated_trace lanes it under the
        # client's own pid (span dedupe keeps a shared ring honest)
        self.pid = os.getpid()

    def statusz(self) -> "Optional[dict]":
        return self._statusz() if self._statusz is not None else None

    def trace_spans(self, trace_id: str) -> "list[dict]":
        return self.tracer.trace(trace_id) if self.tracer is not None else []

    def trace_index(self, limit: int = 20) -> "list[dict]":
        return (self.tracer.trace_index(limit)
                if self.tracer is not None else [])


class HttpReplica:
    """A remote replica endpoint: the debug surfaces of its serving
    plane (serving.py) over HTTP, hardened for the live-fleet case.

    Every failure mode of the scrape path is CLASSIFIED, never raised
    raw: connect refusal, read/connect timeout, HTTP error status, a
    truncated or otherwise invalid JSON body, and an oversized response
    (clamped at MAX_SCRAPE_BYTES) each raise `ScrapeError` with a named
    kind — the FleetView join turns that into a named error row, and
    because it still RAISES, the existing per-replica probe breaker
    counts the failure and backs off exactly as before.

    `pid` is learned from the replica's own statusz/spans payloads
    (serving.py stamps os.getpid()); the federated trace lanes spans
    under the replica's REAL pid once observed."""

    def __init__(self, name: str, base_url: str,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 max_bytes: int = MAX_SCRAPE_BYTES):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.max_bytes = max_bytes
        self.pid: "Optional[int]" = None       # learned from payloads
        self.last_scrape_ms: "Optional[float]" = None
        self.last_scrape_ts: "Optional[float]" = None

    def _get_json(self, path: str):
        url = self.base_url + path
        req = urllib.request.Request(url,
                                     headers={"Accept": "application/json"})
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                # read ONE byte past the clamp: len > max_bytes proves the
                # body kept going without ever buffering all of it
                body = resp.read(self.max_bytes + 1)
        except urllib.error.HTTPError as e:
            raise ScrapeError(f"http-{e.code}", f"{url}: {e.reason}") from e
        except (socket.timeout, TimeoutError) as e:
            raise ScrapeError(
                "timeout", f"{url}: no response within "
                f"{self.timeout_s:.1f}s") from e
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, (socket.timeout, TimeoutError)):
                raise ScrapeError(
                    "timeout", f"{url}: no response within "
                    f"{self.timeout_s:.1f}s") from e
            raise ScrapeError("connect", f"{url}: {reason}") from e
        except OSError as e:  # connection reset mid-read and kin
            raise ScrapeError("connect", f"{url}: {e}") from e
        if len(body) > self.max_bytes:
            raise ScrapeError(
                "oversized-response",
                f"{url}: body exceeds {self.max_bytes} bytes (clamped)")
        try:
            doc = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise ScrapeError(
                "invalid-json",
                f"{url}: unparseable body ({e}; truncated write or "
                f"non-JSON error page)") from e
        self.last_scrape_ms = (time.perf_counter() - t0) * 1e3
        self.last_scrape_ts = time.time()
        if isinstance(doc, dict) and isinstance(doc.get("pid"), int):
            self.pid = doc["pid"]
        return doc

    def statusz(self) -> "Optional[dict]":
        return self._get_json("/debug/statusz")

    def trace_spans(self, trace_id: str) -> "list[dict]":
        try:
            doc = self._get_json(f"/debug/traces?id={trace_id}&format=spans")
        except ScrapeError as e:
            if e.kind == "http-404":  # replica has no spans for this id
                return []
            raise
        return doc.get("spans", [])

    def trace_index(self, limit: int = 20) -> "list[dict]":
        doc = self._get_json(f"/debug/traces?index=1&limit={limit}")
        return doc.get("traces", [])


class FleetView:
    """The aggregator. Membership changes go through add/remove_replica,
    which keep the (optional) FleetRouter's member set in lockstep — the
    pinning fleetz reports is computed by the SAME router instance that
    routes traffic, so the joined view can never disagree with routing."""

    def __init__(self, router=None, name: str = "fleet",
                 tracer: "Optional[Tracer]" = None,
                 clock: "Optional[Clock]" = None):
        self.router = router
        self.name = name
        # the CLIENT-side ring: where the fleet frontend's queue-wait and
        # rpc spans live (the other half of every federated trace)
        self.tracer = tracer if tracer is not None else TRACER
        self.clock = clock or Clock()
        self._lock = threading.Lock()
        self._replicas: "dict[str, object]" = {}
        self._joined_epoch: "dict[str, int]" = {}
        self._epoch = 0
        # health-gated membership (fleet/membership.py) is the epoch
        # authority when wired: fleetz stamps ITS monotone epoch so every
        # observer orders membership views off one source
        self._epoch_source: "Optional[Callable[[], int]]" = None
        self._probe_breakers: "dict[str, CircuitBreaker]" = {}
        self._consec_failures: "dict[str, int]" = {}

    def set_epoch_source(self, source: "Callable[[], int]") -> None:
        """Delegate the fleetz membership epoch to an external monotone
        counter (the MembershipManager's)."""
        self._epoch_source = source

    # -- membership ------------------------------------------------------------

    def add_replica(self, replica) -> None:
        with self._lock:
            self._epoch += 1
            self._replicas[replica.name] = replica
            self._joined_epoch[replica.name] = self._epoch
        if self.router is not None:
            self.router.add_replica(replica.name)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            if name in self._replicas:
                self._epoch += 1
            self._replicas.pop(name, None)
            self._joined_epoch.pop(name, None)
            self._probe_breakers.pop(name, None)
            self._consec_failures.pop(name, None)
        if self.router is not None:
            try:
                self.router.remove_replica(name)
            except KeyError:
                pass

    def replicas(self) -> "list[str]":
        with self._lock:
            return sorted(self._replicas)

    # -- fleetz ----------------------------------------------------------------

    def _probe_breaker(self, name: str) -> CircuitBreaker:
        """Callers hold self._lock or run single-threaded (fleetz)."""
        br = self._probe_breakers.get(name)
        if br is None:
            br = CircuitBreaker(
                f"fleetz:{name}", clock=self.clock,
                failure_threshold=PROBE_FAILURE_THRESHOLD,
                recovery_time=PROBE_BACKOFF_S)
            self._probe_breakers[name] = br
        return br

    @staticmethod
    def _scrape_metrics():
        """Lazy: fleet/metrics pulls the fleet package (and through it the
        solver stack); fleetview must stay importable without either."""
        try:
            from ..fleet import metrics as fleet_metrics
        except Exception:  # noqa: BLE001 — metrics are best-effort here
            return None
        return fleet_metrics

    def _replica_summary(self, replica) -> dict:
        """One replica's row: fetched + fenced. The summary extracts the
        triage-relevant subset of statusz (full snapshots federate badly
        — N x 100KB joins help nobody) and keeps the raw sections it
        came from discoverable by name. A replica that keeps failing is
        probed through a breaker: PROBE_FAILURE_THRESHOLD consecutive
        failures suppress the fetch until the backoff window lapses, so
        a corpse never costs every snapshot a full timeout.

        With real subprocess replicas the row additionally carries the
        scrape evidence itself: scrape_ms (HTTP round-trip), staleness_s
        (view clock minus the snapshot's own ts), and the serving
        process's pid. Classified transport failures (ScrapeError) keep
        their kind as `scrape_error` so the error row names WHY."""
        name = replica.name
        breaker = self._probe_breaker(name)
        fails = self._consec_failures.get(name, 0)
        if not breaker.allow():
            return {"healthy": False,
                    "error": f"probe suppressed ({fails} consecutive "
                             f"failures; retry after "
                             f"{PROBE_BACKOFF_S:.0f}s backoff)",
                    "probe_suppressed": True,
                    "consecutive_failures": fails}
        t0 = time.perf_counter()
        try:
            snap = replica.statusz()
        except Exception as e:  # noqa: BLE001 — a dead replica is a row, not an outage
            breaker.record_failure()
            self._consec_failures[name] = fails + 1
            row = {"healthy": False, "error": f"{type(e).__name__}: {e}",
                   "consecutive_failures": fails + 1}
            if isinstance(e, ScrapeError):
                row["scrape_error"] = e.kind
                fm = self._scrape_metrics()
                if fm is not None:
                    fm.SCRAPE_ERRORS.inc(kind=e.kind)
            return row
        scrape_ms = (time.perf_counter() - t0) * 1e3
        fm = self._scrape_metrics()
        if fm is not None:
            fm.SCRAPE_LATENCY.observe(scrape_ms / 1e3)
        # the transport answered: the backoff targets timeout burn, so a
        # reachable replica with a degraded payload still resets it
        breaker.record_success()
        self._consec_failures[name] = 0
        if not snap:
            return {"healthy": False, "error": "no statusz",
                    "scrape_ms": round(scrape_ms, 3),
                    "consecutive_failures": 0}
        if "error" in snap and len(snap) == 1:
            return {"healthy": False, "error": snap["error"],
                    "scrape_ms": round(scrape_ms, 3),
                    "consecutive_failures": 0}
        out = {
            "healthy": True,
            "schema": snap.get("schema"),
            "version": snap.get("version"),
            "ts": snap.get("ts"),
            "scrape_ms": round(scrape_ms, 3),
            "consecutive_failures": 0,
        }
        pid = snap.get("pid")
        if isinstance(pid, int):
            out["pid"] = pid
        ts = snap.get("ts")
        if isinstance(ts, (int, float)):
            # staleness of the EVIDENCE: how old the replica's self-report
            # is by the view's clock (meaningful when both share a clock
            # domain — wall time in the live fleet, FakeClock in tests)
            out["staleness_s"] = round(max(0.0, self.clock.now() - ts), 3)
        serving = snap.get("serving")
        if isinstance(serving, dict) and serving.get("bound"):
            out["serving"] = serving.get("bound")
        watchdog = (snap.get("resilience") or {}).get("watchdog")
        if isinstance(watchdog, dict):
            out["healthy"] = bool(watchdog.get("healthy", True))
        hbm = snap.get("hbm") or {}
        if isinstance(hbm, dict) and "solvers" in hbm:
            out["resident_solvers"] = sorted(hbm["solvers"])
            out["hbm_resident_bytes"] = hbm.get("resident_bytes_total")
            out["hbm_pressure"] = hbm.get("pressure")
        fleet = snap.get("fleet") or {}
        fronts = fleet.get("frontends") if isinstance(fleet, dict) else None
        if fronts:
            out["tenants"] = {
                f.get("name", "?"): f.get("tenant_telemetry")
                for f in fronts if isinstance(f, dict)}
            out["queued"] = sum(f.get("queued", 0) for f in fronts
                                if isinstance(f, dict))
            # per-replica throughput evidence: the drill computes each
            # replica's solves/s by differencing this across scrapes
            out["served"] = sum(f.get("served", 0) for f in fronts
                                if isinstance(f, dict))
        return out

    def _merged_tenant_table(self, rows: "dict[str, dict]") -> "list[dict]":
        """Fleet-wide top tenants: sum each tenant's sketch count across
        replicas (a tenant pinned to one replica appears once; counts are
        upper bounds exactly as in the per-replica sketches), heaviest
        first."""
        totals: "dict[str, float]" = {}
        errors: "dict[str, float]" = {}
        for row in rows.values():
            for telemetry in (row.get("tenants") or {}).values():
                if not isinstance(telemetry, dict):
                    continue
                for ent in telemetry.get("tracked", ()):
                    t = ent.get("tenant", "")
                    totals[t] = totals.get(t, 0.0) + ent.get("count", 0.0)
                    errors[t] = errors.get(t, 0.0) + ent.get("error", 0.0)
        return [{"tenant": t, "count": totals[t], "error": errors.get(t, 0.0)}
                for t in sorted(totals, key=lambda t: (-totals[t], t))]

    def fleetz(self, tenant_ids=None) -> dict:
        """The joined snapshot. `tenant_ids` scopes the pinning table
        (routing is a pure function, so the full tenant universe isn't
        enumerable from the router — callers name the tenants they care
        about; the merged tenant table's tenants are used otherwise)."""
        with self._lock:
            replicas = dict(self._replicas)
            joined = dict(self._joined_epoch)
            epoch = self._epoch
        if self._epoch_source is not None:
            epoch = self._epoch_source()
        rows = {name: self._replica_summary(r)
                for name, r in sorted(replicas.items())}
        for name, row in rows.items():
            row["joined_epoch"] = joined.get(name)
        tenants = self._merged_tenant_table(rows)
        pinning: "dict[str, str]" = {}
        if self.router is not None:
            if tenant_ids is None:
                tenant_ids = [t["tenant"] for t in tenants
                              if not t["tenant"].startswith("_")]
            try:
                pinning = self.router.assignment(tenant_ids)
            except Exception:  # noqa: BLE001 — empty membership etc.
                pinning = {}
        return {
            "tool": "karpenter-tpu-fleetz",
            "schema": FLEETZ_SCHEMA_VERSION,
            "ts": time.time(),
            "name": self.name,
            "membership_epoch": epoch,
            "replicas": rows,
            "pinning": pinning,
            "tenants": tenants,
        }

    # -- trace federation ------------------------------------------------------

    def federated_trace(self, trace_id: str) -> "Optional[dict]":
        """One Chrome/Perfetto trace for the id, client + every replica.

        Layout: one Perfetto "process" lane per participating OS process
        — the client lane under THIS process's pid, each replica under
        its REAL pid when the transport has learned one (HttpReplica
        reads it off the spans payload; serving.py stamps os.getpid()).
        Lanes whose pid is unknown or would collide with an
        already-assigned lane (e.g. two LocalReplicas sharing the
        client's process) fall back to small synthetic pids, so lanes
        always stay distinct. Each lane carries a process_name metadata
        event, so Perfetto renders the federation as parallel process
        lanes sharing one clock. Spans are deduped by span_id (an
        in-process replica may share the client's ring). Returns None
        when NOBODY has spans for the id (-> 404)."""
        lanes: "list[tuple[str, list[dict], Optional[int]]]" = [
            ("client:" + self.name, self.tracer.trace(trace_id),
             os.getpid())]
        with self._lock:
            replicas = sorted(self._replicas.items())
        for name, replica in replicas:
            try:
                spans = replica.trace_spans(trace_id)
            except Exception:  # noqa: BLE001 — a dead replica drops its lane only
                spans = []
            # read pid AFTER the fetch: HttpReplica learns it from the
            # payload it just scraped
            real = getattr(replica, "pid", None)
            lanes.append((name, spans,
                          real if isinstance(real, int) else None))
        if not any(spans for _name, spans, _pid in lanes):
            return None
        events: "list[dict]" = []
        seen: "set[str]" = set()
        used_pids: "set[int]" = set()
        synthetic = 0
        for lane_name, spans, real_pid in lanes:
            if not spans:
                continue
            if real_pid is not None and real_pid not in used_pids:
                pid = real_pid
            else:
                while synthetic in used_pids:
                    synthetic += 1
                pid = synthetic
            used_pids.add(pid)
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": lane_name}})
            tids: "dict[str, int]" = {}
            for s in spans:
                sid = s.get("span_id", "")
                if sid and sid in seen:
                    continue
                seen.add(sid)
                thread = str(s.get("thread", ""))
                tid = tids.setdefault(thread, len(tids))
                args = dict(s.get("attributes", {}))
                args["replica"] = lane_name
                events.append({
                    "name": s.get("name", "?"),
                    "cat": s.get("trace_id", trace_id),
                    "ph": "X",
                    "ts": s.get("start_ts", 0.0) * 1e6,
                    "dur": s.get("duration_ms", 0.0) * 1e3,
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                })
        events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def trace_index(self, limit: int = 20) -> "list[dict]":
        """The merged `/debug/traces` index: client + replica indexes,
        deduped by trace id (client row wins — it has the tenant
        annotations), newest first."""
        merged: "dict[str, dict]" = {}
        for row in self.tracer.trace_index(limit):
            merged.setdefault(row["trace_id"], row)
        with self._lock:
            replicas = sorted(self._replicas.items())
        for name, replica in replicas:
            try:
                rows = replica.trace_index(limit)
            except Exception:  # noqa: BLE001
                continue
            for row in rows:
                prev = merged.get(row["trace_id"])
                if prev is None:
                    row = dict(row)
                    row.setdefault("replicas", [])
                    merged[row["trace_id"]] = row
                    prev = row
                reps = set(prev.get("replicas") or [])
                reps.add(name)
                prev["replicas"] = sorted(reps)
        rows = sorted(merged.values(),
                      key=lambda r: r.get("start_ts", 0.0), reverse=True)
        return rows[:limit] if limit else rows
