"""Operator introspection plane: watchdog, statusz, flight recorder.

The serving plane's probes answer a boolean; this package answers *what a
live controller is doing* and captures consistent state at the moment
something goes wrong — the layer that makes the tracing plane (PR 1) and
chaos plane (PR 2) operable:

- `watchdog`       per-controller heartbeat registry + deadman check; feeds
                   `/readyz`, `karpenter_controller_healthy{controller}` and
                   stall/recovery events.
- `statusz`        one consistent JSON snapshot of the whole operator
                   (cluster state, controller health, queue depths, cache
                   stats, recent events, metric values) — `GET
                   /debug/statusz`, `python -m karpenter_tpu statusz`.
- `flightrecorder` bounded ring of periodic statusz snapshots plus
                   trigger-based diagnostics bundles (reconcile exception,
                   watchdog deadman, chaos invariant breach) — `GET
                   /debug/bundle`, `python -m karpenter_tpu diagnose`.
"""

from .watchdog import Watchdog, cycle  # noqa: F401
from .statusz import snapshot  # noqa: F401
from .flightrecorder import FlightRecorder  # noqa: F401
