"""Cardinality-bounded per-tenant telemetry (the top-K tenant guard).

ROADMAP item 2 wants the fleet provable at >= 1000 tenants, but every
tenant-labeled metric family grows one series per distinct tenant id —
at fleet scale that is an unbounded label explosion that melts Prometheus
and makes `/debug/statusz` unreadable exactly when it matters. This
module bounds it: a space-saving sketch (Metwally et al. "Efficient
Computation of Frequent and Top-k Elements in Data Streams") tracks the
K heaviest tenants EXACTLY (within the sketch's documented error bound)
and every other tenant folds into one `tenant="_other"` rollup series,
so a guarded family holds at most K+1 tenant values no matter how many
tenants exist.

Mechanics:

* `TenantTracker` is the sketch: at most K counters. A tracked tenant's
  offer increments its counter. An untracked tenant REPLACES the
  minimum-count entry (count = min + amount, error = min) — the classic
  space-saving admission that guarantees any tenant with true frequency
  above N/K is tracked.
* `CardinalityGuard` wraps the sketch around metric families. Call sites
  route label values through `guard.label(tenant_id)`; when an offer
  evicts a tenant from the top-K, the guard FOLDS that tenant's existing
  series — counter values added into `_other`, histogram buckets/sums/
  totals merged into `_other`, gauge series dropped (gauges are
  last-write; the next tick re-sets the rollup) — so no observation is
  ever double-counted and no evicted series lingers.
* Tenant ids are escaped so a real tenant literally named "_other" can
  never collide with the rollup: any id starting with "_" gains one more
  leading "_" (injective), and only the guard itself ever emits the bare
  `_other`.

K is env-tunable (KARPENTER_TPU_TENANT_TOPK, default 32), validated the
same way the crossover knob is (solver/buckets.py): a garbage value
warns and falls back rather than silently changing series budgets.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Iterable, Optional

from . import Counter, Gauge, Histogram, _Metric

log = logging.getLogger("karpenter.metrics.cardinality")

# the rollup label value; real tenant ids are escaped away from it
OTHER = "_other"

DEFAULT_K = 32
K_ENV = "KARPENTER_TPU_TENANT_TOPK"


def top_k_default() -> int:
    """The env-tunable K, validated: a bad value warns and falls back,
    a value < 1 clamps to 1 (a zero-width sketch cannot exist — every
    guarded family needs at least the rollup plus one exact series)."""
    raw = os.environ.get(K_ENV)
    if raw is None:
        return DEFAULT_K
    try:
        k = int(raw)
    except ValueError:
        log.warning("%s=%r is not an integer; falling back to K=%d",
                    K_ENV, raw, DEFAULT_K)
        return DEFAULT_K
    if k < 1:
        log.warning("%s=%d is < 1; clamping to 1", K_ENV, k)
        return 1
    return k


def escape(tenant_id: str) -> str:
    """Injective escape keeping real tenant ids out of the rollup's
    namespace: ids starting with "_" gain one more "_" (so "_other" ->
    "__other", "__other" -> "___other", ...); everything else passes
    through unchanged. Only the guard emits the bare OTHER value."""
    if tenant_id.startswith("_"):
        return "_" + tenant_id
    return tenant_id


class TenantTracker:
    """The space-saving sketch: at most `k` (tenant -> (count, error))
    counters. Not thread-safe on its own — CardinalityGuard serializes
    access (and tests drive it single-threaded)."""

    __slots__ = ("k", "_counts", "_errors", "offers", "evictions")

    def __init__(self, k: "Optional[int]" = None):
        self.k = top_k_default() if k is None else max(1, int(k))
        self._counts: "dict[str, float]" = {}
        self._errors: "dict[str, float]" = {}
        self.offers = 0
        self.evictions = 0

    def offer(self, key: str, amount: float = 1.0
              ) -> "tuple[str, Optional[str]]":
        """One observation of `key`. Returns (key, evicted): `key` is now
        tracked; `evicted` names the entry it displaced (None when the
        sketch had room or the key was already tracked)."""
        self.offers += 1
        if key in self._counts:
            self._counts[key] += amount
            return key, None
        if len(self._counts) < self.k:
            self._counts[key] = amount
            self._errors[key] = 0.0
            return key, None
        # full: displace the minimum-count entry (ties break by key so
        # the choice is deterministic across processes/replays)
        victim = min(self._counts, key=lambda t: (self._counts[t], t))
        floor = self._counts.pop(victim)
        self._errors.pop(victim, None)
        self._counts[key] = floor + amount
        self._errors[key] = floor
        self.evictions += 1
        return key, victim

    def tracked(self) -> "dict[str, float]":
        return dict(self._counts)

    def lower_bound(self, key: str) -> float:
        """count - error: observations PROVABLY attributable to `key`.
        The space-saving displacement above hands a newcomer the victim's
        floor as its starting count, so under a flood of distinct keys
        the raw count of a brand-new key can read arbitrarily high; the
        inherited floor is also recorded as its error, so this difference
        stays 1 for a first sighting no matter how saturated the sketch
        is (the admission filter's earn test depends on exactly that)."""
        if key not in self._counts:
            return 0.0
        return self._counts[key] - self._errors.get(key, 0.0)

    def __contains__(self, key: str) -> bool:
        return key in self._counts

    def table(self) -> "list[dict]":
        """The top-K table, heaviest first (count is an upper bound on the
        true frequency; count - error a lower bound)."""
        return [{"tenant": t, "count": self._counts[t],
                 "error": self._errors.get(t, 0.0)}
                for t in sorted(self._counts,
                                key=lambda t: (-self._counts[t], t))]


class CardinalityGuard:
    """The label gate in front of tenant-labeled metric families.

    Families are registered with `watch(metric, label="tenant")`; call
    sites route ids through `label(tenant_id)` (which offers to the
    sketch and folds evictions) or `peek(tenant_id)` (read-only: for
    per-tick gauge sweeps that must not inflate sketch counts).
    """

    def __init__(self, k: "Optional[int]" = None,
                 tracker: "Optional[TenantTracker]" = None):
        self._lock = threading.Lock()
        self.tracker = tracker if tracker is not None else TenantTracker(k)
        self.k = self.tracker.k
        self._families: "list[tuple[_Metric, str]]" = []
        self.folded = 0  # evictions whose series were folded into OTHER

    # -- registration ----------------------------------------------------------

    def watch(self, metric: _Metric, label: str = "tenant") -> _Metric:
        """Register a family for eviction folding; returns the metric so
        registration can wrap construction. A family without the label is
        rejected loudly — guarding it would silently do nothing."""
        if label not in metric.label_names:
            raise ValueError(
                f"metric {metric.name} has no {label!r} label "
                f"(labels: {metric.label_names})")
        with self._lock:
            if (metric, label) not in self._families:
                self._families.append((metric, label))
        return metric

    def families(self) -> "list[tuple[_Metric, str]]":
        with self._lock:
            return list(self._families)

    # -- the gate --------------------------------------------------------------

    def label(self, tenant_id: str, amount: float = 1.0) -> str:
        """The label value to emit for one observation of `tenant_id`:
        the (escaped) id itself — offering it to the sketch, folding any
        eviction — since an offered tenant is always tracked afterwards.
        Empty ids go straight to the rollup."""
        if not tenant_id:
            return OTHER
        key = escape(tenant_id)
        with self._lock:
            _, evicted = self.tracker.offer(key, amount)
            families = list(self._families)
            if evicted is not None:
                self.folded += 1
        if evicted is not None:
            for metric, lname in families:
                _fold_series(metric, lname, evicted, OTHER)
        return key

    def peek(self, tenant_id: str) -> str:
        """Read-only gate: the id when tracked, else OTHER. For gauge
        sweeps (queue depth per tick) that must not count as traffic."""
        if not tenant_id:
            return OTHER
        key = escape(tenant_id)
        with self._lock:
            return key if key in self.tracker else OTHER

    def is_tracked_label(self, label: str) -> bool:
        """Whether an ALREADY-ESCAPED label value is currently live (the
        rollup always is). Gauge sweeps consult this before zeroing a
        stale label: re-setting a label the sketch evicted would
        resurrect the series the eviction fold just deleted."""
        if label == OTHER:
            return True
        with self._lock:
            return label in self.tracker

    # -- read side -------------------------------------------------------------

    def series_values(self, metric: _Metric, label: str = "tenant"
                      ) -> "set[str]":
        """Distinct label values currently present in the family."""
        try:
            idx = metric.label_names.index(label)
        except ValueError:
            return set()
        with metric._lock:
            if isinstance(metric, Histogram):
                keys: "Iterable[tuple]" = metric._totals.keys()
            else:
                keys = metric._values.keys()
            return {k[idx] for k in keys}

    def series_count(self, metric: _Metric, label: str = "tenant") -> int:
        return len(self.series_values(metric, label))

    def snapshot(self) -> dict:
        """The statusz/fleetz tenant table: K, the top-K with counts and
        error bounds, offer/eviction totals, and per-family series
        counts (each must stay <= K+1 — the whole point)."""
        with self._lock:
            table = self.tracker.table()
            offers = self.tracker.offers
            evictions = self.tracker.evictions
            families = list(self._families)
            folded = self.folded
        return {
            "k": self.k,
            "tracked": table,
            "offers": offers,
            "evictions": evictions,
            "folded": folded,
            "series_per_family": {
                m.name: self.series_count(m, lname)
                for m, lname in families},
        }


def _fold_series(metric: _Metric, label: str, from_value: str,
                 to_value: str) -> None:
    """Merge every series of `metric` whose `label` equals `from_value`
    into the matching series with `to_value` (other labels preserved),
    then drop the source series. Counters add, histograms merge
    buckets/sums/totals (the source's exemplar is discarded — its trace
    names a tenant the rollup no longer identifies), gauges drop (last-
    write semantics: summing two gauges fabricates a number nobody set)."""
    try:
        idx = metric.label_names.index(label)
    except ValueError:
        return
    with metric._lock:
        if isinstance(metric, Histogram):
            for key in [k for k in metric._totals if k[idx] == from_value]:
                dst = key[:idx] + (to_value,) + key[idx + 1:]
                counts = metric._counts.pop(key)
                dst_counts = metric._counts.setdefault(
                    dst, [0] * len(metric.buckets))
                for i, c in enumerate(counts):
                    dst_counts[i] += c
                metric._sums[dst] = metric._sums.get(dst, 0.0) + \
                    metric._sums.pop(key)
                metric._totals[dst] = metric._totals.get(dst, 0) + \
                    metric._totals.pop(key)
                metric._exemplars.pop(key, None)
        elif isinstance(metric, Gauge):
            for key in [k for k in metric._values if k[idx] == from_value]:
                metric._values.pop(key)
        elif isinstance(metric, Counter):
            for key in [k for k in metric._values if k[idx] == from_value]:
                dst = key[:idx] + (to_value,) + key[idx + 1:]
                metric._values[dst] = metric._values.get(dst, 0.0) + \
                    metric._values.pop(key)
