"""Prometheus-style metrics registry (no external deps).

Parity target: the reference's metric families (SURVEY.md §5.5 /
website metrics.md:13-92): karpenter_cloudprovider_duration_seconds,
karpenter_provisioner_*, karpenter_nodes_*, karpenter_pods_*,
karpenter_interruption_*, scheduling/deprovisioning duration histograms —
plus the cloudprovider duration decorator (`metrics.Decorate`, main.go:46).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

NAMESPACE = "karpenter"

DURATION_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)


class _Metric:
    def __init__(self, name: str, help_: str, label_names: "tuple[str, ...]"):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()

    def _label_key(self, labels: "dict[str, str]") -> tuple:
        return tuple(labels.get(k, "") for k in self.label_names)


class Counter(_Metric):
    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, tuple(label_names))
        self._values: "dict[tuple, float]" = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._label_key(labels), 0.0)

    def collect(self):
        with self._lock:
            for key, v in sorted(self._values.items()):
                yield dict(zip(self.label_names, key)), v


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._label_key(labels)] = value


class Histogram(_Metric):
    def __init__(self, name, help_="", label_names=(), buckets=DURATION_BUCKETS):
        super().__init__(name, help_, tuple(label_names))
        self.buckets = tuple(buckets)
        self._counts: "dict[tuple, list[int]]" = {}
        self._sums: "dict[tuple, float]" = {}
        self._totals: "dict[tuple, int]" = {}
        # last exemplar per series: {key: {"trace_id", "value", "ts"}} —
        # an aggregate that looks wrong must name ONE concrete trace to
        # pull from /debug/traces (OpenMetrics exemplar semantics)
        self._exemplars: "dict[tuple, dict]" = {}

    def observe(self, value: float, exemplar: "Optional[str]" = None,
                **labels) -> None:
        key = self._label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
            if exemplar:
                self._exemplars[key] = {"trace_id": exemplar,
                                        "value": value, "ts": time.time()}

    def exemplar(self, **labels) -> "Optional[dict]":
        with self._lock:
            e = self._exemplars.get(self._label_key(labels))
            return dict(e) if e else None

    def count(self, **labels) -> int:
        with self._lock:
            return self._totals.get(self._label_key(labels), 0)

    def sum(self, **labels) -> float:
        with self._lock:
            return self._sums.get(self._label_key(labels), 0.0)

    def percentile(self, q: float, **labels) -> Optional[float]:
        key = self._label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            total = self._totals.get(key, 0)
        if not counts or not total:
            return None
        target = q * total
        for i, c in enumerate(counts):
            if c >= target:
                return self.buckets[i]
        return float("inf")

    def time(self, **labels):
        return _Timer(self, labels)


class _Timer:
    def __init__(self, hist: Histogram, labels):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)
        return False


class Registry:
    def __init__(self):
        self._metrics: "dict[str, _Metric]" = {}
        self._lock = threading.Lock()

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self._register(name, lambda: Counter(name, help_, label_names))

    def gauge(self, name, help_="", label_names=()) -> Gauge:
        return self._register(name, lambda: Gauge(name, help_, label_names))

    def histogram(self, name, help_="", label_names=(), buckets=DURATION_BUCKETS) -> Histogram:
        return self._register(name, lambda: Histogram(name, help_, label_names, buckets))

    def _register(self, name, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            if isinstance(m, Histogram):
                lines.append(f"# TYPE {m.name} histogram")
                with m._lock:
                    for key, counts in sorted(m._counts.items()):
                        labels = dict(zip(m.label_names, key))
                        for b, c in zip(m.buckets, counts):
                            lab = ",".join(f'{k}="{v}"' for k, v in {**labels, "le": b}.items())
                            lines.append(f"{m.name}_bucket{{{lab}}} {c}")
                        # mandatory +Inf bucket == total observation count;
                        # the series' last exemplar rides on it (OpenMetrics
                        # `# {trace_id=...}` suffix — ignored by classic
                        # Prometheus text parsers, resolvable at
                        # /debug/traces?id=<trace_id>)
                        lab = ",".join(f'{k}="{v}"' for k, v in {**labels, "le": "+Inf"}.items())
                        ex = m._exemplars.get(key)
                        suffix = (f' # {{trace_id="{ex["trace_id"]}"}} '
                                  f'{ex["value"]} {ex["ts"]}' if ex else "")
                        lines.append(
                            f"{m.name}_bucket{{{lab}}} {m._totals[key]}{suffix}")
                        lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
                        sep = f"{{{lab}}}" if lab else ""
                        lines.append(f"{m.name}_sum{sep} {m._sums[key]}")
                        lines.append(f"{m.name}_count{sep} {m._totals[key]}")
            else:
                kind = "gauge" if isinstance(m, Gauge) else "counter"
                lines.append(f"# TYPE {m.name} {kind}")
                for labels, v in m.collect():
                    lab = ",".join(f'{k}="{v2}"' for k, v2 in labels.items())
                    sep = f"{{{lab}}}" if lab else ""
                    lines.append(f"{m.name}{sep} {v}")
        return "\n".join(lines) + "\n"


REGISTRY = Registry()


def decorate_cloudprovider(cp, registry: Optional[Registry] = None):
    """Wrap every public CloudProvider method with a duration histogram
    (core `metrics.Decorate`, main.go:46 ->
    karpenter_cloudprovider_duration_seconds)."""
    reg = registry or REGISTRY
    hist = reg.histogram(
        f"{NAMESPACE}_cloudprovider_duration_seconds",
        "Duration of cloud provider method calls.",
        ("controller", "method"),
    )

    class _Decorated:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            attr = getattr(self._inner, name)
            if not callable(attr) or name.startswith("_"):
                return attr

            def wrapped(*args, **kwargs):
                with hist.time(controller="cloudprovider", method=name):
                    return attr(*args, **kwargs)

            return wrapped

    return _Decorated(cp)
