"""Reconcile-cycle deadline budgets, propagated instead of stacked.

The controller plane's old timeout story was stacked independents: the
solver client had 10s, every HTTP call had 10s, the batcher had its own
window — so a cycle could legally burn minutes while every layer was
individually "within timeout". A DeadlineBudget is created ONCE at the top
of a controller cycle and every layer below checks *remaining* budget:
fail fast when it's gone, and ship the remainder across the solver wire
(`deadline_ms` in solver.proto — the REMAINING milliseconds at send time,
not an absolute timestamp: the two processes share no clock, and FakeClock
runs make absolute deadlines meaningless) so the service can shed solves
whose caller has already given up on the cycle.

Propagation is a thread-local: providers and the solver client consult
`current()` without threading a parameter through every signature. Launch
pool threads intentionally do NOT inherit it — an in-flight launch past
the cycle deadline must complete (half-launched capacity would leak).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from ..utils.clock import Clock

# a controller cycle's wall budget; generous vs the loop intervals so only
# genuinely wedged dependencies exhaust it
DEFAULT_CYCLE_BUDGET_S = 60.0


class DeadlineExceeded(RuntimeError):
    def __init__(self, what: str = "cycle"):
        super().__init__(f"deadline budget exhausted ({what})")
        self.what = what


class DeadlineBudget:
    def __init__(self, clock: Optional[Clock] = None,
                 budget_s: float = DEFAULT_CYCLE_BUDGET_S):
        self.clock = clock or Clock()
        self.total = budget_s
        self._deadline = self.clock.now() + budget_s

    def remaining(self) -> float:
        return self._deadline - self.clock.now()

    def remaining_ms(self) -> int:
        return max(0, int(self.remaining() * 1000))

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "cycle") -> None:
        if self.expired():
            raise DeadlineExceeded(what)


_local = threading.local()


def current() -> Optional[DeadlineBudget]:
    """The active cycle budget on THIS thread (None outside a cycle)."""
    return getattr(_local, "budget", None)


@contextlib.contextmanager
def cycle(clock: Optional[Clock] = None,
          budget_s: float = DEFAULT_CYCLE_BUDGET_S):
    """Install a fresh cycle budget for the duration of one reconcile.
    Nested cycles keep the OUTER (tighter-scoped callers must not widen
    an enclosing budget)."""
    outer = current()
    budget = outer if outer is not None \
        else DeadlineBudget(clock=clock, budget_s=budget_s)
    _local.budget = budget
    try:
        yield budget
    finally:
        _local.budget = outer
