"""Declarative retry policy + per-dependency retry budget.

Design target: SRE-style overload control (PAPERS.md) — retries are a
*budgeted* resource, not a free amplifier. A cloud 5xx burst must never
turn into a retry storm: every retry spends a token from the dependency's
budget, successes slowly refill it, and an empty bucket turns retries into
immediate give-ups until the dependency earns trust back.

Determinism contract (the chaos plane replays seeds): backoff jitter comes
from a seeded splitmix64 PRNG — no `random` module — and sleeping goes
through an injectable sleep function (the operator's clock by default; the
chaos runner swaps in FakeClock.step so retries consume *virtual* time and
never block the single-threaded scenario driver).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..metrics import NAMESPACE, REGISTRY
from ..utils.clock import Clock

_MASK = (1 << 64) - 1


class _SplitMix64:
    """Tiny seeded PRNG (same generator family as chaos.plan.ChaosRng,
    duplicated here so resilience never imports the chaos plane)."""

    def __init__(self, seed: int):
        self._state = seed & _MASK

    def next_u64(self) -> int:
        self._state = (self._state + 0x9E3779B97F4A7C15) & _MASK
        z = self._state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
        return z ^ (z >> 31)

    def uniform(self) -> float:
        return self.next_u64() / float(1 << 64)


class RetryBudget:
    """Token bucket bounding retries per dependency: a retry spends one
    token, a success refills `refill_per_success` (slowly — earning back
    a retry takes many successes). The bucket can never go negative and
    never exceeds capacity; `min_tokens` is the watermark the chaos
    *retry-budget-never-exceeded* invariant audits."""

    def __init__(self, capacity: float = 10.0,
                 refill_per_success: float = 0.2):
        self.capacity = float(capacity)
        self.refill_per_success = float(refill_per_success)
        self._tokens = float(capacity)
        self._lock = threading.Lock()
        self.spent_total = 0
        self.denied_total = 0
        self.min_tokens = float(capacity)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent_total += 1
                self.min_tokens = min(self.min_tokens, self._tokens)
                return True
            self.denied_total += 1
            return False

    def refill(self) -> None:
        with self._lock:
            self._tokens = min(self.capacity,
                               self._tokens + self.refill_per_success)

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def evidence(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity,
                    "tokens": round(self._tokens, 3),
                    "min_tokens": round(self.min_tokens, 3),
                    "spent_total": self.spent_total,
                    "denied_total": self.denied_total}


class RetryPolicy:
    """Exponential backoff with decorrelated jitter, budget-gated.

    `call(fn)` is the wrap-a-callable form; in-place retry loops that
    can't be inverted (httpkube's phase-aware loop, cloudbackend's
    linear replay) use the lower-level `try_retry()` / `sleep_backoff()` /
    `note_success()` primitives so *every* retry path still spends from
    the same budget and feeds the same breaker and metrics.
    """

    def __init__(self, dep: str, clock: Optional[Clock] = None,
                 base: float = 0.05, cap: float = 5.0,
                 max_attempts: int = 4, seed: int = 0,
                 budget: Optional[RetryBudget] = None,
                 breaker=None, registry=None,
                 sleep: "Optional[Callable[[float], None]]" = None):
        self.dep = dep
        self.clock = clock or Clock()
        self.base = base
        self.cap = cap
        self.max_attempts = max(1, max_attempts)
        self.budget = budget if budget is not None else RetryBudget()
        self.breaker = breaker
        self._rng = _SplitMix64((seed << 8) ^ _stable_hash(dep))
        self._prev = base
        self._sleep = sleep if sleep is not None else self.clock.sleep
        self._lock = threading.Lock()
        reg = registry if registry is not None else REGISTRY
        self.retries_total = reg.counter(
            f"{NAMESPACE}_resilience_retries_total",
            "Retry decisions per dependency: retry, give_up, "
            "budget_exhausted, breaker_open.", ("dep", "outcome"))
        self.sleeps_total = 0.0  # backoff seconds spent (virtual in chaos)

    # -- primitives (in-place loops) -------------------------------------------

    def set_sleep(self, sleep: "Callable[[float], None]") -> None:
        self._sleep = sleep

    def next_backoff(self) -> float:
        """Decorrelated jitter (cap-bounded): uniform in [base, 3*prev]."""
        with self._lock:
            span = max(0.0, 3.0 * self._prev - self.base)
            delay = min(self.cap, self.base + self._rng.uniform() * span)
            self._prev = delay
            return delay

    def try_retry(self) -> bool:
        """Spend one retry token; False means the budget is empty and the
        caller must give up NOW (counts as budget_exhausted)."""
        if not self.budget.try_spend():
            self.retries_total.inc(dep=self.dep, outcome="budget_exhausted")
            return False
        self.retries_total.inc(dep=self.dep, outcome="retry")
        return True

    def sleep_backoff(self) -> float:
        delay = self.next_backoff()
        with self._lock:
            self.sleeps_total += delay
        self._sleep(delay)
        return delay

    def sleep_retry_after(self, seconds: float) -> float:
        """Honor a server-directed backoff (HTTP 429 Retry-After): sleep
        what the server asked, clamped to the policy cap — a throttling
        apiserver gets to slow this client down, never to wedge it. The
        directed delay flows through the same injectable sleep and the
        same sleeps_total ledger as jittered backoff (FakeClock-testable),
        and resets the decorrelated-jitter state so a subsequent backoff
        does not compound on top of the server's figure."""
        delay = min(self.cap, max(0.0, float(seconds)))
        with self._lock:
            self.sleeps_total += delay
            self._prev = self.base
        self._sleep(delay)
        return delay

    def note_success(self) -> None:
        self.budget.refill()
        with self._lock:
            self._prev = self.base  # backoff resets once the dep answers
        if self.breaker is not None:
            self.breaker.record_success()

    def note_failure(self) -> None:
        if self.breaker is not None:
            self.breaker.record_failure()

    def release_probe(self) -> None:
        """Resolve a half-open probe the breaker may have admitted for a
        call that exited without learning anything about dependency health
        (non-retriable business error, self-inflicted deadline, caller
        bug). No-op when the probe was already judged via note_success /
        note_failure — safe to call from a finally."""
        if self.breaker is not None:
            self.breaker.release_probe()

    # -- the declarative form ----------------------------------------------------

    def call(self, fn: Callable, retriable=(Exception,),
             description: str = ""):
        """Run fn; retry retriable failures with jittered backoff while the
        budget holds and attempts remain. `retriable` is an exception
        class/tuple or a predicate `exc -> bool` (lets callers match by
        error CODE, e.g. transient cloud 5xx vs business errors). The
        breaker (when wired) is consulted once up front — a known-down
        dependency fails fast."""
        if self.breaker is not None and not self.breaker.allow():
            self.retries_total.inc(dep=self.dep, outcome="breaker_open")
            from .breaker import BreakerOpen

            raise BreakerOpen(self.dep)
        matches = retriable if callable(retriable) \
            and not isinstance(retriable, type) \
            else (lambda e: isinstance(e, retriable))
        try:
            for attempt in range(self.max_attempts):
                try:
                    result = fn()
                except Exception as e:
                    if not matches(e):
                        raise
                    self.note_failure()
                    if attempt + 1 >= self.max_attempts \
                            or not self.try_retry():
                        self.retries_total.inc(dep=self.dep,
                                               outcome="give_up")
                        raise
                    self.sleep_backoff()
                    continue
                self.note_success()
                return result
        finally:
            # every exit path must resolve a probe the allow() above may
            # have admitted: the retriable paths already judged it via
            # note_success/note_failure (release is then a no-op), but a
            # non-retriable raise — or a BaseException — would otherwise
            # leave it unjudged and wedge the breaker in HALF_OPEN forever
            self.release_probe()

    def evidence(self) -> dict:
        with self._lock:
            sleeps = round(self.sleeps_total, 6)
        return {"budget": self.budget.evidence(),
                "backoff_seconds_total": sleeps}


def _stable_hash(s: str) -> int:
    """Deterministic across processes (hash() is salted)."""
    h = 1469598103934665603  # FNV-1a 64
    for b in s.encode():
        h = ((h ^ b) * 1099511628211) & _MASK
    return h
