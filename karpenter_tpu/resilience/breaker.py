"""Per-dependency circuit breakers: closed -> open -> half-open probes.

One breaker per dependency edge (cloud API, kube apiserver, solver
sidecar, pricing endpoint). K consecutive failures while closed trip it
open; while open every call fails fast (no socket, no timeout burn).
After `recovery_time` ONE half-open probe is admitted at a time;
`success_threshold` consecutive probe successes close it again, any probe
failure re-opens and re-arms the recovery timer (hysteresis — a flapping
dependency stays open, it does not oscillate per call).

Transitions are edge-triggered events (`BreakerOpened` / `BreakerClosed`)
through the shared EventRecorder and a `karpenter_resilience_breaker_state`
gauge (0=closed, 1=open, 2=half-open). The transition ledger feeds the
chaos *breaker-opens-within-K-consecutive-failures* invariant.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..metrics import NAMESPACE, REGISTRY
from ..utils.clock import Clock

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_VALUE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class BreakerOpen(RuntimeError):
    """Fail-fast rejection: the dependency's breaker is open."""

    def __init__(self, dep: str):
        super().__init__(f"circuit breaker for dependency '{dep}' is open")
        self.dep = dep


class CircuitBreaker:
    def __init__(self, dep: str, clock: Optional[Clock] = None,
                 failure_threshold: int = 5, recovery_time: float = 30.0,
                 success_threshold: int = 2, recorder=None, registry=None):
        self.dep = dep
        self.clock = clock or Clock()
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_time = recovery_time
        self.success_threshold = max(1, success_threshold)
        self.recorder = recorder
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._probe_successes = 0
        self._opened_at: Optional[float] = None
        self._probe_in_flight = False
        # evidence for the chaos invariant: the longest failure streak ever
        # observed while closed must never exceed failure_threshold
        self.max_closed_streak = 0
        self.opened_total = 0
        self.closed_total = 0
        self.rejected_total = 0
        self.transitions: "list[dict]" = []
        reg = registry if registry is not None else REGISTRY
        self._gauge = reg.gauge(
            f"{NAMESPACE}_resilience_breaker_state",
            "Circuit breaker state per dependency "
            "(0=closed, 1=open, 2=half-open).", ("dep",))
        self._gauge.set(0, dep=dep)

    # -- admission ---------------------------------------------------------------

    def allow(self) -> bool:
        """May a call proceed right now? Open breakers admit exactly one
        probe once the recovery window has elapsed."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self.clock.now()
            if self._state == OPEN:
                if (self._opened_at is not None
                        and now - self._opened_at >= self.recovery_time):
                    self._transition(HALF_OPEN, "recovery window elapsed")
                    self._probe_in_flight = True
                    return True
                self.rejected_total += 1
                return False
            # HALF_OPEN: one probe at a time
            if self._probe_in_flight:
                self.rejected_total += 1
                return False
            self._probe_in_flight = True
            return True

    def guard(self, fn):
        """allow() + record_* around one call; raises BreakerOpen when
        rejected."""
        if not self.allow():
            raise BreakerOpen(self.dep)
        try:
            result = fn()
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result

    # -- outcome feedback --------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._probe_in_flight = False
                self._probe_successes += 1
                if self._probe_successes >= self.success_threshold:
                    self._transition(
                        CLOSED,
                        f"{self._probe_successes} consecutive probe "
                        "successes")

    def release_probe(self) -> None:
        """Resolve an admitted half-open probe WITHOUT judging it. For exit
        paths that say nothing about dependency health — a non-retriable
        business error from a live server, a caller-side bug, a
        self-inflicted deadline — where neither record_success nor
        record_failure is honest. Clears the in-flight flag so the next
        allow() can probe again; without this an unjudged probe would
        reject every future HALF_OPEN call forever (no timeout escape).
        No-op when the probe was already judged or none is in flight."""
        with self._lock:
            if self._state == HALF_OPEN and self._probe_in_flight:
                self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == CLOSED:
                self._consecutive_failures += 1
                self.max_closed_streak = max(self.max_closed_streak,
                                             self._consecutive_failures)
                if self._consecutive_failures >= self.failure_threshold:
                    self._transition(
                        OPEN,
                        f"{self._consecutive_failures} consecutive failures")
            elif self._state == HALF_OPEN:
                # failed probe: re-open and re-arm the full recovery window
                self._probe_in_flight = False
                self._transition(OPEN, "half-open probe failed")

    # -- state machine internals -------------------------------------------------

    def _transition(self, to: str, why: str) -> None:
        """Callers hold self._lock."""
        frm = self._state
        if frm == to:
            return
        self._state = to
        now = self.clock.now()
        self.transitions.append(
            {"ts": round(now, 3), "from": frm, "to": to, "why": why})
        self._gauge.set(_STATE_VALUE[to], dep=self.dep)
        if to == OPEN:
            self._opened_at = now
            self._probe_successes = 0
            self.opened_total += 1
            # edge-triggered: only the closed->open edge warns (the
            # half-open->open re-trip is the same outage continuing, and
            # the recorder's dedupe TTL absorbs repeats regardless)
            if self.recorder is not None and frm == CLOSED:
                self.recorder.warning(
                    f"resilience/{self.dep}", "BreakerOpened",
                    f"{self.dep} circuit opened: {why}")
        elif to == CLOSED:
            self._consecutive_failures = 0
            self._probe_successes = 0
            self._opened_at = None
            self.closed_total += 1
            if self.recorder is not None:
                self.recorder.normal(
                    f"resilience/{self.dep}", "BreakerClosed",
                    f"{self.dep} circuit closed: {why}")

    # -- observability -----------------------------------------------------------

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "max_closed_streak": self.max_closed_streak,
                "opened_total": self.opened_total,
                "closed_total": self.closed_total,
                "rejected_total": self.rejected_total,
                "opened_at": self._opened_at,
            }

    def evidence(self) -> dict:
        """Deterministic subset for chaos scenario dicts."""
        with self._lock:
            return {
                "failure_threshold": self.failure_threshold,
                "max_closed_streak": self.max_closed_streak,
                "opened_total": self.opened_total,
                "closed_total": self.closed_total,
                "rejected_total": self.rejected_total,
                "final_state": self._state,
                "transitions": [dict(t) for t in self.transitions],
            }
