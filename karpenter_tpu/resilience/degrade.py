"""DegradeLadder: the implicit fallback chains, made explicit and sticky.

The solver->native-packer->scalar-oracle chain and pricing's live->static
fallback used to be scattered try/excepts: every cycle re-tried the broken
best rung, paid its full failure latency, and "which backend are we
actually on" was never observable. A ladder names the rungs (index 0 =
best), remembers where it is (sticky — no flapping), and climbs back up
only through scheduled recovery probes:

  start_rung()          -> where this cycle should start attempting
  record_failure(rung)  -> degrade below the failing rung (event + gauge)
  record_success(rung)  -> steady state, or promote after a probe success

Recovery is single-step: a probe tries exactly one rung above the current
one, so a half-healed dependency can't yank the chain all the way up and
immediately back down. The transition ledger (reason "failure" for every
down-move, "probe-success" for every up-move) is what the chaos
*degrade-monotone-during-fault-window* invariant audits.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from ..metrics import NAMESPACE, REGISTRY
from ..utils.clock import Clock


class DegradeLadder:
    def __init__(self, chain: str, rungs: Sequence[str],
                 clock: Optional[Clock] = None, recorder=None,
                 registry=None, probe_interval_s: float = 120.0):
        if len(rungs) < 2:
            raise ValueError("a ladder needs at least two rungs")
        self.chain = chain
        self.rungs = tuple(rungs)
        self.clock = clock or Clock()
        self.recorder = recorder
        self.probe_interval_s = probe_interval_s
        self._lock = threading.Lock()
        self._rung = 0
        self._probing = False
        self._since: Optional[float] = None  # last degrade/probe timestamp
        self.probes_total = 0
        self.transitions: "list[dict]" = []
        reg = registry if registry is not None else REGISTRY
        self._gauge = reg.gauge(
            f"{NAMESPACE}_resilience_degrade_rung",
            "Current rung per degradation chain (0 = best).", ("chain",))
        self._gauge.set(0, chain=chain)

    # -- per-cycle routing -------------------------------------------------------

    def start_rung(self) -> int:
        """Rung to start attempts at this cycle. Sticky while degraded;
        when a probe is due, admit ONE attempt a single rung up."""
        with self._lock:
            if self._rung == 0:
                return 0
            now = self.clock.now()
            if (not self._probing and self._since is not None
                    and now - self._since >= self.probe_interval_s):
                self._probing = True
                self._since = now
                self.probes_total += 1
                return self._rung - 1
            return self._rung

    def record_failure(self, rung: int) -> None:
        with self._lock:
            if self._probing and rung == self._rung - 1:
                # failed probe: stay put, re-arm the probe timer
                self._probing = False
                self._since = self.clock.now()
                return
            if rung >= self._rung and rung + 1 < len(self.rungs):
                self._move(rung + 1, "failure")

    def abort_probe(self) -> None:
        """A probe admitted by start_rung() that never actually ran (e.g.
        the cycle deadline expired first): re-arm the timer without judging
        the rung either way."""
        with self._lock:
            if self._probing:
                self._probing = False
                self._since = self.clock.now()

    def record_success(self, rung: int) -> None:
        with self._lock:
            if self._probing and rung == self._rung - 1:
                self._probing = False
                self._move(rung, "probe-success")
            # success at or below the current rung is steady state; success
            # ABOVE it without a probe (caller skipped rungs on its own,
            # e.g. no remote consolidator configured) never promotes

    # -- internals ---------------------------------------------------------------

    def _move(self, to: int, reason: str) -> None:
        """Callers hold self._lock."""
        frm = self._rung
        if to == frm:
            return
        self._rung = to
        now = self.clock.now()
        self._since = now
        self.transitions.append({"ts": round(now, 3), "from": frm,
                                 "to": to, "reason": reason})
        self._gauge.set(to, chain=self.chain)
        if self.recorder is not None:
            if to > frm:
                self.recorder.warning(
                    f"resilience/{self.chain}", "DegradedTo",
                    f"{self.chain} chain degraded "
                    f"{self.rungs[frm]} -> {self.rungs[to]}")
            else:
                self.recorder.normal(
                    f"resilience/{self.chain}", "RecoveredTo",
                    f"{self.chain} chain recovered "
                    f"{self.rungs[frm]} -> {self.rungs[to]}")

    # -- observability -----------------------------------------------------------

    def rung(self) -> int:
        with self._lock:
            return self._rung

    def rung_name(self) -> str:
        with self._lock:
            return self.rungs[self._rung]

    def snapshot(self) -> dict:
        with self._lock:
            return {"rungs": list(self.rungs),
                    "current": self.rungs[self._rung],
                    "current_index": self._rung,
                    "probing": self._probing,
                    "probes_total": self.probes_total,
                    "transitions": len(self.transitions)}

    def evidence(self) -> dict:
        with self._lock:
            return {"rungs": list(self.rungs),
                    "final_rung": self._rung,
                    "probes_total": self.probes_total,
                    "transitions": [dict(t) for t in self.transitions]}
