"""Resilience plane: retry budgets, circuit breakers, deadlines, ladders.

One clock-injectable subsystem owning every dependency edge's failure
policy (docs/designs/resilience.md):

- `policy.RetryPolicy` / `RetryBudget` — budgeted, jittered, seeded retries
- `breaker.CircuitBreaker` — fail-fast state machine per dependency
- `deadline.DeadlineBudget` — one cycle budget, propagated not stacked
- `degrade.DegradeLadder` — explicit fallback chains with recovery probes

`ResilienceHub` assembles the per-dependency instances (cloud, kube,
solver, pricing) plus the three degradation chains and is constructed once
by the Operator; controllers, providers, batchers and the solver client
all borrow from it so state (breaker trips, budget levels) is shared
across every call path touching the same dependency.
"""

from __future__ import annotations

from typing import Optional

from ..utils.clock import Clock
from .breaker import BreakerOpen, CircuitBreaker
from .deadline import (DEFAULT_CYCLE_BUDGET_S, DeadlineBudget,
                       DeadlineExceeded)
from . import deadline
from .degrade import DegradeLadder
from .policy import RetryBudget, RetryPolicy

__all__ = [
    "BreakerOpen", "CircuitBreaker", "DeadlineBudget", "DeadlineExceeded",
    "DEFAULT_CYCLE_BUDGET_S", "DegradeLadder", "ResilienceHub",
    "RetryBudget", "RetryPolicy", "deadline",
]

# (failure_threshold, recovery_time_s, budget_capacity, refill_per_success,
#  max_attempts) per dependency edge — the solver and pricing edges trip
# faster: their calls are expensive and both have in-process fallbacks
_DEP_TUNING = {
    "cloud":   (5, 30.0, 10.0, 0.2, 3),
    "kube":    (5, 15.0, 10.0, 0.2, 2),
    "solver":  (3, 30.0, 5.0, 0.2, 2),
    "pricing": (3, 60.0, 5.0, 0.2, 3),
}

_CHAINS = {
    # solve rungs are FIXED backend identities (tpu is always rung 0):
    # provisioning's size-crossover preference reorders attempts, never
    # the rung a verdict is recorded against (backend-stable ladder state)
    "solve": ("tpu", "native", "oracle"),
    "consolidate": ("remote", "tpu", "oracle"),
    "pricing": ("live", "static"),
}


class ResilienceHub:
    DEPS = tuple(_DEP_TUNING)
    CHAINS = dict(_CHAINS)

    def __init__(self, clock: Optional[Clock] = None, recorder=None,
                 registry=None, seed: int = 0):
        self.clock = clock or Clock()
        self.breakers: "dict[str, CircuitBreaker]" = {}
        self.budgets: "dict[str, RetryBudget]" = {}
        self.policies: "dict[str, RetryPolicy]" = {}
        for dep, (k, recov, cap, refill, attempts) in _DEP_TUNING.items():
            br = CircuitBreaker(dep, clock=self.clock,
                                failure_threshold=k, recovery_time=recov,
                                recorder=recorder, registry=registry)
            budget = RetryBudget(capacity=cap, refill_per_success=refill)
            self.breakers[dep] = br
            self.budgets[dep] = budget
            self.policies[dep] = RetryPolicy(
                dep, clock=self.clock, max_attempts=attempts, seed=seed,
                budget=budget, breaker=br, registry=registry)
        self.ladders: "dict[str, DegradeLadder]" = {
            chain: DegradeLadder(chain, rungs, clock=self.clock,
                                 recorder=recorder, registry=registry)
            for chain, rungs in _CHAINS.items()
        }

    def policy(self, dep: str) -> RetryPolicy:
        return self.policies[dep]

    def breaker(self, dep: str) -> CircuitBreaker:
        return self.breakers[dep]

    def ladder(self, chain: str) -> DegradeLadder:
        return self.ladders[chain]

    def use_virtual_sleep(self) -> None:
        """Chaos/FakeClock mode: backoff sleeps STEP the fake clock instead
        of blocking on it (nobody else would advance it mid-cycle —
        a FakeClock sleep would deadlock the single-threaded driver)."""
        step = getattr(self.clock, "step", None)
        if step is None:
            return
        for p in self.policies.values():
            p.set_sleep(step)

    def open_breakers(self) -> "list[str]":
        return sorted(d for d, b in self.breakers.items()
                      if b.state() != "closed")

    # -- surfaces ----------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/statusz "resilience" section. The two summary lists
        lead so an operator staring at a wedged cluster sees "what is
        broken right now" before the per-dependency detail."""
        return {
            "open_breakers": self.open_breakers(),
            "degraded": sorted(c for c, ld in self.ladders.items()
                               if ld.rung() > 0),
            "breakers": {d: b.snapshot()
                         for d, b in sorted(self.breakers.items())},
            "budgets": {d: b.evidence()
                        for d, b in sorted(self.budgets.items())},
            "ladders": {c: ld.snapshot()
                        for c, ld in sorted(self.ladders.items())},
        }

    def evidence(self) -> dict:
        """Deterministic ledger for chaos scenario dicts (pure function of
        the seed under FakeClock + virtual sleep)."""
        return {
            "breakers": {d: b.evidence()
                         for d, b in sorted(self.breakers.items())},
            "policies": {d: p.evidence()
                         for d, p in sorted(self.policies.items())},
            "ladders": {c: ld.evidence()
                        for c, ld in sorted(self.ladders.items())},
        }
