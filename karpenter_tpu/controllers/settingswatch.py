"""Settings live-watch controller.

Parity target: the reference live-watches the `karpenter-global-settings`
ConfigMap and injects the parsed struct into every reconcile context
(settings.go:72-93 Inject; website settings.md). Here the Settings object is
shared by reference across the operator, so one in-place `apply` makes the
change visible everywhere — batching windows, feature gates, tags — without
restarts. Invalid updates are rejected and logged, keeping the last good
configuration (knative configmap-watcher semantics).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..apis.settings import Settings, SettingsError
from ..introspect.watchdog import cycle as _wd_cycle
from ..utils.clock import Clock

log = logging.getLogger("karpenter.settings")

CONFIGMAP_NAME = "karpenter-global-settings"


class SettingsWatchController:
    def __init__(self, kube, settings: Settings, clock: Optional[Clock] = None,
                 watchdog=None):
        self.kube = kube
        self.settings = settings
        self.clock = clock or Clock()
        self.watchdog = watchdog
        self._last_applied: "Optional[dict]" = None

    def reconcile_once(self) -> "list[str]":
        with _wd_cycle(self.watchdog, "settingswatch"):
            return self._reconcile_once()

    def _reconcile_once(self) -> "list[str]":
        """Apply the ConfigMap if it changed; returns changed field names."""
        cm = self.kube.get("configmaps", CONFIGMAP_NAME)
        if cm is None:
            return []
        data = dict(cm.get("data", cm) if isinstance(cm, dict) else cm.data)
        if data == self._last_applied:
            return []
        try:
            parsed = Settings.from_dict(data)
        except (SettingsError, ValueError) as e:
            log.warning("rejecting settings update: %s", e)
            self._last_applied = data  # don't re-log every cycle
            return []
        changed = self.settings.apply(parsed)
        self._last_applied = data
        if changed:
            log.info("settings updated: %s", ", ".join(changed))
        return changed
