"""Interruption handling pipeline: queue -> parse -> act.

Parity targets:
- Message model + parser registry — /root/reference/pkg/controllers/
  interruption/messages/types.go:21-42 (Parser/Message interfaces),
  parser.go:31-60 (registry keyed by (version, source, detail-type);
  4 event kinds + noop: spotInterruption, rebalanceRecommendation,
  scheduledChange, stateChange stopping/stopped/shutting-down/terminated).
- Queue provider — sqs.go:33-148 (lazy URL discovery, 20s long poll / 10
  messages, receive/send/delete).
- Controller — controller.go:83-115: singleton long-poll loop, instance-id ->
  node map, 10-way parallel message handling (workqueue.ParallelizeUntil
  analogue), spot interruption also poisons the ICE cache (:186-192),
  cordon&drain via node deletion (:193-208), metrics (metrics.go:31-60:
  received/deleted/latency/actions).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ... import explain
from ...apis import wellknown as wk
from ...events import EventRecorder
from ...introspect.watchdog import cycle as _wd_cycle
from ...metrics import NAMESPACE, REGISTRY, Registry
from ...models.cluster import ClusterState
from ...recovery.crashpoints import crashpoint
from ...utils.clock import Clock

log = logging.getLogger("karpenter.interruption")

# -- message model ----------------------------------------------------------------

KIND_SPOT_INTERRUPTION = "SpotInterruption"
KIND_REBALANCE = "RebalanceRecommendation"
KIND_SCHEDULED_CHANGE = "ScheduledChange"
KIND_STATE_CHANGE = "StateChange"
KIND_NOOP = "NoOp"

ACTION_CORDON_AND_DRAIN = "CordonAndDrain"
ACTION_NOOP = "NoOp"

STOPPING_STATES = frozenset({"stopping", "stopped", "shutting-down", "terminated"})


@dataclasses.dataclass
class InterruptionMessage:
    kind: str
    instance_ids: "list[str]"
    detail: "dict" = dataclasses.field(default_factory=dict)
    raw: str = ""
    receipt: str = ""
    enqueued_at: float = 0.0

    def action(self) -> str:
        if self.kind in (KIND_SPOT_INTERRUPTION, KIND_SCHEDULED_CHANGE):
            return ACTION_CORDON_AND_DRAIN
        if self.kind == KIND_REBALANCE:
            return ACTION_NOOP  # rebalance is advisory (reference default)
        if self.kind == KIND_STATE_CHANGE:
            state = self.detail.get("state", "")
            return ACTION_CORDON_AND_DRAIN if state in STOPPING_STATES else ACTION_NOOP
        return ACTION_NOOP


class ParserRegistry:
    """(source, detail-type) -> parser fn (parser.go:31-60)."""

    def __init__(self):
        self._parsers = {}

    def register(self, source: str, detail_type: str, fn):
        self._parsers[(source, detail_type)] = fn

    def parse(self, body: str, receipt: str = "", enqueued_at: float = 0.0
              ) -> InterruptionMessage:
        try:
            data = json.loads(body)
        except json.JSONDecodeError:
            return InterruptionMessage(KIND_NOOP, [], raw=body, receipt=receipt)
        key = (data.get("source", ""), data.get("detail-type", ""))
        fn = self._parsers.get(key)
        if fn is None:
            return InterruptionMessage(KIND_NOOP, [], detail=data, raw=body,
                                       receipt=receipt, enqueued_at=enqueued_at)
        msg = fn(data)
        msg.raw = body
        msg.receipt = receipt
        msg.enqueued_at = enqueued_at
        return msg


def default_parsers() -> ParserRegistry:
    reg = ParserRegistry()

    def ids(data):
        d = data.get("detail", {})
        one = d.get("instance-id")
        return [one] if one else list(d.get("instance-ids", []))

    reg.register("cloud.spot", "Spot Instance Interruption Warning",
                 lambda d: InterruptionMessage(KIND_SPOT_INTERRUPTION, ids(d), d.get("detail", {})))
    reg.register("cloud.spot", "Instance Rebalance Recommendation",
                 lambda d: InterruptionMessage(KIND_REBALANCE, ids(d), d.get("detail", {})))
    reg.register("cloud.health", "Scheduled Change",
                 lambda d: InterruptionMessage(
                     KIND_SCHEDULED_CHANGE,
                     [r.split("/")[-1] for r in d.get("resources", [])],
                     d.get("detail", {})))
    reg.register("cloud.compute", "Instance State-change Notification",
                 lambda d: InterruptionMessage(KIND_STATE_CHANGE, ids(d), d.get("detail", {})))
    return reg


# -- queue provider ---------------------------------------------------------------
# The client boundary lives in queues.py (QueueProvider interface + FakeQueue
# + RemoteQueueProvider real-client stub); re-exported here for compatibility.

from .queues import (FakeQueue, QueueAPI, QueueMessage, QueueNotFound,  # noqa: F401,E402
                     QueueProvider, RemoteQueueProvider)

# -- controller -------------------------------------------------------------------

class InterruptionController:
    def __init__(self, kube, cluster: ClusterState, queue: QueueProvider,
                 unavailable_offerings,
                 termination=None, clock: Optional[Clock] = None,
                 recorder: Optional[EventRecorder] = None,
                 registry: Optional[Registry] = None,
                 parallelism: int = 10,
                 watchdog=None):
        self.kube = kube
        self.watchdog = watchdog
        self.cluster = cluster
        self.queue = queue
        self.ice = unavailable_offerings
        self.termination = termination
        self.clock = clock or Clock()
        self.recorder = recorder or EventRecorder(clock=self.clock)
        self.parsers = default_parsers()
        reg = registry or REGISTRY
        self.received = reg.counter(
            f"{NAMESPACE}_interruption_received_messages_total",
            "Interruption messages received.", ("message_type",))
        self.deleted = reg.counter(
            f"{NAMESPACE}_interruption_deleted_messages_total",
            "Interruption messages deleted.")
        self.latency = reg.histogram(
            f"{NAMESPACE}_interruption_message_latency_time_seconds",
            "Queue time of interruption messages.")
        self.actions = reg.counter(
            f"{NAMESPACE}_interruption_actions_performed_total",
            "Actions taken on interruption messages.", ("action",))
        # per-batch drain rate: the attribution signal for queue-throughput
        # regressions — a ladder that degrades superlinearly with batch
        # size shows up HERE (per-batch msgs/s falling as batches fill)
        # before it shows up in end-to-end latency. `reason` splits drains
        # the platform forced (reactive-reclaim) from drains the spot
        # plane chose (proactive-rebalance, observed by RebalanceController
        # against this same family) so a storm's churn is attributable.
        self.drain_throughput = reg.histogram(
            f"{NAMESPACE}_interruption_drain_throughput_msgs_per_second",
            "Messages drained per second, per receive batch "
            "(handle + delete, wall time), by drain reason.", ("reason",),
            buckets=(50, 100, 250, 500, 1000, 2500, 5000, 10000))
        self.deduped = reg.counter(
            f"{NAMESPACE}_interruption_deduped_messages_total",
            "Redelivered interruption messages skipped by the dedupe set.")
        # per-message pipeline phase split (docs/designs/slo.md): the drain
        # ladder droops superlinearly with scale, and without per-phase
        # timing the droop cannot be localized to parse vs index lookup vs
        # the dedupe store write vs the ack round-trip. Sub-ms buckets —
        # individual phases are microseconds-to-milliseconds each.
        self.phase_seconds = reg.histogram(
            f"{NAMESPACE}_interruption_phase_seconds",
            "Per-message interruption pipeline phase wall time "
            "(parse / index_lookup / store_write / ack).", ("phase",),
            buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3,
                     0.01, 0.05, 0.1, 0.5, 1, 5))
        # receipt -> handled-at timestamp, persisted through the kube store:
        # the at-least-once queue redelivers a message whose handler ran but
        # whose ack was lost to a crash — a REBORN consumer must recognize
        # it (the in-memory inflight map died with the process)
        self._dedupe: "Optional[dict]" = None
        self._dedupe_lock = threading.Lock()
        self.deduped_count = 0
        self._pool = ThreadPoolExecutor(max_workers=parallelism,
                                        thread_name_prefix="interruption")

    DEDUPE_NAME = "interruption-dedupe"
    DEDUPE_CAP = 512  # bounded: visibility timeouts expire long before this

    def _dedupe_map(self) -> dict:
        """Lazy-loaded on first use so a reborn consumer picks up the set a
        prior incarnation persisted. Caller holds _dedupe_lock."""
        if self._dedupe is None:
            stored = self.kube.get("configmaps", self.DEDUPE_NAME)
            if isinstance(stored, dict):
                # HttpKubeStore round-trips configmaps as {"data": {...}}
                stored = stored.get("data", stored)
            self._dedupe = dict(stored) if isinstance(stored, dict) else {}
        return self._dedupe

    def _is_duplicate(self, receipt: str) -> bool:
        if not receipt:
            return False
        with self._dedupe_lock:
            return receipt in self._dedupe_map()

    def _mark_handled(self, receipt: str) -> None:
        """Persist the receipt BEFORE the ack: crash-between means the
        redelivered copy is skipped, not re-acted-on (at-least-once queue +
        this set = effectively-once actions)."""
        if not receipt:
            return
        with self._dedupe_lock:
            m = self._dedupe_map()
            m[receipt] = self.clock.now()
            while len(m) > self.DEDUPE_CAP:
                m.pop(min(m, key=m.get))
            try:
                self.kube.update("configmaps", self.DEDUPE_NAME, dict(m))
            except Exception as e:
                log.warning("persisting interruption dedupe set failed: %s", e)

    def reconcile_once(self, wait_seconds: float = 0.0) -> int:
        with _wd_cycle(self.watchdog, "interruption"):
            return self._reconcile_once(wait_seconds)

    def _reconcile_once(self, wait_seconds: float = 0.0) -> int:
        """One poll cycle: receive -> parse -> handle (10-way parallel) ->
        delete (controller.go:83-115)."""
        messages = self.queue.receive(max_messages=10, wait_seconds=wait_seconds)
        if not messages:
            return 0
        # wall time, not FakeClock: the drain rate measures real handler +
        # delete cost even in hermetic runs where the fake clock is frozen
        batch_start = time.perf_counter()
        futures = [self._pool.submit(self._handle, m) for m in messages]
        for f in futures:
            try:
                f.result()
            except Exception as e:
                # message stays un-deleted -> redelivered after the
                # visibility timeout (at-least-once)
                log.warning("interruption message handling failed: %s", e)
        elapsed = time.perf_counter() - batch_start
        if elapsed > 0:
            self.drain_throughput.observe(len(messages) / elapsed,
                                          reason="reactive-reclaim")
        return len(messages)

    def _handle(self, qmsg) -> None:
        """instance-id -> node resolution uses the cluster's incrementally
        maintained index (vs makeInstanceIDMap's per-poll rebuild,
        controller.go:236-255 — O(1) per message at any cluster size)."""
        if self._is_duplicate(qmsg.receipt):
            # redelivery of a message a prior incarnation handled but never
            # acked (crash between handle and delete): acting again would
            # double-fire the termination — ack and skip
            self.deduped.inc()
            self.deduped_count += 1
            self.queue.delete(qmsg.receipt)
            self.deleted.inc()
            return
        t0 = time.perf_counter()
        msg = self.parsers.parse(qmsg.body, qmsg.receipt, qmsg.enqueued_at)
        self.phase_seconds.observe(time.perf_counter() - t0, phase="parse")
        self.received.inc(message_type=msg.kind)
        if msg.enqueued_at:
            self.latency.observe(max(0.0, self.clock.now() - msg.enqueued_at))
        lookup_s = 0.0
        for iid in msg.instance_ids:
            t1 = time.perf_counter()
            node = self.cluster.node_by_instance_id(iid)
            lookup_s += time.perf_counter() - t1
            node_name = node.name if node is not None else None
            if msg.kind == KIND_SPOT_INTERRUPTION and node is not None:
                if node.capacity_type == wk.CAPACITY_TYPE_SPOT:
                    # interrupted spot pool is effectively ICE (controller.go:186-192)
                    self.ice.mark_unavailable(
                        "SpotInterruption", node.instance_type, node.zone,
                        wk.CAPACITY_TYPE_SPOT)
            action = msg.action()
            if action == ACTION_CORDON_AND_DRAIN and node_name:
                if self.termination is not None:
                    self.termination.request_deletion(node_name)
                explain.note_drain(node_name, "interruption",
                                   "reactive-reclaim",
                                   ts=self.clock.now(),
                                   detail={"instance": iid,
                                           "kind": msg.kind})
                self.recorder.warning(
                    f"node/{node_name}", msg.kind,
                    f"interruption event for instance {iid} "
                    f"(reason reactive-reclaim)")
                self.actions.inc(action=ACTION_CORDON_AND_DRAIN)
            else:
                if node_name and msg.kind == KIND_REBALANCE:
                    # rebalance recommendations surface on the node without
                    # any action (deprovisioning.md:113). Benign state
                    # changes stay silent — the reference's parser downgrades
                    # non-stopping states to NoOp before events are emitted
                    # (statechange/parser.go:27-38), and an event per
                    # 'running' notification would spam every scale-up.
                    self.recorder.normal(
                        f"node/{node_name}", msg.kind,
                        f"advisory interruption event for instance {iid}")
                self.actions.inc(action=ACTION_NOOP)
        self.phase_seconds.observe(lookup_s, phase="index_lookup")
        t2 = time.perf_counter()
        self._mark_handled(qmsg.receipt)
        self.phase_seconds.observe(time.perf_counter() - t2,
                                   phase="store_write")
        crashpoint("interruption.pre_ack")
        t3 = time.perf_counter()
        self.queue.delete(qmsg.receipt)
        self.phase_seconds.observe(time.perf_counter() - t3, phase="ack")
        self.deleted.inc()

    def run(self, stop_event: threading.Event, gate=None) -> None:
        """Singleton long-poll loop (NewSingletonManagedBy analogue); with
        `gate` (leader election) the poller idles until elected."""
        while not stop_event.is_set():
            if gate is not None and not gate.is_set():
                stop_event.wait(0.2)
                continue
            try:
                n = self.reconcile_once(wait_seconds=1.0)
                if n == 0:
                    self.clock.sleep(0.2)
            except Exception as e:
                log.exception("interruption reconcile failed: %s", e)
                self.clock.sleep(1.0)

    def stop(self):
        self._pool.shutdown(wait=False)
