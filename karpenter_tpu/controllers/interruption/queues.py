"""Interruption queue client boundary.

Parity target: /root/reference/pkg/controllers/interruption/sqs.go:33-148 —
the SQSProvider wraps the low-level SQS API with LAZY queue-URL discovery
(resolved on first use, cached), invalidation when the configured queue name
changes, and receive/send/delete against the resolved URL.

The boundary here is `QueueProvider`: the controller depends only on this
interface, with two implementations —

- `FakeQueue`: in-memory at-least-once queue with visibility-timeout
  redelivery (the hermetic test backend, reference pkg/fake/sqsapi.go);
- `RemoteQueueProvider`: the real-client stub over a minimal `QueueAPI`
  (get_queue_url / send_message / receive_message / delete_message), with
  the reference's lazy discovery + name-change invalidation + stale-URL
  recovery semantics. Wire it to a real broker by implementing QueueAPI.
"""

from __future__ import annotations

import dataclasses
import logging
import queue as queue_mod
import threading
from typing import Callable, Optional, Protocol, runtime_checkable

from ...utils.clock import Clock

log = logging.getLogger("karpenter.interruption.queue")


@dataclasses.dataclass
class QueueMessage:
    body: str
    receipt: str
    enqueued_at: float = 0.0


@runtime_checkable
class QueueProvider(Protocol):
    """What the interruption controller needs from a queue."""

    name: str

    def send(self, body: str) -> None: ...

    def receive(self, max_messages: int = 10, wait_seconds: float = 0.0
                ) -> "list[QueueMessage]": ...

    def delete(self, receipt: str) -> None: ...

    def approximate_depth(self) -> int: ...


class FakeQueue:
    """In-memory SQS-like queue with visibility-timeout redelivery
    (at-least-once: an un-deleted message reappears after the timeout)."""

    def __init__(self, name: str = "interruptions", clock: Optional[Clock] = None,
                 visibility_seconds: float = 30.0):
        self.name = name
        self.clock = clock or Clock()
        self.visibility_seconds = visibility_seconds
        self._q: "queue_mod.Queue[QueueMessage]" = queue_mod.Queue()
        self._inflight: "dict[str, tuple[float, QueueMessage]]" = {}
        self._receipt = 0
        self._lock = threading.Lock()

    def send(self, body: str) -> None:
        with self._lock:
            self._receipt += 1
            receipt = f"r-{self._receipt}"
        self._q.put(QueueMessage(body=body, receipt=receipt,
                                 enqueued_at=self.clock.now()))

    def _redeliver_expired(self) -> None:
        now = self.clock.now()
        with self._lock:
            expired = [r for r, (taken, _) in self._inflight.items()
                       if now - taken >= self.visibility_seconds]
            for r in expired:
                _, msg = self._inflight.pop(r)
                self._q.put(msg)

    def receive(self, max_messages: int = 10, wait_seconds: float = 0.0
                ) -> "list[QueueMessage]":
        """Long-poll receive (sqs.go:80-105: 20s wait, <=10 messages)."""
        self._redeliver_expired()
        out: "list[QueueMessage]" = []
        try:
            if wait_seconds > 0:
                out.append(self._q.get(timeout=wait_seconds))
            else:
                out.append(self._q.get_nowait())
        except queue_mod.Empty:
            return out
        while len(out) < max_messages:
            try:
                out.append(self._q.get_nowait())
            except queue_mod.Empty:
                break
        now = self.clock.now()
        with self._lock:
            for m in out:
                self._inflight[m.receipt] = (now, m)
        return out

    def delete(self, receipt: str) -> None:
        with self._lock:
            self._inflight.pop(receipt, None)

    def approximate_depth(self) -> int:
        return self._q.qsize()


class QueueNotFound(Exception):
    """The broker does not know the queue (URL stale or queue recreated)."""


class QueueAPI(Protocol):
    """Minimal low-level broker API the real provider is generic over
    (aws-sdk sqsiface analogue). Implementations raise QueueNotFound for
    unknown queue names/URLs."""

    def get_queue_url(self, name: str) -> str: ...

    def send_message(self, queue_url: str, body: str) -> None: ...

    def receive_message(self, queue_url: str, max_messages: int,
                        wait_seconds: float) -> "list[QueueMessage]": ...

    def delete_message(self, queue_url: str, receipt: str) -> None: ...


class RemoteQueueProvider:
    """QueueProvider over a QueueAPI with the reference's URL lifecycle:

    - the queue URL is discovered LAZILY on first use and cached
      (sqs.go queueURL sync once-per-name);
    - a change of the configured queue name (live settings watch)
      invalidates the cached URL so the next call re-discovers;
    - a QueueNotFound from the broker (queue deleted/recreated under us)
      also invalidates, and the operation is retried once against the
      freshly discovered URL.
    """

    def __init__(self, api: QueueAPI,
                 name_source: "Callable[[], str] | str"):
        self.api = api
        self._name_source = (name_source if callable(name_source)
                             else (lambda: name_source))
        self._lock = threading.Lock()
        self._url: "Optional[str]" = None
        self._url_for_name: "Optional[str]" = None

    @property
    def name(self) -> str:
        return self._name_source()

    def _queue_url(self) -> str:
        name = self.name
        with self._lock:
            if self._url is None or self._url_for_name != name:
                self._url = self.api.get_queue_url(name)
                self._url_for_name = name
                log.info("resolved queue %s -> %s", name, self._url)
            return self._url

    def _invalidate(self) -> None:
        with self._lock:
            self._url = None
            self._url_for_name = None

    def _with_url(self, op):
        try:
            return op(self._queue_url())
        except QueueNotFound:
            # stale URL (queue recreated): re-discover once and retry
            self._invalidate()
            return op(self._queue_url())

    def send(self, body: str) -> None:
        self._with_url(lambda url: self.api.send_message(url, body))

    def receive(self, max_messages: int = 10, wait_seconds: float = 0.0
                ) -> "list[QueueMessage]":
        return self._with_url(lambda url: self.api.receive_message(
            url, max_messages, wait_seconds))

    def delete(self, receipt: str) -> None:
        self._with_url(lambda url: self.api.delete_message(url, receipt))

    def approximate_depth(self) -> int:
        return -1  # brokers expose this asynchronously; not part of QueueAPI
