"""Counters controller: live per-provisioner consumption in status.resources.

Parity target: karpenter-core's counters controller (SURVEY.md §2.2; the
reference's Provisioner carries status.resources maintained by a dedicated
reconcile so `kubectl get provisioner -o yaml` shows what the pool
consumes). The sums come from the SAME cluster-state source the limits
gate reads (`ClusterState.total_usage`, designs/limits.md), so the
displayed numbers and the enforcement numbers cannot disagree.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..introspect.watchdog import cycle as _wd_cycle
from ..models.cluster import ClusterState

log = logging.getLogger("karpenter.counters")


def _fmt_resources(cpu_millis: int, mem_bytes: int, nodes: int) -> "dict[str, str]":
    return {
        "cpu": f"{cpu_millis}m",
        "memory": f"{mem_bytes // 2**20}Mi",
        "nodes": str(nodes),
    }


class CountersController:
    def __init__(self, kube, cluster: ClusterState, watchdog=None):
        self.kube = kube
        self.cluster = cluster
        self.watchdog = watchdog

    def reconcile_once(self) -> "list[str]":
        with _wd_cycle(self.watchdog, "counters"):
            return self._reconcile_once()

    def _reconcile_once(self) -> "list[str]":
        """Write status.resources for every provisioner whose consumption
        changed; returns the names updated."""
        import dataclasses

        node_counts: "dict[str, int]" = {}
        for node in self.cluster.nodes.values():
            if node.provisioner_name:
                node_counts[node.provisioner_name] = \
                    node_counts.get(node.provisioner_name, 0) + 1
        updated = []
        for prov in self.kube.provisioners():
            cpu, mem = self.cluster.total_usage(prov.name)
            want = _fmt_resources(cpu, mem, node_counts.get(prov.name, 0))
            if prov.status_resources == want:
                continue
            # Write a COPY via CAS against the object we read:
            # - never mutate the shared informer-cache object (a failed
            #   write would leave the cache claiming the new status and the
            #   equality early-out would skip the retry forever);
            # - CAS so a concurrent user edit to the spec raises Conflict
            #   instead of being clobbered by our stale read (the
            #   read-modify-write rule every status writer here follows).
            fresh = dataclasses.replace(prov, status_resources=want)
            try:
                self.kube.compare_and_swap("provisioners", prov.name,
                                           prov, fresh)
                updated.append(prov.name)
            except Exception as e:  # conflict/transient: next sweep converges
                log.debug("counters update %s failed: %s", prov.name, e)
        return updated
