"""Provisioning controller: pending pods -> batch window -> solve -> machines.

Parity target: karpenter-core's provisioning controller (SURVEY.md §2.2 /
§3.2): watches unschedulable pods, batches them (batchIdleDuration=1s /
batchMaxDuration=10s, settings.md:43-47), runs the scheduler over cluster
state, creates Machines via the CloudProvider, enforces provisioner limits
(designs/limits.md), and emits scheduling events/metrics
(karpenter_allocation_controller_scheduling_duration_seconds, metrics.md:91).

The solve itself is the TPU kernel via TPUSolver; on any solver failure the
scalar oracle runs the SAME semantics in-process (the fallback contract,
BASELINE.json north star).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..apis.settings import Settings
from ..events import EventRecorder
from ..metrics import NAMESPACE, REGISTRY, Registry
from ..models.cluster import ClusterState, StateNode
from ..models.machine import Machine, MachineSpec
from ..models.pod import PodSpec
from ..models.requirements import IncompatibleError, Requirement, Requirements, OP_IN
from ..oracle.scheduler import Scheduler
from ..introspect.watchdog import cycle as _wd_cycle
from ..recovery.crashpoints import crashpoint
from ..recovery.journal import LAUNCH
from ..resilience import DegradeLadder, deadline
from ..solver.core import NativeSolver, SolveResult, TPUSolver
from ..tracing import TRACER
from ..utils.clock import Clock

log = logging.getLogger("karpenter.provisioning")


class ProvisioningController:
    # one deadline budget per reconcile cycle; downstream calls (solver RPC,
    # batched cloud ops) check the REMAINING budget instead of stacking
    # their own timeouts
    CYCLE_BUDGET_S = deadline.DEFAULT_CYCLE_BUDGET_S

    def __init__(
        self,
        kube,
        cloudprovider,
        cluster: ClusterState,
        settings: Settings,
        clock: Optional[Clock] = None,
        recorder: Optional[EventRecorder] = None,
        registry: Optional[Registry] = None,
        solver_factory=None,
        launch_workers: int = 10,
        watchdog=None,
        resilience=None,
        journal=None,
    ):
        self.kube = kube
        self.watchdog = watchdog
        self.journal = journal
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.settings = settings
        self.clock = clock or Clock()
        self.recorder = recorder or EventRecorder(clock=self.clock)
        reg = registry or REGISTRY
        self.sched_duration = reg.histogram(
            f"{NAMESPACE}_allocation_controller_scheduling_duration_seconds",
            "Duration of scheduling solves.", ("solver",))
        self.nodes_created = reg.counter(
            f"{NAMESPACE}_nodes_created_total", "Nodes created.", ("provisioner",))
        self.pods_bound = reg.counter(
            f"{NAMESPACE}_pods_bound_total",
            "Pods bound to nodes by the provisioner.", ("provisioner",))
        self.pods_unschedulable = reg.gauge(
            f"{NAMESPACE}_pods_unschedulable", "Pods that failed to schedule.")
        self._solver_factory = solver_factory or (
            lambda catalog, provs: TPUSolver(catalog, provs))
        # Solver instances are cached across reconciles, invalidated by
        # catalog CONTENT hash + provisioner hash (the same trick the gRPC
        # service uses, solver/service.py LRU) — steady-state reconciles pay
        # ZERO option-grid rebuilds (reference analogue: seqnum-memoized
        # instance types, instancetypes.go:104-120).
        self._solver_cache: "dict[tuple, object]" = {}
        self._native_cache: "dict[tuple, NativeSolver]" = {}
        # memoized content hashes. The memo holds STRONG references to the
        # hashed objects: comparing `is` against a live object is sound,
        # while an id() of a freed one could be recycled by the allocator
        # and alias a different catalog.
        self._cat_memo: "Optional[tuple]" = None   # (catalog, seqnum, hash)
        self._prov_memo: "Optional[tuple]" = None  # (prov tuple, hash)
        self.solver_rebuilds = 0  # observability + rebuild-free assertion in tests
        # Size-based routing (docs/designs/solver-boundary.md): below the
        # measured device-vs-native crossover the in-process C++ scan wins
        # (on a tunneled chip it wins at EVERY measured size — threshold None
        # means "native first always"). Operators override via
        # KARPENTER_TPU_ROUTE_CROSSOVER.
        from ..utils.capture import route_crossover
        self.route_threshold = route_crossover()
        # the solver->native->oracle chain as an explicit DegradeLadder:
        # sticky rung + recovery probes replace per-cycle re-trying of a
        # broken best backend (shared with the hub when the operator wires
        # one; standalone controllers get a private ladder)
        self.solve_ladder = (
            resilience.ladder("solve") if resilience is not None
            else DegradeLadder("solve", ("tpu", "native", "oracle"),
                               clock=self.clock, recorder=self.recorder,
                               registry=reg))
        self.last_solver_kind: "Optional[str]" = None
        # delta-aware solving plane: extracts the dirty subproblem and
        # warm-starts a small solve when KARPENTER_TPU_INCREMENTAL is on
        # (strict-noop otherwise); holds the resident masks between cycles
        from ..incremental import IncrementalSolver
        self._incremental = IncrementalSolver(cluster)
        # spot plane's risk-aware objective (spot/objective.py), injected by
        # the operator; None (or inactive: no elevated forecast) leaves
        # every solve on the exact pre-spot path
        self.spot_objective = None
        self._machine_seq = 0
        # per-process machine-name suffix: two HA replicas sharing one store
        # must never collide on create (the reference uses generateName)
        import uuid

        self._name_suffix = uuid.uuid4().hex[:5]
        self._pool = ThreadPoolExecutor(max_workers=launch_workers,
                                        thread_name_prefix="launch")
        self._lock = threading.Lock()
        # Watch-driven batching: the store notifies on pod events and the
        # batcher rescans pending pods only when something actually changed —
        # no fixed-rate full-store polling (the reference batches off a watch
        # stream, settings.md:43-47). Starts dirty so pre-existing pending
        # pods are picked up on boot.
        self._pods_dirty = threading.Event()
        self._pods_dirty.set()
        kube.watch(self._on_store_event)

    # -- batching window -------------------------------------------------------

    def _on_store_event(self, kind: str, action: str, obj) -> None:
        # provisioner/nodetemplate changes can unblock previously
        # unschedulable pods — they re-arm the batcher too
        if kind in ("pods", "provisioners", "nodetemplates"):
            self._pods_dirty.set()

    def wait_for_batch(self) -> "list[PodSpec]":
        """Pod batching: return once no new pending pod arrived for
        batchIdleDuration, or batchMaxDuration elapsed (settings.md:81-99).

        The pending set is rescanned only when the watch flagged a pod
        change; between events the loop just ticks the clock for window
        deadlines (cheap — no store scan at 20 Hz)."""
        first = None
        seen: "set[str]" = set()
        last_new = None
        pods: "list[PodSpec]" = []
        while True:
            if self._pods_dirty.is_set():
                self._pods_dirty.clear()
                pods = self.kube.pending_pods()
                names = {p.name for p in pods}
                if names - seen:
                    seen = names
                    last_new = self.clock.now()
                    if first is None:
                        first = last_new
            now = self.clock.now()
            if first is None:
                self.clock.sleep(0.05)
                continue
            windows = self.settings.snapshot()  # idle+max read consistently
            if (now - last_new >= windows.batch_idle_duration
                    or now - first >= windows.batch_max_duration):
                return pods
            self.clock.sleep(0.05)

    # -- one reconcile ---------------------------------------------------------

    def reconcile_once(self, pods: "Optional[list[PodSpec]]" = None) -> "Optional[SolveResult]":
        with _wd_cycle(self.watchdog, "provisioning"):
            with deadline.cycle(self.clock, self.CYCLE_BUDGET_S):
                return self._reconcile_once(pods)

    def _reconcile_once(self, pods: "Optional[list[PodSpec]]" = None) -> "Optional[SolveResult]":
        pods = self.kube.pending_pods() if pods is None else pods
        if not pods:
            return None
        with TRACER.start_span("provisioning.cycle", pods=len(pods)) as root:
            with TRACER.start_span("provisioning.mask") as mask:
                provisioners = sorted(self.kube.provisioners(),
                                      key=lambda p: (-p.weight, p.name))
                if not provisioners:
                    self.recorder.warning(
                        "controller/provisioning", "NoProvisioners",
                        "no provisioners configured")
                    return None
                catalog = self.cloudprovider.catalog_for(None)
                provisioners = self.cloudprovider.constrain_to_template_zones(
                    provisioners, catalog)
                daemon_overhead = self._daemon_overhead()
                # HOT:BEGIN(provisioning-mask) — columnar snapshot: encode
                # reads label/taint/resource columns directly, per-node
                # dataclass views only materialize if the oracle fallback or
                # an affinity pass touches them (hack/check_hot_loops.py)
                existing = self.cluster.existing_columns()
                # HOT:END(provisioning-mask)
                mask.set_attributes(provisioners=len(provisioners),
                                    types=len(catalog.types),
                                    existing=len(existing))

            with TRACER.start_span("provisioning.solve",
                                   pods=len(pods)) as solve_span:
                t0 = time.perf_counter()
                from ..incremental import enabled as _inc_enabled
                spot_obj = self.spot_objective
                if spot_obj is not None and spot_obj.active():
                    # elevated interruption forecast: the risk-aware
                    # objective drives the solve (adjusted prices +
                    # diversity floor) through the same routed chain; it
                    # bypasses the incremental plane for the storm window —
                    # a delta-solve against risk-adjusted prices would
                    # compare against residents packed under real ones
                    kinds: "list[str]" = []

                    def _spot_solve(cat, mask, barred, pod_xform=None):
                        ps = pods if pod_xform is None else pod_xform(pods)
                        r, k = self._routed_solve(
                            cat, provisioners, ps, existing,
                            daemon_overhead, option_mask=mask, barred=barred)
                        kinds.append(k)
                        return r

                    result, _spot_info = spot_obj.solve(catalog, _spot_solve)
                    solver_kind = kinds[-1] if kinds else "oracle"
                    solve_span.set_attribute("spot_risk", True)
                elif _inc_enabled():
                    result, solver_kind = self._incremental.solve(
                        pods, existing,
                        lambda ps, ex: self._routed_solve(
                            catalog, provisioners, ps, ex, daemon_overhead),
                        catalog=catalog, provisioners=provisioners,
                        overhead=daemon_overhead)
                else:
                    result, solver_kind = self._routed_solve(
                        catalog, provisioners, pods, existing, daemon_overhead)
                self.last_solver_kind = solver_kind
                self.sched_duration.observe(time.perf_counter() - t0,
                                            solver=solver_kind)
                # the solver may have annotated a MORE specific routing
                # in-place ("tpu-sharded" when the shape router sent the
                # solve to the mesh) — keep it; only fill in the generic
                # ladder-rung name when the solver left nothing
                routing = solve_span.attributes.get("routing")
                if not (isinstance(routing, str)
                        and routing.startswith(solver_kind)):
                    routing = solver_kind
                solve_span.set_attribute("routing", routing)
                # the chosen solver annotated the span in-place (core.py
                # last_solve_info); guarantee the load-bearing attrs exist
                # even on the oracle path
                solve_span.attributes.setdefault("compile_cache", "n/a")
                solve_span.attributes.setdefault("transfer_ms", 0.0)
                solve_span.attributes.setdefault("bucket", "n/a")
                root.set_attribute("routing", routing)

            with TRACER.start_span("provisioning.bind") as bind:
                self._apply(result, pods, catalog=catalog,
                            provisioners=provisioners,
                            daemon_overhead=daemon_overhead,
                            solve_attrs=dict(solve_span.attributes))
                bind.set_attributes(
                    nodes=len(result.nodes),
                    unschedulable=result.unschedulable_count())
            return result

    # -- solver cache + routing ------------------------------------------------

    def _content_key(self, catalog, provisioners) -> tuple:
        from ..solver import wire

        memo = self._cat_memo
        if memo is None or memo[0] is not catalog or memo[1] != catalog.seqnum:
            memo = (catalog, catalog.seqnum, wire.catalog_hash(catalog))
            self._cat_memo = memo
        provs = tuple(provisioners)
        pmemo = self._prov_memo
        if pmemo is None or len(pmemo[0]) != len(provs) or any(
                a is not b for a, b in zip(pmemo[0], provs)):
            pmemo = (provs, wire.provisioners_hash(provs))
            self._prov_memo = pmemo
        return (memo[2], pmemo[1])

    def _cached(self, cache: dict, key: tuple, build):
        solver = cache.get(key)
        if solver is None:
            # the evicted predecessor donates its static state (grid layout
            # + group-encode folds) to the replacement — an ICE-only catalog
            # change then skips the grid/encode rebuild entirely
            old = next(iter(cache.values()), None)
            solver = build(old)
            cache.clear()  # one resident grid per backend is enough in-process
            cache[key] = solver
        return solver

    def _routed_solve(self, catalog, provisioners, pods, existing, overhead,
                      option_mask=None, barred=None):
        """Route by batch size (measured crossover), degrade down the chain.
        Order: preferred backend -> other backend -> scalar oracle; every
        backend enforces identical semantics (parity-tested), so routing is
        purely a latency decision.

        `option_mask` / `barred` carry the spot plane's diversity-floor bar
        in both backends' vocabularies ([T,S] dense mask for the kernels,
        pool-key set for the scalar oracle) — same dimension, parity-
        audited; both None on every non-spot solve."""
        key = self._content_key(catalog, provisioners)
        # only thread the kwarg when a mask is actually set: injected
        # solver factories (tests, chaos fault doubles) predate the
        # parameter, and every non-spot solve must stay byte-identical
        mask_kw = {} if option_mask is None else {"option_mask": option_mask}

        def run_primary():
            def build(old):
                self.solver_rebuilds += 1
                s = self._solver_factory(catalog, provisioners)
                if old is not None and hasattr(s, "adopt_static"):
                    s.adopt_static(old)
                return s
            solver = self._cached(self._solver_cache, key, build)
            return solver.solve(pods, existing=existing,
                                daemon_overhead=overhead, **mask_kw)

        def run_native():
            def build(old):
                s = NativeSolver(catalog, provisioners)
                if old is not None:
                    s.adopt_static(old)  # ICE-only change: reuse static grid
                return s
            solver = self._cached(self._native_cache, key, build)
            return solver.solve(pods, existing=existing,
                                daemon_overhead=overhead, **mask_kw)

        # Ladder rungs bind to FIXED backend identities — 0 = tpu,
        # 1 = native, 2 = oracle (matching the hub's "solve" chain) — so
        # failures and probe promotions recorded in one cycle mean the same
        # backend in every later cycle regardless of batch size. The
        # measured size crossover is applied separately below: it reorders
        # ATTEMPTS among healthy backends, never the rung a verdict lands
        # on. A degraded ladder skips straight past known-broken rungs and
        # only re-tries them on its scheduled recovery probes.
        backends = (("tpu", run_primary), ("native", run_native))
        ladder = self.solve_ladder
        start = ladder.start_rung()
        probing = start < ladder.rung()
        attempts = [(r,) + backends[r] for r in range(start, len(backends))]
        small = self.route_threshold is None or len(pods) < self.route_threshold
        if small and start == 0 and not probing:
            # latency preference (native wins below the crossover): both
            # backends are healthy candidates, try native first. Never
            # applied to an admitted recovery probe — skipping the probe
            # rung would leave the ladder probing forever.
            attempts.reverse()
        dl = deadline.current()
        failed: "set[int]" = set()

        def flush_failures(upto: int) -> None:
            # chain-consistent verdicts: a failure at rung r may degrade
            # the ladder only when every better candidate rung failed too
            # (the linear-chain assumption record_failure encodes) — a
            # worse rung failing while a better one is healthy must not
            # push the ladder past the healthy backend
            for r in range(start, upto):
                if r not in failed:
                    break
                ladder.record_failure(r)

        for rung, kind, fn in attempts:
            if dl is not None and dl.expired():
                # deadline exhaustion mid-chain: the remaining budget can't
                # absorb another backend failure — shed straight to the
                # in-process oracle (only rungs that actually FAILED move
                # the ladder; an un-run probe is re-armed unjudged)
                log.warning("reconcile deadline exhausted before %s solve; "
                            "falling through to oracle", kind)
                flush_failures(len(backends))
                ladder.abort_probe()
                break
            try:
                result = fn()
            except Exception as e:
                log.warning("%s solver failed (%s); degrading", kind, e)
                failed.add(rung)
                continue
            flush_failures(rung)  # e.g. a failed probe rung: judge it first
            ladder.record_success(rung)
            return result, kind
        else:
            flush_failures(len(backends))
        result = self._oracle_solve(catalog, provisioners, pods,
                                    existing, overhead, barred=barred)
        ladder.record_success(len(backends))
        return result, "oracle"

    def _oracle_solve(self, catalog, provisioners, pods, existing, overhead,
                      barred=None):
        sched = Scheduler(catalog, provisioners, overhead, barred=barred)
        res = sched.schedule(list(pods), existing=existing)
        return _oracle_to_solve_result(res, sched)

    def _daemon_overhead(self) -> "list[int]":
        vec = [0] * wk.NUM_RESOURCES
        for p in self.kube.daemon_pods():
            if p.node_name:
                continue  # only template daemonset pods (unbound) count
            for i, v in enumerate(p.resource_vector()):
                vec[i] += v
        return vec

    # -- applying a solve ------------------------------------------------------

    def _apply(self, result: SolveResult, pods: "list[PodSpec]",
               catalog, provisioners, daemon_overhead,
               solve_attrs: "Optional[dict]" = None) -> None:
        # binding fan-out attribution (docs/designs/slo.md): the pool
        # workers below run OFF the reconcile thread, so their create/bind
        # spans need the bind span passed explicitly (thread-local
        # parenting can't see across the executor boundary)
        bind_span = TRACER.current_span()
        # per-group pod-name queues; binding pops from the front
        by_group = {g_idx: list(group.pod_names)
                    for g_idx, group in enumerate(result.groups)}
        # bind pods placed onto existing nodes (exact per-group plan)
        with TRACER.start_span("provisioning.bind.existing",
                               nodes=len(result.existing_by_group)):
            for node_name, per_group in result.existing_by_group.items():
                self._bind_from_groups(by_group, per_group, node_name)
        # Pre-partition each new node's pod names HERE, in the reconcile
        # thread: concurrent launch workers must not pop from the shared
        # per-group queues (double-bind/skip race under the thread pool).
        assignments = []
        for solved in result.nodes:
            take: "dict[int, list[str]]" = {}
            for g_idx, count in solved.pod_counts.items():
                names = by_group.get(g_idx, [])
                take[g_idx] = names[:count]
                by_group[g_idx] = names[count:]
            assignments.append(take)
        # Diagnose the unschedulable groups BEFORE the launch fan-out so
        # the DecisionRecord (and the id the events cite) exists when the
        # first Launched event fires. Diagnosed against the SAME
        # catalog/provisioners/overhead the failed solve used (a refresh
        # between solve and apply must not contradict it); one diagnosis
        # per GROUP — identical pods fail identically — and a hard cap
        # bounds the fold cost in pathological storms.
        unsched = result.unschedulable_count()
        diagnoses: "list[tuple[list[str], str]]" = []
        explain_unassigned: "list[dict]" = []
        if unsched:
            from .. import explain
            from ..models.encode import (build_grid, diagnose_unschedulable,
                                         kubelet_arrays)

            diag_grid = diag_kub = None
            diagnosed = 0
            for g_idx, count in result.unschedulable.items():
                names = by_group.get(g_idx, [])[:count]
                if not names:
                    continue
                why = "no compatible instance type available"
                attribution = None
                if diagnosed < 32:
                    diagnosed += 1
                    try:
                        # the group's OWN spec — the exact pod the solve
                        # failed on (a store fetch could race an edit/delete
                        # and explain a different pod)
                        pod = result.groups[g_idx].spec
                        if diag_grid is None:  # once per cycle
                            diag_grid = build_grid(catalog)
                            diag_kub = kubelet_arrays(provisioners, catalog)
                        why = diagnose_unschedulable(
                            pod, provisioners, catalog,
                            daemon_overhead=daemon_overhead,
                            grid=diag_grid, kubelet=diag_kub)
                        if explain.enabled():
                            # the lazy mask-attribution pass: per-dimension
                            # rejection counts + ranked summary, recorded
                            # next to the oracle's clause so the parity
                            # audit rides in the record itself
                            attribution = explain.attribute_pod(
                                pod, provisioners, catalog,
                                daemon_overhead=daemon_overhead,
                                grid=diag_grid, kubelet=diag_kub)
                    except Exception:
                        pass  # diagnosis must never break the event
                diagnoses.append((names, why))
                if attribution is not None:
                    explain_unassigned.append({
                        "pod": names[0], "group": g_idx, "count": count,
                        "pods": names[:8],
                        "oracle_reason": why,
                        "parity": attribution["reason"] == why,
                        **attribution,
                    })
        decision_id = self._emit_decision(result, assignments,
                                          explain_unassigned, solve_attrs)
        # launch new nodes in parallel (reconcile-loop concurrency analogue,
        # MaxConcurrentReconciles=10)
        futures = [self._pool.submit(self._launch_node, solved, take, result,
                                     bind_span, decision_id)
                   for solved, take in zip(result.nodes, assignments)]
        # Drain EVERY worker before letting a crash propagate: _launch_node
        # absorbs Exceptions itself, so only BaseException (SimulatedCrash,
        # ^C) reaches result() — and abandoning the remaining futures would
        # leave a worker thread mutating the store/cloud while the stack
        # unwinds (in the crash drill: a zombie launch racing the reborn
        # leader's replay).
        crash = None
        for f in futures:
            try:
                f.result()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                crash = crash or e
        if crash is not None:
            raise crash
        self.pods_unschedulable.set(unsched)
        # name the failing constraint (the reference's scheduler errors
        # say WHY: "incompatible with provisioner …"); when the explain
        # plane recorded this solve, the event cites the DecisionRecord
        # holding the full per-dimension attribution.
        cite = f" (decision {decision_id})" if decision_id else ""
        for names, why in diagnoses:
            for name in names:
                self.recorder.warning(
                    f"pod/{name}", "FailedScheduling", why + cite)

    def _emit_decision(self, result: SolveResult, assignments,
                       unassigned: "list[dict]",
                       solve_attrs: "Optional[dict]") -> "Optional[str]":
        """One provisioning DecisionRecord per solve into the explain ring
        (assignments with the winning bucket rung, per-unassigned-pod
        attribution, the solve's trace id); returns the record id, or None
        when the plane is disabled (strict-noop) or emission fails."""
        from .. import explain

        if not explain.enabled():
            return None
        try:
            attrs = dict(solve_attrs or {})
            span = TRACER.current_span()
            assigns = []
            for solved, take in zip(result.nodes[:64], assignments):
                assigns.append({
                    "itype": solved.option.itype.name,
                    "zone": solved.option.zone,
                    "capacity_type": solved.option.capacity_type,
                    "price": solved.option.price,
                    "provisioner": solved.provisioner.name,
                    "pod_count": solved.pod_count,
                    "pods": [n for names in take.values() for n in names][:8],
                })
            record = {
                "trace_id": span.trace_id if span is not None else None,
                "routing": attrs.get("routing"),
                "bucket": attrs.get("bucket", "n/a"),
                "rung": (attrs.get("decision") or {}).get("rung"),
                "dimensions": list(explain.DIMENSIONS),
                "nodes": len(result.nodes),
                "nodes_listed": min(len(result.nodes), 64),
                "existing_nodes": len(result.existing_by_group),
                "unschedulable_pods": result.unschedulable_count(),
                "assignments": assigns,
                "unassigned": unassigned,
            }
            rid = explain.DECISIONS.emit("provisioning", record,
                                         ts=self.clock.now())
            if rid is not None and span is not None:
                # decision <-> trace cross-link: the record carries the
                # trace id above; the span carries the record id here
                TRACER.annotate(decision_id=rid)
            return rid
        except Exception:
            log.debug("decision record emission failed", exc_info=True)
            return None

    def _bind_from_groups(self, by_group: "dict[int, list[str]]",
                          group_counts: "dict[int, int]", node_name: str) -> None:
        """Single-threaded path (existing nodes): pops from the shared
        queues, then binds."""
        take = {}
        for g_idx, count in group_counts.items():
            names = by_group.get(g_idx, [])
            take[g_idx] = names[:count]
            by_group[g_idx] = names[count:]
        self._bind_assigned(take, node_name)

    def _bind_assigned(self, assigned: "dict[int, list[str]]",
                       node_name: str) -> None:
        for pod_names in assigned.values():
            for pod_name in pod_names:
                try:
                    self.kube.bind_pod(pod_name, node_name)
                    node = self.cluster.nodes.get(node_name)
                    pod = self.kube.get("pods", pod_name)
                    # the operator's watch hook may have already added the
                    # bound pod to the resident list (notify runs on this
                    # thread); the direct append covers standalone use
                    # where no watch is attached
                    if (node is not None and pod is not None
                            and all(p.name != pod.name for p in node.pods)):
                        node.pods.append(pod)
                    self.pods_bound.inc(provisioner=(
                        node.provisioner_name if node else ""))
                except Exception as e:
                    log.warning("bind %s -> %s failed: %s", pod_name, node_name, e)

    def _launch_node(self, solved, assigned, result: SolveResult,
                     parent_span=None,
                     decision_id: "Optional[str]" = None) -> Optional[StateNode]:
        prov: Provisioner = solved.provisioner
        if not self._within_limits(prov, solved):
            self.recorder.warning(
                f"provisioner/{prov.name}", "LimitExceeded",
                "provisioner limit reached; skipping node launch")
            return None
        with self._lock:
            self._machine_seq += 1
            name = f"{prov.name}-{self._name_suffix}-{self._machine_seq:05d}"
        reqs = prov.scheduling_requirements().copy()
        opt = solved.option
        reqs.add(Requirement.create(wk.LABEL_INSTANCE_TYPE, OP_IN, [opt.itype.name]))
        reqs.add(Requirement.create(wk.LABEL_ZONE, OP_IN, [opt.zone]))
        reqs.add(Requirement.create(wk.LABEL_CAPACITY_TYPE, OP_IN, [opt.capacity_type]))
        machine = Machine(
            name=name,
            spec=MachineSpec(
                requirements=reqs,
                resource_requests=self._machine_requests(solved, result),
                taints=prov.taints,
                startup_taints=prov.startup_taints,
                machine_template_ref=prov.provider_ref or "default",
                provisioner_name=prov.name,
                kubelet=prov.kubelet,
            ),
            labels={wk.LABEL_PROVISIONER: prov.name, **dict(prov.labels)},
        )
        if self.journal is not None:
            # write-ahead: a crash anywhere between here and resolve would
            # otherwise strand a cloud instance (or a half-registered node)
            # until the registration-TTL sweep notices
            self.journal.record(LAUNCH, name, {
                "machine": name, "provisioner": prov.name})
        # create-vs-bind split (docs/designs/slo.md): the cloud/machine
        # create and the pod-bind fan-out are distinct phases of the bind
        # span; parented explicitly because this runs on a pool thread
        create_span = TRACER.start_span("provisioning.create",
                                        parent=parent_span, machine=name)
        try:
            self.kube.create("machines", name, machine)
            machine = self.cloudprovider.create(machine)
            crashpoint("launch.pre_register")
            self.kube.update("machines", name, machine)
        except Exception as e:
            create_span.set_attribute("error", True)
            create_span.end()
            log.warning("machine %s launch failed: %s", name, e)
            self.recorder.warning(f"machine/{name}", "LaunchFailed", str(e))
            try:
                self.kube.delete("machines", name)
                if self.journal is not None:
                    self.journal.resolve(LAUNCH, name, outcome="aborted")
            except Exception as cleanup_err:
                # a lost cleanup write must not mask the launch failure; the
                # stranded machine is reaped by the registration-TTL liveness
                # sweep (machinelifecycle) — and the UNRESOLVED journal
                # record lets a reborn leader roll it back immediately
                log.warning("cleanup of failed machine %s deferred to "
                            "registration TTL: %s", name, cleanup_err)
            return None
        create_span.end()
        node = StateNode(
            name=machine.status.node_name or name,
            labels=dict(machine.labels),
            allocatable=wk.capacity_vector(machine.status.allocatable),
            provider_id=machine.status.provider_id,
            provisioner_name=prov.name,
            instance_type=machine.status.instance_type,
            zone=machine.status.zone,
            capacity_type=machine.status.capacity_type,
            price=machine.status.price,
            taints=prov.taints,
            startup_taints=prov.startup_taints,
            created_ts=self.clock.now(),
            machine_name=name,
            initialized=False,  # the machine lifecycle controller flips this
            # provisioner annotations are applied to every node it launches
            # (reference CRD spec.annotations)
            annotations=dict(prov.annotations),
        )
        self.cluster.add_node(node)
        self.kube.create("nodes", node.name, node)
        crashpoint("launch.mid_bind")
        self.nodes_created.inc(provisioner=prov.name)
        self.recorder.normal(f"machine/{name}", "Launched",
                             f"launched {machine.status.instance_type} in "
                             f"{machine.status.zone}"
                             + (f" (decision {decision_id})"
                                if decision_id else ""))
        # bind this node's pods
        with TRACER.start_span("provisioning.bind.pods",
                               parent=parent_span, node=node.name,
                               pods=sum(len(v) for v in assigned.values())):
            self._bind_assigned(assigned, node.name)
        if self.journal is not None:
            self.journal.resolve(LAUNCH, name)
        return node

    def _machine_requests(self, solved, result: SolveResult) -> "dict[str, int]":
        """Sum of the machine's assigned pod vectors (Machine.Spec.Resources)."""
        total = [0] * wk.NUM_RESOURCES
        for g_idx, count in solved.pod_counts.items():
            if g_idx < len(result.groups):
                for i, v in enumerate(result.groups[g_idx].vector):
                    total[i] += v * count
        return {name: val for name, val in zip(wk.RESOURCE_AXIS, total) if val > 0}

    def _within_limits(self, prov: Provisioner, solved) -> bool:
        if prov.limits.cpu_millis is None and prov.limits.memory_bytes is None:
            return True
        used_cpu, used_mem = self.cluster.total_usage(prov.name)
        alloc = solved.option.alloc
        new_cpu = used_cpu + alloc[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]]
        new_mem = used_mem + alloc[wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]] * 2**20
        return prov.limits.exceeded_by(new_cpu, new_mem) is None

    def run(self, stop_event: threading.Event,
            gate: "Optional[threading.Event]" = None) -> None:
        """Reconcile loop; with `gate` (leader election) the controller
        idles until this replica is elected."""
        last_retry_scan = 0.0
        while not stop_event.is_set():
            if gate is not None and not gate.is_set():
                stop_event.wait(0.2)
                continue
            try:
                # idle iterations never reach reconcile_once, so the loop
                # itself is the heartbeat: a live-but-idle batcher must not
                # read as stalled (only a hung wait/solve goes stale)
                if self.watchdog is not None:
                    self.watchdog.beat("provisioning")
                # idle until the watch reports churn; a slow retry scan
                # (1 Hz) re-arms for pods left pending by a failed solve —
                # e.g. an ICE TTL expiring produces no store event at all
                if not self._pods_dirty.wait(timeout=0.1):
                    now = self.clock.now()
                    if now - last_retry_scan >= 1.0:
                        last_retry_scan = now
                        if self.kube.pending_pods():
                            self._pods_dirty.set()
                    continue
                self._pods_dirty.clear()
                if not self.kube.pending_pods():
                    continue
                self._pods_dirty.set()  # re-arm wait_for_batch's scan gate
                pods = self.wait_for_batch()
                self.reconcile_once(pods)
            except Exception as e:
                log.exception("provisioning reconcile failed: %s", e)
                self._pods_dirty.set()  # the failed batch must retry
                self.clock.sleep(1.0)

    def stop(self):
        self._pool.shutdown(wait=False)
        self.kube.unwatch(self._on_store_event)  # no dead-replica watcher leak


def _oracle_to_solve_result(res, sched) -> SolveResult:
    """Adapt oracle SchedulingResult to the SolveResult interface: one
    synthetic group per placement set, so binding and machine-request math
    work identically on the fallback path."""
    from ..models.pod import PodGroup, group_pods
    from ..solver.core import SolvedNode

    groups: "list[PodGroup]" = []
    nodes: "list[SolvedNode]" = []

    def add_subgroups(pods) -> "dict[int, int]":
        counts = {}
        for sub in group_pods(list(pods)):
            counts[len(groups)] = sub.count
            groups.append(sub)
        return counts

    for n in res.new_nodes:
        nodes.append(SolvedNode(option=n.decided,
                                pod_counts=add_subgroups(n.pods),
                                provisioner=n.provisioner))
    existing_counts = {}
    existing_by_group = {}
    for name, pods in res.existing_assignments.items():
        if not pods:
            continue
        existing_counts[name] = len(pods)
        existing_by_group[name] = add_subgroups(pods)
    unschedulable = {}
    for p in res.unschedulable:
        g_idx = len(groups)
        groups.append(PodGroup(spec=p, count=1, pod_names=[p.name]))
        unschedulable[g_idx] = 1
    return SolveResult(nodes=nodes, existing_counts=existing_counts,
                       unschedulable=unschedulable, groups=groups,
                       existing_by_group=existing_by_group)
