"""NodeTemplate status controller.

Parity target: /root/reference/pkg/controllers/nodetemplate/controller.go —
reconcile resolved subnets (sorted by free IPs descending, :79-97) and
security-group IDs (:99-112) into Status, on generation change + 5m requeue,
10-way concurrent.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..apis.nodetemplate import NodeTemplate, NodeTemplateStatus
from ..introspect.watchdog import cycle as _wd_cycle
from ..utils.clock import Clock

log = logging.getLogger("karpenter.nodetemplate")

REQUEUE_SECONDS = 300.0


class NodeTemplateController:
    def __init__(self, kube, subnet_provider, securitygroup_provider,
                 clock: Optional[Clock] = None, watchdog=None):
        self.kube = kube
        self.watchdog = watchdog
        self.subnets = subnet_provider
        self.security_groups = securitygroup_provider
        self.clock = clock or Clock()
        self._last_seen: "dict[str, tuple[int, float]]" = {}

    def reconcile(self, template: NodeTemplate) -> NodeTemplate:
        import dataclasses

        subnets = self.subnets.list(template.subnet_selector)
        subnets = sorted(subnets, key=lambda s: -s.free_ips)  # most-free first
        sg_ids = self.security_groups.ids(template.security_group_selector) \
            if template.security_group_selector else []
        # CAS on a COPY (the read-modify-write rule for status writers,
        # controllers/counters.py): never mutate the shared informer-cache
        # object, and never clobber a concurrent user edit with our stale
        # read — a Conflict just retries on the next sweep.
        fresh = dataclasses.replace(template, status=NodeTemplateStatus(
            subnets=[{"id": s.id, "zone": s.zone} for s in subnets],
            security_groups=sg_ids,
        ))
        self.kube.compare_and_swap("nodetemplates", template.name,
                                   template, fresh)
        return fresh

    def reconcile_once(self) -> int:
        with _wd_cycle(self.watchdog, "nodetemplate"):
            return self._reconcile_once()

    def _reconcile_once(self) -> int:
        """Generation-change predicate + periodic requeue."""
        count = 0
        now = self.clock.now()
        for template in self.kube.nodetemplates():
            seen = self._last_seen.get(template.name)
            due = (seen is None or seen[0] != template.generation
                   or now - seen[1] >= REQUEUE_SECONDS)
            if not due:
                continue
            try:
                self.reconcile(template)
                self._last_seen[template.name] = (template.generation, now)
                count += 1
            except Exception as e:
                log.warning("nodetemplate %s reconcile failed: %s",
                            template.name, e)
        return count
