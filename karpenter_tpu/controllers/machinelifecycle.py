"""Machine lifecycle controller: Launched -> Registered -> Initialized.

Parity target: karpenter-core's machine lifecycle (SURVEY.md §2.2 "Machine
lifecycle": create -> launch -> registration -> initialization). Here:

- LAUNCHED -> REGISTERED: the node object for the machine exists in the
  cluster (the node "joined"; core watches node registration).
- REGISTERED -> INITIALIZED: the backing instance reports `running`, the
  node's startup taints are cleared (v1alpha5 startupTaints: "registered
  with, expected to be removed before pods schedule"), and the node is
  marked initialized — the gate consolidation eligibility checks
  (oracle/consolidation.py eligible()).

Emits karpenter_machines_initialized_total and the launch->initialized
latency histogram (reference: karpenter_nodes_* metrics, metrics.md).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..metrics import NAMESPACE, REGISTRY, Registry
from ..models.machine import INITIALIZED, LAUNCHED, REGISTERED, parse_provider_id
from ..utils.clock import Clock
from ..utils.errors import CloudError

log = logging.getLogger("karpenter.machinelifecycle")


class MachineLifecycleController:
    def __init__(self, kube, cloudprovider, cluster,
                 clock: Optional[Clock] = None,
                 registry: Optional[Registry] = None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.clock = clock or Clock()
        reg = registry or REGISTRY
        self.initialized = reg.counter(
            f"{NAMESPACE}_machines_initialized_total",
            "Machines that reached Initialized.", ("provisioner",))
        self.init_time = reg.histogram(
            f"{NAMESPACE}_machines_initialization_time_seconds",
            "Time from launch to Initialized.")

    def _node_for(self, machine):
        name = machine.status.node_name
        if name and name in self.cluster.nodes:
            return self.cluster.nodes[name]
        for node in self.cluster.nodes.values():
            if node.machine_name == machine.name:
                return node
        return None

    def reconcile_once(self) -> int:
        """Advance every machine one lifecycle step; returns transitions."""
        moved = 0
        for machine in self.kube.machines():
            state = machine.status.state
            if state == LAUNCHED:
                if self._node_for(machine) is not None:
                    machine.status.state = REGISTERED
                    moved += 1
            elif state == REGISTERED:
                node = self._node_for(machine)
                if node is None:
                    continue
                if not machine.status.provider_id:
                    continue
                try:
                    _, iid = parse_provider_id(machine.status.provider_id)
                    instance = self.cloudprovider.instances.get_by_id(iid)
                except (CloudError, ValueError) as e:
                    log.warning("lifecycle check for %s failed: %s",
                                machine.name, e)
                    continue
                if instance.state != "running":
                    continue
                machine.status.state = INITIALIZED
                node.startup_taints = ()
                node.initialized = True
                moved += 1
                self.initialized.inc(
                    provisioner=machine.spec.provisioner_name or "")
                if node.created_ts:
                    self.init_time.observe(
                        max(0.0, self.clock.now() - node.created_ts))
        return moved
