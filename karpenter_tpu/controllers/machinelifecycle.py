"""Machine lifecycle controller: Launched -> Registered -> Initialized.

Parity target: karpenter-core's machine lifecycle (SURVEY.md §2.2 "Machine
lifecycle": create -> launch -> registration -> initialization). Here:

- LAUNCHED -> REGISTERED: the node object for the machine exists in the
  cluster (the node "joined"; core watches node registration).
- REGISTERED -> INITIALIZED: the backing instance reports `running`, the
  node's startup taints are cleared (v1alpha5 startupTaints: "registered
  with, expected to be removed before pods schedule"), and the node is
  marked initialized — the gate consolidation eligibility checks
  (oracle/consolidation.py eligible()).

Emits karpenter_machines_initialized_total and the launch->initialized
latency histogram (reference: karpenter_nodes_* metrics, metrics.md).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..introspect.watchdog import cycle as _wd_cycle
from ..metrics import NAMESPACE, REGISTRY, Registry
from ..models.machine import (INITIALIZED, LAUNCHED, PENDING, REGISTERED,
                              parse_provider_id)
from ..utils.clock import Clock
from ..utils.errors import CloudError, is_not_found

log = logging.getLogger("karpenter.machinelifecycle")

# Liveness: a machine that has not registered a node within this window is
# presumed dead and reaped (karpenter-core's registration TTL). This is the
# backstop for launch paths whose cleanup was itself interrupted — e.g. a
# lost machine-delete write leaves a Launched machine that owns a live
# instance but will never grow a node, which forward GC cannot reap because
# the instance looks owned.
REGISTRATION_TTL_SECONDS = 15 * 60.0


class MachineLifecycleController:
    def __init__(self, kube, cloudprovider, cluster,
                 clock: Optional[Clock] = None,
                 registry: Optional[Registry] = None,
                 registration_ttl: float = REGISTRATION_TTL_SECONDS,
                 watchdog=None):
        self.kube = kube
        self.watchdog = watchdog
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.clock = clock or Clock()
        self.registration_ttl = registration_ttl
        # machine name -> first time this controller observed it pre-registration
        self._pre_registration_since: "dict[str, float]" = {}
        reg = registry or REGISTRY
        self.initialized = reg.counter(
            f"{NAMESPACE}_machines_initialized_total",
            "Machines that reached Initialized.", ("provisioner",))
        self.init_time = reg.histogram(
            f"{NAMESPACE}_machines_initialization_time_seconds",
            "Time from launch to Initialized.")
        self.registration_timeouts = reg.counter(
            f"{NAMESPACE}_machines_registration_timeout_total",
            "Machines reaped for failing to register within the TTL.")

    def _node_for(self, machine):
        name = machine.status.node_name
        if name and name in self.cluster.nodes:
            return self.cluster.nodes[name]
        for node in self.cluster.nodes.values():
            if node.machine_name == machine.name:
                return node
        return None

    def _reap_unregistered(self, machine) -> bool:
        """Registration-TTL liveness: terminate the backing instance (if
        any) and delete the machine object once a machine has sat
        pre-registration past the TTL. Returns True when reaped."""
        now = self.clock.now()
        since = self._pre_registration_since.setdefault(machine.name, now)
        if now - since < self.registration_ttl:
            return False
        pid = machine.status.provider_id
        if pid:
            try:
                self.cloudprovider.instances.delete(parse_provider_id(pid)[1])
            except (CloudError, ValueError) as e:
                if not is_not_found(e):
                    log.warning("registration-ttl terminate for %s failed: %s",
                                machine.name, e)
                    return False  # keep the machine until capacity is gone
        try:
            self.kube.delete("machines", machine.name)
        except Exception as e:
            log.warning("registration-ttl delete of machine %s failed: %s",
                        machine.name, e)
            return False
        self._pre_registration_since.pop(machine.name, None)
        self.registration_timeouts.inc()
        log.info("reaped machine %s: no node registered within %.0fs",
                 machine.name, self.registration_ttl)
        return True

    def reconcile_once(self) -> int:
        with _wd_cycle(self.watchdog, "machinelifecycle"):
            return self._reconcile_once()

    def _reconcile_once(self) -> int:
        """Advance every machine one lifecycle step; returns transitions."""
        moved = 0
        live = set()
        for machine in self.kube.machines():
            state = machine.status.state
            if state in (PENDING, LAUNCHED) and self._node_for(machine) is None:
                live.add(machine.name)
                if self._reap_unregistered(machine):
                    live.discard(machine.name)
                    moved += 1
                    continue
            if state == LAUNCHED:
                if self._node_for(machine) is not None:
                    machine.status.state = REGISTERED
                    moved += 1
            elif state == REGISTERED:
                node = self._node_for(machine)
                if node is None:
                    continue
                if not machine.status.provider_id:
                    continue
                try:
                    _, iid = parse_provider_id(machine.status.provider_id)
                    instance = self.cloudprovider.instances.get_by_id(iid)
                except (CloudError, ValueError) as e:
                    log.warning("lifecycle check for %s failed: %s",
                                machine.name, e)
                    continue
                if instance.state != "running":
                    continue
                machine.status.state = INITIALIZED
                node.startup_taints = ()
                node.initialized = True
                moved += 1
                self.initialized.inc(
                    provisioner=machine.spec.provisioner_name or "")
                if node.created_ts:
                    self.init_time.observe(
                        max(0.0, self.clock.now() - node.created_ts))
        # a machine that registered (or vanished) must not inherit a stale
        # pre-registration clock if its name is ever reused
        self._pre_registration_since = {
            k: v for k, v in self._pre_registration_since.items() if k in live}
        return moved
