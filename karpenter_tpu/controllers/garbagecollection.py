"""Garbage collection: leaked cloud capacity with no coordination-plane owner.

Parity target: the reference tolerates double-launch races with a tag-scoped
Get-then-Delete sweep (/root/reference/pkg/cloudprovider/instance.go:151-192:
instances discoverable by cluster+machine tags, deleted when their claim
lost the race) and ships cleanup tooling for leaked test capacity
(/root/reference/test/cmd). Later karpenter-core versions promote this to a
GC controller; this build does the same.

Rule: a cluster-tagged cloud instance whose machine object no longer exists
in the store, and whose age exceeds the grace period (eventual consistency —
a just-launched instance's machine write may still be in flight), is
terminated. Runs on the leader only (registered in operator loops).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..introspect.watchdog import cycle as _wd_cycle
from ..metrics import NAMESPACE, REGISTRY, Registry
from ..models.machine import parse_provider_id
from ..utils.clock import Clock

log = logging.getLogger("karpenter.gc")

GRACE_SECONDS = 5 * 60.0  # eventual-consistency window before reaping


class GarbageCollectionController:
    def __init__(self, kube, cloudprovider, clock: Optional[Clock] = None,
                 registry: Optional[Registry] = None,
                 grace_seconds: float = GRACE_SECONDS,
                 cluster=None, termination=None, watchdog=None):
        self.kube = kube
        self.watchdog = watchdog
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.termination = termination
        self.clock = clock or Clock()
        self.grace_seconds = grace_seconds
        reg = registry or REGISTRY
        self.collected = reg.counter(
            f"{NAMESPACE}_garbage_collected_instances_total",
            "Leaked cloud instances terminated by GC.")
        self.retired = reg.counter(
            f"{NAMESPACE}_garbage_collected_machines_total",
            "Machines retired because their cloud instance vanished.")
        # machine name -> first sweep timestamp at which its instance was
        # absent from the cluster listing (inverse-direction grace window)
        self._missing_since: "dict[str, float]" = {}

    def reconcile_once(self) -> "list[str]":
        with _wd_cycle(self.watchdog, "garbagecollection"):
            return self._reconcile_once()

    def _reconcile_once(self) -> "list[str]":
        """One sweep; returns the terminated instance ids. One cluster-tag
        listing per sweep — the listing already carries launch_time, so no
        per-candidate describe round trips."""
        try:
            instances = self.cloudprovider.instances.list_cluster_instances()
        except Exception as e:
            log.warning("gc list failed: %s", e)
            return []
        owned = set()
        for m in self.kube.machines():
            pid = m.status.provider_id
            if pid:
                try:
                    owned.add(parse_provider_id(pid)[1])
                except ValueError:
                    continue
        now = self.clock.now()
        reaped = []
        for inst in instances:
            if inst.id in owned:
                continue
            launched = getattr(inst, "launch_time", None)
            if launched is not None and now - launched < self.grace_seconds:
                continue  # machine write may still be in flight
            try:
                self.cloudprovider.instances.delete(inst.id)
            except Exception as e:
                log.warning("gc terminate %s failed: %s", inst.id, e)
                continue
            self.collected.inc()
            log.info("garbage-collected leaked instance %s (no machine)",
                     inst.id)
            reaped.append(inst.id)
        self._retire_vanished_machines({i.id for i in instances})
        self._retire_orphaned_nodes(now)
        return reaped

    def _retire_orphaned_nodes(self, now: float) -> None:
        """Level-triggered backstop for the ownership cascade: a node whose
        provisioner no longer EXISTS is terminated (reference
        deprovisioning.md:22 — upstream gets this from node ownerReferences
        + the apiserver's GC, which also catches deletions that raced a
        node's registration or happened while the controller was down).
        The launch grace window guards a node registering while its
        provisioner create is still being admitted."""
        if self.cluster is None or self.termination is None:
            return
        provs = {p.name for p in self.kube.provisioners()}
        for name in sorted(self.cluster.nodes):
            node = self.cluster.nodes.get(name)
            if (node is None or node.marked_for_deletion
                    or not node.provisioner_name
                    or node.provisioner_name in provs):
                continue
            if now - node.created_ts < self.grace_seconds:
                continue
            verdict = self.termination.request_deletion(name)
            if verdict == self.termination.MARKED_NEW:
                self.retired.inc()
                log.info("terminating orphaned node %s: provisioner %s "
                         "no longer exists", name, node.provisioner_name)

    def _retire_vanished_machines(self, present: "set[str]") -> None:
        """Inverse direction: a store machine whose cloud instance is GONE
        (out-of-band termination the interruption pipeline missed) is
        retired through the normal drain path — its pods are dead anyway
        and reschedule onto live capacity (reference analogue: the
        cloud-node-lifecycle deletion of NotReady nodes whose instance
        disappeared).

        Absence must be *confirmed*: the instance listing is eventually
        consistent and snapshotted at sweep start, so a machine whose
        instance launched mid-sweep would look vanished for one pass. A
        machine is only retired once its instance has been absent from the
        listing continuously for grace_seconds (missing-since window — the
        inverse analogue of the forward direction's launch_time grace)."""
        now = self.clock.now()
        seen_missing = set()
        for m in self.kube.machines():
            pid = m.status.provider_id
            if not pid:
                continue  # not launched yet
            try:
                _, iid = parse_provider_id(pid)
            except ValueError:
                continue
            if iid in present:
                self._missing_since.pop(m.name, None)
                continue
            seen_missing.add(m.name)
            first = self._missing_since.setdefault(m.name, now)
            if now - first < self.grace_seconds:
                continue  # not yet confirmed absent; listing may be stale
            node = None
            if self.cluster is not None:
                node = next((n for n in self.cluster.nodes.values()
                             if n.machine_name == m.name), None)
            if node is not None and self.termination is not None:
                # only a mark WE created counts as a GC retirement: a node
                # already marked (by us last sweep while it drains, or by an
                # unrelated emptiness/expiration path) must not re-increment
                # the counter every grace window
                verdict = self.termination.request_deletion(node.name)
                if verdict == self.termination.MARKED_NEW:
                    self.retired.inc()
                    log.info("retiring machine %s: instance %s vanished",
                             m.name, iid)
                if verdict:
                    self._missing_since.pop(m.name, None)
            else:
                # no node joined (died between launch and registration)
                self.kube.delete("machines", m.name)
                self.retired.inc()
                self._missing_since.pop(m.name, None)
                log.info("deleted machine %s: instance %s vanished before "
                         "registration", m.name, iid)
        # forget machines that disappeared from the store on their own
        for name in list(self._missing_since):
            if name not in seen_missing:
                del self._missing_since[name]
