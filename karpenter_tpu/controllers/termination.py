"""Termination controller: finalizer -> cordon + drain -> cloud delete.

Parity target: karpenter-core's termination controller (SURVEY.md §2.2;
website deprovisioning.md:24-58; designs/termination.md): nodes carry a
finalizer; deletion cordons the node, drains pods respecting PDBs and the
`karpenter.sh/do-not-evict` annotation, then calls CloudProvider.Delete and
removes the finalizer.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..events import EventRecorder
from ..introspect.watchdog import cycle as _wd_cycle
from ..metrics import NAMESPACE, REGISTRY, Registry
from ..models.cluster import ClusterState, pod_evictable
from ..recovery.crashpoints import crashpoint
from ..recovery.journal import TERMINATION
from ..utils import errors as cloud_errors
from ..utils.clock import Clock

log = logging.getLogger("karpenter.termination")


class TerminationController:
    def __init__(self, kube, cloudprovider, cluster: ClusterState,
                 clock: Optional[Clock] = None,
                 recorder: Optional[EventRecorder] = None,
                 registry: Optional[Registry] = None,
                 watchdog=None, journal=None):
        self.kube = kube
        self.watchdog = watchdog
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.journal = journal
        self.clock = clock or Clock()
        self.recorder = recorder or EventRecorder(clock=self.clock)
        reg = registry or REGISTRY
        self.terminated = reg.counter(
            f"{NAMESPACE}_nodes_terminated_total", "Nodes terminated.",
            ("provisioner",))
        self.termination_time = reg.histogram(
            f"{NAMESPACE}_nodes_termination_time_seconds",
            "Time from deletion request to cloud delete.")

    MARKED_NEW = "marked"
    MARKED_ALREADY = "already-marked"

    def request_deletion(self, node_name: str) -> str:
        """Mark a node for deletion (the finalizer-bearing delete).

        Returns MARKED_NEW if this call created the mark, MARKED_ALREADY if a
        concurrent path (emptiness/expiration/interruption) got there first,
        or "" (falsy) if the node doesn't exist. The distinction lets a
        multi-node rollback undo only the marks it created instead of
        cancelling an unrelated pending deletion."""
        node = self.cluster.nodes.get(node_name)
        if node is None:
            return ""
        if node.marked_for_deletion:
            return self.MARKED_ALREADY
        node.marked_for_deletion = True
        node.deletion_requested_ts = self.clock.now()
        if self.journal is not None:
            # write-ahead: the mark lives only on the in-memory StateNode —
            # without this record a crash loses the intent and the node
            # outlives its deletion request until some sweep notices
            self.journal.record(TERMINATION, node_name, {
                "node": node_name, "machine": node.machine_name,
                "provider_id": node.provider_id})
        try:
            # server-side cordon: on a real cluster kube-scheduler must
            # stop targeting the draining node (spec.unschedulable);
            # best-effort — our own solver already excludes marked nodes
            self.kube.cordon_node(node_name)
        except Exception as e:
            log.warning("cordon %s failed: %s", node_name, e)
        self.recorder.normal(f"node/{node_name}", "TerminationRequested",
                             "node marked for deletion")
        return self.MARKED_NEW

    def reconcile_once(self) -> "list[str]":
        with _wd_cycle(self.watchdog, "termination"):
            return self._reconcile_once()

    def _reconcile_once(self) -> "list[str]":
        """Process all marked nodes; returns names fully terminated."""
        done = []
        for name in sorted(self.cluster.nodes):
            node = self.cluster.nodes[name]
            if not node.marked_for_deletion:
                continue
            if not self._drain(node):
                continue  # retry next reconcile (PDB/do-not-evict pressure)
            try:
                machine = self.kube.get("machines", node.machine_name)
                if machine is not None:
                    self.cloudprovider.delete(machine)
                    crashpoint("termination.mid_delete")
                    self.kube.delete("machines", node.machine_name)
                elif node.provider_id:
                    from ..models.machine import parse_provider_id

                    _, iid = parse_provider_id(node.provider_id)
                    self.cloudprovider.instances.delete(iid)
            except cloud_errors.CloudError as e:
                if not cloud_errors.is_not_found(e):
                    log.warning("cloud delete of %s failed: %s", name, e)
                    continue
            self.cluster.delete_node(name)
            self.kube.delete("nodes", name)
            if self.journal is not None:
                self.journal.resolve(TERMINATION, name)
            self.terminated.inc(provisioner=node.provisioner_name)
            if node.deletion_requested_ts:
                self.termination_time.observe(
                    self.clock.now() - node.deletion_requested_ts)
            self.recorder.normal(f"node/{name}", "Terminated", "node terminated")
            done.append(name)
        return done

    def _drain(self, node) -> bool:
        """Evict pods; False when any pod cannot be evicted yet
        (PDB exhausted / do-not-evict, deprovisioning.md:24-58)."""
        healthy = {
            pdb.name: sum(1 for n in self.cluster.nodes.values()
                          for p in n.pods if pdb.matches(p))
            for pdb in self.cluster.pdbs
        }
        blockers = [p for p in node.non_daemon_pods()
                    if not pod_evictable(p, self.cluster.pdbs, healthy)]
        if blockers:
            self.recorder.warning(
                f"node/{node.name}", "FailedDraining",
                f"{len(blockers)} pod(s) cannot be evicted")
            return False
        for pod in list(node.non_daemon_pods()):
            self.kube.delete("pods", pod.name)
            self.recorder.normal(f"pod/{pod.name}", "Evicted",
                                 f"evicted from {node.name}")
        node.pods = [p for p in node.pods if p.is_daemon()]
        return True
