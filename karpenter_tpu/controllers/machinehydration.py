"""MachineHydration controller: backfill Machine objects for pre-existing
nodes (migration shim).

Parity target: /root/reference/pkg/controllers/machinehydration/controller.go
— for every node owned by a provisioner that has no Machine, create a Machine
from the node + provisioner (:55-98, machineutil.New analogue) and tag the
backing instance via CloudProvider.Hydrate (:82-98, cloudprovider.go:221-251).
The reference defines this controller but leaves it unregistered
(controllers.go:31-39); here it is always wired into the Operator — this
build has no migration-era compatibility concern, so hydration simply runs.

Checkpoint/resume role (SURVEY.md §5.4): state lives in the cluster and the
cloud — after a controller restart, hydration + list_machines rebuild the
Machine inventory from instance tags, no checkpoint files.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..apis import wellknown as wk
from ..introspect.watchdog import cycle as _wd_cycle
from ..models.machine import Machine, MachineSpec, parse_provider_id
from ..models.requirements import OP_IN, Requirement, Requirements
from ..utils.clock import Clock
from ..utils.errors import CloudError

log = logging.getLogger("karpenter.machinehydration")


class MachineHydrationController:
    def __init__(self, kube, cloudprovider, cluster=None,
                 clock: Optional[Clock] = None, watchdog=None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.clock = clock or Clock()
        self.watchdog = watchdog

    def reconcile_once(self) -> int:
        with _wd_cycle(self.watchdog, "machinehydration"):
            return self._reconcile_once()

    def _reconcile_once(self) -> int:
        """Sweep all nodes; hydrate each provisioner-owned node without a
        Machine. Returns the number hydrated."""
        all_machines = self.kube.list("machines")
        machines = {m.name for m in all_machines}
        by_provider_id = {
            m.status.provider_id: m.name
            for m in all_machines if m.status.provider_id
        }
        count = 0
        for node in self.kube.list("nodes"):
            if self._hydrate_node(node, machines, by_provider_id):
                count += 1
        return count

    def _hydrate_node(self, node, machines: "set[str]",
                      by_provider_id: "dict[str, str]") -> bool:
        provisioner_name = node.labels.get(wk.LABEL_PROVISIONER, "")
        if not provisioner_name:
            return False  # not karpenter-owned (controller.go: provisioner label gate)
        # every owned node joins cluster state, whether or not a Machine needs
        # backfilling — restart recovery (SURVEY.md §5.4) must make restored
        # nodes visible to existing-capacity scheduling and consolidation.
        # Guards: never resurrect a node the termination controller is tearing
        # down (marked_for_deletion), and re-check store membership at join
        # time — the sweep list may be stale against a concurrent delete.
        if (self.cluster is not None and node.name not in self.cluster.nodes
                and not node.marked_for_deletion
                and self.kube.get("nodes", node.name) is not None):
            self.cluster.add_node(node)
        if node.machine_name and node.machine_name in machines:
            return False
        if node.provider_id and node.provider_id in by_provider_id:
            # machine exists but the node lost the back-reference; relink
            node.machine_name = by_provider_id[node.provider_id]
            return False
        if not node.provider_id:
            return False
        prov = self.kube.get("provisioners", provisioner_name)
        try:
            _, instance_id = parse_provider_id(node.provider_id)
            instance = self.cloudprovider.instances.get_by_id(instance_id)
            machine = self.cloudprovider.hydrate(
                instance, kubelet=prov.kubelet if prov is not None else None)
        except (CloudError, ValueError) as e:
            log.warning("hydrate %s failed: %s", node.name, e)
            return False
        machine.name = f"{node.name}-hydrated"
        machine.labels = dict(node.labels)
        machine.spec = MachineSpec(
            requirements=self._node_requirements(node),
            provisioner_name=provisioner_name,
            machine_template_ref=self._template_ref(provisioner_name),
        )
        try:
            self.kube.create("machines", machine.name, machine)
        except Exception as e:
            log.warning("machine create for %s failed: %s", node.name, e)
            return False
        node.machine_name = machine.name
        machines.add(machine.name)
        if machine.status.provider_id:
            by_provider_id[machine.status.provider_id] = machine.name
        log.info("hydrated machine %s from node %s", machine.name, node.name)
        return True

    def _node_requirements(self, node) -> Requirements:
        """Machine requirements from the node's concrete labels
        (machineutil.New: node labels become single-valued requirements)."""
        reqs = Requirements()
        for key, value in sorted(node.labels.items()):
            if key in wk.RESTRICTED_LABELS:
                continue
            reqs.add(Requirement.create(key, OP_IN, [value]))
        return reqs

    def _template_ref(self, provisioner_name: str) -> str:
        prov = self.kube.get("provisioners", provisioner_name)
        if prov is not None and prov.provider_ref:
            return prov.provider_ref
        return "default"
