"""Deprovisioning controller: emptiness / expiration / drift / consolidation.

Parity target: karpenter-core's deprovisioning controller (SURVEY.md §2.2 /
§3.3; website deprovisioning.md:7-18):
- emptiness: last non-daemon pod gone -> wait ttlSecondsAfterEmpty -> delete
- expiration: node age > ttlSecondsUntilExpired -> delete (replacement via
  normal provisioning)
- drift: CloudProvider.IsMachineDrifted (feature-gated) -> replace
- consolidation: the TPU-batched delete/replace search (ops/consolidate),
  single action per cycle, replacement launched BEFORE the old node drains
  (consolidation.md "when it is ready").
"""

from __future__ import annotations

import logging
from typing import Optional

from ..apis import wellknown as wk
from ..events import EventRecorder
from ..metrics import NAMESPACE, REGISTRY, Registry
from ..models.cluster import ClusterState
from ..ops.consolidate import run_consolidation
from ..oracle.consolidation import find_consolidation
from ..utils.clock import Clock
from .termination import TerminationController

log = logging.getLogger("karpenter.deprovisioning")


class DeprovisioningController:
    def __init__(self, kube, cloudprovider, cluster: ClusterState,
                 termination: TerminationController,
                 clock: Optional[Clock] = None,
                 recorder: Optional[EventRecorder] = None,
                 registry: Optional[Registry] = None,
                 use_tpu_solver: bool = True,
                 provisioning=None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.termination = termination
        self.clock = clock or Clock()
        self.recorder = recorder or EventRecorder(clock=self.clock)
        self.use_tpu_solver = use_tpu_solver
        self.provisioning = provisioning  # for replacement launches
        reg = registry or REGISTRY
        self.actions = reg.counter(
            f"{NAMESPACE}_deprovisioning_actions_performed_total",
            "Deprovisioning actions.", ("action",))
        self.eval_duration = reg.histogram(
            f"{NAMESPACE}_deprovisioning_evaluation_duration_seconds",
            "Consolidation evaluation duration.", ("method",))
        self._empty_since: "dict[str, float]" = {}

    def _prov(self, name: str):
        return next((p for p in self.kube.provisioners() if p.name == name), None)

    # -- emptiness -------------------------------------------------------------

    def reconcile_emptiness(self) -> "list[str]":
        acted = []
        now = self.clock.now()
        for name in sorted(self.cluster.nodes):
            node = self.cluster.nodes[name]
            if node.marked_for_deletion:
                continue
            prov = self._prov(node.provisioner_name)
            if prov is None or prov.ttl_seconds_after_empty is None:
                continue
            if not node.is_empty():
                self._empty_since.pop(name, None)
                continue
            since = self._empty_since.setdefault(name, now)
            if now - since >= prov.ttl_seconds_after_empty:
                if self.termination.request_deletion(name):
                    self.actions.inc(action="emptiness")
                    self.recorder.normal(f"node/{name}", "EmptinessTTLExpired",
                                         "empty node TTL expired")
                    acted.append(name)
        return acted

    # -- expiration ------------------------------------------------------------

    def reconcile_expiration(self) -> "list[str]":
        acted = []
        now = self.clock.now()
        for name in sorted(self.cluster.nodes):
            node = self.cluster.nodes[name]
            if node.marked_for_deletion:
                continue
            prov = self._prov(node.provisioner_name)
            if prov is None or prov.ttl_seconds_until_expired is None:
                continue
            if now - node.created_ts >= prov.ttl_seconds_until_expired:
                if self.termination.request_deletion(name):
                    self.actions.inc(action="expiration")
                    self.recorder.normal(f"node/{name}", "Expired",
                                         "node exceeded ttlSecondsUntilExpired")
                    acted.append(name)
        return acted

    # -- drift -----------------------------------------------------------------

    def reconcile_drift(self) -> "list[str]":
        acted = []
        for name in sorted(self.cluster.nodes):
            node = self.cluster.nodes[name]
            if node.marked_for_deletion:
                continue
            machine = self.kube.get("machines", node.machine_name)
            if machine is None:
                continue
            try:
                drifted = self.cloudprovider.is_machine_drifted(machine)
            except Exception:
                continue
            if drifted and not node.drifted:
                node.drifted = True
                if self.termination.request_deletion(name):
                    self.actions.inc(action="drift")
                    self.recorder.normal(f"node/{name}", "Drifted",
                                         "machine drifted from template")
                    acted.append(name)
        return acted

    # -- consolidation ---------------------------------------------------------

    def reconcile_consolidation(self):
        """One consolidation action per cycle (consolidation.md single-node
        changes)."""
        provisioners = [p for p in self.kube.provisioners() if p.consolidation_enabled]
        if not provisioners:
            return None
        eligible_provs = {p.name for p in provisioners}
        # only nodes of consolidation-enabled provisioners are candidates;
        # build a view-cluster excluding others as candidates (still hosts)
        cluster = self.cluster
        catalog = self.cloudprovider.catalog_for(None)
        all_provs = sorted(self.kube.provisioners(), key=lambda p: (-p.weight, p.name))
        method = "tpu" if self.use_tpu_solver else "oracle"
        # only nodes of consolidation-enabled provisioners may be candidates
        # (pre-search: a vetoed node must not shadow the next-best action)
        cand_filter = lambda n: n.provisioner_name in eligible_provs
        import time as _time

        t0 = _time.perf_counter()
        try:
            if self.use_tpu_solver:
                action = run_consolidation(cluster, catalog, all_provs,
                                           now=self.clock.now(),
                                           candidate_filter=cand_filter)
            else:
                raise RuntimeError("oracle requested")
        except Exception as e:
            if self.use_tpu_solver:
                log.warning("TPU consolidation failed (%s); oracle fallback", e)
            method = "oracle"
            from ..oracle.consolidation import find_multi_consolidation

            action = find_consolidation(cluster, catalog, all_provs,
                                        now=self.clock.now(),
                                        candidate_filter=cand_filter)
            if action is None:
                # sequential pair simulation is O(pairs) scheduler runs:
                # cap hard (8 candidates -> <=28) on the fallback path
                action = find_multi_consolidation(
                    cluster, catalog, all_provs, now=self.clock.now(),
                    max_candidates=8, candidate_filter=cand_filter)
        self.eval_duration.observe(_time.perf_counter() - t0, method=method)
        if action is None:
            return None
        nodes = [self.cluster.nodes.get(n) for n in action.nodes]
        if any(n is None or n.provisioner_name not in eligible_provs
               for n in nodes):
            return None
        if action.kind == "replace" and self.provisioning is not None:
            # launch the replacement before draining (consolidation.md:
            # "when it is ready, delete the existing node")
            self.recorder.normal(f"node/{action.node}", "ConsolidationReplace",
                                 f"replacing with {action.replacement[0]}")
        # all-or-nothing: a multi-node action executed partially would drain
        # one node while claiming the combined savings. Roll back only marks
        # THIS action created — a member already marked by a concurrent path
        # (emptiness/interruption) keeps its pending deletion.
        newly_marked = []
        for n in action.nodes:
            status = self.termination.request_deletion(n)
            if not status:
                for done in newly_marked:
                    node = self.cluster.nodes.get(done)
                    if node is not None:
                        node.marked_for_deletion = False
                        node.deletion_requested_ts = 0.0
                log.warning("consolidation aborted: %s not deletable", n)
                return None
            if status == self.termination.MARKED_NEW:
                newly_marked.append(n)
        suffix = "-multi" if len(action.nodes) > 1 else ""
        self.actions.inc(action=f"consolidation-{action.kind}{suffix}")
        self.recorder.normal(
            f"node/{action.node}", "Consolidated",
            f"{action.kind} {','.join(action.nodes)}: "
            f"saves ${action.savings:.4f}/h")
        return action

    def reconcile_once(self):
        """Full deprovisioning pass in reference priority order."""
        self.reconcile_emptiness()
        self.reconcile_expiration()
        drift_enabled = self.cloudprovider.settings.feature_gates.drift_enabled
        if drift_enabled:
            self.reconcile_drift()
        return self.reconcile_consolidation()
