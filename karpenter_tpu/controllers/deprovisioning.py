"""Deprovisioning controller: emptiness / expiration / drift / consolidation.

Parity target: karpenter-core's deprovisioning controller (SURVEY.md §2.2 /
§3.3; website deprovisioning.md:7-18):
- emptiness: last non-daemon pod gone -> wait ttlSecondsAfterEmpty -> delete
- expiration: node age > ttlSecondsUntilExpired -> delete (replacement via
  normal provisioning)
- drift: CloudProvider.IsMachineDrifted (feature-gated) -> replace
- consolidation: the TPU-batched delete/replace search (ops/consolidate),
  single action per cycle, replacement launched BEFORE the old node drains
  (consolidation.md "when it is ready").
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from ..apis import wellknown as wk
from ..events import EventRecorder
from ..metrics import NAMESPACE, REGISTRY, Registry
from ..models.cluster import ClusterState
from ..introspect.watchdog import cycle as _wd_cycle
from ..ops import consolidate as consolidate_ops
from ..ops.consolidate import run_consolidation
from ..oracle.consolidation import find_consolidation
from ..recovery.crashpoints import crashpoint
from ..recovery.journal import REPLACE, TERMINATION
from ..resilience import DegradeLadder, deadline
from ..tracing import TRACER
from ..utils.clock import Clock
from .termination import TerminationController

log = logging.getLogger("karpenter.deprovisioning")


class DeprovisioningController:
    # Replacement-launch state machine (consolidation.md:15 "launch the new
    # cheaper node and when it is ready delete the existing node"):
    REPLACE_INIT_TIMEOUT_S = 300.0  # roll the replacement back after this
    # Post-action stabilization (consolidation.md:65): don't chain actions
    # against a cluster still in flux — 5 min while replaced pods are
    # pending, a short settle window otherwise.
    STABILIZATION_PENDING_S = 300.0
    STABILIZATION_S = 30.0
    CYCLE_BUDGET_S = deadline.DEFAULT_CYCLE_BUDGET_S

    def __init__(self, kube, cloudprovider, cluster: ClusterState,
                 termination: TerminationController,
                 clock: Optional[Clock] = None,
                 recorder: Optional[EventRecorder] = None,
                 registry: Optional[Registry] = None,
                 use_tpu_solver: bool = True,
                 provisioning=None,
                 remote_consolidator=None,
                 watchdog=None,
                 resilience=None,
                 journal=None):
        self.kube = kube
        self.watchdog = watchdog
        self.journal = journal
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.termination = termination
        self.clock = clock or Clock()
        self.recorder = recorder or EventRecorder(clock=self.clock)
        self.use_tpu_solver = use_tpu_solver
        self.provisioning = provisioning  # for replacement launches
        # callable(cluster, catalog, provisioners, eligible_names, now)
        # -> action | None: runs the batched search on the solver SIDECAR's
        # device (solver/client.py consolidate). The controller container
        # has no chip in the deployed split; in-process stays the fallback.
        self.remote_consolidator = remote_consolidator
        reg = registry or REGISTRY
        # the remote->tpu->oracle search chain as an explicit DegradeLadder
        # (sticky + probed recovery) instead of per-cycle try/excepts
        self.consolidate_ladder = (
            resilience.ladder("consolidate") if resilience is not None
            else DegradeLadder("consolidate", ("remote", "tpu", "oracle"),
                               clock=self.clock, recorder=self.recorder,
                               registry=reg))
        self.actions = reg.counter(
            f"{NAMESPACE}_deprovisioning_actions_performed_total",
            "Deprovisioning actions.", ("action",))
        self.eval_duration = reg.histogram(
            f"{NAMESPACE}_deprovisioning_evaluation_duration_seconds",
            "Consolidation evaluation duration.", ("method",))
        self._empty_since: "dict[str, float]" = {}
        # in-flight replace action: {"action", "replacement", "started_ts"}
        self._pending_replace: "Optional[dict]" = None
        self._last_action_ts: "Optional[float]" = None
        # pods already awareness-logged for consolidation-blocking
        # preferences (deprovisioning.md:40) — log once per pod
        self._pref_logged: "set[str]" = set()

    def _prov(self, name: str):
        return next((p for p in self.kube.provisioners() if p.name == name), None)

    def _prov_ttl_columns(self, attr: str):
        """(ttl-by-provisioner-name dict, ttl vector aligned with the
        cluster's provisioner intern table). First matching provisioner wins
        (the `_prov` convention); nan marks provisioners that are unknown or
        carry no TTL of this kind — one nan test replaces the per-node
        `_prov(...)`/`is None` probe pair in the sweeps."""
        cols = self.cluster.columns
        ttl_by_prov: "dict[str, Optional[float]]" = {}
        for p in self.kube.provisioners():
            ttl_by_prov.setdefault(p.name, getattr(p, attr))
        ttl_of_code = np.full(len(cols.prov_names) + 1, np.nan)
        for code, pname in enumerate(cols.prov_names):
            ttl = ttl_by_prov.get(pname)
            if ttl is not None:
                ttl_of_code[code] = ttl
        # -1 codes (never occupied rows) route to the trailing nan slot
        ttl = ttl_of_code[np.where(cols.prov_code >= 0, cols.prov_code,
                                   len(cols.prov_names))]
        return ttl_by_prov, ttl

    # -- emptiness -------------------------------------------------------------

    def reconcile_emptiness(self) -> "list[str]":
        acted = []
        now = self.clock.now()
        cols = self.cluster.columns
        # HOT:BEGIN(emptiness-sweep) — the per-node loop below only visits
        # nodes that are actually empty; tracked-but-refilled nodes drop
        # their empty-since mark in one vectorized pass
        ttl_by_prov, ttl = self._prov_ttl_columns("ttl_seconds_after_empty")
        tracked = cols.occupied & ~cols.marked & ~np.isnan(ttl)
        refilled = tracked & (cols.non_daemon > 0)
        for r in np.nonzero(refilled)[0]:
            self._empty_since.pop(cols.name_of[r], None)
        empty = tracked & (cols.non_daemon == 0)
        names = sorted(cols.name_of[r] for r in np.nonzero(empty)[0])
        # HOT:END(emptiness-sweep)
        for name in names:
            since = self._empty_since.setdefault(name, now)
            node = self.cluster.nodes[name]
            if now - since >= ttl_by_prov[node.provisioner_name]:
                if self.termination.request_deletion(name):
                    self.actions.inc(action="emptiness")
                    self.recorder.normal(f"node/{name}", "EmptinessTTLExpired",
                                         "empty node TTL expired")
                    acted.append(name)
        return acted

    # -- expiration ------------------------------------------------------------

    def reconcile_expiration(self) -> "list[str]":
        acted = []
        now = self.clock.now()
        cols = self.cluster.columns
        # HOT:BEGIN(expiration-sweep) — age test vectorized over created_ts;
        # nan TTLs (unknown provisioner / no expiry) compare False
        ttl_by_prov, ttl = self._prov_ttl_columns("ttl_seconds_until_expired")
        with np.errstate(invalid="ignore"):
            expired = (cols.occupied & ~cols.marked
                       & (now - cols.created_ts >= ttl))
        names = sorted(cols.name_of[r] for r in np.nonzero(expired)[0])
        # HOT:END(expiration-sweep)
        for name in names:
            if self.termination.request_deletion(name):
                self.actions.inc(action="expiration")
                self.recorder.normal(f"node/{name}", "Expired",
                                     "node exceeded ttlSecondsUntilExpired")
                acted.append(name)
        return acted

    # -- drift -----------------------------------------------------------------

    def reconcile_drift(self) -> "list[str]":
        acted = []
        # column prefilter: marked nodes skip the per-node kube/cloud probes
        for name in self.cluster.scan_names(unmarked=True):
            node = self.cluster.nodes[name]
            machine = self.kube.get("machines", node.machine_name)
            if machine is None:
                continue
            try:
                drifted = self.cloudprovider.is_machine_drifted(machine)
            except Exception:
                continue
            if drifted and not node.drifted:
                node.drifted = True
                if self.termination.request_deletion(name):
                    self.actions.inc(action="drift")
                    self.recorder.normal(f"node/{name}", "Drifted",
                                         "machine drifted from template")
                    acted.append(name)
        return acted

    # -- consolidation ---------------------------------------------------------

    def reconcile_consolidation(self):
        """One consolidation action per cycle (consolidation.md single-node
        changes). Replace actions run as a two-phase state machine: launch
        the replacement first, finish (drain the old nodes) only once the
        machine-lifecycle controller marks it initialized."""
        now = self.clock.now()
        if self._pending_replace is not None:
            return self._finish_pending_replace(now)
        if self._last_action_ts is not None:
            window = self.STABILIZATION_PENDING_S if self.kube.pending_pods() \
                else self.STABILIZATION_S
            if now - self._last_action_ts < window:
                return None
        provisioners = [p for p in self.kube.provisioners() if p.consolidation_enabled]
        if not provisioners:
            return None
        eligible_provs = {p.name for p in provisioners}
        # awareness logging (deprovisioning.md:40): pods with soft scheduling
        # preferences can prevent consolidation — surface each once so a
        # "nothing consolidates" cluster is explicable without a debugger.
        # The seen-set is rebuilt from the LIVE preference pods each pass,
        # so deleted pods don't pin memory for the controller's lifetime.
        # cluster.pref_pod_nodes() is maintained incrementally on bind/
        # unbind, so this pass touches only nodes actually hosting
        # preference pods instead of sweeping every pod in the cluster
        current_pref_pods = set()
        pref_nodes = self.cluster.pref_pod_nodes()
        for name in sorted(pref_nodes):
            node = self.cluster.nodes.get(name)
            if node is None or node.provisioner_name not in eligible_provs:
                continue  # never a candidate: its pods can't block anything
            for pod_name in sorted(pref_nodes[name]):
                current_pref_pods.add(pod_name)
                if pod_name not in self._pref_logged:
                    log.info("pod %s has scheduling preferences which "
                             "can prevent consolidation", pod_name)
        self._pref_logged = current_pref_pods
        # Mechanism 1 — Empty Node Consolidation (deprovisioning.md:74-77):
        # entirely empty nodes delete in PARALLEL before any search. With
        # consolidation enabled, ttlSecondsAfterEmpty is excluded by the
        # API, so this is the ONLY reclaim path for empty nodes here.
        empty_act = self._consolidate_empty_nodes(eligible_provs, now)
        if empty_act is not None:
            return empty_act
        # only nodes of consolidation-enabled provisioners are candidates;
        # build a view-cluster excluding others as candidates (still hosts)
        cluster = self.cluster
        catalog = self.cloudprovider.catalog_for(None)
        # replacement solves must respect template subnet zones too
        # (same fold as provisioning — a replacement decided in a zone the
        # template can't launch into would fail-loop forever)
        all_provs = self.cloudprovider.constrain_to_template_zones(
            sorted(self.kube.provisioners(), key=lambda p: (-p.weight, p.name)),
            catalog)
        # only nodes of consolidation-enabled provisioners may be candidates
        # (pre-search: a vetoed node must not shadow the next-best action)
        cand_filter = lambda n: n.provisioner_name in eligible_provs
        # HOT:BEGIN(consolidation-candidates) — dirty-driven generation,
        # shared by all three rungs: the column prefilter plus cached
        # per-node evictability verdicts mean only rows dirtied since their
        # last evaluation rerun the pod-level checks
        cands = cluster.consolidation_candidates(cand_filter)
        # HOT:END(consolidation-candidates)
        import time as _time

        def run_remote():
            return self.remote_consolidator(
                cluster, catalog, all_provs, {n.name for n in cands},
                self.clock.now())

        def run_tpu():
            from .. import incremental
            if incremental.enabled():
                # streamed candidate batches: constant-shape chunks through
                # the resident program instead of one C-lane mega-encode
                from ..ops.consolidate import stream_consolidation
                return stream_consolidation(cluster, catalog, all_provs,
                                            now=self.clock.now(),
                                            cand_nodes=cands)
            return run_consolidation(cluster, catalog, all_provs,
                                     now=self.clock.now(),
                                     cand_nodes=cands)

        def run_oracle():
            from ..oracle.consolidation import find_multi_consolidation

            # mechanism order matches the reference (multi before single,
            # deprovisioning.md:74-77); sequential pair simulation is
            # O(pairs) scheduler runs, so cap hard (8 candidates -> <=28)
            # on this fallback path
            a = find_multi_consolidation(
                cluster, catalog, all_provs, now=self.clock.now(),
                max_candidates=8, nodes=cands)
            if a is None:
                a = find_consolidation(cluster, catalog, all_provs,
                                       now=self.clock.now(), nodes=cands)
            return a

        # rung index -> configured backend; None marks rungs this deployment
        # doesn't have (no solver sidecar / oracle-only mode) — they are
        # skipped without being judged by the ladder
        chain = [
            ("remote", run_remote if self.remote_consolidator is not None
             else None),
            ("tpu", run_tpu if self.use_tpu_solver else None),
            ("oracle", run_oracle),
        ]
        from .. import explain
        if explain.enabled():
            # clear the previous pass's capture so the audit record below
            # can't cite stale verdicts when a non-TPU rung serves this pass
            consolidate_ops.last_verdicts = None
        ladder = self.consolidate_ladder
        start = ladder.start_rung()
        if chain[start][1] is None:
            ladder.abort_probe()  # probing an unconfigured rung judges nothing
            start = next(i for i in range(start, len(chain))
                         if chain[i][1] is not None)
        t0 = _time.perf_counter()
        action = None
        method = None
        for rung in range(start, len(chain)):
            name, fn = chain[rung]
            if fn is None:
                continue
            try:
                action = fn()
            except Exception as e:
                log.warning("%s consolidation failed (%s); degrading",
                            name, e)
                ladder.record_failure(rung)
                continue
            method = name
            ladder.record_success(rung)
            break
        self.eval_duration.observe(_time.perf_counter() - t0,
                                   method=method or "oracle")
        TRACER.annotate(routing=method or "none")  # backend that actually ran
        decision_id = self._emit_consolidation_decision(
            action, method or "none",
            consolidate_ops.last_verdicts if method == "tpu" else None)
        if action is None:
            return None
        nodes = [self.cluster.nodes.get(n) for n in action.nodes]
        if any(n is None or n.provisioner_name not in eligible_provs
               for n in nodes):
            return None
        if action.kind == "replace" and self.provisioning is not None:
            # two-phase replace: launch now, drain once the replacement is
            # initialized (consolidation.md: "when it is ready, delete the
            # existing node") — pods never pass through a pending window
            if self.journal is not None:
                # write-ahead: the replace state machine otherwise lives only
                # in _pending_replace (process memory) — a crash between the
                # replacement launch and the old nodes' marks would leak a
                # node no reborn controller remembers launching
                self.journal.record(REPLACE, action.node, {
                    "nodes": list(action.nodes), "replacement": None})
            replacement = self._launch_replacement(action)
            if replacement is None:
                self._resolve_replace(action, "aborted")
                return None
            if self.journal is not None:
                self.journal.record(REPLACE, action.node, {
                    "nodes": list(action.nodes),
                    "replacement": replacement.name})
            crashpoint("deprovisioning.mid_replace")
            cite = f" (decision {decision_id})" if decision_id else ""
            self.recorder.normal(
                f"node/{action.node}", "ConsolidationReplace",
                f"launched replacement {replacement.name} "
                f"({action.replacement[0]}); draining once initialized{cite}")
            self._pending_replace = {"action": action,
                                     "replacement": replacement.name,
                                     "started_ts": now,
                                     "decision_id": decision_id}
            return action
        if not self._mark_all_or_nothing(action):
            return None
        self._record_action(action, now, decision_id=decision_id)
        return action

    # a just-launched node may be empty only because its workload has not
    # bound yet (two-phase replace: pods rebind AFTER the old nodes evict);
    # nodes younger than this are never mechanism-1 candidates — the
    # analogue of the reference's node nomination protection
    EMPTY_NODE_PROTECT_S = 180.0

    def _consolidate_empty_nodes(self, eligible_provs: "set[str]",
                                 now: float):
        """Delete every entirely-empty consolidation-eligible node in one
        parallel pass (mechanism 1, deprovisioning.md:75). PDB/eviction
        checks are moot (no resident pods); the do-not-consolidate veto and
        initialization gate still apply. Skipped entirely while pods are
        PENDING: in-flight (re)scheduling may be about to claim exactly
        this capacity, and deleting it forces a relaunch loop."""
        from ..oracle.consolidation import ANNOTATION_DO_NOT_CONSOLIDATE
        from ..oracle.consolidation import ConsolidationAction

        if self.kube.pending_pods():
            return None
        cols = self.cluster.columns
        # HOT:BEGIN(empty-consolidation) — the whole eligibility gate is one
        # column expression; only the surviving handful re-read live state
        prov_codes = [c for c, pname in enumerate(cols.prov_names)
                      if pname in eligible_provs]
        mask = (cols.occupied & ~cols.marked & cols.initialized
                & (cols.non_daemon == 0) & ~cols.no_consolidate
                & (now - cols.created_ts >= self.EMPTY_NODE_PROTECT_S)
                & np.isin(cols.prov_code, prov_codes))
        names = sorted(cols.name_of[r] for r in np.nonzero(mask)[0])
        # HOT:END(empty-consolidation)
        empties = []
        for name in names:
            node = self.cluster.nodes[name]
            # live veto re-read (tests poke node.annotations in place)
            if node.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) == "true":
                continue
            empties.append(node)
        if not empties:
            return None
        action = ConsolidationAction(
            "delete", empties[0].name, 0.0,
            savings=sum(n.price for n in empties),
            nodes=tuple(n.name for n in empties))
        if not self._mark_all_or_nothing(action):
            return None
        decision_id = self._emit_consolidation_decision(
            action, "empty-sweep", None)
        self._record_action(action, now, label="consolidation-delete-empty",
                            decision_id=decision_id)
        return action

    def _mark_all_or_nothing(self, action) -> bool:
        """Mark every node of the action for deletion, or none: a multi-node
        action executed partially would drain one node while claiming the
        combined savings. Roll back only marks THIS action created — a member
        already marked by a concurrent path (emptiness/interruption) keeps
        its pending deletion."""
        newly_marked = []
        for n in action.nodes:
            status = self.termination.request_deletion(n)
            if not status:
                for done in newly_marked:
                    node = self.cluster.nodes.get(done)
                    if node is not None:
                        node.marked_for_deletion = False
                        node.deletion_requested_ts = 0.0
                    try:
                        # clear the server-side cordon too, or a real
                        # scheduler shuns this healthy node forever
                        self.kube.uncordon_node(done)
                    except Exception as e:
                        log.warning("uncordon %s failed: %s", done, e)
                    if self.termination.journal is not None:
                        # the aborted mark's write-ahead record must go with
                        # it, or a reborn leader re-kills the rolled-back node
                        self.termination.journal.resolve(
                            TERMINATION, done, outcome="aborted")
                log.warning("consolidation aborted: %s not deletable", n)
                return False
            if status == self.termination.MARKED_NEW:
                newly_marked.append(n)
        return True

    def _resolve_replace(self, action, outcome: str) -> None:
        if self.journal is not None:
            self.journal.resolve(REPLACE, action.node, outcome=outcome)

    def _emit_consolidation_decision(self, action, method: str,
                                     verdicts) -> "Optional[str]":
        """One consolidation audit DecisionRecord: the action taken (or
        None), the backend that decided it, and — when the TPU batched
        search ran with the explain plane on — every candidate lane's
        keep/evict verdict with its cost delta. Advisory: failures are
        swallowed, and an idle pass (no action, no verdicts) emits
        nothing."""
        from .. import explain

        if not explain.enabled() or (action is None and not verdicts):
            return None
        try:
            span = TRACER.current_span()
            record = {
                "trace_id": span.trace_id if span else None,
                "routing": method,
                "action": None if action is None else {
                    "kind": action.kind,
                    "nodes": list(action.nodes),
                    "savings_per_hour": round(action.savings, 6),
                    "replacement": (list(action.replacement)
                                    if getattr(action, "replacement", None)
                                    else None),
                },
                "verdicts": list(verdicts or ()),
                "verdict_vocabulary": list(explain.CONSOLIDATION_VERDICTS),
            }
            rid = explain.DECISIONS.emit("consolidation", record,
                                         ts=self.clock.now())
            if rid:
                TRACER.annotate(decision_id=rid)
            return rid
        except Exception as e:
            log.debug("consolidation decision record failed: %s", e)
            return None

    def _record_action(self, action, now: float, label: str = "",
                       decision_id: "Optional[str]" = None) -> None:
        suffix = "-multi" if len(action.nodes) > 1 else ""
        self.actions.inc(action=label or f"consolidation-{action.kind}{suffix}")
        cite = f" (decision {decision_id})" if decision_id else ""
        self.recorder.normal(
            f"node/{action.node}", "Consolidated",
            f"{action.kind} {','.join(action.nodes)}: "
            f"saves ${action.savings:.4f}/h{cite}")
        self._last_action_ts = now

    def _launch_replacement(self, action):
        """Launch the replacement machine (no pod bindings — the drained
        pods rebind onto it via normal provisioning once the old nodes
        evict). Returns the StateNode or None."""
        from ..oracle.scheduler import Option
        from ..solver.core import SolvedNode, SolveResult

        prov = self._prov(self.cluster.nodes[action.node].provisioner_name)
        if prov is None:
            return None
        itype_name, zone, capacity_type, price = action.replacement
        catalog = self.cloudprovider.catalog_for(None)
        itype = catalog.by_name.get(itype_name)
        if itype is None:
            return None
        solved = SolvedNode(
            option=Option(index=-1, itype=itype, zone=zone,
                          capacity_type=capacity_type, price=price,
                          alloc=tuple(itype.allocatable_vector())),
            pod_counts={}, provisioner=prov)
        empty = SolveResult(nodes=[], existing_counts={}, unschedulable={},
                            groups=[])
        try:
            return self.provisioning._launch_node(solved, {}, empty)
        except Exception as e:
            log.warning("replacement launch failed: %s", e)
            return None

    def _finish_pending_replace(self, now: float):
        """Phase 2: the old nodes drain only after the replacement is
        initialized AND the action still holds against current cluster state
        (the reference revalidates its command after the wait). A replacement
        that never initializes within the timeout is rolled back (deleted)
        and the action abandoned; every abandonment restarts the settle
        window so a persistent failure can't relaunch-loop."""
        pr = self._pending_replace
        action, rep_name = pr["action"], pr["replacement"]
        rep = self.cluster.nodes.get(rep_name)
        if rep is None or rep.marked_for_deletion:
            # replacement vanished or is itself terminating (interruption /
            # manual delete): draining into it would strand the pods
            log.warning("replacement %s gone or terminating; abandoning "
                        "replace", rep_name)
            self._pending_replace = None
            self._resolve_replace(action, "abandoned")
            self._last_action_ts = now
            return None
        if rep.initialized:
            self._pending_replace = None
            if not self._revalidate_replace(action, rep_name) \
                    or not self._mark_all_or_nothing(action):
                # cluster moved under us (new pods bound to the old nodes /
                # members no longer drainable): roll the replacement back
                self.termination.request_deletion(rep_name)
                self._resolve_replace(action, "rolled_back")
                self._last_action_ts = now
                return None
            self._record_action(action, now,
                                decision_id=pr.get("decision_id"))
            self._resolve_replace(action, "completed")
            return action
        if now - pr["started_ts"] >= self.REPLACE_INIT_TIMEOUT_S:
            log.warning("replacement %s not initialized within %.0fs; "
                        "rolling back", rep_name, self.REPLACE_INIT_TIMEOUT_S)
            self.recorder.warning(f"node/{rep_name}", "ReplacementTimeout",
                                  "replacement failed to initialize; rolled back")
            self.termination.request_deletion(rep_name)
            self._pending_replace = None
            self._resolve_replace(action, "rolled_back")
            self._last_action_ts = now
        return None

    def _revalidate_replace(self, action, rep_name: str) -> bool:
        """The action was computed before the init wait; during that window
        provisioning may have bound NEW pods onto the old nodes (they were
        unmarked capacity). Re-simulate: the old nodes' CURRENT pods must fit
        on the surviving cluster (which now includes the replacement) with
        zero fresh launches and zero unschedulable pods."""
        pods = []
        for n in action.nodes:
            node = self.cluster.nodes.get(n)
            if node is None:
                return False
            pods.extend(node.non_daemon_pods())
        if not pods:
            return True
        survivors = self.cluster.existing_views(exclude=set(action.nodes))
        catalog = self.cloudprovider.catalog_for(None)
        provs = self.cloudprovider.constrain_to_template_zones(
            sorted(self.kube.provisioners(), key=lambda p: (-p.weight, p.name)),
            catalog)
        try:
            res = self._reval_solver(catalog, provs).solve(
                pods, existing=survivors)
            ok = res.unschedulable_count() == 0 and not res.nodes
        except Exception:
            from ..oracle.scheduler import Scheduler

            r = Scheduler(catalog, provs).schedule(list(pods),
                                                   existing=survivors)
            ok = not r.unschedulable and not r.new_nodes
        if not ok:
            log.warning("replace %s revalidation failed: pods no longer fit "
                        "the surviving cluster; abandoning",
                        ",".join(action.nodes))
        return ok

    def _reval_solver(self, catalog, provs):
        """Content-keyed memo of the replace-revalidation solver: the init
        wait re-runs revalidation every reconcile tick, and building a fresh
        NativeSolver each time re-derives the whole group-encode state. An
        evicted predecessor donates its static grid arrays (adopt_static)
        so availability-only catalog churn keeps the folds warm."""
        from ..solver import wire
        from ..solver.core import NativeSolver

        key = (wire.catalog_hash(catalog), wire.provisioners_hash(provs))
        cached = getattr(self, "_reval_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        solver = NativeSolver(catalog, provs)
        if cached is not None:
            solver.adopt_static(cached[1])
        self._reval_cache = (key, solver)
        return solver

    def reconcile_once(self):
        with _wd_cycle(self.watchdog, "deprovisioning"):
            with deadline.cycle(self.clock, self.CYCLE_BUDGET_S):
                return self._reconcile_once()

    def _reconcile_once(self):
        """Full deprovisioning pass in reference priority order."""
        with TRACER.start_span("deprovisioning.cycle",
                               nodes=len(self.cluster.nodes)) as root:
            with TRACER.start_span("deprovisioning.emptiness"):
                acted = list(self.reconcile_emptiness())
            with TRACER.start_span("deprovisioning.expiration"):
                acted += self.reconcile_expiration()
            drift_enabled = \
                self.cloudprovider.settings.feature_gates.drift_enabled
            if drift_enabled:
                with TRACER.start_span("deprovisioning.drift"):
                    acted += self.reconcile_drift()
            if acted:
                # other deprovisioners disrupted the cluster this pass:
                # restart the consolidation settle window (consolidation.md:65)
                self._last_action_ts = self.clock.now()
            with TRACER.start_span("deprovisioning.consolidation") as cons:
                action = self.reconcile_consolidation()
                cons.set_attribute("found", action is not None)
            root.set_attributes(acted=len(acted),
                                consolidated=action is not None)
            return action
