"""Event recording with dedupe.

Parity target: karpenter-core's event recorder (consumed at
/root/reference/pkg/controllers/interruption/controller.go:141,157,183 and
main.go wiring) — events are emitted for user-visible actions and deduplicated
so hot loops don't spam.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from ..utils.clock import Clock

DEDUPE_TTL = 120.0


@dataclasses.dataclass(frozen=True)
class Event:
    kind: str          # Normal | Warning
    reason: str        # CamelCase machine-readable reason
    object_ref: str    # "pod/default/inflate-0", "node/xyz", "machine/m-1"
    message: str


MAX_EVENTS = 10_000


class EventRecorder:
    def __init__(self, clock: Optional[Clock] = None, dedupe_ttl: float = DEDUPE_TTL,
                 max_events: int = MAX_EVENTS, sink=None):
        from collections import deque

        self.clock = clock or Clock()
        self.dedupe_ttl = dedupe_ttl
        self.events: "deque[tuple[float, Event]]" = deque(maxlen=max_events)
        self._seen: "dict[tuple, float]" = {}
        self._lock = threading.Lock()
        # optional sink(ts, event) invoked for every RECORDED (post-dedupe)
        # event — the operator wires it to persist Events into the
        # coordination plane so `kubectl get events` works (reference:
        # events go through the k8s event recorder to the apiserver)
        self._sink = sink

    def set_sink(self, sink) -> None:
        self._sink = sink

    def publish(self, event: Event) -> bool:
        """Record unless an identical event fired within the dedupe window.
        Returns True when actually recorded."""
        key = (event.kind, event.reason, event.object_ref, event.message)
        now = self.clock.now()
        with self._lock:
            last = self._seen.get(key)
            if last is not None and now - last < self.dedupe_ttl:
                return False
            if len(self._seen) > 4 * MAX_EVENTS:  # bound the dedupe index too
                cutoff = now - self.dedupe_ttl
                self._seen = {k: t for k, t in self._seen.items() if t >= cutoff}
            self._seen[key] = now
            self.events.append((now, event))
        if self._sink is not None:
            try:  # persistence must never break the emitting controller
                self._sink(now, event)
            except Exception as e:
                err = f"{type(e).__name__}: {e}"
                if err != getattr(self, "_last_sink_error", None):
                    self._last_sink_error = err  # don't spam per event
                    import logging

                    logging.getLogger("karpenter.events").warning(
                        "event persistence failing (%s); events remain "
                        "in-memory only", err)
        return True

    def normal(self, object_ref: str, reason: str, message: str) -> bool:
        return self.publish(Event("Normal", reason, object_ref, message))

    def warning(self, object_ref: str, reason: str, message: str) -> bool:
        return self.publish(Event("Warning", reason, object_ref, message))

    def by_reason(self, reason: str) -> "list[Event]":
        with self._lock:
            return [e for _, e in self.events if e.reason == reason]

    def recent(self, n: "Optional[int]" = None) -> "list[tuple[float, Event]]":
        """Most recent `n` (ts, event) pairs, oldest first — the /eventz
        and statusz/bundle read side."""
        with self._lock:
            items = list(self.events)
        return items if n is None else items[-n:]
