"""Event recording with dedupe.

Parity target: karpenter-core's event recorder (consumed at
/root/reference/pkg/controllers/interruption/controller.go:141,157,183 and
main.go wiring) — events are emitted for user-visible actions and deduplicated
so hot loops don't spam.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from ..utils.clock import Clock

DEDUPE_TTL = 120.0


@dataclasses.dataclass(frozen=True)
class Event:
    kind: str          # Normal | Warning
    reason: str        # CamelCase machine-readable reason
    object_ref: str    # "pod/default/inflate-0", "node/xyz", "machine/m-1"
    message: str


MAX_EVENTS = 10_000


class EventRecorder:
    def __init__(self, clock: Optional[Clock] = None, dedupe_ttl: float = DEDUPE_TTL,
                 max_events: int = MAX_EVENTS):
        from collections import deque

        self.clock = clock or Clock()
        self.dedupe_ttl = dedupe_ttl
        self.events: "deque[tuple[float, Event]]" = deque(maxlen=max_events)
        self._seen: "dict[tuple, float]" = {}
        self._lock = threading.Lock()

    def publish(self, event: Event) -> bool:
        """Record unless an identical event fired within the dedupe window.
        Returns True when actually recorded."""
        key = (event.kind, event.reason, event.object_ref, event.message)
        now = self.clock.now()
        with self._lock:
            last = self._seen.get(key)
            if last is not None and now - last < self.dedupe_ttl:
                return False
            if len(self._seen) > 4 * MAX_EVENTS:  # bound the dedupe index too
                cutoff = now - self.dedupe_ttl
                self._seen = {k: t for k, t in self._seen.items() if t >= cutoff}
            self._seen[key] = now
            self.events.append((now, event))
            return True

    def normal(self, object_ref: str, reason: str, message: str) -> bool:
        return self.publish(Event("Normal", reason, object_ref, message))

    def warning(self, object_ref: str, reason: str, message: str) -> bool:
        return self.publish(Event("Warning", reason, object_ref, message))

    def by_reason(self, reason: str) -> "list[Event]":
        with self._lock:
            return [e for _, e in self.events if e.reason == reason]
