"""TPU-native FFD bin-packing kernel.

Parity target: the reference's provisioning hot loop — First-Fit-Decreasing
pod packing with a shrinking instance-type set per node
(/root/reference/designs/bin-packing.md:17-43) and price-ordered final
selection (/root/reference/pkg/cloudprovider/instance.go:445-462). The scalar
spec is karpenter_tpu/oracle/scheduler.py; this kernel is differential-tested
against it (tests/test_packer_parity.py).

TPU-first design (NOT a translation of the Go loop):

* The Go reference is O(pods x nodes x types) sequential. Here the scan runs
  over POD GROUPS (deduplicated identical pods) — O(#deployments) sequential
  steps — and each step places the whole group with vectorized math:

  - existing nodes fill via an exclusive-cumsum waterfall (first-fit order
    preserved, no inner loop),
  - open node-claims fill the same way, with per-(node, type) int32 capacity
    quotients `q = (alloc - used) // vec` computed as one [N,T,R] reduction,
  - fresh nodes open in bulk: k* = max pods/node over feasible options, the
    group's remainder opens ceil(rem/k*) identical slots in one iota-masked
    write.

* All capacity math is int32 (canonical units are integers < 2**24), so device
  results are bit-identical to the scalar oracle — no float drift.

* Node state is (used [N,R], option-mask [N,T,S]): the reference's
  "requirements tighten as pods are added" is option-mask intersection, and
  the final launch decision is one masked argmin over a precomputed
  price-order tiebreak grid.

Shapes are static per (G, N, T, S, Ne) bucket; the solver service buckets pod
counts to avoid recompilation storms (SURVEY.md §7.3 dynamic shapes).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..apis import wellknown as wk
from . import pallas_kernels

_PODS_I = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]

# plain int, NOT jnp.int32(...): a module-level jnp scalar initializes the
# XLA backend at import time, which breaks jax.distributed.initialize for
# any process that imports the kernels before joining the mesh (the
# multi-host bootstrap order). Arithmetic against int32 arrays stays int32
# under weak typing; 2**30 fits comfortably.
INT_BIG = 2**30

# f32 one-correction division in the Pallas quotient kernel is bit-exact only
# below 2**24; encode clamps values at INT_BIG (2**30), so a catalog with a
# huge extended-resource count could breach the parity contract. Inputs are
# checked host-side (pallas_value_safe) and oversized problems take the XLA
# path via the use_pallas static arg.
F24 = 2**24


def pallas_value_safe(*arrays) -> bool:
    """True when every host-side input magnitude stays below 2**24 (`used`
    never exceeds alloc elementwise — the waterfall only places what fits —
    so checking alloc/vec/overhead bounds every value the kernel sees)."""
    import numpy as np

    return all(
        int(np.abs(np.asarray(a)).max(initial=0)) < F24
        for a in arrays if a is not None)


class PackInputs(NamedTuple):
    # catalog (device-resident)
    alloc_t: jax.Array    # i32 [T, R]
    tiebreak: jax.Array   # i32 [T, S] (INT_BIG where no valid offering)
    # groups (FFD-sorted)
    group_vec: jax.Array      # i32 [G, R]
    group_count: jax.Array    # i32 [G]
    group_cap: jax.Array      # i32 [G] per-node cap (INT_BIG if none)
    group_feas: jax.Array     # bool [G, Pv, T, S]
    group_newprov: jax.Array  # i32 [G] (-1 => no provisioner admits)
    overhead: jax.Array       # i32 [R]
    # existing nodes
    ex_alloc: jax.Array   # i32 [Ne, R]
    ex_used: jax.Array    # i32 [Ne, R]
    ex_feas: jax.Array    # bool [G, Ne]
    # per-provisioner kubelet configuration effects (None when every
    # provisioner uses defaults — the common case keeps the compiled
    # program unchanged). See oracle/scheduler.py kubelet_* helpers.
    prov_overhead: "jax.Array | None" = None  # i32 [Pv, R] extra node overhead
    prov_pods_cap: "jax.Array | None" = None  # i32 [Pv, T] max pods per node
    # per-(group, existing-node) REMAINING group cap: group_cap minus pods of
    # the group already resident on that node (hostname spread / anti-affinity
    # must count residents — designs/bin-packing.md domain counting). None
    # when no group is capped (common case: compiled program unchanged).
    ex_cap: "jax.Array | None" = None  # i32 [G, Ne]
    # Origin-representative row per group: subgroups produced by zone-split
    # pre-passes (notably ScheduleAnyway soft splits, whose hard requirements
    # are identical) must SHARE one per-node cap budget, matching the oracle's
    # origin-keyed group_counts. group_origin[g] is the first row index with
    # the same origin_key; None => every row is its own origin (identity),
    # which is exact whenever no group is capped or no origins are shared.
    group_origin: "jax.Array | None" = None  # i32 [G]
    # Resource-axis compression (build_pack_inputs): a resource whose demand
    # is zero in EVERY group contributes INT_BIG to every quotient whatever
    # its availability, so the kernel only needs the active columns — and
    # the [N, T, R] quotient tensor is the per-step compute floor. res_sel
    # gathers the active columns out of the (full-width, device-resident)
    # alloc_t inside the kernel; every other R-shaped leaf arrives already
    # compressed from the host. res_sel[0] is ALWAYS the pods resource (the
    # kubelet pods-cap path needs its index statically). res_mask is False
    # on ladder-padding lanes (their gathered columns are zeroed: vec=0 and
    # avail=0 make them INT_BIG no-ops). None => legacy full-width layout.
    res_sel: "jax.Array | None" = None   # i32 [Rb]
    res_mask: "jax.Array | None" = None  # bool [Rb]


class PackState(NamedTuple):
    used: jax.Array      # i32 [N, R]
    optmask: jax.Array   # bool [N, T, S]
    nprov: jax.Array     # i32 [N]
    active: jax.Array    # bool [N]
    n_open: jax.Array    # i32 []
    ex_used: jax.Array   # i32 [Ne, R]
    # in-run pods placed per (origin row, node): the shared cap budget
    # consumed so far by ALL subgroups of an origin (oracle group_counts)
    ex_placed: jax.Array     # i32 [G, Ne]
    claim_placed: jax.Array  # i32 [G, N]


class PackResult(NamedTuple):
    assign: jax.Array      # i32 [G, N] pods of group g placed on claim slot n
    ex_assign: jax.Array   # i32 [G, Ne] pods placed on existing nodes
    unsched: jax.Array     # i32 [G] pods that could not be placed
    used: jax.Array        # i32 [N, R]
    active: jax.Array      # bool [N]
    nprov: jax.Array       # i32 [N]
    decided: jax.Array     # i32 [N] flat option id (t*S+s), -1 if inactive
    n_open: jax.Array      # i32 []


def _use_fast_div() -> bool:
    # trace-time choice, same doctrine as pallas_kernels.enabled(): XLA:CPU
    # lowers s32 divide to a scalar idiv per element (no SIMD), which at the
    # step's [N, T, R] quotient tensor is ~85% of kernel step time; the
    # float32 path below is exact and ~4x faster there. Other backends keep
    # the native integer divide.
    return jax.default_backend() == "cpu"


def _floor_div(a: jax.Array, v: jax.Array) -> jax.Array:
    """Exact floor(a / v) for 0 <= a <= INT_BIG, v >= 1 (int32), without
    the scalar s32 idiv. The a-bound is the encode invariant (every
    capacity/allocatable array is INT_BIG-clamped at encode) and keeps the
    f32 estimate's int32 cast in range. float32-reciprocal estimate, then:

    * small-quotient lanes (v > 2^24 so q < 2^7): the estimate's absolute
      error is q * O(2^-22) << 1, a +-1 integer fix is enough;
    * everywhere else, a coarse stage first: subtract a margin that
      provably dominates the f32 error (est >> 20 grows with the quotient
      exactly as the error does) so q1 <= true q, take the remainder
      r = a - q1*v (fits int32: r <= a*2^-19 + 11v on these lanes), and
      estimate r/v — a quotient <= ~2^12, back in +-1 territory.

    The final fix computes rf = a - q*v in wraparound int32 (the true
    value fits whenever |q - true| <= 1, which both paths guarantee) and
    nudges by the sign: rf >= v means one more fits, rf < 0 means one too
    many. Bit-exact vs // for the full int32 domain (property-tested in
    tests/test_packer_parity.py)."""
    af = a.astype(jnp.float32)
    recip = 1.0 / v.astype(jnp.float32)
    est = jnp.floor(af * recip).astype(jnp.int32)
    m = (est >> 20) + 4
    q1 = jnp.maximum(est - m, 0)
    r = a - q1 * v
    q2 = q1 + jnp.floor(r.astype(jnp.float32) * recip).astype(jnp.int32)
    q = jnp.where(v > (1 << 24), est, q2)
    q = jnp.maximum(q, 0)
    rf = a - q * v
    return q + (rf >= v).astype(jnp.int32) - (rf < 0).astype(jnp.int32)


def _quotient(avail: jax.Array, vec: jax.Array) -> jax.Array:
    """How many `vec`-sized pods fit into `avail`: min over resources of
    floor(avail/vec), with zero-demand resources ignored. avail [..., R]."""
    pos = vec > 0
    vsafe = jnp.maximum(vec, 1)
    div = (_floor_div(jnp.maximum(avail, 0), vsafe) if _use_fast_div()
           else avail // vsafe)
    q = jnp.where(pos, div, INT_BIG)
    q = jnp.where(avail < 0, jnp.where(pos, -1, INT_BIG), q)
    return jnp.clip(jnp.min(q, axis=-1), -1, INT_BIG)


def _waterfall(count: jax.Array, fill: jax.Array) -> jax.Array:
    """First-fit distribution of `count` pods over slots with per-slot
    capacity `fill` (in slot order): m_i = clip(count - sum_{j<i} fill_j,
    0, fill_i). One exclusive cumsum — the vectorized form of the
    reference's per-pod first-fit walk.

    fill is clamped to `count` first: per-slot capacity can be INT_BIG (a
    zero-request pod fits "infinitely"), and an unclamped int32 cumsum over
    several INT_BIG slots would wrap and double-place pods."""
    fill = jnp.minimum(fill, count)
    before = jnp.cumsum(fill) - fill
    return jnp.clip(count - before, 0, fill)


def _pods_cap_quotient(cap_avail: jax.Array, vec_pods: jax.Array) -> jax.Array:
    """How many more pods the kubelet pods cap admits: floor(cap_avail/vec)
    with the same zero-demand/negative conventions as _quotient."""
    vsafe = jnp.maximum(vec_pods, 1)
    div = (_floor_div(jnp.maximum(cap_avail, 0), vsafe) if _use_fast_div()
           else cap_avail // vsafe)
    q = jnp.where(vec_pods > 0, div, INT_BIG)
    q = jnp.where(cap_avail < 0, jnp.where(vec_pods > 0, -1, INT_BIG), q)
    return jnp.clip(q, -1, INT_BIG)


def _step(inputs: PackInputs, state: PackState, g: jax.Array,
          use_pallas: bool = False):
    vec = inputs.group_vec[g]          # [R]
    cap = inputs.group_cap[g]          # []
    count = inputs.group_count[g]      # []
    # origin row whose cap budget this row consumes (identity when absent)
    og = g if inputs.group_origin is None else inputs.group_origin[g]

    # ---- 1) existing nodes (oracle step "existing first") --------------------
    q_ex = _quotient(inputs.ex_alloc - state.ex_used, vec)        # [Ne]
    # per-node remaining cap: resident pods (static ex_cap) plus pods placed
    # in-run by any subgroup sharing the origin (oracle: resident_counts[okey]
    # + group_counts[okey])
    cap_ex = cap if inputs.ex_cap is None else inputs.ex_cap[g]
    cap_ex = cap_ex - state.ex_placed[og]
    fill_ex = jnp.clip(jnp.minimum(q_ex, cap_ex), 0, INT_BIG)
    fill_ex = jnp.where(inputs.ex_feas[g], fill_ex, 0)
    m_ex = _waterfall(count, fill_ex)                              # [Ne]
    ex_used = state.ex_used + m_ex[:, None] * vec[None, :]
    ex_placed = state.ex_placed.at[og].add(m_ex)
    rem = count - jnp.sum(m_ex)

    # ---- 2) open claims, first-fit in creation order -------------------------
    gf = inputs.group_feas[g]                                      # [Pv, T, S]
    # Pv is a static shape: with one provisioner every node row gathers the
    # same feasibility plane, so broadcast instead of an [N]-row gather
    feas_n = gf[0][None] if gf.shape[0] == 1 \
        else gf[jnp.clip(state.nprov, 0, None)]                    # [N, T, S]
    nodefeas = state.optmask & feas_n & state.active[:, None, None]
    if use_pallas:
        q_nt = pallas_kernels.quotient_nt_auto(inputs.alloc_t, state.used, vec)
    else:
        q_nt = _quotient(inputs.alloc_t[None, :, :] - state.used[:, None, :], vec)  # [N, T]
    # pods column index: 0 in the compressed layout (res_sel pins it there),
    # the wellknown index in the legacy full-width layout
    pods_i = 0 if inputs.res_sel is not None else _PODS_I
    if inputs.prov_pods_cap is not None:
        # kubelet pods cap of the node's provisioner bounds the quotient
        cap_nt = inputs.prov_pods_cap[jnp.clip(state.nprov, 0, None)]   # [N, T]
        q_extra = _pods_cap_quotient(
            cap_nt - state.used[:, pods_i][:, None], vec[pods_i])
        q_nt = jnp.minimum(q_nt, q_extra)
    # max feasible quotient per node: q is S-independent, so reduce the
    # mask over S first instead of building an [N, T, S] quotient tensor
    feas_t = jnp.any(nodefeas, axis=-1)                            # [N, T]
    qmax = jnp.max(jnp.where(feas_t, q_nt, -1), axis=-1)           # [N]
    # per-claim remaining budget shared across subgroups of the origin
    cap_n = cap - state.claim_placed[og]                           # [N]
    fill_n = jnp.clip(jnp.minimum(qmax, cap_n), 0, INT_BIG)
    m_n = _waterfall(rem, fill_n)                                  # [N]
    new_used = state.used + m_n[:, None] * vec[None, :]
    # compare on [N, T] and broadcast: the quotient is S-independent
    shrunk = nodefeas & (q_nt >= m_n[:, None])[:, :, None]
    placed = m_n > 0
    optmask = jnp.where(placed[:, None, None], shrunk, state.optmask)
    used = jnp.where(placed[:, None], new_used, state.used)
    rem = rem - jnp.sum(m_n)

    # ---- 3) bulk-open fresh nodes -------------------------------------------
    p = inputs.group_newprov[g]
    freshfeas = inputs.group_feas[g][jnp.clip(p, 0, None)] & (p >= 0)  # [T, S]
    ovh = inputs.overhead
    if inputs.prov_overhead is not None:
        ovh = ovh + inputs.prov_overhead[jnp.clip(p, 0, None)]
    q0 = _quotient(inputs.alloc_t - ovh[None, :], vec)                 # [T]
    if inputs.prov_pods_cap is not None:
        cap_t = inputs.prov_pods_cap[jnp.clip(p, 0, None)]             # [T]
        q0 = jnp.minimum(q0, _pods_cap_quotient(
            cap_t - ovh[pods_i], vec[pods_i]))
    kstar = jnp.max(jnp.where(freshfeas, q0[:, None], 0))
    kstar = jnp.clip(jnp.minimum(kstar, cap), 0, INT_BIG)
    n_new = jnp.where(kstar > 0, (rem + kstar - 1) // jnp.maximum(kstar, 1), 0)
    n_slots = state.active.shape[0]
    n_new = jnp.minimum(n_new, n_slots - state.n_open)  # overflow -> unschedulable
    placed_new = jnp.where(n_new > 0, (n_new - 1) * kstar, 0)
    last_cnt = jnp.clip(rem - placed_new, 0, kstar)

    idx = jnp.arange(n_slots, dtype=jnp.int32)
    in_range = (idx >= state.n_open) & (idx < state.n_open + n_new)
    cnt = jnp.where(idx == state.n_open + n_new - 1, last_cnt, kstar)
    cnt = jnp.where(in_range, cnt, 0)                              # [N]
    fresh_used = ovh[None, :] + cnt[:, None] * vec[None, :]
    used = jnp.where(in_range[:, None], fresh_used, used)
    fresh_mask = freshfeas[None, :, :] & (q0[None, :] >= cnt[:, None])[:, :, None]
    optmask = jnp.where(in_range[:, None, None], fresh_mask, optmask)
    active = state.active | in_range
    nprov = jnp.where(in_range, p, state.nprov)
    n_open = state.n_open + n_new
    unsched = rem - jnp.sum(cnt)

    claim_placed = state.claim_placed.at[og].add(m_n + cnt)
    new_state = PackState(used, optmask, nprov, active, n_open, ex_used,
                          ex_placed, claim_placed)
    return new_state, (m_n + cnt, m_ex, unsched)


def pack_impl(inputs: PackInputs, n_slots: int,
              use_pallas: "bool | None" = None) -> PackResult:
    # use_pallas is a STATIC choice: None defers to the env flag (read at
    # trace time, as before); build_pack_inputs passes an explicit bool
    # that also folds in the pallas_value_safe() 2**24 exactness check.
    if use_pallas is None:
        use_pallas = pallas_kernels.enabled()
    if inputs.res_sel is not None:
        # gather the active resource columns out of the full-width resident
        # catalog array ONCE per solve (loop-invariant); padding lanes are
        # zeroed so they stay INT_BIG no-ops in every quotient
        alloc_a = jnp.where(inputs.res_mask[None, :],
                            inputs.alloc_t[:, inputs.res_sel], 0)
        inputs = inputs._replace(alloc_t=alloc_a)
    G = inputs.group_vec.shape[0]
    T, S = inputs.tiebreak.shape
    R = inputs.group_vec.shape[1]
    Ne = inputs.ex_alloc.shape[0]
    init = PackState(
        used=jnp.zeros((n_slots, R), jnp.int32),
        optmask=jnp.zeros((n_slots, T, S), bool),
        nprov=jnp.full((n_slots,), -1, jnp.int32),
        active=jnp.zeros((n_slots,), bool),
        n_open=jnp.int32(0),
        ex_used=inputs.ex_used,
        ex_placed=jnp.zeros((G, Ne), jnp.int32),
        claim_placed=jnp.zeros((G, n_slots), jnp.int32),
    )

    # Effective trip count: one past the last row holding any pods. A
    # count=0 row is an exact identity step (every waterfall fills 0, no
    # mask/state write fires), so the loop simply stops at the last real
    # row and bucket padding costs memory, not FLOPs — the rung ladder
    # (solver/buckets.py) can stay coarse without the padded rows taxing
    # every solve. In-graph scalar: the jit cache key is unchanged; under
    # vmap the wave runs to the widest member and Sync-warmup's all-zero
    # synthetic problems compile the full program but execute no steps.
    gi = jnp.arange(G, dtype=jnp.int32)
    n_eff = jnp.max(jnp.where(inputs.group_count > 0, gi + 1, 0))

    def body(g, carry):
        state, assign, ex_assign, unsched = carry
        new_state, (row_n, row_ex, row_us) = _step(
            inputs, state, g, use_pallas=use_pallas)
        return (new_state, assign.at[g].set(row_n),
                ex_assign.at[g].set(row_ex), unsched.at[g].set(row_us))

    final, assign, ex_assign, unsched = jax.lax.fori_loop(
        0, n_eff, body,
        (init, jnp.zeros((G, n_slots), jnp.int32),
         jnp.zeros((G, Ne), jnp.int32), jnp.zeros((G,), jnp.int32)))

    # decision: cheapest surviving option per active claim (instance.go:445-462)
    rank = jnp.where(final.optmask, inputs.tiebreak[None, :, :], INT_BIG)
    flatrank = rank.reshape(n_slots, -1)
    best = jnp.argmin(flatrank, axis=-1).astype(jnp.int32)
    has_opt = jnp.min(flatrank, axis=-1) < INT_BIG
    decided = jnp.where(final.active & has_opt, best, -1)

    return PackResult(
        assign=assign, ex_assign=ex_assign, unsched=unsched,
        used=final.used, active=final.active, nprov=final.nprov,
        decided=decided, n_open=final.n_open,
    )


pack = functools.partial(
    jax.jit, static_argnames=("n_slots", "use_pallas"))(pack_impl)


def pack_flat_impl(inputs: PackInputs, n_slots: int,
                   use_pallas: "bool | None" = None) -> jax.Array:
    """pack_impl with everything the decoder needs flattened into ONE i32
    vector, so the host pays exactly one device->host transfer per solve.
    On a tunneled/remote device each sync is a full network round trip
    (~tens of ms), which would otherwise dominate the <100ms cycle budget
    (SURVEY.md §7.3 "host-device round-trip budget").

    Layout: [assign (G*N) | ex_assign (G*Ne) | unsched (G) | active (N) |
             nprov (N) | decided (N) | n_open (1)]
    """
    r = pack_impl(inputs, n_slots, use_pallas=use_pallas)
    return flatten_result(r)


def flatten_result(r: PackResult) -> jax.Array:
    """The one flat-layout owner (pack_flat_impl + the sharded flat variant
    in parallel/sharded.py): both paths MUST produce bit-identical buffers
    for the same problem, so the concat order lives in exactly one place."""
    return jnp.concatenate([
        r.assign.ravel(), r.ex_assign.ravel(), r.unsched.ravel(),
        r.active.astype(jnp.int32), r.nprov, r.decided,
        r.n_open.reshape(1),
    ])


pack_flat = functools.partial(
    jax.jit, static_argnames=("n_slots", "use_pallas"))(pack_flat_impl)


def pack_cache_size() -> int:
    """Compiled-program count across the jitted pack entry points. A delta
    across a dispatch means the solve paid an XLA compile (a fresh shape
    bucket escaped the padding doctrine) — the tracing plane records this
    as the compile_cache hit/miss attribute because a miss turns a ~ms
    solve into a multi-second one. Returns -1 when the jit cache
    introspection API is unavailable (callers report "unknown")."""
    n = 0
    for fn in (pack, pack_flat):
        try:
            n += fn._cache_size()
        except Exception:
            return -1
    return n


def unflatten_result(flat, G: int, N: int, Ne: int) -> PackResult:
    """Host-side parse of pack_flat's single buffer back into PackResult
    (used is omitted — the decoder never reads it)."""
    import numpy as np

    o = 0
    assign = flat[o:o + G * N].reshape(G, N); o += G * N
    ex_assign = flat[o:o + G * Ne].reshape(G, Ne); o += G * Ne
    unsched = flat[o:o + G]; o += G
    active = flat[o:o + N].astype(bool); o += N
    nprov = flat[o:o + N]; o += N
    decided = flat[o:o + N]; o += N
    n_open = flat[o]
    return PackResult(
        assign=assign, ex_assign=ex_assign, unsched=unsched,
        used=np.zeros((0,), np.int32), active=active, nprov=nprov,
        decided=decided, n_open=n_open,
    )
