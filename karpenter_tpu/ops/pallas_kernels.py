"""Pallas TPU kernels for the packer's inner hot op.

The packer scan step's dominant compute is the per-(node, type) capacity
quotient (ops/packer.py `_step` step 2):

    q[n, t] = min over r of floor((alloc[t, r] - used[n, r]) / vec[r])

Stock XLA evaluates this as a fused elementwise+reduce over a virtual
[N, T, R] iteration space. This kernel restructures it VPU-first: one grid
program per node tile, the R axis statically unrolled (R = 11), each r step a
[TILE_N, T] broadcast-subtract + divide + min — no [N, T, R] intermediate and
lane-aligned [*, T] tiles throughout.

Numerics: canonical units keep every value < 2**24 (apis/wellknown.py), so
f32 division is used with one exact correction step (products stay < 2**24,
so `q*vec` comparisons are exact) — results are bit-identical to the int32
reference (tests/test_pallas_kernels.py).

Selection: enabled on TPU backends when KARPENTER_TPU_PALLAS=1 (or
force_enable()); everywhere else the stock-XLA `_quotient` path runs. On CPU
the kernel runs in interpreter mode for semantics tests only.

Measured (TPU v5e via tunnel, N=128 T=551 R=11, 100-iter on-device loop to
amortize the ~66 ms host<->device RTT): pallas ~735-745 us/iter vs XLA
~760-770 us/iter end-to-end — i.e. parity to ~3% total; XLA's own fusion of
the subtract/div/min reduction is already near-optimal for this shape, and
the solve cycle is RTT-bound, not compute-bound. Kept flag-gated (default
off) as the hook for larger option grids where the [N, T, S] masks stop
fitting in cache-friendly tiles.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INT_BIG = 2**30  # plain int: jnp scalars would be captured as tracer consts
_LANE = 128
_SUBLANE = 8
_TILE_N = 64

_force = {"on": False}


def force_enable(on: bool = True) -> None:
    _force["on"] = on


def enabled() -> bool:
    if _force["on"]:
        return True
    return os.environ.get("KARPENTER_TPU_PALLAS", "") == "1"


def _pad_to(x, axis, multiple, value):
    n = x.shape[axis]
    target = -(-n // multiple) * multiple
    if target == n:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, target - n)
    return jnp.pad(x, widths, constant_values=value)


def _quotient_kernel(alloc_ref, used_ref, vec_ref, out_ref, *, n_res: int):
    """One program = TILE_N node slots x all types.

    alloc_ref: [R16, Tp] i32 (type-major: row r = resource r across types)
    used_ref:  [TILE_N, R16] i32
    vec_ref:   [1, R16] i32 (SMEM)
    out_ref:   [TILE_N, Tp] i32
    """
    for r in range(n_res):  # static unroll over the resource axis
        vec_r = vec_ref[0, r]

        @pl.when(vec_r > 0)  # vec_r == 0: resource not demanded, no-op
        def _():
            avail = alloc_ref[r:r + 1, :] - used_ref[:, r:r + 1]  # [TILE_N, Tp]
            af = avail.astype(jnp.float32)
            vf = vec_r.astype(jnp.float32)
            qr = jnp.floor(af / vf).astype(jnp.int32)
            # one exact correction step: qr*vec and avail are < 2**24, so the
            # comparisons below are exact even though the division was f32
            over = qr * vec_r > avail
            under = (qr + 1) * vec_r <= avail
            qr = jnp.where(over, qr - 1, jnp.where(under, qr + 1, qr))
            qr = jnp.where(avail < 0, -1, qr)
            out_ref[:] = jnp.minimum(out_ref[:], qr)


def _quotient_init_kernel(out_ref):
    out_ref[:] = jnp.full(out_ref.shape, INT_BIG, jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quotient_nt(alloc_t: jax.Array, used: jax.Array, vec: jax.Array,
                interpret: bool = False) -> jax.Array:
    """q[n, t] = min_r floor((alloc_t[t, r] - used[n, r]) / vec[r]) with the
    packer's conventions: zero-demand resources ignored (INT_BIG), negative
    availability -> -1, result clipped to [-1, INT_BIG].

    Drop-in for ops/packer._quotient(alloc_t[None] - used[:, None], vec).
    """
    N, R = used.shape
    T = alloc_t.shape[0]
    Rp = -(-R // 16) * 16
    Tp = -(-T // _LANE) * _LANE
    Np = -(-N // _TILE_N) * _TILE_N

    alloc_rt = _pad_to(_pad_to(alloc_t.T, 0, 16, 0), 1, _LANE, 0)   # [Rp, Tp]
    used_p = _pad_to(_pad_to(used, 0, _TILE_N, 0), 1, 16, 0)        # [Np, Rp]
    vec_p = _pad_to(vec.reshape(1, R), 1, 16, 0)                     # [1, Rp]

    grid = (Np // _TILE_N,)
    out = pl.pallas_call(
        functools.partial(_seeded_kernel, n_res=R),
        out_shape=jax.ShapeDtypeStruct((Np, Tp), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((Rp, Tp), lambda n: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_N, Rp), lambda n: (n, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Rp), lambda n: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_TILE_N, Tp), lambda n: (n, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(alloc_rt, used_p, vec_p)
    return jnp.clip(out[:N, :T], -1, INT_BIG)


def _seeded_kernel(alloc_ref, used_ref, vec_ref, out_ref, *, n_res: int):
    _quotient_init_kernel(out_ref)
    _quotient_kernel(alloc_ref, used_ref, vec_ref, out_ref, n_res=n_res)


def quotient_nt_auto(alloc_t: jax.Array, used: jax.Array, vec: jax.Array) -> jax.Array:
    """Backend-appropriate invocation: compiled on TPU, interpreter elsewhere
    (parity tests on the CPU platform)."""
    interpret = jax.default_backend() not in ("tpu", "axon")
    return quotient_nt(alloc_t, used, vec, interpret=interpret)
