"""TPU-native batched consolidation search.

Parity target: the consolidation hot loop of /root/reference/designs/
consolidation.md — "for each candidate node: simulate re-scheduling its pods
onto (existing cluster − node) ∪ {one cheaper replacement}" — which the Go
reference evaluates candidate-by-candidate and explicitly limits to
single-node changes for cost reasons (consolidation.md 'Selecting Nodes').

TPU-first design: ALL candidates are evaluated in ONE vmapped packer launch —
the per-candidate simulated scheduling run is a lane of the batched kernel:

  vmap over C candidates of pack(groups_c, existing \\ {c}, cheaper-option mask)

with the catalog arrays broadcast (in_axes=None). A 500-candidate sweep
(BASELINE.json configs[3]) costs one device dispatch instead of 500 scheduler
runs. n_slots=2 detects the ">1 new node" abort condition.

Scoring (disruption cost, lifetime weighting) and action selection stay on
host — they are O(C) scalar math (oracle/consolidation.py is the spec).
"""

from __future__ import annotations

import dataclasses
import functools
import os as _os
import time as _time
from typing import Optional, Sequence

import jax
import numpy as np

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..models.cluster import ClusterState, StateNode
from ..models.encode import (INT_BIG, OptionGrid, build_grid, encode_group,
                             fold_node_mask)
from ..models.instancetype import Catalog
from ..models.pod import tolerates_all
from ..oracle.consolidation import (
    ConsolidationAction, MAX_PAIR_CANDIDATES, REPLACE_PRICE_EPS,
    candidate_pairs, disruption_cost, eligible,
)
from ..oracle.scheduler import prepare_groups
from .packer import PackInputs, pack_impl

N_SLOTS = 2  # 1 replacement allowed; a 2nd opening proves non-consolidatable

# phase-attributed sweeps (encode/flatten/put/dispatch/fetch/decode split,
# read from consolidate.last_timings) — capture-tool diagnostics, same flag
# as solver/core.py so one env var attributes the whole controller cycle
_SOLVE_TIMING = _os.environ.get("KARPENTER_TPU_SOLVE_TIMING") == "1"
last_timings: "dict | None" = None

# grid memo for grid-less callers (the deprovisioner's in-process path, the
# benchmark harness): build_grid costs ~120ms at 551 types and dominated
# every sweep (profiled round 4). weakref to the catalog: identity
# comparison against a LIVE object stays sound (a dead ref is just a miss,
# never an id()-recycling alias) without pinning a retired catalog + grid
# in memory for the process lifetime.
import weakref as _weakref

_grid_memo: "tuple | None" = None  # (weakref(catalog), seqnum, grid)


def _grid_for(catalog: Catalog, grid: "Optional[OptionGrid]") -> OptionGrid:
    global _grid_memo
    if grid is not None and grid.seqnum == catalog.seqnum:
        return grid
    m = _grid_memo
    if m is not None and m[0]() is catalog and m[1] == catalog.seqnum:
        return m[2]
    # a caller-held or memoized stale grid can still donate its static
    # arrays when only availability changed (build_grid layout check)
    g = build_grid(catalog, reuse=grid if grid is not None
                   else (m[2] if m is not None else None))
    _grid_memo = (_weakref.ref(catalog), catalog.seqnum, g)
    return g


@dataclasses.dataclass
class ConsolidationBatch:
    inputs: PackInputs  # group/ex leaves carry a leading C axis
    candidates: "list[tuple[StateNode, ...]]"  # one SET per lane (singles or pairs)
    provisioners: "list[Provisioner]"
    grid: OptionGrid
    # group feasibility ships as a unique-row table + per-lane indices and
    # is expanded to the full [C,Gb,Pv,T,S] ON DEVICE (inputs.group_feas is
    # None): candidate lanes in a real cluster repeat a handful of distinct
    # (group spec, price band) rows, so the dense array is ~97% duplicate
    # bytes — 1.6MB at 500 singles, ~13MB at the 2016-lane pair sweep —
    # and h2d bandwidth on a degraded tunnel link is ~15MB/s
    # (docs/designs/solver-boundary.md cost model). Row 0 is all-False
    # (padded/unused groups).
    feas_table: "np.ndarray" = None  # [U, Pv, T, S] bool
    feas_idx: "np.ndarray" = None  # [C, Gb] int32


def encode_consolidation(
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    grid: Optional[OptionGrid] = None,
    cand_sets: "Optional[list[tuple[StateNode, ...]]]" = None,
    candidate_filter=None,
) -> Optional[ConsolidationBatch]:
    """cand_sets=None encodes the single-node sweep; pass node tuples (e.g.
    candidate_pairs) for the multi-node search — each set is one vmap lane
    whose group batch is the set's combined pods and whose cheaper-option
    mask is priced against the set's combined price."""
    grid = _grid_for(catalog, grid)
    provs = sorted(provisioners, key=lambda p: (-p.weight, p.name))
    overhead = np.asarray(daemon_overhead if daemon_overhead is not None
                          else [0] * wk.NUM_RESOURCES, dtype=np.int32)
    cols = grid.get_cols()
    T, S, R, Pv = grid.T, grid.S, wk.NUM_RESOURCES, len(provs)
    # [T, S]; inf only where NO offering is defined — unavailable offerings
    # carry real prices on the static grid, so every price test must mask
    # with grid.valid
    price = grid.price

    if cand_sets is None:
        cand_sets = [(cluster.nodes[name],) for name in sorted(cluster.nodes)
                     if eligible(cluster.nodes[name], cluster)
                     and (candidate_filter is None
                          or candidate_filter(cluster.nodes[name]))]
    candidates = cand_sets
    if not candidates:
        return None

    all_nodes = sorted(cluster.nodes)
    node_index = {n: i for i, n in enumerate(all_nodes)}
    Ne = len(all_nodes)
    # HOT:BEGIN(consolidation-encode) — existing rows gather straight off
    # the cluster's columns (int64 clamped to the kernel's i32 domain);
    # per-node dataclass views never materialize on this path unless an
    # affinity/topology pre-pass touches them
    ccols = cluster.columns
    rows = np.fromiter((ccols.row_of[n] for n in all_nodes),
                       dtype=np.int64, count=Ne)
    ex_alloc = np.minimum(ccols.alloc[rows], INT_BIG).astype(np.int32)
    ex_used = np.minimum(ccols.used[rows], INT_BIG).astype(np.int32)
    # HOT:END(consolidation-encode)

    C = len(candidates)
    per_cand = []
    gmax = 1
    # cheaper-option mask + zone set depend ONLY on the set's total price —
    # homogeneous clusters (and especially the O(n^2) pair sweep) repeat a
    # handful of distinct prices across thousands of lanes, so both are
    # memoized per price (profiled round 4: the per-lane [T,S] scan was
    # ~40% of pair-sweep encode)
    by_price: "dict[float, tuple]" = {}
    for cand in candidates:
        total_price = sum(n.price for n in cand)
        hit = by_price.get(total_price)
        if hit is None:
            # AND with availability: the static grid carries real prices on
            # unavailable options (old grids encoded them as inf)
            cheaper_opt = (price < (total_price - REPLACE_PRICE_EPS)) \
                & grid.valid  # [T, S]
            zs = {grid.zones[s // len(grid.capacity_types)]
                  for t, s in zip(*np.nonzero(cheaper_opt))}
            hit = by_price[total_price] = (cheaper_opt, sorted(zs))
        cheaper_opt, zones_c = hit
        pods = [p for n in cand for p in n.non_daemon_pods()]
        # domain-population-aware split must see the surviving nodes (the
        # oracle path passes cluster.existing_views(exclude=cand) the same
        # way, oracle/consolidation.py:107) — but both pre-passes gate on
        # the pod set's terms before touching `existing`
        # (resolve_pod_affinity: pod_(anti_)affinity; split_zone_spread:
        # zone topology / anti_affinity_zone), so lanes with term-free
        # pods skip the per-lane snapshot entirely: it was ~40% of the
        # 996-lane streamed encode on a plain-pod cluster
        if any(p.pod_affinity or p.pod_anti_affinity or p.topology
               or p.anti_affinity_zone for p in pods):
            survivors = cluster.existing_columns(
                exclude={n.name for n in cand})
        else:
            survivors = ()
        groups = prepare_groups(pods, zones_c, survivors)
        gmax = max(gmax, len(groups))
        per_cand.append((cand, total_price, groups))

    Gb = gmax
    group_vec = np.zeros((C, Gb, R), dtype=np.int32)
    group_count = np.zeros((C, Gb), dtype=np.int32)
    group_cap = np.full((C, Gb), INT_BIG, dtype=np.int32)
    feas_idx = np.zeros((C, Gb), dtype=np.int32)  # 0 = all-False row
    feas_rows: "list[np.ndarray]" = []  # unique [Pv,T,S] rows, 1-based
    feas_row_index: "dict[tuple, int]" = {}
    group_newprov = np.full((C, Gb), -1, dtype=np.int32)
    ex_feas = np.zeros((C, Gb, Ne), dtype=bool)
    # origin-representative rows: zone-split subgroups share one per-node cap
    # budget (identity for padded/unsplit rows — see encode_problem)
    group_origin = np.broadcast_to(
        np.arange(Gb, dtype=np.int32), (C, Gb)).copy()

    # label/taint fit of a pod-group against the existing nodes, memoized as
    # ONE boolean vector per distinct group spec (token-keyed): the same
    # spec recurs across most candidate lanes in a homogeneous cluster, and
    # per-(lane, node) scalar checks were the pair-sweep encode hotspot
    # (125k calls at 64 nodes, profiled round 4). Now folded over the label
    # columns (RAW labels — this path tests matches_labels(node.labels)
    # with no hostname defaulting, unlike the scheduler's effective view)
    # with each distinct interned taint set checked once, not per node.
    # HOT:BEGIN(consolidation-fit)
    alive = ~ccols.marked[rows]
    taint_codes = ccols.taint_code[rows]
    gather_cache: "dict[str, object]" = {}

    def _label_lookup(key):
        hit = gather_cache.get(key, False)
        if hit is not False:
            return hit
        kc = ccols.label_cols.get(key)
        out = None if kc is None else (kc.codes[rows], kc.num[rows], kc.vocab)
        gather_cache[key] = out
        return out

    fitvec_cache: "dict[int, np.ndarray]" = {}

    def fit_vector(spec) -> "np.ndarray":
        tok = spec.group_token()
        vec = fitvec_cache.get(tok)
        if vec is None:
            vec = fold_node_mask(spec.requirements, _label_lookup, Ne)
            for code in np.unique(taint_codes):
                taints = ccols.taint_sets[int(code)]
                if taints and not tolerates_all(spec.tolerations, taints):
                    vec = vec & (taint_codes != code)
            vec &= alive
            fitvec_cache[tok] = vec
        return vec
    # HOT:END(consolidation-fit)

    from ..models.encode import kubelet_arrays

    prov_overhead, prov_pods_cap = kubelet_arrays(provs, catalog)
    feas_cache: "dict[tuple, tuple]" = {}
    ex_cap_arr = None  # [C, Gb, Ne] remaining caps; built on first capped group
    # per-origin-key resident counts over ALL nodes, memoized across lanes
    # (the incremental StateNode aggregates make this O(Ne) with no pod scan)
    rc_cache: "dict[object, np.ndarray]" = {}

    def resident_vec(okey) -> "np.ndarray":
        v = rc_cache.get(okey)
        if v is None:
            v = np.fromiter(
                (cluster.nodes[n]._resident_counts.get(okey, 0)
                 for n in all_nodes), dtype=np.int32, count=Ne)
            rc_cache[okey] = v
        return v

    for ci, (cand, total_price, groups) in enumerate(per_cand):
        cheaper_opt = by_price[total_price][0]
        member_idx = [node_index[n.name] for n in cand]
        first_by_origin: "dict[object, int]" = {}
        for gi, g in enumerate(groups):
            group_origin[ci, gi] = first_by_origin.setdefault(
                g.spec.origin_key(), gi)
        for gi, g in enumerate(groups):
            gkey = (g.spec.group_token(), total_price)
            enc = feas_cache.get(gkey)
            if enc is None:
                enc = encode_group(g, provs, grid, cols, overhead,
                                   extra_mask=cheaper_opt,
                                   prov_overhead=prov_overhead,
                                   prov_pods_cap=prov_pods_cap)
                feas_cache[gkey] = enc
            vec, cap, feas, newprov = enc
            group_vec[ci, gi] = vec
            group_count[ci, gi] = g.count
            group_cap[ci, gi] = cap
            ridx = feas_row_index.get(gkey)
            if ridx is None:
                feas_rows.append(feas)
                ridx = feas_row_index[gkey] = len(feas_rows)  # 1-based
            feas_idx[ci, gi] = ridx
            group_newprov[ci, gi] = newprov
            row = ex_feas[ci, gi]
            row[:] = fit_vector(g.spec)
            row[member_idx] = False  # pods must not land back on the set
            if cap < int(INT_BIG):
                # hostname spread/anti-affinity counts pods RESIDENT on the
                # surviving nodes (mirrors encode_problem's ex_cap; the
                # in-run group_counts term is zero here — resident counts
                # come fresh off the node aggregates each sweep). Candidate
                # members keep the raw cap: their pods are the ones being
                # moved, and ex_feas already bars landing back on the set
                if ex_cap_arr is None:
                    ex_cap_arr = np.full((C, Gb, Ne), INT_BIG, dtype=np.int32)
                okey = g.spec.origin_key()
                ex_cap_arr[ci, gi, :] = np.maximum(0, cap - resident_vec(okey))
                ex_cap_arr[ci, gi, member_idx] = cap

    feas_table = np.zeros((1 + len(feas_rows), Pv, T, S), dtype=bool)
    for i, feas in enumerate(feas_rows):
        feas_table[1 + i] = feas
    inputs = PackInputs(
        alloc_t=grid.alloc_t, tiebreak=grid.tiebreak,
        group_vec=group_vec, group_count=group_count, group_cap=group_cap,
        group_feas=None,  # expanded on device from (feas_table, feas_idx)
        group_newprov=group_newprov,
        overhead=np.asarray(overhead, dtype=np.int32),
        # ex_used is IDENTICAL across lanes (a candidate's own nodes are
        # excluded via ex_feas, never via usage), so it rides the shared
        # in_axes=None lane like ex_alloc: at 500 lanes x 500 nodes the old
        # per-lane broadcast shipped ~6MB h2d per sweep — the dominant cost
        # on a ~15MB/s degraded tunnel link (linkprobe_20260730T154547Z)
        ex_alloc=ex_alloc, ex_used=ex_used,
        ex_feas=ex_feas,
        prov_overhead=prov_overhead, prov_pods_cap=prov_pods_cap,
        ex_cap=ex_cap_arr, group_origin=group_origin,
    )
    return ConsolidationBatch(inputs, candidates, provs, grid,
                              feas_table=feas_table, feas_idx=feas_idx)


@functools.partial(jax.jit, static_argnames=("n_slots",))
def _batched_pack(inputs: PackInputs, n_slots: int):
    axes = PackInputs(
        alloc_t=None, tiebreak=None,
        group_vec=0, group_count=0, group_cap=0, group_feas=0, group_newprov=0,
        overhead=None, ex_alloc=None, ex_used=None, ex_feas=0,
        prov_overhead=None, prov_pods_cap=None,  # shared across candidates
        ex_cap=None if inputs.ex_cap is None else 0,
        group_origin=None if inputs.group_origin is None else 0,
    )
    return jax.vmap(lambda inp: pack_impl(inp, n_slots), in_axes=(axes,))(inputs)


def _reduce_verdicts(r):
    """PackResult -> [C, 3] verdict table: (total unschedulable, nodes
    opened, decided option of slot 0). The ONE definition of the column
    contract _decode_actions indexes by position — shared by the dense,
    flat, and sharded dispatch paths."""
    return jax.numpy.stack(
        [r.unsched.sum(axis=1), r.n_open, r.decided[:, 0]], axis=1)


@functools.partial(jax.jit, static_argnames=("n_slots",))
def _batched_pack_verdicts(inputs: PackInputs, n_slots: int,
                           feas_table=None, feas_idx=None):
    """The batched pack reduced ON DEVICE to the [C, 3] verdict table the
    action decoder actually reads: (total unschedulable, nodes opened,
    decided option of slot 0). The full PackResult for C=500 lanes is
    megabytes (assign [C,G,N], ex_assign [C,G,Ne]); over a tunneled device
    every d2h transfer is the latency budget, so the sweep ships ~6KB
    instead (same discipline as packer.pack_flat — one read per dispatch).
    When (feas_table, feas_idx) are given, inputs.group_feas is None and
    the dense [C,Gb,Pv,T,S] feasibility is gathered here on device — the
    h2d direction ships the unique rows only (ConsolidationBatch)."""
    if feas_table is not None:
        inputs = inputs._replace(
            group_feas=jax.numpy.take(feas_table, feas_idx, axis=0))
    return _reduce_verdicts(_batched_pack(inputs, n_slots))


def _note_verdict(capture: "list[dict]", cand, verdict: str,
                  savings: float = 0.0, replacement=None) -> None:
    """One consolidation keep/evict verdict into the explain capture.
    `verdict` must be a reasons.CONSOLIDATION_VERDICTS literal at every
    call site — hack/check_decision_reasons.py lints the lockstep."""
    total_price = sum(n.price for n in cand)
    capture.append({
        "nodes": sorted(n.name for n in cand),
        "verdict": verdict,
        "evict": verdict in ("delete", "replace"),
        "current_price_per_hour": round(total_price, 6),
        "savings_per_hour": round(savings, 6),
        "cost_delta_per_hour": round(-savings, 6),
        "replacement": replacement,
    })


# Per-pass keep/evict verdicts for the deprovisioner's consolidation
# audit record (set by _decode_actions when the explain plane is ON;
# untouched — strict-noop — when it is disabled).
last_verdicts: "list[dict] | None" = None


def _decode_actions(batch: ConsolidationBatch, verdicts, now: float
                    ) -> "list[ConsolidationAction]":
    """verdicts: [C, 3] host array — (unsched_total, n_open, decided0) per
    candidate lane (see _batched_pack_verdicts)."""
    global last_verdicts
    from .. import explain

    capture: "list[dict] | None" = [] if explain.enabled() else None
    actions = []
    for ci, cand in enumerate(batch.candidates):
        if int(verdicts[ci, 0]) > 0:  # any pod unschedulable in this lane
            if capture is not None:
                _note_verdict(capture, cand, "unschedulable-pods")
            continue
        opened = int(verdicts[ci, 1])
        if opened > 1:
            if capture is not None:
                _note_verdict(capture, cand, "opens-more-than-one-node")
            continue
        total_price = sum(n.price for n in cand)
        cost = sum(
            disruption_cost(
                n, next((p for p in batch.provisioners
                         if p.name == n.provisioner_name), None), now)
            for n in cand)
        names = tuple(sorted(n.name for n in cand))
        if opened == 0:
            if capture is not None:
                _note_verdict(capture, cand, "delete", savings=total_price)
            actions.append(ConsolidationAction(
                "delete", names[0], cost, savings=total_price, nodes=names))
            continue
        if any(n.capacity_type == wk.CAPACITY_TYPE_SPOT for n in cand):
            # spot nodes consolidate by DELETION only — replacing with the
            # now-cheapest offering would defeat capacity-optimized spot
            # selection and raise interruption rates (reference
            # website deprovisioning.md:88; mirrored in the oracle's
            # evaluate_candidate_set)
            if capture is not None:
                _note_verdict(capture, cand, "spot-replace-barred")
            continue
        flat = int(verdicts[ci, 2])
        if flat < 0:
            raise AssertionError(
                f"candidate {names}: open claim slot has no surviving option")
        opt = batch.grid.options[flat]
        if opt.price >= total_price - REPLACE_PRICE_EPS:
            if capture is not None:
                _note_verdict(capture, cand, "no-cheaper-option")
            continue
        repl = (opt.itype.name, opt.zone, opt.capacity_type, opt.price)
        if capture is not None:
            _note_verdict(capture, cand, "replace",
                          savings=total_price - opt.price, replacement=repl)
        actions.append(ConsolidationAction(
            "replace", names[0], cost, savings=total_price - opt.price,
            replacement=repl,
            nodes=names))
    if capture is not None:
        last_verdicts = capture
    return actions


# device-resident catalog arrays for grid-less callers (the deprovisioner,
# the capture harness): without this every sweep re-shipped alloc_t/tiebreak
# host->device. Keyed on the grid OBJECT (weakref — numpy arrays are not
# weakref-able) + seqnum; a dead ref is a miss, never an aliasing hazard.
_dev_grid_memo: "tuple | None" = None  # (weakref(grid), seqnum, dev_alloc, dev_tb)


def _dev_grid_arrays(grid: OptionGrid):
    global _dev_grid_memo
    m = _dev_grid_memo
    if m is not None and m[0]() is grid and m[1] == grid.seqnum:
        return m[2], m[3]
    dev_alloc = jax.device_put(grid.alloc_t)
    dev_tb = jax.device_put(grid.tiebreak)
    _dev_grid_memo = (_weakref.ref(grid), grid.seqnum, dev_alloc, dev_tb)
    return dev_alloc, dev_tb


def _flatten_batch(batch: ConsolidationBatch):
    """Host-side pack of every DYNAMIC leaf into two contiguous buffers
    (one i32, one u8): on the tunneled device each host->device transfer is
    a per-OPERATION cost (solver-boundary.md cost model — the round-4
    on-chip sweep paid ~16 per-leaf puts), so the sweep ships exactly two
    arrays however many leaves the problem has. The static catalog arrays
    (alloc_t/tiebreak) stay device-resident via _dev_grid_arrays.

    Returns (i32_buf, u8_buf, dims) where dims is the static shape tuple
    _verdicts_flat uses to slice the buffers back apart at trace time."""
    inp = batch.inputs
    C, Gb, R = inp.group_vec.shape
    Ne = inp.ex_alloc.shape[0]
    U = batch.feas_table.shape[0]
    Pv, T, S = batch.feas_table.shape[1:]
    i32_parts = [inp.group_vec, inp.group_count, inp.group_cap,
                 inp.group_newprov, inp.group_origin, inp.overhead,
                 inp.ex_alloc, inp.ex_used, batch.feas_idx]
    if inp.ex_cap is not None:
        i32_parts.append(inp.ex_cap)
    if inp.prov_overhead is not None:
        i32_parts.append(inp.prov_overhead)
    if inp.prov_pods_cap is not None:
        i32_parts.append(inp.prov_pods_cap)
    i32 = np.concatenate(
        [np.ascontiguousarray(a, dtype=np.int32).ravel() for a in i32_parts])
    u8 = np.concatenate(
        [np.ascontiguousarray(inp.ex_feas, dtype=np.uint8).ravel(),
         np.ascontiguousarray(batch.feas_table, dtype=np.uint8).ravel()])
    dims = (C, Gb, R, Ne, U, Pv, T, S,
            inp.ex_cap is not None, inp.prov_overhead is not None,
            inp.prov_pods_cap is not None)
    return i32, u8, dims


@functools.partial(jax.jit, static_argnames=("dims", "n_slots"))
def _verdicts_flat(i32, u8, alloc_t, tiebreak, dims, n_slots):
    """Device-side unpack of _flatten_batch's two buffers + the batched
    pack reduced to the [C, 3] verdict table. Slicing/reshaping is trace
    time bookkeeping (XLA sees static offsets); the whole sweep is ONE
    h2d-light dispatch and one 12-byte-per-lane read."""
    import jax.numpy as jnp

    (C, Gb, R, Ne, U, Pv, T, S, has_excap, has_povh, has_pcap) = dims
    o = [0]

    def take(n, shape):
        part = i32[o[0]:o[0] + n]  # static offsets: resolved at trace time
        o[0] += n
        return part.reshape(shape)

    group_vec = take(C * Gb * R, (C, Gb, R))
    group_count = take(C * Gb, (C, Gb))
    group_cap = take(C * Gb, (C, Gb))
    group_newprov = take(C * Gb, (C, Gb))
    group_origin = take(C * Gb, (C, Gb))
    overhead = take(R, (R,))
    ex_alloc = take(Ne * R, (Ne, R))
    ex_used = take(Ne * R, (Ne, R))
    feas_idx = take(C * Gb, (C, Gb))
    ex_cap = take(C * Gb * Ne, (C, Gb, Ne)) if has_excap else None
    prov_overhead = take(Pv * R, (Pv, R)) if has_povh else None
    prov_pods_cap = take(Pv * T, (Pv, T)) if has_pcap else None
    # trace-time drift guard: a new array added to _flatten_batch without
    # the matching take() here would otherwise read shifted garbage that
    # still reshapes cleanly — fail loudly instead
    assert o[0] == i32.shape[0], (
        f"i32 layout drift: consumed {o[0]} of {i32.shape[0]}")
    assert u8.shape[0] == C * Gb * Ne + U * Pv * T * S, (
        f"u8 layout drift: {u8.shape[0]} != {C * Gb * Ne + U * Pv * T * S}")
    ex_feas = u8[:C * Gb * Ne].reshape(C, Gb, Ne).astype(bool)
    feas_table = u8[C * Gb * Ne:].reshape(U, Pv, T, S).astype(bool)
    inputs = PackInputs(
        alloc_t=alloc_t, tiebreak=tiebreak,
        group_vec=group_vec, group_count=group_count, group_cap=group_cap,
        group_feas=jnp.take(feas_table, feas_idx, axis=0),
        group_newprov=group_newprov, overhead=overhead,
        ex_alloc=ex_alloc, ex_used=ex_used, ex_feas=ex_feas,
        prov_overhead=prov_overhead, prov_pods_cap=prov_pods_cap,
        ex_cap=ex_cap, group_origin=group_origin,
    )
    return _reduce_verdicts(_batched_pack(inputs, n_slots))


def _verdicts(batch: ConsolidationBatch, mesh, timings: "dict | None" = None):
    """Single-device dispatch, or candidate lanes sharded over a mesh
    (pure data parallelism — see parallel/sharded.py make_lane_mesh)."""
    if mesh is not None:
        from ..parallel.sharded import sharded_consolidation_verdicts

        return sharded_consolidation_verdicts(
            batch.inputs, N_SLOTS, mesh,
            feas_table=batch.feas_table, feas_idx=batch.feas_idx)
    from ..solver.core import host_fetch  # honors --readback callback

    t0 = _time.perf_counter()
    i32, u8, dims = _flatten_batch(batch)
    dev_alloc, dev_tb = _dev_grid_arrays(batch.grid)
    t1 = _time.perf_counter()
    dev_i32 = jax.device_put(i32)
    dev_u8 = jax.device_put(u8)
    t2 = _time.perf_counter()
    flat = _verdicts_flat(dev_i32, dev_u8, dev_alloc, dev_tb, dims, N_SLOTS)
    t3 = _time.perf_counter()
    out = host_fetch(flat)
    if timings is not None:
        t4 = _time.perf_counter()
        timings.update({
            "flatten_ms": round((t1 - t0) * 1000, 3),
            "put_ms": round((t2 - t1) * 1000, 3),
            "dispatch_ms": round((t3 - t2) * 1000, 3),
            "fetch_ms": round((t4 - t3) * 1000, 3),
        })
    return out


def run_consolidation(
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
    grid: Optional[OptionGrid] = None,
    multi_node: bool = True,
    max_pair_candidates: int = MAX_PAIR_CANDIDATES,
    candidate_filter=None,
    mesh=None,
    cand_nodes: "Optional[Sequence[StateNode]]" = None,
) -> Optional[ConsolidationAction]:
    """Batched equivalent of the oracle search (bit-parity tested).

    Mechanism order matches the reference (deprovisioning.md:74-77,
    v0.24.0): MULTI-NODE pairs decide before single-node — a bigger win
    shadows a smaller one. Pair lanes and single lanes ride ONE combined
    dispatch (one device round trip — the unit a tunneled link charges);
    mechanism precedence is applied to the decoded verdicts instead of
    sequencing two dispatches. `cand_nodes` reuses an eligibility sweep
    already done (the controller's dirty-driven candidate list)."""
    global last_timings
    t0 = _time.perf_counter()
    provs_sorted = sorted(provisioners, key=lambda p: (-p.weight, p.name))
    if cand_nodes is None:
        cand_nodes = [cluster.nodes[name] for name in sorted(cluster.nodes)
                      if eligible(cluster.nodes[name], cluster)
                      and (candidate_filter is None
                           or candidate_filter(cluster.nodes[name]))]
    else:
        cand_nodes = list(cand_nodes)
    if not cand_nodes:
        return None
    sets: "list[tuple]" = [(n,) for n in cand_nodes]
    if multi_node:
        sets = candidate_pairs(cluster, provs_sorted, now,
                               max_pair_candidates, nodes=cand_nodes) + sets
    batch = encode_consolidation(cluster, catalog, provisioners,
                                 daemon_overhead, grid, cand_sets=sets)
    if batch is None:
        return None
    # timings always collected now: the tracing plane records the phase
    # split + lane count on the active consolidation span; last_timings
    # stays gated behind the capture tool's flag as before
    timings: dict = {}
    t1 = _time.perf_counter()
    verdicts = _verdicts(batch, mesh, timings=timings)
    t2 = _time.perf_counter()
    actions = _decode_actions(batch, verdicts, now)
    timings["encode_ms"] = round((t1 - t0) * 1000, 3)
    timings["verdicts_ms"] = round((t2 - t1) * 1000, 3)
    timings["decode_ms"] = round((_time.perf_counter() - t2) * 1000, 3)
    timings["lanes"] = len(batch.candidates)
    from ..tracing import TRACER

    TRACER.annotate(transfer_ms=timings.get("fetch_ms", 0.0), **timings)
    if _SOLVE_TIMING:
        last_timings = timings
    if not actions:
        return None
    multi_actions = [a for a in actions if len(a.nodes) > 1]
    return min(multi_actions or actions, key=ConsolidationAction.sort_key)


STREAM_LANES_ENV = "KARPENTER_TPU_CONSOLIDATE_STREAM_LANES"
# 128 lanes/chunk: the width sweep on the 996-lane 500-node sweep bottoms
# out here (32/64/96/128 -> 219/195/175/158 ms p50 on the 1-core CPU
# ladder host) — wide enough to amortize per-dispatch overhead, small
# enough that the working set stays chunk-sized; fewer dispatches also
# means fewer per-operation charges on the tunneled device link
DEFAULT_STREAM_LANES = 128


class _TypePrunedGrid:
    """Type-axis subset view of an OptionGrid for the streamed sweep's
    dispatch+decode: every feasibility row is already ANDed with the
    cheaper-option mask at encode (encode_group extra_mask), so types not
    cheaper than ANY candidate set's price carry all-False feasibility in
    every lane and can never be decided — slicing them off the [T, S] axis
    shrinks the pack kernel's option scan without changing any verdict.
    Exposes exactly what _dev_grid_arrays (alloc_t/tiebreak/seqnum) and
    _decode_actions (options[flat]) read; `flat` indexes PRUNED coords, so
    the options list is re-laid-out to match. Tiebreak ranks are a subset
    of the full grid's total order — relative rank among survivors is
    preserved, so min-rank picks the same option."""

    def __init__(self, grid: OptionGrid, keep_idx: np.ndarray):
        S = grid.S
        self.alloc_t = np.ascontiguousarray(grid.alloc_t[keep_idx])
        self.tiebreak = np.ascontiguousarray(grid.tiebreak[keep_idx])
        self.seqnum = grid.seqnum
        self.options = [grid.options[int(t) * S + s]
                        for t in keep_idx for s in range(S)]


def stream_lanes() -> int:
    raw = _os.environ.get(STREAM_LANES_ENV)
    if raw is None:
        return DEFAULT_STREAM_LANES
    try:
        v = int(raw)
        return v if v > 0 else DEFAULT_STREAM_LANES
    except ValueError:
        return DEFAULT_STREAM_LANES


def stream_consolidation(
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
    grid: Optional[OptionGrid] = None,
    multi_node: bool = True,
    max_pair_candidates: int = MAX_PAIR_CANDIDATES,
    candidate_filter=None,
    mesh=None,
    cand_nodes: "Optional[Sequence[StateNode]]" = None,
    batch_lanes: "Optional[int]" = None,
) -> Optional[ConsolidationAction]:
    """run_consolidation, streamed: the same candidate sets in the same
    order, encoded and dispatched as fixed-width chunks of `batch_lanes`
    lanes instead of one C-lane mega-batch. The one-shot 500-node sweep
    flattens+uploads a [C,Gb,Ne] problem in one go (~1.7 s at C=500);
    chunking keeps the per-dispatch working set small and constant-shaped
    — the last chunk is PADDED by repeating its final set so every chunk
    reuses one compiled program — while decode still sees every lane, and
    mechanism precedence (multi-node shadows single) plus min-cost
    selection are applied over the FULL action list, so the chosen action
    is identical to the mega-batch's."""
    global last_timings
    t0 = _time.perf_counter()
    provs_sorted = sorted(provisioners, key=lambda p: (-p.weight, p.name))
    if cand_nodes is None:
        cand_nodes = [cluster.nodes[name] for name in sorted(cluster.nodes)
                      if eligible(cluster.nodes[name], cluster)
                      and (candidate_filter is None
                           or candidate_filter(cluster.nodes[name]))]
    else:
        cand_nodes = list(cand_nodes)
    if not cand_nodes:
        return None
    sets: "list[tuple]" = [(n,) for n in cand_nodes]
    if multi_node:
        sets = candidate_pairs(cluster, provs_sorted, now,
                               max_pair_candidates, nodes=cand_nodes) + sets
    width = batch_lanes if batch_lanes is not None else stream_lanes()
    # type-axis prune, ONE shape for the whole call: types not cheaper
    # (after availability) than the PRICIEST candidate set can't be a
    # replacement for any lane — their feasibility rows are all-False by
    # the encode-time cheaper mask, so slicing them shrinks the option
    # scan with provably identical verdicts (see _TypePrunedGrid)
    full_grid = _grid_for(catalog, grid)
    max_price = max(sum(n.price for n in s) for s in sets)
    cheap_any = (full_grid.price < (max_price - REPLACE_PRICE_EPS)) \
        & full_grid.valid
    keep_t = cheap_any.any(axis=1)
    keep_idx = np.nonzero(keep_t)[0]
    pruned = (_TypePrunedGrid(full_grid, keep_idx)
              if 0 < len(keep_idx) < full_grid.T else None)
    timings: dict = {"encode_ms": 0.0, "verdicts_ms": 0.0, "decode_ms": 0.0}
    actions: "list[ConsolidationAction]" = []
    chunks = 0
    for start in range(0, len(sets), width):
        chunk = sets[start:start + width]
        live = len(chunk)
        if len(chunk) < width and chunks > 0:
            # pad to the compiled width (duplicate verdicts are dropped
            # below); a single undersized chunk (C <= width) just runs
            # at its natural size — nothing to reuse a program with
            chunk = chunk + [chunk[-1]] * (width - len(chunk))
        tc0 = _time.perf_counter()
        batch = encode_consolidation(cluster, catalog, provisioners,
                                     daemon_overhead, full_grid,
                                     cand_sets=chunk)
        tc1 = _time.perf_counter()
        if batch is None:
            continue
        if pruned is not None \
                and not batch.feas_table[:, :, ~keep_t, :].any():
            # safety net: a feasible bit on a pruned type (can't happen
            # while encode applies the cheaper mask) dispatches this
            # chunk on the full grid instead of silently mis-decoding
            batch.feas_table = np.ascontiguousarray(
                batch.feas_table[:, :, keep_t, :])
            batch.inputs = batch.inputs._replace(
                alloc_t=pruned.alloc_t, tiebreak=pruned.tiebreak)
            batch.grid = pruned
        verdicts = _verdicts(batch, mesh)
        tc2 = _time.perf_counter()
        # decode walks batch.candidates by lane index: truncating to the
        # live prefix skips the padded lanes' (duplicate) verdict rows
        batch.candidates = batch.candidates[:live]
        actions.extend(_decode_actions(batch, verdicts, now))
        timings["encode_ms"] += tc1 - tc0
        timings["verdicts_ms"] += tc2 - tc1
        timings["decode_ms"] += _time.perf_counter() - tc2
        chunks += 1
    timings = {k: round(v * 1000, 3) for k, v in timings.items()}
    timings["lanes"] = len(sets)
    timings["chunks"] = chunks
    timings["stream_width"] = width
    timings["total_ms"] = round((_time.perf_counter() - t0) * 1000, 3)
    from ..tracing import TRACER

    TRACER.annotate(streamed=True, **timings)
    if _SOLVE_TIMING:
        last_timings = timings
    if not actions:
        return None
    multi_actions = [a for a in actions if len(a.nodes) > 1]
    return min(multi_actions or actions, key=ConsolidationAction.sort_key)
