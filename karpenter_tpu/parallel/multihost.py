"""Multi-host scale-out: DCN x ICI hybrid meshes for the solver.

Parity/architecture target: the reference's scale story is a single Go
process; this build's distributed backend is XLA collectives over ICI within
a slice and DCN across hosts (SURVEY.md §5.8, §2.3 "communication backend
#3"), driven by `jax.distributed` + GSPMD — never hand-written sends.

Axis placement follows the scaling-book recipe applied to this workload:
- the NODES axis is data-parallel-like: per-slot state with one exclusive
  cumsum per scan step — cheap, latency-tolerant collectives that can ride
  **DCN** across hosts;
- the TYPES axis is tensor-parallel-like: per-step masked argmax/min
  all-reduces over the option grid — bandwidth-sensitive, so it stays on
  **ICI** within a slice.

Single-host processes (tests, the laptop CLI) fall back to the plain ICI
mesh from parallel/sharded.py — call sites never branch.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .sharded import AXIS_NODES, AXIS_TYPES, make_mesh

log = logging.getLogger("karpenter.multihost")


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """jax.distributed bootstrap. Arguments default from the standard env
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID or the
    TPU pod metadata jax discovers on its own). Returns True when running
    multi-process afterwards; safe to call when single-process."""
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None and num_processes is None:
        # nothing configured: single-process mode (or TPU-pod auto-detect
        # already done by the runtime)
        return jax.process_count() > 1
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:  # already initialized is fine
        log.info("distributed init skipped: %s", e)
    return jax.process_count() > 1


def make_hybrid_mesh(types_dim: Optional[int] = None) -> Mesh:
    """(nodes, types) mesh whose nodes axis spans hosts over DCN and whose
    types axis stays inside each host's ICI domain.

    Multi-process: mesh_utils.create_hybrid_device_mesh builds a
    DCN-outermost device order, so sharding the leading nodes axis places
    the inter-host hops on the latency-tolerant collectives. Single-process:
    identical to parallel.sharded.make_mesh."""
    n_proc = jax.process_count()
    if n_proc <= 1:
        return make_mesh()
    local = jax.local_device_count()
    if types_dim is None:
        types_dim = 2 if local % 2 == 0 and local >= 2 else 1
    nodes_local = local // types_dim
    all_devices = jax.devices()
    slices = {getattr(d, "slice_index", None) for d in all_devices}
    if None not in slices and len(slices) == n_proc:
        # TPU pods (one real DCN slice per process): let jax order by the
        # actual slice topology
        from jax.experimental import mesh_utils

        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(nodes_local, types_dim),
            dcn_mesh_shape=(n_proc, 1),
        )
    else:
        # no slice metadata (CPU multi-process, some GPU setups): build the
        # DCN-outermost order by process — each host's block is contiguous
        # on the nodes axis, so inter-host hops ride the latency-tolerant
        # axis exactly as on a pod
        by_proc: "dict[int, list]" = {}
        for d in all_devices:
            by_proc.setdefault(d.process_index, []).append(d)
        rows = [np.array(by_proc[pi]).reshape(nodes_local, types_dim)
                for pi in sorted(by_proc)]
        devices = np.concatenate(rows, axis=0)
    assert devices.shape == (nodes_local * n_proc, types_dim)
    return Mesh(devices, (AXIS_NODES, AXIS_TYPES))


def mesh_description(mesh: Mesh) -> dict:
    """Telemetry-friendly summary (which axes cross hosts)."""
    dev = np.asarray(mesh.devices)
    procs_by_row = [
        len({d.process_index for d in dev[i].flat if hasattr(d, "process_index")})
        for i in range(dev.shape[0])
    ] if dev.ndim == 2 else []
    nodes_procs = len({d.process_index for d in dev.flat
                       if hasattr(d, "process_index")})
    return {
        "axes": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "n_devices": int(dev.size),
        "n_processes": jax.process_count(),
        "types_axis_crosses_hosts": any(p > 1 for p in procs_by_row),
        # the nodes axis SHOULD span every process (DCN-outermost layout)
        "nodes_axis_spans_processes": nodes_procs == jax.process_count(),
    }
