"""Multi-chip scale-out for the packer kernel.

Parity target: the reference's scale story is single-process Go with
request-batching (SURVEY.md §2.3); this module is the NEW capability the TPU
build adds — `pjit`-sharded solving of the 50k-pod x 1k-offering stress config
(BASELINE.json configs[4]) across an ICI mesh.

Mesh axes and their classic-parallelism analogues for this workload:
- "nodes": node-claim slots sharded like DATA parallelism — each device owns a
  slice of the bin (node) population; the first-fit waterfall's exclusive
  cumsum becomes a cross-device prefix sum XLA lowers onto ICI.
- "types": the instance-type axis sharded like TENSOR parallelism — the
  [N, T, S] option-mask state and the [N, T] capacity quotients are computed
  shard-local; qmax/kstar argmax-style reductions become all-reduces.
- the group scan is the sequential (pipeline-like) axis; groups are inherently
  order-dependent under FFD, so they stay unsharded — the reference has the
  same sequential dependence (designs/bin-packing.md step 4).

GSPMD inserts all collectives: we only annotate input/state shardings
(scaling-book recipe: pick a mesh, annotate, let XLA do the rest).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.packer import (INT_BIG, PackInputs, PackResult, flatten_result,
                          pack_impl)

AXIS_NODES = "nodes"
AXIS_TYPES = "types"


def pad_types(inputs: PackInputs, multiple: int) -> PackInputs:
    """Pad the instance-type axis to a multiple of the mesh's type dimension
    with never-selectable entries: zero capacity, INT_BIG tiebreak, infeasible
    everywhere. Transparent to consumers — `decided` flat ids are t*S+s with S
    unchanged, so real types keep their ids."""
    T = inputs.alloc_t.shape[0]
    Tp = -(-T // multiple) * multiple
    if Tp == T:
        return inputs
    pad_n = Tp - T

    def pad(a, axis, value):
        a = np.asarray(a)
        w = [(0, 0)] * a.ndim
        w[axis] = (0, pad_n)
        return np.pad(a, w, constant_values=value)

    out = inputs._replace(
        alloc_t=pad(inputs.alloc_t, 0, 0),
        tiebreak=pad(inputs.tiebreak, 0, int(INT_BIG)),
        group_feas=pad(inputs.group_feas, 2, False),
    )
    if inputs.prov_pods_cap is not None:
        out = out._replace(prov_pods_cap=pad(inputs.prov_pods_cap, 1, 0))
    return out


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    types_dim = 2 if n % 2 == 0 and n >= 2 else 1
    nodes_dim = n // types_dim
    return Mesh(np.array(devs).reshape(nodes_dim, types_dim), (AXIS_NODES, AXIS_TYPES))


def input_shardings(mesh: Mesh) -> PackInputs:
    """PartitionSpecs per input leaf: catalog arrays sharded over types,
    group masks over types, small per-group vectors replicated."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))
    return PackInputs(
        alloc_t=s(AXIS_TYPES, None),
        tiebreak=s(AXIS_TYPES, None),
        group_vec=s(), group_count=s(), group_cap=s(),
        group_feas=s(None, None, AXIS_TYPES, None),
        group_newprov=s(), overhead=s(),
        ex_alloc=s(), ex_used=s(), ex_feas=s(),
        prov_overhead=s(), prov_pods_cap=s(None, AXIS_TYPES),
        ex_cap=s(), group_origin=s(),
        res_sel=s(), res_mask=s(),
    )


def _constrained_pack(inputs: PackInputs, n_slots: int, mesh: Mesh) -> PackResult:
    """pack_impl under the mesh: the [N, T, S] scan-carry sharding comes from
    GSPMD propagation off the type-sharded inputs; we pin only the [N, R]
    `used` output to the nodes axis to anchor the node dimension."""
    result = pack_impl(inputs, n_slots)
    used = jax.lax.with_sharding_constraint(result.used, NamedSharding(mesh, P(AXIS_NODES, None)))
    return result._replace(used=used)


def sharded_pack(inputs: PackInputs, n_slots: int, mesh: Mesh) -> PackResult:
    """Run the packer SPMD over `mesh`. Bit-identical to single-device pack
    (tests/test_sharded.py)."""
    inputs = pad_types(inputs, mesh.shape[AXIS_TYPES])
    shardings = input_shardings(mesh)
    if inputs.prov_overhead is None:
        shardings = shardings._replace(prov_overhead=None, prov_pods_cap=None)
    if inputs.ex_cap is None:
        shardings = shardings._replace(ex_cap=None)
    if inputs.group_origin is None:
        shardings = shardings._replace(group_origin=None)
    if inputs.res_sel is None:
        shardings = shardings._replace(res_sel=None, res_mask=None)
    inputs = jax.tree.map(
        lambda a, sh: jax.device_put(jax.numpy.asarray(a), sh), inputs, shardings
    )
    fn = jax.jit(
        _constrained_pack,
        static_argnames=("n_slots", "mesh"),
        in_shardings=(shardings,),
    )
    with mesh:
        return fn(inputs, n_slots, mesh)


# -- flat serving path (persistent mesh, resident catalog) --------------------------
#
# sharded_pack above ships everything (catalog included) per call — right for
# dryrun_multichip's one-shot parity run, wrong for a serving loop. The
# serving path splits the argument tree the same way core.py's single-chip
# resident dispatch does: the type-sharded catalog arrays live on the mesh
# across solves (uploaded once per synced grid), only the per-solve delta
# crosses the boundary, and the result comes back as pack_flat's single i32
# buffer so the wire service still pays exactly one device->host read.


def pad_types_catalog(alloc_t, tiebreak, multiple: int):
    """pad_types' catalog half, standalone: the serving path pads + uploads
    these ONCE per synced grid (never-selectable rows: zero capacity,
    INT_BIG tiebreak)."""
    T = alloc_t.shape[0]
    Tp = -(-T // multiple) * multiple
    if Tp == T:
        return np.asarray(alloc_t), np.asarray(tiebreak)
    pad_n = Tp - T
    alloc_t = np.pad(np.asarray(alloc_t), [(0, pad_n), (0, 0)],
                     constant_values=0)
    tiebreak = np.pad(np.asarray(tiebreak), [(0, pad_n), (0, 0)],
                      constant_values=int(INT_BIG))
    return alloc_t, tiebreak


def pad_types_delta(inputs: PackInputs, multiple: int) -> PackInputs:
    """pad_types' per-solve half: the type axis of the delta leaves
    (group_feas, prov_pods_cap) padded infeasible/zero to the mesh
    multiple. alloc_t/tiebreak are expected absent (resident)."""
    T = inputs.group_feas.shape[2]
    Tp = -(-T // multiple) * multiple
    if Tp == T:
        return inputs
    pad_n = Tp - T

    def pad(a, axis, value):
        a = np.asarray(a)
        w = [(0, 0)] * a.ndim
        w[axis] = (0, pad_n)
        return np.pad(a, w, constant_values=value)

    out = inputs._replace(group_feas=pad(inputs.group_feas, 2, False))
    if inputs.prov_pods_cap is not None:
        out = out._replace(prov_pods_cap=pad(inputs.prov_pods_cap, 1, 0))
    return out


def delta_shardings(mesh: Mesh, delta: PackInputs) -> PackInputs:
    """Shardings for the per-solve delta tree (None exactly where the delta
    has None leaves, so tree.map lines up): type-axis leaves shard over
    AXIS_TYPES, the small per-group/existing leaves replicate."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))
    return PackInputs(
        alloc_t=None, tiebreak=None,
        group_vec=s(), group_count=s(), group_cap=s(),
        group_feas=s(None, None, AXIS_TYPES, None),
        group_newprov=s(), overhead=s(),
        ex_alloc=s(), ex_used=s(), ex_feas=s(),
        prov_overhead=None if delta.prov_overhead is None else s(),
        prov_pods_cap=(None if delta.prov_pods_cap is None
                       else s(None, AXIS_TYPES)),
        ex_cap=None if delta.ex_cap is None else s(),
        group_origin=None if delta.group_origin is None else s(),
        res_sel=None if delta.res_sel is None else s(),
        res_mask=None if delta.res_mask is None else s(),
    )


# donate=True variants donate the DELTA argument only (argnums=1): the
# resident catalog tuple at argnums=0 must never be donated or the buffers
# the next solve depends on would be invalidated. Donation is skipped on
# backends that don't implement it (cpu) — core._donate_deltas() decides.
_FLAT_FNS: "dict[bool, object]" = {}
_FLAT_FNS_LOCK = threading.Lock()


def _sharded_flat_fn(donate: bool):
    with _FLAT_FNS_LOCK:
        fn = _FLAT_FNS.get(donate)
        if fn is not None:
            return fn

        def impl(cat, delta, n_slots, use_pallas, mesh):
            inputs = delta._replace(alloc_t=cat[0], tiebreak=cat[1])
            r = pack_impl(inputs, n_slots, use_pallas=use_pallas)
            pin = lambda a, *spec: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(*spec)))
            # anchor the node axis on outputs that survive into the flat
            # buffer (pack_flat drops `used`, so pinning only `used` as
            # _constrained_pack does would be dead code here)
            r = r._replace(assign=pin(r.assign, None, AXIS_NODES),
                           active=pin(r.active, AXIS_NODES),
                           nprov=pin(r.nprov, AXIS_NODES),
                           decided=pin(r.decided, AXIS_NODES))
            return flatten_result(r)

        kwargs = {"static_argnames": ("n_slots", "use_pallas", "mesh")}
        if donate:
            kwargs["donate_argnums"] = (1,)
        fn = jax.jit(impl, **kwargs)
        _FLAT_FNS[donate] = fn
        return fn


def sharded_flat_cache_size() -> int:
    """Compiled-program count of the mesh flat variants (joins
    core._dispatch_cache_size so sharded compiles show up in the
    compile_cache hit/miss attribute too). -1 when introspection is
    unavailable."""
    n = 0
    with _FLAT_FNS_LOCK:
        fns = list(_FLAT_FNS.values())
    for fn in fns:
        try:
            n += fn._cache_size()
        except Exception:
            return -1
    return n


class ShardedContext:
    """Process-lifetime device context for the serving path: ONE mesh (and
    its 1-D lane-mesh view for consolidation), built when the service
    starts syncing, plus the type-sharded resident catalog arrays per
    synced grid. TPUSolver calls dispatch_flat when its router picks the
    mesh kernel; everything stateful about multi-chip serving lives here
    so solver instances stay cheap to build per synced catalog."""

    RESIDENT_CAPACITY = 4  # matches SolverService.LRU_CAPACITY

    def __init__(self, devices=None, n_devices: "Optional[int]" = None):
        devs = list(devices) if devices is not None else jax.devices()
        if n_devices is not None:
            devs = devs[:n_devices]
        self.devices = devs
        self.mesh = make_mesh(devices=devs)
        self.lane_mesh = make_lane_mesh(devices=devs)
        self._lock = threading.Lock()
        # id(grid.alloc_t) -> (dev_alloc_t, dev_tiebreak), insertion = LRU
        self._resident: "dict[int, tuple]" = {}

    @property
    def device_count(self) -> int:
        return int(self.mesh.devices.size)

    def describe(self) -> str:
        return (f"{AXIS_NODES}={self.mesh.shape[AXIS_NODES]}"
                f"x{AXIS_TYPES}={self.mesh.shape[AXIS_TYPES]}")

    def catalog_arrays(self, grid) -> "tuple":
        """Type-sharded resident (alloc_t, tiebreak) for a grid, uploaded
        on first use and served from residency after (the upload counters
        prove it: repeat Solves add zero catalog uploads)."""
        from ..solver.buckets import tracked_device_put

        key = id(grid.alloc_t)
        with self._lock:
            hit = self._resident.get(key)
            if hit is not None:
                return hit
        tm = self.mesh.shape[AXIS_TYPES]
        alloc_t, tiebreak = pad_types_catalog(grid.alloc_t, grid.tiebreak, tm)
        sh = NamedSharding(self.mesh, P(AXIS_TYPES, None))
        cat = (tracked_device_put(alloc_t, "catalog", sh),
               tracked_device_put(tiebreak, "catalog", sh))
        with self._lock:
            self._resident[key] = cat
            while len(self._resident) > self.RESIDENT_CAPACITY:
                self._resident.pop(next(iter(self._resident)))
        return cat

    def dispatch_flat(self, inputs: PackInputs, n_slots: int,
                      use_pallas: "bool | None", grid,
                      donate: bool = False):
        """Enqueue one solve on the mesh; returns the flat device buffer
        (bit-identical layout to single-chip pack_flat — fetch_pack
        decodes both). No device read happens here."""
        from ..solver.buckets import tracked_tree_put

        cat = self.catalog_arrays(grid)
        tm = self.mesh.shape[AXIS_TYPES]
        delta = pad_types_delta(
            inputs._replace(alloc_t=None, tiebreak=None), tm)
        delta = tracked_tree_put(delta, "delta",
                                 delta_shardings(self.mesh, delta))
        fn = _sharded_flat_fn(donate)
        with self.mesh:
            return fn(cat, delta, n_slots, use_pallas, self.mesh)


# -- consolidation lanes ------------------------------------------------------------

AXIS_LANES = "lanes"


def make_lane_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh for the consolidation sweep: candidate lanes are mutually
    independent simulations, so the batch shards like DATA parallelism —
    every device owns C/n lanes and no collective crosses lanes at all
    (the cheapest possible scale-out; contrast the pack mesh above where
    the type axis all-reduces)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS_LANES,))


def _pad_lanes(inputs: PackInputs, multiple: int) -> "tuple[PackInputs, int]":
    """Pad the leading candidate axis to a device multiple with NO-OP lanes
    (zero pod counts, infeasible everywhere): they place nothing, open
    nothing, and the caller slices verdicts back to the true lane count."""
    C = inputs.group_vec.shape[0]
    Cp = -(-C // multiple) * multiple
    if Cp == C:
        return inputs, C
    pad_n = Cp - C

    def pad(a, value=0):
        a = np.asarray(a)
        w = [(0, 0)] * a.ndim
        w[0] = (0, pad_n)
        return np.pad(a, w, constant_values=value)

    out = inputs._replace(
        group_vec=pad(inputs.group_vec), group_count=pad(inputs.group_count),
        group_cap=pad(inputs.group_cap, int(INT_BIG)),
        group_newprov=pad(inputs.group_newprov, -1),
        ex_feas=pad(inputs.ex_feas, False),
    )
    if inputs.group_feas is not None:  # None when a feas table+idx rides along
        out = out._replace(group_feas=pad(inputs.group_feas, False))
    if inputs.ex_cap is not None:
        out = out._replace(ex_cap=pad(inputs.ex_cap, int(INT_BIG)))
    if inputs.group_origin is not None:
        out = out._replace(group_origin=pad(inputs.group_origin))
    return out, C


def sharded_consolidation_verdicts(inputs: PackInputs, n_slots: int,
                                   mesh: Mesh, feas_table=None,
                                   feas_idx=None) -> np.ndarray:
    """The [C, 3] verdict table of ops.consolidate._batched_pack_verdicts,
    with candidate lanes sharded across `mesh`. Bit-identical to the
    single-device sweep (tests/test_sharded.py). When a unique-row
    feasibility table rides along (inputs.group_feas is None), the table
    replicates and only the per-lane indices shard — the dense expansion
    happens device-side inside the jitted verdicts fn."""
    from ..ops.consolidate import _batched_pack_verdicts

    n = mesh.devices.size
    inputs, C = _pad_lanes(inputs, n)
    lane = lambda *rest: NamedSharding(mesh, P(AXIS_LANES, *rest))
    rep = NamedSharding(mesh, P())
    if feas_idx is not None:
        Cp = inputs.group_vec.shape[0]
        if feas_idx.shape[0] != Cp:  # pad lanes -> all-False row 0
            feas_idx = np.pad(feas_idx,
                              [(0, Cp - feas_idx.shape[0]), (0, 0)])
    shardings = PackInputs(
        alloc_t=rep, tiebreak=rep,
        group_vec=lane(), group_count=lane(), group_cap=lane(),
        group_feas=None if inputs.group_feas is None else lane(),
        group_newprov=lane(), overhead=rep,
        ex_alloc=rep, ex_used=rep, ex_feas=lane(),  # ex_used: shared, no lane axis
        prov_overhead=None if inputs.prov_overhead is None else rep,
        prov_pods_cap=None if inputs.prov_pods_cap is None else rep,
        ex_cap=None if inputs.ex_cap is None else lane(),
        group_origin=None if inputs.group_origin is None else lane(),
    )
    dev_inputs = jax.tree.map(
        lambda a, sh: jax.device_put(jax.numpy.asarray(a), sh),
        inputs, shardings)
    if feas_table is not None:
        feas_table = jax.device_put(jax.numpy.asarray(feas_table), rep)
        feas_idx = jax.device_put(jax.numpy.asarray(feas_idx), lane())
    with mesh:  # _batched_pack_verdicts is already jitted at definition
        verdicts = _batched_pack_verdicts(dev_inputs, n_slots,
                                          feas_table=feas_table,
                                          feas_idx=feas_idx)
    from ..solver.core import host_fetch  # honors --readback callback

    return host_fetch(verdicts)[:C]
