"""Multi-chip scale-out for the packer kernel.

Parity target: the reference's scale story is single-process Go with
request-batching (SURVEY.md §2.3); this module is the NEW capability the TPU
build adds — `pjit`-sharded solving of the 50k-pod x 1k-offering stress config
(BASELINE.json configs[4]) across an ICI mesh.

Mesh axes and their classic-parallelism analogues for this workload:
- "nodes": node-claim slots sharded like DATA parallelism — each device owns a
  slice of the bin (node) population; the first-fit waterfall's exclusive
  cumsum becomes a cross-device prefix sum XLA lowers onto ICI.
- "types": the instance-type axis sharded like TENSOR parallelism — the
  [N, T, S] option-mask state and the [N, T] capacity quotients are computed
  shard-local; qmax/kstar argmax-style reductions become all-reduces.
- the group scan is the sequential (pipeline-like) axis; groups are inherently
  order-dependent under FFD, so they stay unsharded — the reference has the
  same sequential dependence (designs/bin-packing.md step 4).

GSPMD inserts all collectives: we only annotate input/state shardings
(scaling-book recipe: pick a mesh, annotate, let XLA do the rest).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.packer import INT_BIG, PackInputs, PackResult, pack_impl

AXIS_NODES = "nodes"
AXIS_TYPES = "types"


def pad_types(inputs: PackInputs, multiple: int) -> PackInputs:
    """Pad the instance-type axis to a multiple of the mesh's type dimension
    with never-selectable entries: zero capacity, INT_BIG tiebreak, infeasible
    everywhere. Transparent to consumers — `decided` flat ids are t*S+s with S
    unchanged, so real types keep their ids."""
    T = inputs.alloc_t.shape[0]
    Tp = -(-T // multiple) * multiple
    if Tp == T:
        return inputs
    pad_n = Tp - T

    def pad(a, axis, value):
        a = np.asarray(a)
        w = [(0, 0)] * a.ndim
        w[axis] = (0, pad_n)
        return np.pad(a, w, constant_values=value)

    out = inputs._replace(
        alloc_t=pad(inputs.alloc_t, 0, 0),
        tiebreak=pad(inputs.tiebreak, 0, int(INT_BIG)),
        group_feas=pad(inputs.group_feas, 2, False),
    )
    if inputs.prov_pods_cap is not None:
        out = out._replace(prov_pods_cap=pad(inputs.prov_pods_cap, 1, 0))
    return out


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    types_dim = 2 if n % 2 == 0 and n >= 2 else 1
    nodes_dim = n // types_dim
    return Mesh(np.array(devs).reshape(nodes_dim, types_dim), (AXIS_NODES, AXIS_TYPES))


def input_shardings(mesh: Mesh) -> PackInputs:
    """PartitionSpecs per input leaf: catalog arrays sharded over types,
    group masks over types, small per-group vectors replicated."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))
    return PackInputs(
        alloc_t=s(AXIS_TYPES, None),
        tiebreak=s(AXIS_TYPES, None),
        group_vec=s(), group_count=s(), group_cap=s(),
        group_feas=s(None, None, AXIS_TYPES, None),
        group_newprov=s(), overhead=s(),
        ex_alloc=s(), ex_used=s(), ex_feas=s(),
        prov_overhead=s(), prov_pods_cap=s(None, AXIS_TYPES),
        ex_cap=s(), group_origin=s(),
    )


def _constrained_pack(inputs: PackInputs, n_slots: int, mesh: Mesh) -> PackResult:
    """pack_impl under the mesh: the [N, T, S] scan-carry sharding comes from
    GSPMD propagation off the type-sharded inputs; we pin only the [N, R]
    `used` output to the nodes axis to anchor the node dimension."""
    result = pack_impl(inputs, n_slots)
    used = jax.lax.with_sharding_constraint(result.used, NamedSharding(mesh, P(AXIS_NODES, None)))
    return result._replace(used=used)


def sharded_pack(inputs: PackInputs, n_slots: int, mesh: Mesh) -> PackResult:
    """Run the packer SPMD over `mesh`. Bit-identical to single-device pack
    (tests/test_sharded.py)."""
    inputs = pad_types(inputs, mesh.shape[AXIS_TYPES])
    shardings = input_shardings(mesh)
    if inputs.prov_overhead is None:
        shardings = shardings._replace(prov_overhead=None, prov_pods_cap=None)
    if inputs.ex_cap is None:
        shardings = shardings._replace(ex_cap=None)
    if inputs.group_origin is None:
        shardings = shardings._replace(group_origin=None)
    inputs = jax.tree.map(
        lambda a, sh: jax.device_put(jax.numpy.asarray(a), sh), inputs, shardings
    )
    fn = jax.jit(
        _constrained_pack,
        static_argnames=("n_slots", "mesh"),
        in_shardings=(shardings,),
    )
    with mesh:
        return fn(inputs, n_slots, mesh)


# -- consolidation lanes ------------------------------------------------------------

AXIS_LANES = "lanes"


def make_lane_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh for the consolidation sweep: candidate lanes are mutually
    independent simulations, so the batch shards like DATA parallelism —
    every device owns C/n lanes and no collective crosses lanes at all
    (the cheapest possible scale-out; contrast the pack mesh above where
    the type axis all-reduces)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS_LANES,))


def _pad_lanes(inputs: PackInputs, multiple: int) -> "tuple[PackInputs, int]":
    """Pad the leading candidate axis to a device multiple with NO-OP lanes
    (zero pod counts, infeasible everywhere): they place nothing, open
    nothing, and the caller slices verdicts back to the true lane count."""
    C = inputs.group_vec.shape[0]
    Cp = -(-C // multiple) * multiple
    if Cp == C:
        return inputs, C
    pad_n = Cp - C

    def pad(a, value=0):
        a = np.asarray(a)
        w = [(0, 0)] * a.ndim
        w[0] = (0, pad_n)
        return np.pad(a, w, constant_values=value)

    out = inputs._replace(
        group_vec=pad(inputs.group_vec), group_count=pad(inputs.group_count),
        group_cap=pad(inputs.group_cap, int(INT_BIG)),
        group_newprov=pad(inputs.group_newprov, -1),
        ex_feas=pad(inputs.ex_feas, False),
    )
    if inputs.group_feas is not None:  # None when a feas table+idx rides along
        out = out._replace(group_feas=pad(inputs.group_feas, False))
    if inputs.ex_cap is not None:
        out = out._replace(ex_cap=pad(inputs.ex_cap, int(INT_BIG)))
    if inputs.group_origin is not None:
        out = out._replace(group_origin=pad(inputs.group_origin))
    return out, C


def sharded_consolidation_verdicts(inputs: PackInputs, n_slots: int,
                                   mesh: Mesh, feas_table=None,
                                   feas_idx=None) -> np.ndarray:
    """The [C, 3] verdict table of ops.consolidate._batched_pack_verdicts,
    with candidate lanes sharded across `mesh`. Bit-identical to the
    single-device sweep (tests/test_sharded.py). When a unique-row
    feasibility table rides along (inputs.group_feas is None), the table
    replicates and only the per-lane indices shard — the dense expansion
    happens device-side inside the jitted verdicts fn."""
    from ..ops.consolidate import _batched_pack_verdicts

    n = mesh.devices.size
    inputs, C = _pad_lanes(inputs, n)
    lane = lambda *rest: NamedSharding(mesh, P(AXIS_LANES, *rest))
    rep = NamedSharding(mesh, P())
    if feas_idx is not None:
        Cp = inputs.group_vec.shape[0]
        if feas_idx.shape[0] != Cp:  # pad lanes -> all-False row 0
            feas_idx = np.pad(feas_idx,
                              [(0, Cp - feas_idx.shape[0]), (0, 0)])
    shardings = PackInputs(
        alloc_t=rep, tiebreak=rep,
        group_vec=lane(), group_count=lane(), group_cap=lane(),
        group_feas=None if inputs.group_feas is None else lane(),
        group_newprov=lane(), overhead=rep,
        ex_alloc=rep, ex_used=rep, ex_feas=lane(),  # ex_used: shared, no lane axis
        prov_overhead=None if inputs.prov_overhead is None else rep,
        prov_pods_cap=None if inputs.prov_pods_cap is None else rep,
        ex_cap=None if inputs.ex_cap is None else lane(),
        group_origin=None if inputs.group_origin is None else lane(),
    )
    dev_inputs = jax.tree.map(
        lambda a, sh: jax.device_put(jax.numpy.asarray(a), sh),
        inputs, shardings)
    if feas_table is not None:
        feas_table = jax.device_put(jax.numpy.asarray(feas_table), rep)
        feas_idx = jax.device_put(jax.numpy.asarray(feas_idx), lane())
    with mesh:  # _batched_pack_verdicts is already jitted at definition
        verdicts = _batched_pack_verdicts(dev_inputs, n_slots,
                                          feas_table=feas_table,
                                          feas_idx=feas_idx)
    from ..solver.core import host_fetch  # honors --readback callback

    return host_fetch(verdicts)[:C]
