"""proto <-> model converters for the solver gRPC boundary.

The conversions must be EXACT round trips: pod grouping (PodSpec.group_key)
runs independently on both sides of the wire, and group indices in
SolveResponse are only meaningful if client and server derive the identical
deterministic grouping from the identical pod list (order-preserving
first-occurrence order of group_pods, models/pod.py).
"""

from __future__ import annotations

import hashlib

from ..apis.provisioner import KubeletConfiguration, Limits, Provisioner
from ..models.instancetype import Catalog, InstanceType, Offering, Offerings
from ..models.pod import (PodAffinityTerm, PodSpec, Taint, Toleration,
                          TopologySpreadConstraint, group_pods)
from ..models.requirements import Requirement, Requirements
from ..oracle.scheduler import ExistingNode
from ..tracing import SpanContext
from . import solver_pb2 as pb

# -- trace context ----------------------------------------------------------------


def trace_context_to_wire(ctx) -> "pb.TraceContextMsg":
    """SpanContext (or None) -> wire msg. An empty message means "caller not
    tracing"; the service then roots its own trace."""
    if ctx is None:
        return pb.TraceContextMsg()
    return pb.TraceContextMsg(trace_id=ctx.trace_id, span_id=ctx.span_id)


def trace_context_from_wire(m) -> "SpanContext | None":
    if m is None or not m.trace_id:
        return None
    return SpanContext(trace_id=m.trace_id, span_id=m.span_id)


# -- requirements -----------------------------------------------------------------


def reqs_to_wire(reqs: Requirements) -> "list[pb.RequirementSpec]":
    return [pb.RequirementSpec(key=k, op=op, values=list(vals))
            for k, op, vals in reqs.to_specs()]


def reqs_from_wire(specs) -> Requirements:
    r = Requirements()
    for s in specs:
        r.add(Requirement.create(s.key, s.op, list(s.values)))
    return r


def _kvs(pairs) -> "list[pb.KV]":
    return [pb.KV(key=k, value=v) for k, v in pairs]


def _qtys(pairs) -> "list[pb.ResourceQty]":
    return [pb.ResourceQty(key=k, value=v) for k, v in pairs]


def _taints_to_wire(taints) -> "list[pb.TaintSpec]":
    return [pb.TaintSpec(key=t.key, value=t.value, effect=t.effect) for t in taints]


def _taints_from_wire(msgs) -> "tuple[Taint, ...]":
    return tuple(Taint(key=m.key, value=m.value, effect=m.effect) for m in msgs)


# -- pods -------------------------------------------------------------------------


def pod_to_wire(p: PodSpec) -> pb.PodSpecMsg:
    return pb.PodSpecMsg(
        name=p.name,
        namespace=p.namespace,
        labels=_kvs(p.labels),
        requests=_qtys(p.requests),
        requirements=reqs_to_wire(p.requirements),
        tolerations=[pb.TolerationSpec(key=t.key, operator=t.operator,
                                       value=t.value, effect=t.effect)
                     for t in p.tolerations],
        topology=[pb.TopologySpreadSpec(max_skew=t.max_skew,
                                        topology_key=t.topology_key,
                                        when_unsatisfiable=t.when_unsatisfiable)
                  for t in p.topology],
        anti_affinity_hostname=p.anti_affinity_hostname,
        anti_affinity_zone=p.anti_affinity_zone,
        priority=p.priority,
        deletion_cost=p.deletion_cost,
        owner_kind=p.owner_kind,
        do_not_evict=p.do_not_evict,
        node_name=p.node_name,
        preferences=[pb.RequirementsTerm(requirements=reqs_to_wire(t))
                     for t in p.preferences],
        pod_affinity=[pb.PodAffinityTermSpec(match_labels=_kvs(t.match_labels),
                                             topology_key=t.topology_key)
                      for t in p.pod_affinity],
        pod_anti_affinity=[pb.PodAffinityTermSpec(
            match_labels=_kvs(t.match_labels), topology_key=t.topology_key)
            for t in p.pod_anti_affinity],
    )


def pod_from_wire(m: pb.PodSpecMsg) -> PodSpec:
    return PodSpec(
        name=m.name,
        namespace=m.namespace,
        labels=tuple((kv.key, kv.value) for kv in m.labels),
        requests=tuple((q.key, q.value) for q in m.requests),
        requirements=reqs_from_wire(m.requirements),
        tolerations=tuple(Toleration(key=t.key, operator=t.operator,
                                     value=t.value, effect=t.effect)
                          for t in m.tolerations),
        topology=tuple(TopologySpreadConstraint(
            max_skew=t.max_skew, topology_key=t.topology_key,
            when_unsatisfiable=t.when_unsatisfiable) for t in m.topology),
        anti_affinity_hostname=m.anti_affinity_hostname,
        anti_affinity_zone=m.anti_affinity_zone,
        priority=m.priority,
        deletion_cost=m.deletion_cost,
        owner_kind=m.owner_kind,
        do_not_evict=m.do_not_evict,
        node_name=m.node_name,
        preferences=tuple(reqs_from_wire(t.requirements)
                          for t in m.preferences),
        pod_affinity=tuple(
            PodAffinityTerm(
                match_labels=tuple((kv.key, kv.value) for kv in t.match_labels),
                topology_key=t.topology_key)
            for t in m.pod_affinity),
        pod_anti_affinity=tuple(
            PodAffinityTerm(
                match_labels=tuple((kv.key, kv.value) for kv in t.match_labels),
                topology_key=t.topology_key)
            for t in m.pod_anti_affinity),
    )


# -- catalog ----------------------------------------------------------------------


def itype_to_wire(t: InstanceType) -> pb.InstanceTypeMsg:
    return pb.InstanceTypeMsg(
        name=t.name,
        labels=_kvs(t.labels),
        capacity=_qtys(t.capacity),
        overhead=_qtys(t.overhead),
        offerings=[pb.OfferingMsg(zone=o.zone, capacity_type=o.capacity_type,
                                  price=o.price, available=o.available)
                   for o in t.offerings],
    )


def itype_from_wire(m: pb.InstanceTypeMsg) -> InstanceType:
    return InstanceType(
        name=m.name,
        labels=tuple((kv.key, kv.value) for kv in m.labels),
        capacity=tuple((q.key, q.value) for q in m.capacity),
        overhead=tuple((q.key, q.value) for q in m.overhead),
        offerings=Offerings(Offering(zone=o.zone, capacity_type=o.capacity_type,
                                     price=o.price, available=o.available)
                            for o in m.offerings),
    )


def catalog_to_wire(c: Catalog) -> pb.CatalogMsg:
    return pb.CatalogMsg(types=[itype_to_wire(t) for t in c.types], seqnum=c.seqnum)


def catalog_from_wire(m: pb.CatalogMsg) -> Catalog:
    return Catalog(types=[itype_from_wire(t) for t in m.types], seqnum=m.seqnum)


# -- provisioners -----------------------------------------------------------------


def provisioner_to_wire(p: Provisioner) -> pb.ProvisionerMsg:
    k = p.kubelet
    return pb.ProvisionerMsg(
        name=p.name,
        requirements=reqs_to_wire(p.requirements),
        taints=_taints_to_wire(p.taints),
        startup_taints=_taints_to_wire(p.startup_taints),
        labels=_kvs(p.labels),
        limit_cpu_millis=-1 if p.limits.cpu_millis is None else p.limits.cpu_millis,
        limit_memory_bytes=-1 if p.limits.memory_bytes is None else p.limits.memory_bytes,
        weight=p.weight,
        ttl_seconds_after_empty=(-1 if p.ttl_seconds_after_empty is None
                                 else p.ttl_seconds_after_empty),
        ttl_seconds_until_expired=(-1 if p.ttl_seconds_until_expired is None
                                   else p.ttl_seconds_until_expired),
        consolidation_enabled=p.consolidation_enabled,
        kubelet=pb.KubeletConfigMsg(
            max_pods=k.max_pods or 0,
            pods_per_core=k.pods_per_core or 0,
            system_reserved_cpu_millis=k.system_reserved_cpu_millis,
            system_reserved_memory_bytes=k.system_reserved_memory_bytes,
            kube_reserved_cpu_millis=(-1 if k.kube_reserved_cpu_millis is None
                                      else k.kube_reserved_cpu_millis),
            kube_reserved_memory_bytes=(-1 if k.kube_reserved_memory_bytes is None
                                        else k.kube_reserved_memory_bytes),
            eviction_hard_memory_bytes=k.eviction_hard_memory_bytes,
        ),
        provider_ref=p.provider_ref or "",
    )


def provisioner_from_wire(m: pb.ProvisionerMsg) -> Provisioner:
    k = m.kubelet
    return Provisioner(
        name=m.name,
        requirements=reqs_from_wire(m.requirements),
        taints=_taints_from_wire(m.taints),
        startup_taints=_taints_from_wire(m.startup_taints),
        labels=tuple((kv.key, kv.value) for kv in m.labels),
        limits=Limits(
            cpu_millis=None if m.limit_cpu_millis < 0 else m.limit_cpu_millis,
            memory_bytes=None if m.limit_memory_bytes < 0 else m.limit_memory_bytes,
        ),
        weight=m.weight,
        ttl_seconds_after_empty=(None if m.ttl_seconds_after_empty < 0
                                 else m.ttl_seconds_after_empty),
        ttl_seconds_until_expired=(None if m.ttl_seconds_until_expired < 0
                                   else m.ttl_seconds_until_expired),
        consolidation_enabled=m.consolidation_enabled,
        kubelet=KubeletConfiguration(
            max_pods=k.max_pods or None,
            pods_per_core=k.pods_per_core or None,
            system_reserved_cpu_millis=k.system_reserved_cpu_millis,
            system_reserved_memory_bytes=k.system_reserved_memory_bytes,
            kube_reserved_cpu_millis=(None if k.kube_reserved_cpu_millis < 0
                                      else k.kube_reserved_cpu_millis),
            kube_reserved_memory_bytes=(None if k.kube_reserved_memory_bytes < 0
                                        else k.kube_reserved_memory_bytes),
            eviction_hard_memory_bytes=k.eviction_hard_memory_bytes,
        ),
        provider_ref=m.provider_ref or None,
    )


def _digest64(chunks) -> int:
    """64-bit blake2b over length-delimited chunks. These fingerprints are
    the SOLE staleness gate for Solve, so a 32-bit CRC's collision odds
    (birthday bound ~2**16 catalogs) are not acceptable — a collision would
    silently serve placements from the wrong catalog. Length prefixes keep
    chunk boundaries unambiguous."""
    h = hashlib.blake2b(digest_size=8)
    for c in chunks:
        h.update(len(c).to_bytes(4, "little"))
        h.update(c)
    return int.from_bytes(h.digest(), "little")


def catalog_hash(catalog_or_msg) -> int:
    """Content fingerprint of a catalog, seqnum EXCLUDED. Seqnums are
    process-local mutation counters: a restarted controller starts over at 0
    while a long-lived solver service keeps its old value, so cross-process
    seqnum comparison wrongly brands the fresh client stale forever. Content
    hashing makes sync staleness restart-proof (the durable analogue of the
    reference's seqnum-memoized cache key, instancetypes.go:104-120)."""
    m = catalog_or_msg if isinstance(catalog_or_msg, pb.CatalogMsg) \
        else catalog_to_wire(catalog_or_msg)
    return _digest64(t.SerializeToString() for t in m.types)


def provisioners_hash(provisioners) -> int:
    """Stable fingerprint of the synced provisioner specs; lets the server
    reject a Solve whose provisioner set drifted since the last Sync (the
    seqnum trick applied to the other half of the problem definition)."""
    return _digest64(provisioner_to_wire(p).SerializeToString()
                     for p in provisioners)


# -- existing nodes ---------------------------------------------------------------


def existing_to_wire(e: ExistingNode) -> pb.ExistingNodeMsg:
    return pb.ExistingNodeMsg(
        name=e.name,
        labels=_kvs(sorted(e.labels.items())),
        allocatable=list(e.allocatable),
        used=list(e.used),
        taints=_taints_to_wire(e.taints),
        resident=[pb.ResidentGroup(spec=pod_to_wire(g.spec), count=g.count)
                  for g in group_pods(list(e.resident))],
    )


def existing_from_wire(m: pb.ExistingNodeMsg) -> ExistingNode:
    return ExistingNode(
        name=m.name,
        labels={kv.key: kv.value for kv in m.labels},
        allocatable=list(m.allocatable),
        used=list(m.used),
        taints=_taints_from_wire(m.taints),
        resident=tuple(p for rg in m.resident
                       for p in [pod_from_wire(rg.spec)] * rg.count),
    )


# -- consolidation ------------------------------------------------------------------


def consolidation_node_to_wire(n, eligible: bool) -> pb.ConsolidationNodeMsg:
    """StateNode + the controller's eligibility verdict -> wire (an explicit
    parameter — never smuggled through attributes on shared live state).
    Full pod specs travel: priority/deletion-cost feed the disruption
    scoring on the service side, labels feed survivor topology counting."""
    return pb.ConsolidationNodeMsg(
        name=n.name,
        labels=_kvs(sorted(n.labels.items())),
        allocatable=list(n.allocatable),
        taints=_taints_to_wire(n.taints),
        instance_type=n.instance_type,
        zone=n.zone,
        capacity_type=n.capacity_type,
        price=n.price,
        provisioner_name=n.provisioner_name,
        created_ts=n.created_ts,
        initialized=n.initialized,
        eligible=eligible,
        marked_for_deletion=n.marked_for_deletion,
        annotations=_kvs(sorted(n.annotations.items())),
        pods=[pod_to_wire(p) for p in n.pods],
    )


def consolidation_node_from_wire(m: pb.ConsolidationNodeMsg):
    """-> (StateNode, eligible)."""
    from ..models.cluster import StateNode

    node = StateNode(
        name=m.name,
        labels={kv.key: kv.value for kv in m.labels},
        allocatable=list(m.allocatable),
        taints=_taints_from_wire(m.taints),
        instance_type=m.instance_type,
        zone=m.zone,
        capacity_type=m.capacity_type,
        price=m.price,
        provisioner_name=m.provisioner_name,
        created_ts=m.created_ts,
        initialized=m.initialized,
        marked_for_deletion=m.marked_for_deletion,
        annotations={kv.key: kv.value for kv in m.annotations},
        pods=[pod_from_wire(p) for p in m.pods],
    )
    return node, m.eligible


def action_to_response(action, consolidate_ms: float) -> pb.ConsolidateResponse:
    if action is None:
        return pb.ConsolidateResponse(found=False,
                                      consolidate_ms=consolidate_ms)
    resp = pb.ConsolidateResponse(
        found=True, kind=action.kind, nodes=list(action.nodes),
        savings=action.savings, cost=action.disruption_cost,
        consolidate_ms=consolidate_ms)
    if action.replacement is not None:
        itype, zone, ct, price = action.replacement
        resp.replacement_instance_type = itype
        resp.replacement_zone = zone
        resp.replacement_capacity_type = ct
        resp.replacement_price = price
    return resp


def action_from_response(m: pb.ConsolidateResponse):
    from ..oracle.consolidation import ConsolidationAction

    if not m.found:
        return None
    replacement = None
    if m.replacement_instance_type:
        replacement = (m.replacement_instance_type, m.replacement_zone,
                       m.replacement_capacity_type, m.replacement_price)
    return ConsolidationAction(
        m.kind, m.nodes[0] if m.nodes else "", m.cost, savings=m.savings,
        replacement=replacement, nodes=tuple(m.nodes))
