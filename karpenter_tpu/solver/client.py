"""RemoteSolver: controller-side client for the solver gRPC service.

Drop-in replacement for TPUSolver (same .solve signature), pluggable into
ProvisioningController via solver_factory. Sync-on-demand: a Solve rejected
with FAILED_PRECONDITION (stale catalog content hash / provisioner hash)
triggers one catalog Sync + retry — the wire analogue of the reference's
seqnum-invalidated instance-type cache re-resolution
(/root/reference/pkg/cloudprovider/instancetypes.go:104-120). Staleness is
keyed on catalog CONTENT (wire.catalog_hash), not the process-local seqnum,
so a restarted controller (seqnum reset to 0) re-syncs cleanly against a
long-lived solver service instead of being branded stale forever.

Failure contract: any transport error raises SolverUnavailable; the
provisioning controller catches it and runs the in-process oracle with
identical semantics (the fallback contract, BASELINE.json north star —
reference analogue: static pricing fallback, pricing.go:100-116).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import grpc

from ..apis.provisioner import Provisioner
from ..metrics import Counter
from ..models.instancetype import Catalog
from ..models.pod import PodGroup, PodSpec
from ..oracle.scheduler import ExistingNode, Option
from ..resilience import deadline
from ..tracing import TRACER
from .core import SolvedNode, SolveResult
from . import solver_pb2 as pb
from . import wire
from .service import METHODS, SERVICE_NAME

log = logging.getLogger("karpenter.solver.client")

# rolling-upgrade observability: an old server that predates content-hash
# Sync answers catalog_hash=0; without a signal that skew silently costs a
# full re-sync + oracle fallback every cycle (ADVICE r2)
VERSION_SKEW = Counter(
    "karpenter_solver_client_version_skew_total",
    "Sync responses missing the content hash (old server speaking the "
    "legacy seqnum protocol)")

# One channel per target, shared across RemoteSolver instances: the
# per-reconcile solver_factory pattern constructs a fresh RemoteSolver each
# cycle, and per-instance channels would leak sockets.
_channels: "dict[str, grpc.Channel]" = {}
_channels_lock = threading.Lock()

# Recent pod counts solved per target (most recent last, bounded) — shipped
# as SyncRequest.warm_pod_counts so a restarted/re-synced service can pre-jit
# the shape buckets this controller's traffic actually hits. Module-level
# like _channels: RemoteSolver instances are per-reconcile, the traffic
# history is per-target.
_WARM_HINTS_CAP = 8
_warm_hints: "dict[str, list[int]]" = {}
_warm_hints_lock = threading.Lock()


def _note_warm_hint(target: str, pod_count: int) -> None:
    with _warm_hints_lock:
        hints = _warm_hints.setdefault(target, [])
        if pod_count in hints:
            hints.remove(pod_count)
        hints.append(pod_count)
        del hints[:-_WARM_HINTS_CAP]


def _get_warm_hints(target: str) -> "list[int]":
    with _warm_hints_lock:
        return list(reversed(_warm_hints.get(target, ())))


def _shared_channel(target: str) -> grpc.Channel:
    with _channels_lock:
        ch = _channels.get(target)
        if ch is None:
            ch = grpc.insecure_channel(target)
            _channels[target] = ch
        return ch


class SolverUnavailable(RuntimeError):
    pass


class StaleSync(RuntimeError):
    """Server demanded a re-Sync (FAILED_PRECONDITION)."""


class RemoteSolver:
    def __init__(self, catalog: Catalog, provisioners: Sequence[Provisioner],
                 target: str = "127.0.0.1:50151",
                 channel: Optional[grpc.Channel] = None,
                 timeout: float = 10.0, resilience=None,
                 tenant_id: str = ""):
        self.catalog = catalog
        self.provisioners = list(provisioners)
        self.timeout = timeout
        # fleet-serving identity: stamped on every SolveRequest so a
        # multi-tenant frontend can queue/shed/account per tenant. Empty =
        # legacy single-tenant caller (the frontend admits it as "default").
        self.tenant_id = tenant_id
        # shared solver-edge RetryPolicy (breaker + budget) from the hub;
        # standalone clients run bare — the provisioning ladder above is
        # still their safety net
        self._policy = resilience.policy("solver") if resilience is not None \
            else None
        self._target = target
        self._channel = channel or _shared_channel(target)
        self._synced_hash: Optional[int] = None
        self._prov_hash = wire.provisioners_hash(self.provisioners)
        # content hash memoized per seqnum: recomputed only when the catalog
        # object actually mutates (seqnum bump), not per solve
        self._hash_cache: "tuple[int, int]" = (-1, 0)  # (seqnum, hash)
        # stub table derived from the server's METHODS so client and service
        # can't drift (single owner of the RPC name -> message mapping)
        self._stubs = {
            name: self._channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )
            for name, (_req_cls, resp_cls) in METHODS.items()
        }

    # -- RPC plumbing --------------------------------------------------------------

    def _call(self, name: str, request):
        pol = self._policy
        dl = deadline.current()
        # shed a doomed call BEFORE consulting the breaker: an exhausted
        # cycle budget says nothing about solver health, and admitting it
        # as the half-open probe would waste (or wedge) the probe slot
        if dl is not None and dl.expired():
            raise SolverUnavailable(
                f"{name}: reconcile deadline exhausted before RPC")
        if pol is not None and pol.breaker is not None \
                and not pol.breaker.allow():
            # fail fast into SolverUnavailable: the callers' fallback chains
            # (provisioning/deprovisioning ladders) already catch it
            pol.retries_total.inc(dep=pol.dep, outcome="breaker_open")
            raise SolverUnavailable(f"{name}: solver circuit breaker open")
        timeout = self.timeout
        if dl is not None:
            timeout = min(timeout, dl.remaining())
        cur = TRACER.current_span()
        with TRACER.start_span(f"solver.rpc.{name}") as span:
            # inject THIS rpc span's identity so the sidecar's span joins
            # the trace as its child (requests without a trace_context
            # field — Health — just skip injection)
            if hasattr(request, "trace_context"):
                request.trace_context.CopyFrom(
                    wire.trace_context_to_wire(span.context()))
            # deadline propagation: ship the REMAINING budget (ms) so the
            # service can shed work that can't finish in time — remaining
            # time, not an absolute timestamp, because the two processes
            # don't share a clock
            if hasattr(request, "deadline_ms") and dl is not None:
                request.deadline_ms = max(1, int(dl.remaining_ms()))
            try:
                try:
                    resp = self._stubs[name](request, timeout=timeout)
                except grpc.RpcError as e:
                    if e.code() == grpc.StatusCode.FAILED_PRECONDITION:
                        # a structured rejection from a LIVE server: the
                        # solver edge is healthy, only the synced state is
                        # stale
                        if pol is not None:
                            pol.note_success()
                        raise StaleSync(e.details())
                    if (e.code() == grpc.StatusCode.DEADLINE_EXCEEDED
                            and dl is not None):
                        # the RPC timeout was capped to the cycle's
                        # REMAINING budget (and the service sheds
                        # past-deadline work): this is self-inflicted, not
                        # solver sickness — no breaker feedback, or a few
                        # slow cycles would trip the breaker on a healthy
                        # sidecar (the finally releases the probe unjudged)
                        raise SolverUnavailable(
                            f"{name}: cycle budget exhausted mid-RPC: "
                            f"{e.details()}")
                    if pol is not None:
                        pol.note_failure()
                    raise SolverUnavailable(
                        f"{name}: {e.code().name}: {e.details()}")
                if pol is not None:
                    pol.note_success()
            finally:
                # resolve a half-open probe the allow() above may have
                # admitted on ANY exit that didn't judge it (no-op after
                # note_success/note_failure)
                if pol is not None:
                    pol.release_probe()
            if name == "Solve":
                # the service echoes its device-path observability in the
                # response — record it on the CLIENT side of the wire too,
                # and bubble it to the enclosing solve-phase span
                attrs = {"routing": resp.routing or "unknown",
                         "compile_cache": resp.compile_cache or "unknown",
                         "transfer_ms": resp.transfer_ms,
                         "solve_ms": resp.solve_ms,
                         "bucket": resp.bucket or "n/a",
                         "device_count": resp.device_count or 1}
                span.set_attributes(**attrs)
                if cur is not None:
                    cur.set_attributes(**attrs)
            return resp

    def catalog_content_hash(self) -> int:
        if self._hash_cache[0] != self.catalog.seqnum:
            self._hash_cache = (self.catalog.seqnum, wire.catalog_hash(self.catalog))
        return self._hash_cache[1]

    def sync(self) -> int:
        resp = self._call("Sync", pb.SyncRequest(
            catalog=wire.catalog_to_wire(self.catalog),
            provisioners=[wire.provisioner_to_wire(p) for p in self.provisioners],
            # compile-cache warmup hints: the pod counts this target's
            # traffic recently solved for (see service._warm)
            warm_pod_counts=_get_warm_hints(self._target),
        ))
        # Staleness is content-keyed (see wire.catalog_hash): the server
        # installs whatever content we sent, so a mismatch here means the
        # wire round-trip itself is broken — surface it rather than record a
        # sync that every later Solve would fail.
        ours = self.catalog_content_hash()
        if resp.catalog_hash != ours:
            if resp.catalog_hash == 0 and ours != 0:
                # Old server (pre-content-hash protocol): it synced fine but
                # can't echo the hash. Accept via the legacy seqnum handshake
                # instead of branding every future Sync stale — but make the
                # degraded mode visible so a rolling upgrade doesn't silently
                # fall back to the oracle each cycle.
                VERSION_SKEW.inc()
                log.warning(
                    "solver server answered Sync without a catalog content "
                    "hash (version skew: old server); proceeding on the "
                    "legacy seqnum protocol — upgrade the solver service")
                self._synced_hash = ours
                return resp.seqnum
            raise StaleSync(
                f"server installed catalog hash={resp.catalog_hash:x}, "
                f"ours is {ours:x}; wire round-trip mismatch")
        self._synced_hash = ours
        return resp.seqnum

    def health(self) -> pb.HealthResponse:
        return self._call("Health", pb.HealthRequest())

    # -- consolidation -------------------------------------------------------------

    def consolidate(self, cluster, eligible_names: "set[str]",
                    daemon_overhead: Optional[Sequence[int]] = None,
                    now: float = 0.0, multi_node: bool = True,
                    max_pair_candidates: "Optional[int]" = None):
        """Run the consolidation search on the service's device. The
        controller ships its cluster-state views with PRE-COMPUTED
        eligibility verdicts (the service has no PDB store); the synced
        catalog/provisioners key the device-resident state like Solve."""
        if max_pair_candidates is None:
            max_pair_candidates = -1  # wire sentinel: server-side default
        nodes = [wire.consolidation_node_to_wire(
                     cluster.nodes[name], eligible=name in eligible_names)
                 for name in sorted(cluster.nodes)]
        req = pb.ConsolidateRequest(
            catalog_hash=self.catalog_content_hash(),
            provisioner_hash=self._prov_hash,
            nodes=nodes,
            daemon_overhead=list(daemon_overhead or ()),
            multi_node=multi_node,
            max_pair_candidates=max_pair_candidates,
            now=now,
        )
        if self._synced_hash != self.catalog_content_hash():
            self.sync()
        try:
            resp = self._call("Consolidate", req)
        except StaleSync as e:
            if self._policy is not None and not self._policy.try_retry():
                raise SolverUnavailable(
                    f"Consolidate: retry budget exhausted after stale "
                    f"sync: {e}")
            self.sync()
            resp = self._call("Consolidate", req)
        return wire.action_from_response(resp)

    # -- solve ---------------------------------------------------------------------

    def solve(self, pods: "list[PodSpec]",
              existing: Sequence[ExistingNode] = (),
              daemon_overhead: Optional[Sequence[int]] = None) -> SolveResult:
        _note_warm_hint(self._target, len(pods))
        req = pb.SolveRequest(
            catalog_seqnum=self.catalog.seqnum,
            catalog_hash=self.catalog_content_hash(),
            provisioner_hash=self._prov_hash,
            pods=[wire.pod_to_wire(p) for p in pods],
            existing=[wire.existing_to_wire(e) for e in existing],
            daemon_overhead=list(daemon_overhead or ()),
            tenant_id=self.tenant_id,
        )
        if self._synced_hash != self.catalog_content_hash():
            self.sync()
        try:
            resp = self._call("Solve", req)
        except StaleSync as e:
            # one re-sync + retry (server restarted or drifted),
            # budget-gated like every other retry path
            if self._policy is not None and not self._policy.try_retry():
                raise SolverUnavailable(
                    f"Solve: retry budget exhausted after stale sync: {e}")
            self.sync()
            resp = self._call("Solve", req)
        return self._decode(resp, pods)

    def _decode(self, resp: pb.SolveResponse, pods: "list[PodSpec]") -> SolveResult:
        # Groups come back from the server (the encoder's partition is richer
        # than raw group_pods: topology-spread groups split per domain);
        # rebuild PodGroup views against our own PodSpec objects.
        by_name = {p.name: p for p in pods}
        groups = [
            PodGroup(spec=by_name[g.pod_names[0]], count=len(g.pod_names),
                     pod_names=list(g.pod_names))
            for g in resp.groups
        ]
        provs = {p.name: p for p in self.provisioners}
        nodes = []
        for n in resp.nodes:
            itype = self.catalog.by_name[n.instance_type]
            nodes.append(SolvedNode(
                option=Option(index=-1, itype=itype, zone=n.zone,
                              capacity_type=n.capacity_type, price=n.price,
                              alloc=tuple(itype.allocatable_vector())),
                pod_counts={gc.group: gc.count for gc in n.pods},
                provisioner=provs[n.provisioner],
            ))
        existing_by_group = {
            e.node: {gc.group: gc.count for gc in e.pods} for e in resp.existing
        }
        existing_counts = {name: sum(d.values())
                           for name, d in existing_by_group.items()}
        unschedulable = {gc.group: gc.count for gc in resp.unschedulable}
        return SolveResult(nodes, existing_counts, unschedulable, groups,
                           existing_by_group)
