"""TPU solver: encode -> pack kernel -> decode.

This is the "Solver half" of the architecture (SURVEY.md §7.1): the JAX
service the controller calls instead of running the scalar FFD loop. The
scalar oracle (karpenter_tpu/oracle/scheduler.py) remains the in-process
fallback with identical semantics (BASELINE.json north star).

Shape discipline (SURVEY.md §7.3 "dynamic shapes"): pod-group count, claim
slots and existing-node count are padded to the fixed rung ladder in
solver/buckets.py, so a stream of differently-sized solves hits a handful
of compiled programs, not a recompilation per solve. Padded groups have
count=0 / feas=False and are no-ops in the kernel. The same table drives
the jit cache key, Sync-time warmup (warm_shapes) and the single-chip vs
mesh routing decision (buckets.ShapeRouter + parallel/sharded.py).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Optional, Sequence

import jax
import numpy as np

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..models.encode import EncodedProblem, OptionGrid, build_grid, encode_problem
from ..models.instancetype import Catalog
from ..models.pod import PodSpec
from ..ops import pallas_kernels
from ..ops.packer import (INT_BIG, PackInputs, PackResult, pack_flat,
                          pack_flat_impl, pallas_value_safe,
                          unflatten_result)
from ..oracle.scheduler import ExistingNode, Option
from . import buckets

import os as _os

# phase-attributed solves (encode/dispatch/fetch/decode wall-clock split,
# read from TPUSolver.last_timings) — capture-tool diagnostics only
_SOLVE_TIMING = _os.environ.get("KARPENTER_TPU_SOLVE_TIMING") == "1"

# Readback mechanism for EVERY solver device->host read (host_fetch —
# single solves and solve_many waves alike): "get" (default) is a literal
# jax.device_get; "callback" emits results host-ward through io_callback —
# the escape hatch for relays whose link degrades permanently after the
# session's first literal read (hack/tpu_capture.py _io_callback_probe
# measures whether the deployment's relay lets callbacks through in
# streaming mode; flip this on only where that probe's sync_after stays
# sub-ms).
_READBACK = _os.environ.get("KARPENTER_TPU_READBACK", "get")

# The admission rule's mask factorization, in first-rejection order: the
# encoder ANDs exactly these constraint dimensions into group_feas
# (tolerations -> requirement fold -> fresh-node resource fit -> offering
# availability -> the spot plane's optional diversity-floor option mask),
# and whatever survives option admission can only be zeroed by cross-pod
# constraints inside the kernel. The explain plane's
# reason vocabulary (explain/reasons.py DIMENSIONS, one scalar-oracle
# clause per entry) must stay in lockstep — hack/check_decision_reasons.py
# AST-lints both literals.
MASK_DIMENSIONS = (
    "taints",
    "requirements",
    "resources",
    "availability",
    "diversity",
    "constraints",
)


def _bucket(n: int, lo: int = 8) -> int:
    """Ladder-rung bucket (historic name/signature kept: the graft entry
    and the sharded tests pad with it). `lo` names the dimension's ladder:
    8 -> groups/slots, 1 -> existing nodes, 2 -> wave lanes. The old
    doubling-from-lo policy minted a program on every power-of-two
    crossing at small sizes; the fixed ladder (solver/buckets.py) is
    shared with the router so bucket choice, cache key and sharding plan
    all derive from one table."""
    dim = {8: "groups", 1: "existing", 2: "wave"}.get(lo)
    if dim is None:  # unknown lo: legacy doubling (no in-tree callers)
        b = lo
        while b < n:
            b *= 2
        return b
    return buckets.bucket_up(n, dim)


@dataclasses.dataclass
class SolvedNode:
    """One node decision (the Machine the controller would create)."""

    option: Option
    pod_counts: "dict[int, int]"  # group index -> pods
    provisioner: Provisioner

    @property
    def pod_count(self) -> int:
        return sum(self.pod_counts.values())


@dataclasses.dataclass
class SolveResult:
    nodes: "list[SolvedNode]"
    existing_counts: "dict[str, int]"  # existing node name -> pods placed
    unschedulable: "dict[int, int]"  # group index -> pod count
    groups: list
    # existing node name -> {group index -> pods placed} (binding plan)
    existing_by_group: "dict[str, dict[int, int]]" = dataclasses.field(default_factory=dict)

    def decisions(self) -> "list[tuple[str, str, str, int]]":
        """Fingerprint [(type, zone, capacityType, pods)] — comparable with
        oracle SchedulingResult.node_decisions()."""
        return sorted(
            (n.option.itype.name, n.option.zone, n.option.capacity_type, n.pod_count)
            for n in self.nodes
        )

    def unschedulable_count(self) -> int:
        return sum(self.unschedulable.values())


class TPUSolver:
    """Catalog-resident batched solver. Keeps the encoded option grid AND its
    device arrays resident across solves (reference analogue: the
    seqnum-memoized instance type cache, instancetypes.go:104-120) — only the
    per-solve group delta crosses the host-device boundary (SURVEY.md §7.3
    "ship only the pod delta")."""

    def __init__(self, catalog: Catalog, provisioners: Sequence[Provisioner],
                 reuse_from: "Optional[TPUSolver]" = None,
                 mesh_ctx=None, router: "Optional[buckets.ShapeRouter]" = None):
        self.catalog = catalog
        self.provisioners = list(provisioners)
        self._grid: Optional[OptionGrid] = None
        self._donor_grid: Optional[OptionGrid] = None
        self._dev_alloc_t = None
        self._dev_tiebreak = None
        # multi-chip serving (solver service wiring): a persistent
        # parallel/sharded.ShardedContext plus the shape router deciding
        # single-chip vs mesh per bucket. Both None -> always single-chip
        # (in-process controller solvers, tests, single-device hosts).
        self._mesh_ctx = mesh_ctx
        self._router = router
        # raw shape key of the last solve ((G, n_slots, Ne, Pv, optional
        # leaf flags)) — the service's warmup history records these so a
        # re-Sync can pre-jit what traffic actually looked like
        self.last_shape_key: "Optional[tuple]" = None
        # encode_group memo across solves (this instance's provisioner set is
        # fixed; layout/seqnum two-level invalidation — see encode_problem)
        self._group_cache: dict = {}
        if reuse_from is not None:
            self.adopt_static(reuse_from)

    def adopt_static(self, other: "TPUSolver",
                     share_group_cache: bool = True) -> None:
        """An evicted predecessor (solver caches rebuild on catalog content
        changes) donates its grid + group cache: when only availability
        changed (ICE churn), build_grid shares every static array and the
        cache's static level stays warm. The donation is a build_grid REUSE
        DONOR only, never installed as the live grid — seqnums are
        per-catalog counters (two distinct catalogs can share a seqnum), so
        only build_grid's layout_key check may decide what is reusable. The
        donated cache is layout-keyed internally, so adoption is safe even
        when the layout DID change (it just clears).

        share_group_cache=False copies the static level into a fresh dict
        instead of sharing the donor's — required when the donor STAYS LIVE
        (the solver service LRU keeps it serving other clients; two solvers
        mutating one cache dict would race and seqnum-thrash)."""
        if not isinstance(other, TPUSolver):
            return
        self._donor_grid = other._grid or other._donor_grid
        self._dev_alloc_t = other._dev_alloc_t
        self._dev_tiebreak = other._dev_tiebreak
        if list(other.provisioners) != self.provisioners:
            return
        if share_group_cache:
            self._group_cache = other._group_cache
            return
        try:
            src = other._group_cache
            layout = src.get("layout")
            statics = dict(src.get("static") or {})
        except RuntimeError:  # donor inserted concurrently mid-copy
            return
        if layout is not None:
            self._group_cache = {"layout": layout, "static": statics}

    def grid(self) -> OptionGrid:
        if self._grid is None or self._grid.seqnum != self.catalog.seqnum:
            old = self._grid or self._donor_grid
            self._donor_grid = None
            self._grid = build_grid(self.catalog, reuse=old)
            if old is None or self._grid.alloc_t is not old.alloc_t \
                    or self._dev_alloc_t is None:
                self._dev_alloc_t = buckets.tracked_device_put(
                    self._grid.alloc_t, "catalog")
                self._dev_tiebreak = buckets.tracked_device_put(
                    self._grid.tiebreak, "catalog")
        return self._grid

    def solve(
        self,
        pods: "list[PodSpec]",
        existing: Sequence[ExistingNode] = (),
        daemon_overhead: Optional[Sequence[int]] = None,
        n_slots: Optional[int] = None,
        option_mask=None,
    ) -> SolveResult:
        """Two-round driver (shared semantics with the oracle's schedule):
        groups whose required pod-(anti-)affinity terms target CO-PENDING
        groups are deferred; round 1's solved claims join `existing` as
        pseudo nodes carrying their pods as residents, so round 2 resolves
        the terms through the resident-based affinity machinery.

        `option_mask` (bool [T, S] or None) is the spot plane's
        diversity-floor dimension: it ANDs into new-node admission on both
        rounds (models/encode.py encode_problem), matching the oracle
        Scheduler's `barred` pool filter bit-for-bit."""
        import time as _time

        from ..oracle.scheduler import split_deferred_pods
        from ..profiling import GAP_LEDGER

        # gap-ledger wall bracket: outermost opener wins (the service RPC
        # scope subsumes this one), so for in-process callers this IS the
        # headline wall both rounds' phase notes are accounted against
        with GAP_LEDGER.solve_scope("solver"):
            # the affinity-round split scans every pod — that is host
            # problem preparation, so it files under encode (at 10k pods
            # it is ~1 ms, the biggest pre-_solve_once chunk of wall)
            _t0 = _time.perf_counter()
            primary, deferred = split_deferred_pods(pods)
            GAP_LEDGER.note("encode", _time.perf_counter() - _t0,
                            lane="encode")
            if not deferred:
                return self._solve_once(pods, existing, daemon_overhead,
                                        n_slots, option_mask=option_mask)
            res = self._solve_once(primary, existing, daemon_overhead,
                                   n_slots, option_mask=option_mask)
            # Round 2 must see round 1's consumption of the REAL existing
            # nodes (the oracle mutates its views in place; this path
            # re-encodes, so carry used + origin-keyed in-run counts on
            # fresh copies).
            _t1 = _time.perf_counter()
            carried = _carry_round1_existing(existing, res)
            pseudo = self._nodes_as_existing(res, daemon_overhead)
            GAP_LEDGER.note("encode", _time.perf_counter() - _t1,
                            lane="encode")
            res2 = self._solve_once(deferred, carried + pseudo,
                                    daemon_overhead, n_slots,
                                    option_mask=option_mask)
            _t2 = _time.perf_counter()
            merged = _merge_rounds(res, res2, {p.name: i for i, p in
                                               enumerate(pseudo)})
            GAP_LEDGER.note("decode", _time.perf_counter() - _t2,
                            lane="encode")
            return merged

    def solve_many(
        self,
        problems: "Sequence[dict]",
    ) -> "list[SolveResult]":
        """Wave-pipelined batch of independent solves: problems bucket by
        padded shape and each bucket runs as ONE vmapped kernel dispatch
        (wave size padded to a power-of-two so K never mints a new
        compile); all buckets' flat outputs are concatenated device-side
        and fetched with ONE device->host read. Each problem is a dict of
        solve() kwargs (pods, existing, daemon_overhead, n_slots).

        Rationale (docs/designs/solver-boundary.md): on a tunneled device
        the d2h read is both the latency floor (one RTT) and — measured on
        this deployment's relay — a *state degrader*: the first read drops
        the session out of streaming mode. A controller cycle that needs
        provisioning + consolidation + N drift simulations pays one read
        instead of N+2. Problems whose pods carry co-pending affinity terms
        need the two-round driver and fall back to solve() (still correct,
        one extra read each — rare in practice).
        """
        from ..profiling import GAP_LEDGER

        # one wall bracket for the whole wave: solo fallbacks recurse into
        # solve(), whose nested scope is transparent, so every problem's
        # phase notes accumulate against this single wall measurement
        with GAP_LEDGER.solve_scope("solver.many"):
            return self._solve_many_impl(problems)

    def _solve_many_impl(
        self,
        problems: "Sequence[dict]",
    ) -> "list[SolveResult]":
        import time as _time

        import jax.numpy as jnp

        from ..oracle.scheduler import split_deferred_pods
        from ..profiling import GAP_LEDGER

        # ONE catalog snapshot for the whole wave — but encode_problem
        # rebuilds a grid whose seqnum went stale (a concurrent catalog
        # bump mid-loop), so coherence is enforced the other way around:
        # each problem ships the catalog arrays of the grid its encode
        # ACTUALLY used (enc.alloc_t IS grid.alloc_t), the device-resident
        # copies are substituted only while that is still the snapshot,
        # and the bucket key carries the array identity so lanes from
        # different grids can never stack.
        wave_grid = self.grid()
        dev_alloc_t, dev_tiebreak = self._dev_alloc_t, self._dev_tiebreak
        t_enc0 = _time.perf_counter()
        slots: "list[tuple]" = []  # (mode, payload)
        for prob in problems:
            pods = prob.get("pods", [])
            existing = prob.get("existing", ())
            overhead = prob.get("daemon_overhead")
            n_slots = prob.get("n_slots")
            # cheap pre-check (attribute scan) before the real split: only
            # affinity-bearing pod sets can need the two-round driver, and
            # solve() will redo the split for those anyway
            if any(p.pod_affinity or p.pod_anti_affinity for p in pods) \
                    and split_deferred_pods(pods)[1]:
                slots.append(("solo", prob))
                continue
            enc = encode_problem(
                self.catalog, self.provisioners, pods, existing,
                overhead, n_slots, grid=wave_grid,
                group_cache=self._group_cache,
            )
            if enc.alloc_t is wave_grid.alloc_t:
                inputs, dims, up = build_pack_inputs(enc, dev_alloc_t,
                                                     dev_tiebreak)
            else:  # encode rebuilt a fresh grid (catalog bumped mid-wave)
                inputs, dims, up = build_pack_inputs(enc)
            slots.append(("wave", (enc, inputs, dims, up, list(existing))))
        GAP_LEDGER.note("encode", _time.perf_counter() - t_enc0,
                        lane="encode")

        # Same-shape problems fold into ONE vmapped dispatch per bucket
        # (degraded-link cost is per device OPERATION, not per byte —
        # solver-boundary.md), then all buckets concatenate into one read.
        shape_waves: "dict[tuple, list[int]]" = {}
        for i, (mode, payload) in enumerate(slots):
            if mode != "wave":
                continue
            _enc, inputs, dims, up, _ex = payload
            key = (dims, up, id(inputs.alloc_t),  # grid identity
                   inputs.group_vec.shape[1],  # compressed resource width
                   inputs.res_sel is not None,
                   inputs.ex_cap is not None,
                   inputs.group_origin is not None,
                   inputs.prov_overhead is not None,
                   inputs.prov_pods_cap is not None)
            shape_waves.setdefault(key, []).append(i)
        t_link0 = _time.perf_counter()
        flats: "list[tuple[list[int], object]]" = []  # (slot idxs, [K,L] dev)
        for key, idxs in shape_waves.items():
            (_gb, Nb, _neb), up = key[0], key[1]
            members = [slots[i][1][1] for i in idxs]
            if len(members) == 1:
                dev = jax.device_put(members[0])
                flat2d = pack_flat(dev, n_slots=Nb, use_pallas=up)[None, :]
            else:
                dev = jax.device_put(_stack_pack_inputs(members))
                flat2d = _wave_pack_flat(dev, Nb, up)
            flats.append((idxs, flat2d))
        GAP_LEDGER.note("link", _time.perf_counter() - t_link0,
                        lane="solver")
        fetched: "dict[int, PackResult]" = {}
        if flats:
            t_fetch0 = _time.perf_counter()
            cat = host_fetch(jnp.concatenate(
                [f.reshape(-1) for _, f in flats]))
            GAP_LEDGER.note("device_exec",
                            _time.perf_counter() - t_fetch0, lane="device")
            off = 0
            for idxs, f in flats:
                K, L = f.shape
                for j, slot_i in enumerate(idxs):
                    dims = slots[slot_i][1][2]
                    fetched[slot_i] = unflatten_result(
                        cat[off + j * L: off + (j + 1) * L], *dims)
                off += K * L

        out: "list[SolveResult]" = []
        t_dec = 0.0
        for i, (mode, payload) in enumerate(slots):
            if mode == "solo":
                out.append(self.solve(
                    payload.get("pods", []), payload.get("existing", ()),
                    payload.get("daemon_overhead"), payload.get("n_slots")))
            else:
                enc, _, _, _, existing = payload
                t_dec0 = _time.perf_counter()
                out.append(decode(enc, fetched[i],
                                  [e.name for e in existing]))
                t_dec += _time.perf_counter() - t_dec0
        GAP_LEDGER.note("decode", t_dec, lane="encode")
        return out

    def warm_shapes(self, shapes: "Sequence[tuple]",
                    limit: int = 8) -> "list[str]":
        """Pre-jit the pack programs for raw problem shapes (Sync-time
        compile-cache warmup): the first real Solve of a bucket then never
        eats XLA compile latency. Each shape is (G, n_slots, Ne) or the
        extended last_shape_key (adds Pv + optional-leaf flags). Dispatches
        a zero-count synthetic problem at the bucketed shape through the
        REAL dispatch path — group_count=0 rows are kernel no-ops, so the
        execution is cheap and only the compile is bought. Returns the
        bucket labels that actually compiled something new."""
        grid = self.grid()
        warmed: "list[str]" = []
        seen: "set[tuple]" = set()
        for shape in list(shapes)[:max(0, limit)]:
            G, slots_n, Ne = int(shape[0]), int(shape[1]), int(shape[2])
            pv = int(shape[3]) if len(shape) > 3 else max(
                1, len(self.provisioners))
            flags = (tuple(bool(f) for f in shape[4:8])
                     if len(shape) >= 8 else (False, False, False, False))
            plan = buckets.plan_for(G, slots_n, Ne)
            key = (plan, pv, flags)
            if key in seen:
                continue
            seen.add(key)
            inputs = self._synth_inputs(grid, plan, pv, flags)
            # mirror build_pack_inputs' pallas gate: zero deltas are
            # trivially value-safe, the catalog arrays decide
            use_pallas = pallas_kernels.enabled() and pallas_value_safe(
                grid.alloc_t)
            route = ("single" if self._router is None
                     or self._mesh_ctx is None
                     else self._router.steady_route(plan))
            before = _dispatch_cache_size()
            if route == "sharded":
                flat = self._mesh_ctx.dispatch_flat(
                    inputs, plan.slots, use_pallas, grid,
                    donate=_donate_deltas())
            else:
                flat = dispatch_pack_inputs(
                    inputs, (plan.groups, plan.slots, plan.existing),
                    use_pallas)
            flat.block_until_ready()
            after = _dispatch_cache_size()
            if before >= 0 and after > before:
                buckets.COMPILE_WARMUPS.inc()
                warmed.append(plan.label())
            # measured roofline (ISSUE 18): warmup is the one moment the
            # rung's compiled program is in hand and off the hot path, so
            # capture XLA's own cost/memory analysis here — the floor the
            # kernel arc chases becomes the compiler's number, and drift
            # against the hand model is checked per rung
            if route == "single":
                _capture_measured_roofline(inputs, plan, pv, use_pallas)
        return warmed

    def _synth_inputs(self, grid: OptionGrid, plan: "buckets.BucketPlan",
                      pv: int, flags: "tuple") -> PackInputs:
        """Zero-count PackInputs at exactly the padded shapes (and dtypes)
        build_pack_inputs would produce, against the resident catalog
        arrays — compiling through these hits the same jit cache entries
        real solves will."""
        has_ex_cap, has_origin, has_prov_ovh, has_pods_cap = flags
        T, S = grid.tiebreak.shape
        # compressed resource layout, like build_pack_inputs produces for
        # typical (<=4 active resources) problems: zero-demand synthetic
        # groups land on the bottom "resources" rung, which is also where
        # real cpu+mem+pods workloads land — warming any other width would
        # compile a program no real solve dispatches
        R = buckets.LADDERS["resources"][0]
        res_sel = np.zeros((R,), np.int32)
        res_sel[0] = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
        res_mask = np.arange(R) < 1
        Gb, Nb, Neb = plan.groups, plan.slots, plan.existing
        return PackInputs(
            alloc_t=self._dev_alloc_t, tiebreak=self._dev_tiebreak,
            group_vec=np.zeros((Gb, R), np.int32),
            group_count=np.zeros((Gb,), np.int32),
            group_cap=np.full((Gb,), int(INT_BIG), np.int32),
            group_feas=np.zeros((Gb, pv, T, S), bool),
            group_newprov=np.full((Gb,), -1, np.int32),
            overhead=np.zeros((R,), np.int32),
            ex_alloc=np.zeros((Neb, R), np.int32),
            ex_used=np.zeros((Neb, R), np.int32),
            ex_feas=np.zeros((Gb, Neb), bool),
            prov_overhead=(np.zeros((pv, R), np.int32)
                           if has_prov_ovh else None),
            prov_pods_cap=(np.zeros((pv, T), np.int32)
                           if has_pods_cap else None),
            ex_cap=(np.full((Gb, Neb), int(INT_BIG), np.int32)
                    if has_ex_cap else None),
            group_origin=(np.arange(Gb, dtype=np.int32)
                          if has_origin else None),
            res_sel=res_sel, res_mask=res_mask,
        )

    def _nodes_as_existing(self, res: SolveResult,
                           daemon_overhead) -> "list[ExistingNode]":
        """Round-1 claims as existing nodes (mirror of the oracle's
        _claims_as_existing: decided-option labels/alloc, pods resident)."""
        from ..oracle.scheduler import (effective_alloc,
                                        kubelet_overhead_vector, option_labels)

        out = []
        for i, n in enumerate(res.nodes):
            used = [d + k for d, k in zip(
                list(daemon_overhead or [0] * wk.NUM_RESOURCES),
                kubelet_overhead_vector(n.provisioner.kubelet))]
            resident: "list[PodSpec]" = []
            for g_idx, count in n.pod_counts.items():
                spec = res.groups[g_idx].spec
                vec = res.groups[g_idx].vector
                for r in range(wk.NUM_RESOURCES):
                    used[r] += vec[r] * count
                resident.extend([spec] * count)
            out.append(ExistingNode(
                name=f"__round1-claim-{i}",
                labels=option_labels(n.option, n.provisioner),
                allocatable=list(effective_alloc(n.option, n.provisioner)),
                used=used,
                taints=n.provisioner.taints,
                resident=tuple(resident),
            ))
        return out

    def _solve_once(
        self,
        pods: "list[PodSpec]",
        existing: Sequence[ExistingNode] = (),
        daemon_overhead: Optional[Sequence[int]] = None,
        n_slots: Optional[int] = None,
        option_mask=None,
    ) -> SolveResult:
        # one code path, timed always (perf_counter is ns against a multi-ms
        # solve); .last_timings is only published under the capture tool's
        # KARPENTER_TPU_SOLVE_TIMING=1 flag. Phases: encode/dispatch are
        # host work + async enqueue, fetch is the one device sync, decode
        # is host-side result shaping (docs/designs/solver-boundary.md).
        import time as _time

        from ..tracing import TRACER

        t0 = _time.perf_counter()
        enc = encode_problem(
            self.catalog, self.provisioners, pods, existing,
            daemon_overhead, n_slots, grid=self.grid(),
            group_cache=self._group_cache, option_mask=option_mask,
        )
        t1 = _time.perf_counter()
        G = enc.group_vec.shape[0]
        Ne = enc.ex_alloc.shape[0]
        cache_before = _dispatch_cache_size()
        inputs, dims, use_pallas = build_pack_inputs(
            enc, self._dev_alloc_t, self._dev_tiebreak)
        plan = buckets.BucketPlan(groups=dims[0], slots=dims[1],
                                  existing=dims[2])
        route = "single"
        if self._router is not None and self._mesh_ctx is not None:
            route = self._router.route(plan)
        if route == "sharded":
            # enc.grid is the grid this encode actually used (the resident
            # sharded catalog arrays are keyed on its identity)
            flat = self._mesh_ctx.dispatch_flat(
                inputs, dims[1], use_pallas, enc.grid,
                donate=_donate_deltas())
        else:
            flat = dispatch_pack_inputs(inputs, dims, use_pallas)
        cache_after = _dispatch_cache_size()
        t2 = _time.perf_counter()
        result = fetch_pack(flat, dims)
        t3 = _time.perf_counter()
        out = decode(enc, result, [e.name for e in existing])
        t4 = _time.perf_counter()
        if cache_before < 0 or cache_after < 0:
            compile_cache = "unknown"
        elif cache_after > cache_before:
            compile_cache = "miss"
            buckets.COMPILE_MISSES.inc()
        else:
            compile_cache = "hit"
            buckets.COMPILE_HITS.inc()
        buckets.observe_plan(plan, G, enc.n_slots, Ne, route)
        pv = enc.group_feas.shape[1]
        self.last_shape_key = (
            G, enc.n_slots, Ne, pv,
            enc.ex_cap is not None, enc.group_origin is not None,
            enc.prov_overhead is not None, enc.prov_pods_cap is not None)
        # always-on per-solve observability: the tracing plane reads this on
        # both sides of the solver wire (service.py echoes it into
        # SolveResponse; the controller's solve span records it). fetch is
        # the ONE device->host read — its wall time IS the transfer cost.
        self.last_solve_info = {
            "encode_ms": round((t1 - t0) * 1000, 3),
            "dispatch_ms": round((t2 - t1) * 1000, 3),
            "transfer_ms": round((t3 - t2) * 1000, 3),
            "decode_ms": round((t4 - t3) * 1000, 3),
            "compile_cache": compile_cache,
            "routing": "tpu-sharded" if route == "sharded" else "tpu",
            "bucket": plan.label(),
            "device_count": (self._mesh_ctx.device_count
                             if route == "sharded" else 1),
        }
        TRACER.annotate(**self.last_solve_info)
        # decision provenance: the winning bucket rung + mask-dimension
        # vocabulary ride along for the DecisionRecord the controller
        # emits after this solve. Gated so a disabled explain plane
        # leaves the hot path byte-identical (explain-strict-noop).
        from .. import explain
        if explain.enabled():
            self.last_solve_info["decision"] = {
                "rung": plan.rung(),
                "dimensions": MASK_DIMENSIONS,
            }
        # The formerly-dark solver interior becomes first-class phase spans
        # (children of the current solve/service span). Dispatch splits by
        # compile-cache outcome: a hit is pure execute; a miss's wall time
        # is dominated by the XLA compile — distinct span names keep the
        # execute-latency distribution unpolluted by compile stalls, and
        # (miss p50 − hit p50) IS the measured compile cost.
        TRACER.record_span("solver.encode", t1 - t0)
        TRACER.record_span(
            "solver.dispatch.execute" if compile_cache == "hit"
            else "solver.dispatch.compile",
            t2 - t1, compile_cache=compile_cache, bucket=plan.label())
        TRACER.record_span("solver.transfer", t3 - t2)
        TRACER.record_span("solver.decode", t4 - t3)
        # gap-ledger attribution: the same intervals, filed against the
        # enclosing wall scope (solve()/service). fetch is the device sync,
        # so t3-t2 is the device_exec evidence; dispatch wall is host
        # link/compile work plus the async enqueue.
        from ..profiling import GAP_LEDGER
        from ..profiling.continuous import detect_backend
        # end_pc pins each interval at its REAL phase boundary (these four
        # notes fire in a burst after the fact): the critical plane then
        # sees the true serial chain encode->link->fetch->decode instead
        # of four artificially stacked intervals
        GAP_LEDGER.note("encode", t1 - t0, lane="encode", end_pc=t1)
        GAP_LEDGER.note("link", t2 - t1, lane="solver", end_pc=t2)
        GAP_LEDGER.note("device_exec", t3 - t2, lane="device", end_pc=t3)
        GAP_LEDGER.note("decode", t4 - t3, lane="encode", end_pc=t4)
        tb_shape = getattr(enc.grid.tiebreak, "shape", (16, 4))
        GAP_LEDGER.annotate(
            bucket=plan.label(), route=route,
            groups=plan.groups, slots=plan.slots, existing=plan.existing,
            pv=pv, t=int(tb_shape[0]), s=int(tb_shape[-1]),
            backend=detect_backend(),
            device_count=self.last_solve_info["device_count"])
        if _SOLVE_TIMING:
            self.last_timings = {
                "encode_ms": self.last_solve_info["encode_ms"],
                "dispatch_ms": self.last_solve_info["dispatch_ms"],
                "fetch_ms": self.last_solve_info["transfer_ms"],
                "decode_ms": self.last_solve_info["decode_ms"],
            }
        return out


def _carry_round1_existing(existing: "Sequence[ExistingNode]",
                           res: SolveResult) -> "list[ExistingNode]":
    """Fresh ExistingNode copies reflecting round-1 placements: used grows
    by the placed vectors, and group_counts carries the origin-keyed in-run
    counts (the oracle's cap rule is resident_counts[okey] +
    group_counts[okey]; encode_problem consumes both). `resident` stays
    untouched — round-1 placements are NOT affinity anchors in the oracle's
    round 2 either (they live in assignments, not resident)."""
    out: "list[ExistingNode]" = []
    for e in existing:
        per_group = res.existing_by_group.get(e.name, {})
        used = list(e.used)
        # pre-seeded counts are part of the contract now (encode subtracts
        # them from ex_cap); chained solves must not reset them
        counts: "dict[object, int]" = dict(e.group_counts)
        for g_idx, count in per_group.items():
            vec = res.groups[g_idx].vector
            for r in range(wk.NUM_RESOURCES):
                used[r] += vec[r] * count
            okey = res.groups[g_idx].spec.origin_key()
            counts[okey] = counts.get(okey, 0) + count
        ne = ExistingNode(name=e.name, labels=e.labels,
                          allocatable=list(e.allocatable), used=used,
                          taints=e.taints, resident=e.resident)
        ne.group_counts = counts
        out.append(ne)
    return out


def _merge_rounds(res: SolveResult, res2: SolveResult,
                  pseudo_index: "dict[str, int]") -> SolveResult:
    """Fold the deferred round back: group indices offset by round-1's
    group count; dependents placed on pseudo nodes join the claim's
    pod_counts; real-node assignments and unschedulables merge."""
    offset = len(res.groups)
    groups = list(res.groups) + list(res2.groups)
    nodes = list(res.nodes)
    for name, per_group in res2.existing_by_group.items():
        claim_i = pseudo_index.get(name)
        if claim_i is None:
            continue
        counts = nodes[claim_i].pod_counts
        for g_idx, count in per_group.items():
            counts[g_idx + offset] = counts.get(g_idx + offset, 0) + count
    nodes.extend(dataclasses.replace(
        n, pod_counts={g + offset: c for g, c in n.pod_counts.items()})
        for n in res2.nodes)
    existing_by_group = {name: dict(d)
                         for name, d in res.existing_by_group.items()}
    for name, per_group in res2.existing_by_group.items():
        if name in pseudo_index:
            continue
        tgt = existing_by_group.setdefault(name, {})
        for g_idx, count in per_group.items():
            tgt[g_idx + offset] = tgt.get(g_idx + offset, 0) + count
    existing_counts = {name: sum(d.values())
                       for name, d in existing_by_group.items() if d}
    unschedulable = dict(res.unschedulable)
    for g_idx, count in res2.unschedulable.items():
        unschedulable[g_idx + offset] = count
    return SolveResult(nodes, existing_counts, unschedulable, groups,
                       existing_by_group)


class NativeSolver(TPUSolver):
    """Same encode/decode pipeline, C++ scan instead of the device kernel
    (karpenter_tpu/native/). The controller's fallback backend when the TPU
    sidecar is unreachable — and the preferred path for small solves, where
    a tunneled-device round trip would dominate the latency budget. No
    padding/bucketing: dynamic shapes are free on the host."""

    def solve_many(self, problems: "Sequence[dict]") -> "list[SolveResult]":
        """In-process host scans have no read budget to amortize — a plain
        loop keeps the host-only contract (no jax dispatch ever)."""
        return [self.solve(p.get("pods", []), p.get("existing", ()),
                           p.get("daemon_overhead"), p.get("n_slots"))
                for p in problems]

    def grid(self) -> OptionGrid:
        if self._grid is None or self._grid.seqnum != self.catalog.seqnum:
            # host-only: no device_put; a stale or donated grid is only a
            # build_grid reuse donor (layout_key decides, never seqnum)
            old = self._grid or self._donor_grid
            self._donor_grid = None
            self._grid = build_grid(self.catalog, reuse=old)
        return self._grid

    def _solve_once(
        self,
        pods: "list[PodSpec]",
        existing: Sequence[ExistingNode] = (),
        daemon_overhead: Optional[Sequence[int]] = None,
        n_slots: Optional[int] = None,
        option_mask=None,
    ) -> SolveResult:
        from ..native import native_pack

        enc = encode_problem(
            self.catalog, self.provisioners, pods, existing,
            daemon_overhead, n_slots, grid=self.grid(),
            group_cache=self._group_cache, option_mask=option_mask,
        )
        inputs = PackInputs(
            alloc_t=enc.alloc_t, tiebreak=enc.tiebreak,
            group_vec=enc.group_vec, group_count=enc.group_count,
            group_cap=enc.group_cap, group_feas=enc.group_feas,
            group_newprov=enc.group_newprov, overhead=enc.overhead,
            ex_alloc=enc.ex_alloc, ex_used=enc.ex_used, ex_feas=enc.ex_feas,
            prov_overhead=enc.prov_overhead, prov_pods_cap=enc.prov_pods_cap,
            ex_cap=enc.ex_cap, group_origin=enc.group_origin,
        )
        result = native_pack(inputs, n_slots=enc.n_slots)
        out = decode(enc, result, [e.name for e in existing])
        # host-only path: no device transfer, no jit cache in play
        self.last_solve_info = {"transfer_ms": 0.0, "compile_cache": "n/a"}
        return out


def build_pack_inputs(enc: EncodedProblem, dev_alloc_t=None,
                      dev_tiebreak=None):
    """Pad to shape buckets and assemble host-side PackInputs — no device
    work. Returns (inputs, (Gb, Nb, Neb), use_pallas). dispatch_pack ships
    and enqueues one problem; solve_many stacks same-shape inputs from
    several problems into ONE vmapped dispatch (_wave_pack_flat)."""
    G = enc.group_vec.shape[0]
    Gb = _bucket(G)
    Ne = enc.ex_alloc.shape[0]
    Neb = _bucket(Ne, lo=1)
    Nb = _bucket(enc.n_slots)

    def pad(a, n, axis=0, fill=0):
        if a.shape[axis] == n:
            return a
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, n - a.shape[axis])
        return np.pad(a, widths, constant_values=fill)

    # Resource-axis compression (packer.PackInputs.res_sel): the [N, T, R]
    # quotient tensor is the kernel's per-step compute floor and typical
    # workloads demand 3-4 of the wellknown resources, so gather the active
    # columns (demanded by ANY group; pods always, and always first — the
    # kernel's pods-cap path needs a static index) and ship the compressed
    # leaves. alloc_t stays full-width (it is the Sync-resident catalog
    # array); the kernel gathers its columns device-side off res_sel.
    # Exact by the INT_BIG convention: a column with zero demand everywhere
    # quotients to INT_BIG whatever its availability. Wider-than-ladder
    # problems keep the legacy full-width layout.
    pods_res = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
    R_full = enc.group_vec.shape[1]
    act = enc.group_vec.max(axis=0) > 0
    act[pods_res] = True
    n_act = int(act.sum())
    res_sel = res_mask = None
    if n_act <= buckets.LADDERS["resources"][-1] < R_full:
        Rb = buckets.bucket_up(n_act, "resources")
        others = np.flatnonzero(act)
        sel = np.concatenate(
            ([pods_res], others[others != pods_res])).astype(np.int32)
        res_sel = np.zeros((Rb,), np.int32)
        res_sel[:n_act] = sel
        res_mask = np.arange(Rb) < n_act

        def rsel(a):
            if a is None:
                return None
            out = a[..., res_sel]
            out[..., n_act:] = 0
            return out

        enc = dataclasses.replace(
            enc, group_vec=rsel(enc.group_vec), overhead=rsel(enc.overhead),
            ex_alloc=rsel(enc.ex_alloc), ex_used=rsel(enc.ex_used),
            prov_overhead=rsel(enc.prov_overhead))

    ex_feas = pad(enc.ex_feas, Gb)
    if ex_feas.shape[1] != Neb:
        ex_feas = pad(ex_feas, Neb, axis=1)
    ex_cap = enc.ex_cap
    if ex_cap is not None:
        ex_cap = pad(pad(ex_cap, Gb, fill=int(INT_BIG)), Neb, axis=1,
                     fill=int(INT_BIG))
    group_origin = enc.group_origin
    if group_origin is not None:
        # padded rows are their own origin (identity) so they stay no-ops
        ident = np.arange(Gb, dtype=np.int32)
        ident[:group_origin.shape[0]] = group_origin
        group_origin = ident
    inputs = PackInputs(
        alloc_t=dev_alloc_t if dev_alloc_t is not None else enc.alloc_t,
        tiebreak=dev_tiebreak if dev_tiebreak is not None else enc.tiebreak,
        group_vec=pad(enc.group_vec, Gb),
        group_count=pad(enc.group_count, Gb),
        group_cap=pad(enc.group_cap, Gb),
        group_feas=pad(enc.group_feas, Gb),
        group_newprov=pad(enc.group_newprov, Gb, fill=-1),
        overhead=enc.overhead,
        ex_alloc=pad(enc.ex_alloc, Neb),
        ex_used=pad(enc.ex_used, Neb),
        ex_feas=ex_feas,
        prov_overhead=enc.prov_overhead, prov_pods_cap=enc.prov_pods_cap,
        ex_cap=ex_cap, group_origin=group_origin,
        res_sel=res_sel, res_mask=res_mask,
    )
    # Pallas engages only when the env flag is on AND every input magnitude
    # is below the f32-exactness bound (checked on host arrays; see
    # packer.pallas_value_safe) — oversized problems take the XLA path.
    use_pallas = pallas_kernels.enabled() and pallas_value_safe(
        enc.alloc_t, enc.ex_alloc, enc.group_vec, enc.overhead,
        enc.prov_overhead)
    return inputs, (Gb, Nb, Neb), use_pallas


def _donate_deltas() -> bool:
    """Donate per-solve delta buffers to the kernel where the backend can
    actually reuse them (donation is unimplemented on CPU and only emits
    warnings there). The resident catalog tuple is NEVER donated — it must
    survive the solve for the next cycle."""
    return jax.default_backend() not in ("cpu",)


_PACK_FNS: "dict[bool, object]" = {}
_PACK_FNS_LOCK = threading.Lock()


def _resident_pack_fn(donate: bool):
    """Jitted single-device pack over SPLIT arguments: (cat, delta) where
    cat = (alloc_t, tiebreak) is the Sync-resident catalog tuple and delta
    is the per-solve PackInputs with those two leaves None'd out. The split
    exists so donation can cover exactly the delta (argnums=1): donated
    catalog buffers would be consumed by the first solve and force a
    re-upload every cycle — the opposite of residency."""
    with _PACK_FNS_LOCK:
        fn = _PACK_FNS.get(donate)
        if fn is None:
            def impl(cat, delta, n_slots, use_pallas):
                inputs = delta._replace(alloc_t=cat[0], tiebreak=cat[1])
                return pack_flat_impl(inputs, n_slots,
                                      use_pallas=use_pallas)

            fn = jax.jit(impl, static_argnames=("n_slots", "use_pallas"),
                         donate_argnums=(1,) if donate else ())
            _PACK_FNS[donate] = fn
        return fn


def _capture_measured_roofline(inputs: PackInputs, plan, pv: int,
                               use_pallas: bool) -> None:
    """AOT-lower the rung's resident pack program and file XLA's own
    cost_analysis / memory_analysis numbers into the measured roofline
    (profiling/roofline.record_measured, with the drift check against the
    hand model). Warmup-only and advisory: any failure degrades to the
    modelled floor, never to a failed warmup."""
    from ..profiling import critical as profiling_critical
    from ..profiling import roofline as profiling_roofline
    from ..profiling import state as profiling_state

    if not (profiling_state.enabled() and profiling_critical.enabled()):
        return
    try:
        cat = (inputs.alloc_t, inputs.tiebreak)
        delta = inputs._replace(alloc_t=None, tiebreak=None)
        compiled = _resident_pack_fn(_donate_deltas()).lower(
            cat, delta, plan.slots, use_pallas).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return
        flops = float(ca.get("flops", 0.0) or 0.0)
        bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
        mem = None
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                mem = float(
                    getattr(ma, "temp_size_in_bytes", 0)
                    + getattr(ma, "argument_size_in_bytes", 0)
                    + getattr(ma, "output_size_in_bytes", 0))
        except Exception:  # noqa: BLE001 — optional on some backends
            mem = None
        from ..profiling.continuous import detect_backend

        backend = detect_backend()
        tb_shape = getattr(inputs.tiebreak, "shape", (16, 4))
        modelled = profiling_roofline.estimate(
            plan.groups, plan.slots, plan.existing, pv=pv,
            t=int(tb_shape[0]), s=int(tb_shape[-1]),
            backend=backend, bucket=plan.label())
        profiling_roofline.record_measured(
            plan.label(), flops=flops, bytes_accessed=bytes_accessed,
            backend=backend, modelled=modelled, memory_bytes=mem)
    except Exception:  # noqa: BLE001 — advisory capture only
        pass


def dispatch_pack_inputs(inputs: PackInputs, dims, use_pallas):
    """ENQUEUE already-padded PackInputs on the single-chip kernel — no
    device read. Catalog leaves ride resident (tracked_device_put is a
    counted no-op when they already live on device); delta leaves are
    uploaded per solve and donated back to the kernel off-CPU."""
    cat = (buckets.tracked_device_put(inputs.alloc_t, "catalog"),
           buckets.tracked_device_put(inputs.tiebreak, "catalog"))
    delta = buckets.tracked_tree_put(
        inputs._replace(alloc_t=None, tiebreak=None), "delta")
    # One jitted dispatch returning ONE flat buffer: decode pays exactly one
    # device->host round trip (the tunnel RTT floor; SURVEY.md §7.3).
    return _resident_pack_fn(_donate_deltas())(cat, delta, dims[1],
                                               use_pallas)


def _dispatch_cache_size() -> int:
    """Total compiled-program count across every solver dispatch entry
    point (packer jits + resident split fns + wave vmap + sharded mesh
    fns). -1 when the jit cache introspection API is unavailable — callers
    treat that as 'unknown', never as 'hit'."""
    from ..ops.packer import pack_cache_size

    total = pack_cache_size()
    if total < 0:
        return -1
    try:
        with _PACK_FNS_LOCK:
            for fn in _PACK_FNS.values():
                total += fn._cache_size()
        total += _wave_pack_flat._cache_size()
    except Exception:
        return -1
    from ..parallel.sharded import sharded_flat_cache_size

    sharded = sharded_flat_cache_size()
    if sharded < 0:
        return -1
    return total + sharded


def dispatch_pack(enc: EncodedProblem, dev_alloc_t=None, dev_tiebreak=None):
    """build_pack_inputs + ENQUEUE the jitted kernel — no device read.
    Returns (flat device array, (Gb, Nb, Neb)); fetch_pack turns it into a
    PackResult. Dispatch and fetch are separate so wave callers
    (solve_many) can overlap
    dispatches and pay a single device->host read for the whole wave —
    on a tunneled device each read is a full round trip, and (measured on
    the deployment tunnel, docs/designs/solver-boundary.md) the FIRST read
    also degrades the link's sync latency for the session, so reads are the
    scarcest resource the solver spends."""
    inputs, dims, use_pallas = build_pack_inputs(enc, dev_alloc_t,
                                                 dev_tiebreak)
    flat = dispatch_pack_inputs(inputs, dims, use_pallas)
    return flat, dims


def _stack_pack_inputs(members: "list[PackInputs]") -> PackInputs:
    """Stack same-shape per-problem leaves along a new leading K axis,
    padding K to a power-of-two bucket (lo=2) by repeating the first
    member so wave size never mints a fresh compiled shape — the same
    bucketing doctrine as _bucket for G/N/Ne (duplicate rows are simply
    never read back). alloc_t/tiebreak (catalog arrays, possibly already
    device-resident) stay shared from the first member; None leaves stay
    None (tree.map skips empty subtrees)."""
    first = members[0]
    Kb = _bucket(len(members), lo=2)
    members = list(members) + [first] * (Kb - len(members))
    stripped = [m._replace(alloc_t=None, tiebreak=None) for m in members]
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *stripped)
    return stacked._replace(alloc_t=first.alloc_t, tiebreak=first.tiebreak)


@functools.partial(jax.jit, static_argnames=("n_slots", "use_pallas"))
def _wave_pack_flat(stacked: PackInputs, n_slots: int,
                    use_pallas: "bool | None"):
    """K same-shape problems as ONE vmapped kernel dispatch returning
    [K, L] flat results. In the tunnel's degraded link state every device
    operation costs a flat ~66ms sync slot (solver-boundary.md cost
    model), so a wave of K separate dispatches pays K slots — this folds
    them into one. alloc_t/tiebreak are shared (catalog arrays); every
    per-problem leaf carries a leading K axis."""
    from ..ops.packer import pack_flat_impl

    axes = jax.tree.map(lambda _: 0, stacked)._replace(
        alloc_t=None, tiebreak=None)
    return jax.vmap(
        lambda inp: pack_flat_impl(inp, n_slots, use_pallas=use_pallas),
        in_axes=(axes,))(stacked)


def fetch_pack(flat, dims) -> PackResult:
    """The single device->host read for a dispatched pack (routed through
    host_fetch, so KARPENTER_TPU_READBACK=callback covers it too)."""
    Gb, Nb, Neb = dims
    return unflatten_result(host_fetch(flat), Gb, Nb, Neb)


# -- callback readback (KARPENTER_TPU_READBACK=callback) ---------------------------
#
# host_fetch is the ONE device->host read primitive for the solver: the
# default is a literal jax.device_get; the callback mode emits the array
# host-ward from inside a tiny jitted program via io_callback instead, so
# no literal fetch ever runs and (on relays where the io probe confirms
# callbacks stream) the link never leaves streaming mode. One global
# ordered inbox: io_callback bodies are baked into the traced graph, so
# the sink must be a module-level function; the lock serializes
# dispatch->barrier->pop so concurrent solvers cannot interleave, and the
# inbox is cleared on entry AND exit so a failed fetch can never leak a
# stale buffer into the next one.

import collections as _collections
import threading as _threading

_CB_INBOX: "_collections.deque" = _collections.deque()
_CB_LOCK = _threading.Lock()


def _cb_sink(arr):
    # copy: callback arguments may alias runtime-owned transfer buffers
    # that are only valid for the duration of the callback
    _CB_INBOX.append(np.array(arr, copy=True))
    return np.int32(0)


@jax.jit
def _emit_via_cb(x):
    import jax.numpy as jnp
    from jax.experimental import io_callback

    return io_callback(_cb_sink, jax.ShapeDtypeStruct((), jnp.int32),
                       x, ordered=True)


def host_fetch(dev_arr) -> "np.ndarray":
    """Bring a device array to host through the configured readback
    transport. effects_barrier is the wait on the callback path —
    block_until_ready does not cover host callback delivery."""
    if _READBACK != "callback":
        return np.asarray(jax.device_get(dev_arr))
    with _CB_LOCK:
        _CB_INBOX.clear()
        try:
            _emit_via_cb(dev_arr).block_until_ready()
            jax.effects_barrier()
            if len(_CB_INBOX) != 1:
                raise RuntimeError(
                    f"callback readback delivered {len(_CB_INBOX)} buffers "
                    f"(expected 1)")
            return _CB_INBOX.popleft()
        finally:
            _CB_INBOX.clear()


def decode(enc: EncodedProblem, result: PackResult, existing_names: "list[str]") -> SolveResult:
    host = result  # already host-side numpy (see fetch_pack)
    assign, ex_assign, unsched = host.assign, host.ex_assign, host.unsched
    active, decided, nprov = host.active, host.decided, host.nprov
    G = len(enc.groups)

    nodes: "list[SolvedNode]" = []
    for n in np.nonzero(active)[0]:
        counts_col = assign[:G, n]
        counts = {int(g): int(counts_col[g]) for g in np.nonzero(counts_col)[0]}
        if decided[n] < 0:
            # defensive: an active slot must always retain >=1 option
            raise AssertionError(f"active claim slot {n} has no surviving option")
        nodes.append(SolvedNode(
            option=enc.grid.options[int(decided[n])], pod_counts=counts,
            provisioner=enc.provisioners[int(nprov[n])],
        ))
    ex_totals = ex_assign[:G].sum(axis=0)
    existing_counts = {
        name: int(ex_totals[e]) for e, name in enumerate(existing_names)
        if ex_totals[e] > 0
    }
    existing_by_group = {
        name: {int(g): int(ex_assign[g, e]) for g in range(G) if ex_assign[g, e] > 0}
        for e, name in enumerate(existing_names) if ex_totals[e] > 0
    }
    unschedulable = {int(g): int(unsched[g]) for g in np.nonzero(unsched[:G] > 0)[0]}
    return SolveResult(nodes, existing_counts, unschedulable, enc.groups,
                       existing_by_group)
