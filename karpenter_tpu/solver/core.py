"""TPU solver: encode -> pack kernel -> decode.

This is the "Solver half" of the architecture (SURVEY.md §7.1): the JAX
service the controller calls instead of running the scalar FFD loop. The
scalar oracle (karpenter_tpu/oracle/scheduler.py) remains the in-process
fallback with identical semantics (BASELINE.json north star).

Shape discipline (SURVEY.md §7.3 "dynamic shapes"): pod-group count, claim
slots and existing-node count are bucketed to powers of two and padded, so a
stream of differently-sized solves hits a handful of compiled programs, not a
recompilation per solve. Padded groups have count=0 / feas=False and are
no-ops in the kernel.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence

import jax
import numpy as np

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..models.encode import EncodedProblem, OptionGrid, build_grid, encode_problem
from ..models.instancetype import Catalog
from ..models.pod import PodSpec
from ..ops import pallas_kernels
from ..ops.packer import (INT_BIG, PackInputs, PackResult, pack_flat,
                          pallas_value_safe, unflatten_result)
from ..oracle.scheduler import ExistingNode, Option

import os as _os

# phase-attributed solves (encode/dispatch/fetch/decode wall-clock split,
# read from TPUSolver.last_timings) — capture-tool diagnostics only
_SOLVE_TIMING = _os.environ.get("KARPENTER_TPU_SOLVE_TIMING") == "1"

# Readback mechanism for EVERY solver device->host read (host_fetch —
# single solves and solve_many waves alike): "get" (default) is a literal
# jax.device_get; "callback" emits results host-ward through io_callback —
# the escape hatch for relays whose link degrades permanently after the
# session's first literal read (hack/tpu_capture.py _io_callback_probe
# measures whether the deployment's relay lets callbacks through in
# streaming mode; flip this on only where that probe's sync_after stays
# sub-ms).
_READBACK = _os.environ.get("KARPENTER_TPU_READBACK", "get")


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass
class SolvedNode:
    """One node decision (the Machine the controller would create)."""

    option: Option
    pod_counts: "dict[int, int]"  # group index -> pods
    provisioner: Provisioner

    @property
    def pod_count(self) -> int:
        return sum(self.pod_counts.values())


@dataclasses.dataclass
class SolveResult:
    nodes: "list[SolvedNode]"
    existing_counts: "dict[str, int]"  # existing node name -> pods placed
    unschedulable: "dict[int, int]"  # group index -> pod count
    groups: list
    # existing node name -> {group index -> pods placed} (binding plan)
    existing_by_group: "dict[str, dict[int, int]]" = dataclasses.field(default_factory=dict)

    def decisions(self) -> "list[tuple[str, str, str, int]]":
        """Fingerprint [(type, zone, capacityType, pods)] — comparable with
        oracle SchedulingResult.node_decisions()."""
        return sorted(
            (n.option.itype.name, n.option.zone, n.option.capacity_type, n.pod_count)
            for n in self.nodes
        )

    def unschedulable_count(self) -> int:
        return sum(self.unschedulable.values())


class TPUSolver:
    """Catalog-resident batched solver. Keeps the encoded option grid AND its
    device arrays resident across solves (reference analogue: the
    seqnum-memoized instance type cache, instancetypes.go:104-120) — only the
    per-solve group delta crosses the host-device boundary (SURVEY.md §7.3
    "ship only the pod delta")."""

    def __init__(self, catalog: Catalog, provisioners: Sequence[Provisioner],
                 reuse_from: "Optional[TPUSolver]" = None):
        self.catalog = catalog
        self.provisioners = list(provisioners)
        self._grid: Optional[OptionGrid] = None
        self._donor_grid: Optional[OptionGrid] = None
        self._dev_alloc_t = None
        self._dev_tiebreak = None
        # encode_group memo across solves (this instance's provisioner set is
        # fixed; layout/seqnum two-level invalidation — see encode_problem)
        self._group_cache: dict = {}
        if reuse_from is not None:
            self.adopt_static(reuse_from)

    def adopt_static(self, other: "TPUSolver",
                     share_group_cache: bool = True) -> None:
        """An evicted predecessor (solver caches rebuild on catalog content
        changes) donates its grid + group cache: when only availability
        changed (ICE churn), build_grid shares every static array and the
        cache's static level stays warm. The donation is a build_grid REUSE
        DONOR only, never installed as the live grid — seqnums are
        per-catalog counters (two distinct catalogs can share a seqnum), so
        only build_grid's layout_key check may decide what is reusable. The
        donated cache is layout-keyed internally, so adoption is safe even
        when the layout DID change (it just clears).

        share_group_cache=False copies the static level into a fresh dict
        instead of sharing the donor's — required when the donor STAYS LIVE
        (the solver service LRU keeps it serving other clients; two solvers
        mutating one cache dict would race and seqnum-thrash)."""
        if not isinstance(other, TPUSolver):
            return
        self._donor_grid = other._grid or other._donor_grid
        self._dev_alloc_t = other._dev_alloc_t
        self._dev_tiebreak = other._dev_tiebreak
        if list(other.provisioners) != self.provisioners:
            return
        if share_group_cache:
            self._group_cache = other._group_cache
            return
        try:
            src = other._group_cache
            layout = src.get("layout")
            statics = dict(src.get("static") or {})
        except RuntimeError:  # donor inserted concurrently mid-copy
            return
        if layout is not None:
            self._group_cache = {"layout": layout, "static": statics}

    def grid(self) -> OptionGrid:
        if self._grid is None or self._grid.seqnum != self.catalog.seqnum:
            old = self._grid or self._donor_grid
            self._donor_grid = None
            self._grid = build_grid(self.catalog, reuse=old)
            if old is None or self._grid.alloc_t is not old.alloc_t \
                    or self._dev_alloc_t is None:
                self._dev_alloc_t = jax.device_put(self._grid.alloc_t)
                self._dev_tiebreak = jax.device_put(self._grid.tiebreak)
        return self._grid

    def solve(
        self,
        pods: "list[PodSpec]",
        existing: Sequence[ExistingNode] = (),
        daemon_overhead: Optional[Sequence[int]] = None,
        n_slots: Optional[int] = None,
    ) -> SolveResult:
        """Two-round driver (shared semantics with the oracle's schedule):
        groups whose required pod-(anti-)affinity terms target CO-PENDING
        groups are deferred; round 1's solved claims join `existing` as
        pseudo nodes carrying their pods as residents, so round 2 resolves
        the terms through the resident-based affinity machinery."""
        from ..oracle.scheduler import split_deferred_pods

        primary, deferred = split_deferred_pods(pods)
        if not deferred:
            return self._solve_once(pods, existing, daemon_overhead, n_slots)
        res = self._solve_once(primary, existing, daemon_overhead, n_slots)
        # Round 2 must see round 1's consumption of the REAL existing nodes
        # (the oracle mutates its views in place; this path re-encodes, so
        # carry used + origin-keyed in-run counts on fresh copies).
        carried = _carry_round1_existing(existing, res)
        pseudo = self._nodes_as_existing(res, daemon_overhead)
        res2 = self._solve_once(deferred, carried + pseudo,
                                daemon_overhead, n_slots)
        return _merge_rounds(res, res2, {p.name: i for i, p in
                                         enumerate(pseudo)})

    def solve_many(
        self,
        problems: "Sequence[dict]",
    ) -> "list[SolveResult]":
        """Wave-pipelined batch of independent solves: problems bucket by
        padded shape and each bucket runs as ONE vmapped kernel dispatch
        (wave size padded to a power-of-two so K never mints a new
        compile); all buckets' flat outputs are concatenated device-side
        and fetched with ONE device->host read. Each problem is a dict of
        solve() kwargs (pods, existing, daemon_overhead, n_slots).

        Rationale (docs/designs/solver-boundary.md): on a tunneled device
        the d2h read is both the latency floor (one RTT) and — measured on
        this deployment's relay — a *state degrader*: the first read drops
        the session out of streaming mode. A controller cycle that needs
        provisioning + consolidation + N drift simulations pays one read
        instead of N+2. Problems whose pods carry co-pending affinity terms
        need the two-round driver and fall back to solve() (still correct,
        one extra read each — rare in practice).
        """
        import jax.numpy as jnp

        from ..oracle.scheduler import split_deferred_pods

        # ONE catalog snapshot for the whole wave — but encode_problem
        # rebuilds a grid whose seqnum went stale (a concurrent catalog
        # bump mid-loop), so coherence is enforced the other way around:
        # each problem ships the catalog arrays of the grid its encode
        # ACTUALLY used (enc.alloc_t IS grid.alloc_t), the device-resident
        # copies are substituted only while that is still the snapshot,
        # and the bucket key carries the array identity so lanes from
        # different grids can never stack.
        wave_grid = self.grid()
        dev_alloc_t, dev_tiebreak = self._dev_alloc_t, self._dev_tiebreak
        slots: "list[tuple]" = []  # (mode, payload)
        for prob in problems:
            pods = prob.get("pods", [])
            existing = prob.get("existing", ())
            overhead = prob.get("daemon_overhead")
            n_slots = prob.get("n_slots")
            # cheap pre-check (attribute scan) before the real split: only
            # affinity-bearing pod sets can need the two-round driver, and
            # solve() will redo the split for those anyway
            if any(p.pod_affinity or p.pod_anti_affinity for p in pods) \
                    and split_deferred_pods(pods)[1]:
                slots.append(("solo", prob))
                continue
            enc = encode_problem(
                self.catalog, self.provisioners, pods, existing,
                overhead, n_slots, grid=wave_grid,
                group_cache=self._group_cache,
            )
            if enc.alloc_t is wave_grid.alloc_t:
                inputs, dims, up = build_pack_inputs(enc, dev_alloc_t,
                                                     dev_tiebreak)
            else:  # encode rebuilt a fresh grid (catalog bumped mid-wave)
                inputs, dims, up = build_pack_inputs(enc)
            slots.append(("wave", (enc, inputs, dims, up, list(existing))))

        # Same-shape problems fold into ONE vmapped dispatch per bucket
        # (degraded-link cost is per device OPERATION, not per byte —
        # solver-boundary.md), then all buckets concatenate into one read.
        buckets: "dict[tuple, list[int]]" = {}
        for i, (mode, payload) in enumerate(slots):
            if mode != "wave":
                continue
            _enc, inputs, dims, up, _ex = payload
            key = (dims, up, id(inputs.alloc_t),  # grid identity
                   inputs.ex_cap is not None,
                   inputs.group_origin is not None,
                   inputs.prov_overhead is not None,
                   inputs.prov_pods_cap is not None)
            buckets.setdefault(key, []).append(i)
        flats: "list[tuple[list[int], object]]" = []  # (slot idxs, [K,L] dev)
        for key, idxs in buckets.items():
            (_gb, Nb, _neb), up = key[0], key[1]
            members = [slots[i][1][1] for i in idxs]
            if len(members) == 1:
                dev = jax.device_put(members[0])
                flat2d = pack_flat(dev, n_slots=Nb, use_pallas=up)[None, :]
            else:
                dev = jax.device_put(_stack_pack_inputs(members))
                flat2d = _wave_pack_flat(dev, Nb, up)
            flats.append((idxs, flat2d))
        fetched: "dict[int, PackResult]" = {}
        if flats:
            cat = host_fetch(jnp.concatenate(
                [f.reshape(-1) for _, f in flats]))
            off = 0
            for idxs, f in flats:
                K, L = f.shape
                for j, slot_i in enumerate(idxs):
                    dims = slots[slot_i][1][2]
                    fetched[slot_i] = unflatten_result(
                        cat[off + j * L: off + (j + 1) * L], *dims)
                off += K * L

        out: "list[SolveResult]" = []
        for i, (mode, payload) in enumerate(slots):
            if mode == "solo":
                out.append(self.solve(
                    payload.get("pods", []), payload.get("existing", ()),
                    payload.get("daemon_overhead"), payload.get("n_slots")))
            else:
                enc, _, _, _, existing = payload
                out.append(decode(enc, fetched[i],
                                  [e.name for e in existing]))
        return out

    def _nodes_as_existing(self, res: SolveResult,
                           daemon_overhead) -> "list[ExistingNode]":
        """Round-1 claims as existing nodes (mirror of the oracle's
        _claims_as_existing: decided-option labels/alloc, pods resident)."""
        from ..oracle.scheduler import (effective_alloc,
                                        kubelet_overhead_vector, option_labels)

        out = []
        for i, n in enumerate(res.nodes):
            used = [d + k for d, k in zip(
                list(daemon_overhead or [0] * wk.NUM_RESOURCES),
                kubelet_overhead_vector(n.provisioner.kubelet))]
            resident: "list[PodSpec]" = []
            for g_idx, count in n.pod_counts.items():
                spec = res.groups[g_idx].spec
                vec = res.groups[g_idx].vector
                for r in range(wk.NUM_RESOURCES):
                    used[r] += vec[r] * count
                resident.extend([spec] * count)
            out.append(ExistingNode(
                name=f"__round1-claim-{i}",
                labels=option_labels(n.option, n.provisioner),
                allocatable=list(effective_alloc(n.option, n.provisioner)),
                used=used,
                taints=n.provisioner.taints,
                resident=tuple(resident),
            ))
        return out

    def _solve_once(
        self,
        pods: "list[PodSpec]",
        existing: Sequence[ExistingNode] = (),
        daemon_overhead: Optional[Sequence[int]] = None,
        n_slots: Optional[int] = None,
    ) -> SolveResult:
        # one code path, timed always (perf_counter is ns against a multi-ms
        # solve); .last_timings is only published under the capture tool's
        # KARPENTER_TPU_SOLVE_TIMING=1 flag. Phases: encode/dispatch are
        # host work + async enqueue, fetch is the one device sync, decode
        # is host-side result shaping (docs/designs/solver-boundary.md).
        import time as _time

        from ..ops.packer import pack_cache_size
        from ..tracing import TRACER

        t0 = _time.perf_counter()
        enc = encode_problem(
            self.catalog, self.provisioners, pods, existing,
            daemon_overhead, n_slots, grid=self.grid(),
            group_cache=self._group_cache,
        )
        t1 = _time.perf_counter()
        cache_before = pack_cache_size()
        flat, dims = dispatch_pack(enc, self._dev_alloc_t, self._dev_tiebreak)
        cache_after = pack_cache_size()
        t2 = _time.perf_counter()
        result = fetch_pack(flat, dims)
        t3 = _time.perf_counter()
        out = decode(enc, result, [e.name for e in existing])
        t4 = _time.perf_counter()
        # always-on per-solve observability: the tracing plane reads this on
        # both sides of the solver wire (service.py echoes it into
        # SolveResponse; the controller's solve span records it). fetch is
        # the ONE device->host read — its wall time IS the transfer cost.
        self.last_solve_info = {
            "encode_ms": round((t1 - t0) * 1000, 3),
            "dispatch_ms": round((t2 - t1) * 1000, 3),
            "transfer_ms": round((t3 - t2) * 1000, 3),
            "decode_ms": round((t4 - t3) * 1000, 3),
            "compile_cache": ("unknown" if cache_before < 0
                              else "miss" if cache_after > cache_before
                              else "hit"),
        }
        TRACER.annotate(**self.last_solve_info)
        if _SOLVE_TIMING:
            self.last_timings = {
                "encode_ms": self.last_solve_info["encode_ms"],
                "dispatch_ms": self.last_solve_info["dispatch_ms"],
                "fetch_ms": self.last_solve_info["transfer_ms"],
                "decode_ms": self.last_solve_info["decode_ms"],
            }
        return out


def _carry_round1_existing(existing: "Sequence[ExistingNode]",
                           res: SolveResult) -> "list[ExistingNode]":
    """Fresh ExistingNode copies reflecting round-1 placements: used grows
    by the placed vectors, and group_counts carries the origin-keyed in-run
    counts (the oracle's cap rule is resident_counts[okey] +
    group_counts[okey]; encode_problem consumes both). `resident` stays
    untouched — round-1 placements are NOT affinity anchors in the oracle's
    round 2 either (they live in assignments, not resident)."""
    out: "list[ExistingNode]" = []
    for e in existing:
        per_group = res.existing_by_group.get(e.name, {})
        used = list(e.used)
        # pre-seeded counts are part of the contract now (encode subtracts
        # them from ex_cap); chained solves must not reset them
        counts: "dict[object, int]" = dict(e.group_counts)
        for g_idx, count in per_group.items():
            vec = res.groups[g_idx].vector
            for r in range(wk.NUM_RESOURCES):
                used[r] += vec[r] * count
            okey = res.groups[g_idx].spec.origin_key()
            counts[okey] = counts.get(okey, 0) + count
        ne = ExistingNode(name=e.name, labels=e.labels,
                          allocatable=list(e.allocatable), used=used,
                          taints=e.taints, resident=e.resident)
        ne.group_counts = counts
        out.append(ne)
    return out


def _merge_rounds(res: SolveResult, res2: SolveResult,
                  pseudo_index: "dict[str, int]") -> SolveResult:
    """Fold the deferred round back: group indices offset by round-1's
    group count; dependents placed on pseudo nodes join the claim's
    pod_counts; real-node assignments and unschedulables merge."""
    offset = len(res.groups)
    groups = list(res.groups) + list(res2.groups)
    nodes = list(res.nodes)
    for name, per_group in res2.existing_by_group.items():
        claim_i = pseudo_index.get(name)
        if claim_i is None:
            continue
        counts = nodes[claim_i].pod_counts
        for g_idx, count in per_group.items():
            counts[g_idx + offset] = counts.get(g_idx + offset, 0) + count
    nodes.extend(dataclasses.replace(
        n, pod_counts={g + offset: c for g, c in n.pod_counts.items()})
        for n in res2.nodes)
    existing_by_group = {name: dict(d)
                         for name, d in res.existing_by_group.items()}
    for name, per_group in res2.existing_by_group.items():
        if name in pseudo_index:
            continue
        tgt = existing_by_group.setdefault(name, {})
        for g_idx, count in per_group.items():
            tgt[g_idx + offset] = tgt.get(g_idx + offset, 0) + count
    existing_counts = {name: sum(d.values())
                       for name, d in existing_by_group.items() if d}
    unschedulable = dict(res.unschedulable)
    for g_idx, count in res2.unschedulable.items():
        unschedulable[g_idx + offset] = count
    return SolveResult(nodes, existing_counts, unschedulable, groups,
                       existing_by_group)


class NativeSolver(TPUSolver):
    """Same encode/decode pipeline, C++ scan instead of the device kernel
    (karpenter_tpu/native/). The controller's fallback backend when the TPU
    sidecar is unreachable — and the preferred path for small solves, where
    a tunneled-device round trip would dominate the latency budget. No
    padding/bucketing: dynamic shapes are free on the host."""

    def solve_many(self, problems: "Sequence[dict]") -> "list[SolveResult]":
        """In-process host scans have no read budget to amortize — a plain
        loop keeps the host-only contract (no jax dispatch ever)."""
        return [self.solve(p.get("pods", []), p.get("existing", ()),
                           p.get("daemon_overhead"), p.get("n_slots"))
                for p in problems]

    def grid(self) -> OptionGrid:
        if self._grid is None or self._grid.seqnum != self.catalog.seqnum:
            # host-only: no device_put; a stale or donated grid is only a
            # build_grid reuse donor (layout_key decides, never seqnum)
            old = self._grid or self._donor_grid
            self._donor_grid = None
            self._grid = build_grid(self.catalog, reuse=old)
        return self._grid

    def _solve_once(
        self,
        pods: "list[PodSpec]",
        existing: Sequence[ExistingNode] = (),
        daemon_overhead: Optional[Sequence[int]] = None,
        n_slots: Optional[int] = None,
    ) -> SolveResult:
        from ..native import native_pack

        enc = encode_problem(
            self.catalog, self.provisioners, pods, existing,
            daemon_overhead, n_slots, grid=self.grid(),
            group_cache=self._group_cache,
        )
        inputs = PackInputs(
            alloc_t=enc.alloc_t, tiebreak=enc.tiebreak,
            group_vec=enc.group_vec, group_count=enc.group_count,
            group_cap=enc.group_cap, group_feas=enc.group_feas,
            group_newprov=enc.group_newprov, overhead=enc.overhead,
            ex_alloc=enc.ex_alloc, ex_used=enc.ex_used, ex_feas=enc.ex_feas,
            prov_overhead=enc.prov_overhead, prov_pods_cap=enc.prov_pods_cap,
            ex_cap=enc.ex_cap, group_origin=enc.group_origin,
        )
        result = native_pack(inputs, n_slots=enc.n_slots)
        out = decode(enc, result, [e.name for e in existing])
        # host-only path: no device transfer, no jit cache in play
        self.last_solve_info = {"transfer_ms": 0.0, "compile_cache": "n/a"}
        return out


def build_pack_inputs(enc: EncodedProblem, dev_alloc_t=None,
                      dev_tiebreak=None):
    """Pad to shape buckets and assemble host-side PackInputs — no device
    work. Returns (inputs, (Gb, Nb, Neb), use_pallas). dispatch_pack ships
    and enqueues one problem; solve_many stacks same-shape inputs from
    several problems into ONE vmapped dispatch (_wave_pack_flat)."""
    G = enc.group_vec.shape[0]
    Gb = _bucket(G)
    Ne = enc.ex_alloc.shape[0]
    Neb = _bucket(Ne, lo=1)
    Nb = _bucket(enc.n_slots)

    def pad(a, n, axis=0, fill=0):
        if a.shape[axis] == n:
            return a
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, n - a.shape[axis])
        return np.pad(a, widths, constant_values=fill)

    ex_feas = pad(enc.ex_feas, Gb)
    if ex_feas.shape[1] != Neb:
        ex_feas = pad(ex_feas, Neb, axis=1)
    ex_cap = enc.ex_cap
    if ex_cap is not None:
        ex_cap = pad(pad(ex_cap, Gb, fill=int(INT_BIG)), Neb, axis=1,
                     fill=int(INT_BIG))
    group_origin = enc.group_origin
    if group_origin is not None:
        # padded rows are their own origin (identity) so they stay no-ops
        ident = np.arange(Gb, dtype=np.int32)
        ident[:group_origin.shape[0]] = group_origin
        group_origin = ident
    inputs = PackInputs(
        alloc_t=dev_alloc_t if dev_alloc_t is not None else enc.alloc_t,
        tiebreak=dev_tiebreak if dev_tiebreak is not None else enc.tiebreak,
        group_vec=pad(enc.group_vec, Gb),
        group_count=pad(enc.group_count, Gb),
        group_cap=pad(enc.group_cap, Gb),
        group_feas=pad(enc.group_feas, Gb),
        group_newprov=pad(enc.group_newprov, Gb, fill=-1),
        overhead=enc.overhead,
        ex_alloc=pad(enc.ex_alloc, Neb),
        ex_used=pad(enc.ex_used, Neb),
        ex_feas=ex_feas,
        prov_overhead=enc.prov_overhead, prov_pods_cap=enc.prov_pods_cap,
        ex_cap=ex_cap, group_origin=group_origin,
    )
    # Pallas engages only when the env flag is on AND every input magnitude
    # is below the f32-exactness bound (checked on host arrays; see
    # packer.pallas_value_safe) — oversized problems take the XLA path.
    use_pallas = pallas_kernels.enabled() and pallas_value_safe(
        enc.alloc_t, enc.ex_alloc, enc.group_vec, enc.overhead,
        enc.prov_overhead)
    return inputs, (Gb, Nb, Neb), use_pallas


def dispatch_pack(enc: EncodedProblem, dev_alloc_t=None, dev_tiebreak=None):
    """build_pack_inputs + ENQUEUE the jitted kernel — no device read.
    Returns (flat device array, (Gb, Nb, Neb)); fetch_pack turns it into a
    PackResult. Dispatch and fetch are separate so wave callers
    (solve_many) can overlap
    dispatches and pay a single device->host read for the whole wave —
    on a tunneled device each read is a full round trip, and (measured on
    the deployment tunnel, docs/designs/solver-boundary.md) the FIRST read
    also degrades the link's sync latency for the session, so reads are the
    scarcest resource the solver spends."""
    inputs, dims, use_pallas = build_pack_inputs(enc, dev_alloc_t,
                                                 dev_tiebreak)
    inputs = jax.device_put(inputs)  # async enqueue; no sync round trip
    # One jitted dispatch returning ONE flat buffer: decode pays exactly one
    # device->host round trip (the tunnel RTT floor; SURVEY.md §7.3).
    flat = pack_flat(inputs, n_slots=dims[1], use_pallas=use_pallas)
    return flat, dims


def _stack_pack_inputs(members: "list[PackInputs]") -> PackInputs:
    """Stack same-shape per-problem leaves along a new leading K axis,
    padding K to a power-of-two bucket (lo=2) by repeating the first
    member so wave size never mints a fresh compiled shape — the same
    bucketing doctrine as _bucket for G/N/Ne (duplicate rows are simply
    never read back). alloc_t/tiebreak (catalog arrays, possibly already
    device-resident) stay shared from the first member; None leaves stay
    None (tree.map skips empty subtrees)."""
    first = members[0]
    Kb = _bucket(len(members), lo=2)
    members = list(members) + [first] * (Kb - len(members))
    stripped = [m._replace(alloc_t=None, tiebreak=None) for m in members]
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *stripped)
    return stacked._replace(alloc_t=first.alloc_t, tiebreak=first.tiebreak)


@functools.partial(jax.jit, static_argnames=("n_slots", "use_pallas"))
def _wave_pack_flat(stacked: PackInputs, n_slots: int,
                    use_pallas: "bool | None"):
    """K same-shape problems as ONE vmapped kernel dispatch returning
    [K, L] flat results. In the tunnel's degraded link state every device
    operation costs a flat ~66ms sync slot (solver-boundary.md cost
    model), so a wave of K separate dispatches pays K slots — this folds
    them into one. alloc_t/tiebreak are shared (catalog arrays); every
    per-problem leaf carries a leading K axis."""
    from ..ops.packer import pack_flat_impl

    axes = jax.tree.map(lambda _: 0, stacked)._replace(
        alloc_t=None, tiebreak=None)
    return jax.vmap(
        lambda inp: pack_flat_impl(inp, n_slots, use_pallas=use_pallas),
        in_axes=(axes,))(stacked)


def fetch_pack(flat, dims) -> PackResult:
    """The single device->host read for a dispatched pack (routed through
    host_fetch, so KARPENTER_TPU_READBACK=callback covers it too)."""
    Gb, Nb, Neb = dims
    return unflatten_result(host_fetch(flat), Gb, Nb, Neb)


# -- callback readback (KARPENTER_TPU_READBACK=callback) ---------------------------
#
# host_fetch is the ONE device->host read primitive for the solver: the
# default is a literal jax.device_get; the callback mode emits the array
# host-ward from inside a tiny jitted program via io_callback instead, so
# no literal fetch ever runs and (on relays where the io probe confirms
# callbacks stream) the link never leaves streaming mode. One global
# ordered inbox: io_callback bodies are baked into the traced graph, so
# the sink must be a module-level function; the lock serializes
# dispatch->barrier->pop so concurrent solvers cannot interleave, and the
# inbox is cleared on entry AND exit so a failed fetch can never leak a
# stale buffer into the next one.

import collections as _collections
import threading as _threading

_CB_INBOX: "_collections.deque" = _collections.deque()
_CB_LOCK = _threading.Lock()


def _cb_sink(arr):
    # copy: callback arguments may alias runtime-owned transfer buffers
    # that are only valid for the duration of the callback
    _CB_INBOX.append(np.array(arr, copy=True))
    return np.int32(0)


@jax.jit
def _emit_via_cb(x):
    import jax.numpy as jnp
    from jax.experimental import io_callback

    return io_callback(_cb_sink, jax.ShapeDtypeStruct((), jnp.int32),
                       x, ordered=True)


def host_fetch(dev_arr) -> "np.ndarray":
    """Bring a device array to host through the configured readback
    transport. effects_barrier is the wait on the callback path —
    block_until_ready does not cover host callback delivery."""
    if _READBACK != "callback":
        return np.asarray(jax.device_get(dev_arr))
    with _CB_LOCK:
        _CB_INBOX.clear()
        try:
            _emit_via_cb(dev_arr).block_until_ready()
            jax.effects_barrier()
            if len(_CB_INBOX) != 1:
                raise RuntimeError(
                    f"callback readback delivered {len(_CB_INBOX)} buffers "
                    f"(expected 1)")
            return _CB_INBOX.popleft()
        finally:
            _CB_INBOX.clear()


def decode(enc: EncodedProblem, result: PackResult, existing_names: "list[str]") -> SolveResult:
    host = result  # already host-side numpy (see fetch_pack)
    assign, ex_assign, unsched = host.assign, host.ex_assign, host.unsched
    active, decided, nprov = host.active, host.decided, host.nprov
    G = len(enc.groups)

    nodes: "list[SolvedNode]" = []
    for n in np.nonzero(active)[0]:
        counts_col = assign[:G, n]
        counts = {int(g): int(counts_col[g]) for g in np.nonzero(counts_col)[0]}
        if decided[n] < 0:
            # defensive: an active slot must always retain >=1 option
            raise AssertionError(f"active claim slot {n} has no surviving option")
        nodes.append(SolvedNode(
            option=enc.grid.options[int(decided[n])], pod_counts=counts,
            provisioner=enc.provisioners[int(nprov[n])],
        ))
    ex_totals = ex_assign[:G].sum(axis=0)
    existing_counts = {
        name: int(ex_totals[e]) for e, name in enumerate(existing_names)
        if ex_totals[e] > 0
    }
    existing_by_group = {
        name: {int(g): int(ex_assign[g, e]) for g in range(G) if ex_assign[g, e] > 0}
        for e, name in enumerate(existing_names) if ex_totals[e] > 0
    }
    unschedulable = {int(g): int(unsched[g]) for g in np.nonzero(unsched[:G] > 0)[0]}
    return SolveResult(nodes, existing_counts, unschedulable, enc.groups,
                       existing_by_group)
