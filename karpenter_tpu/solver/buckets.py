"""One shape-bucket table for the whole serving path.

Bucket choice, jit cache key, warmup target and single-vs-sharded routing
were previously derived independently (core._bucket doubled from lo=8, the
sharded path was reachable only through dryrun_multichip), so the set of
compiled programs and the set of routed programs could drift. This module
is the single owner:

* LADDERS — a FIXED rung table per padded dimension. Doubling-from-lo
  recompiled on every crossing at small sizes (9->16->17->32->33->64 pods
  groups each minted a program); the coarse x4 ladder trades a little
  padded compute (scan steps over count=0 groups are no-ops) for an order
  of magnitude fewer compiles. The wave axis K keeps x2 spacing on
  purpose: padded wave lanes are REAL vmapped compute (duplicate rows run,
  they're just never read back), so over-padding K doubles device work
  rather than adding no-op scan steps.
* BucketPlan — the padded (groups, slots, existing) shape of one solve;
  its key() is the jit cache identity and its cells() feed the router.
* ShapeRouter — single-chip kernel below the crossover, the
  parallel/sharded.py mesh kernel above it, with hysteresis so jitter
  around the crossover can't flap the route (each flap risks a compile
  and resharding churn).

Residency/compile observability lives here too (REGISTRY-registered so
gen_docs picks them up): host->device upload counters asserted by the
device-residency tests (metrics, not timing), and the compile-cache
hit/miss/warmup counters behind `Sync`-time pre-jit.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import NamedTuple, Optional

from ..metrics import REGISTRY

log = logging.getLogger("karpenter.solver.buckets")

# -- the ladder --------------------------------------------------------------

# Rung tables per padded dimension. Above the top rung growth continues at
# the same spacing (x4, or x2 for the wave axis) — the table bounds the
# compile count at every scale that fits in memory, the tail rule just
# keeps the function total.
LADDERS: "dict[str, tuple[int, ...]]" = {
    "groups": (8, 32, 128, 512, 2048, 8192, 32768),
    "slots": (8, 32, 128, 512, 2048, 8192, 32768),
    "existing": (1, 4, 16, 64, 256, 1024, 4096),
    "wave": (2, 4, 8, 16, 32, 64, 128),
    # active-resource columns (build_pack_inputs compression): above the
    # top rung the kernel falls back to the full wellknown-resource width
    # instead of growing — the table is a compression, not a pad target.
    "resources": (4, 8),
}

_TAIL_FACTOR = {"groups": 4, "slots": 4, "existing": 4, "wave": 2,
                "resources": 2}


def bucket_up(n: int, dim: str) -> int:
    """Smallest ladder rung >= n for the dimension (x4/x2 growth past the
    table's top rung)."""
    ladder = LADDERS[dim]
    for rung in ladder:
        if n <= rung:
            return rung
    b = ladder[-1]
    f = _TAIL_FACTOR[dim]
    while b < n:
        b *= f
    return b


class BucketPlan(NamedTuple):
    """Padded shape of one solve. One table drives everything derived from
    it: the pad targets (build_pack_inputs), the jit cache key (shapes ARE
    the key), the warmup target (warm_shapes synthesizes at these rungs)
    and the routing decision (cells)."""

    groups: int
    slots: int
    existing: int

    def cells(self) -> int:
        """Routing load proxy: the [G, N] assignment surface. The kernel's
        per-step work is O(N*T*S) with T*S fixed by the synced catalog, so
        groups*slots orders problems of one catalog consistently."""
        return self.groups * self.slots

    def label(self) -> str:
        return f"g{self.groups}n{self.slots}e{self.existing}"

    def rung(self) -> dict:
        """Decision-record provenance: the winning ladder rung, as data
        (the explain plane embeds it per solve — "why THIS compiled
        program" is the bucket half of "why this decision")."""
        return {"label": self.label(), "groups": self.groups,
                "slots": self.slots, "existing": self.existing,
                "cells": self.cells()}


def plan_for(n_groups: int, n_slots: int, n_existing: int) -> BucketPlan:
    return BucketPlan(
        groups=bucket_up(n_groups, "groups"),
        slots=bucket_up(n_slots, "slots"),
        existing=bucket_up(n_existing, "existing"),
    )


# -- the router --------------------------------------------------------------

# Default single->sharded crossover in plan cells. 512*512: the 10k-pod
# headline shape (Gb=32..128, Nb<=512) stays on the single-chip kernel
# (mesh collectives would only add latency at that size), the 50k-pod
# stress shape (Nb>=2048) goes to the mesh. Deployments tune it per
# link/topology via the env knob.
DEFAULT_CROSSOVER_CELLS = 512 * 512

# Hysteresis span in rungs-worth of slack: switch UP at >= crossover,
# switch back DOWN only below crossover/4 (one x4 rung), so a workload
# breathing around the crossover keeps its route (and compiled program).
HYSTERESIS_FACTOR = 4


# Canonical knob first; the short alias is accepted for compatibility with
# docs/runbooks that predate the SHARD_ prefix (canonical wins when both
# are set). See docs/designs/serving-sharded.md "Tuning the crossover".
_CROSSOVER_ENV_VARS = ("KARPENTER_TPU_SHARD_CROSSOVER_CELLS",
                       "KARPENTER_TPU_CROSSOVER_CELLS")


def crossover_cells_default() -> int:
    """The env-tunable single->sharded crossover, validated: a knob that
    silently falls back misroutes EVERY solve until someone diffs env
    against code, so a bad value warns loudly (once per read) and a
    negative one clamps to 0 (= always sharded) rather than pretending a
    negative cell count means something."""
    for var in _CROSSOVER_ENV_VARS:
        raw = os.environ.get(var)
        if raw is None:
            continue
        try:
            cells = int(raw)
        except ValueError:
            log.warning(
                "%s=%r is not an integer; falling back to the default "
                "crossover of %d cells", var, raw, DEFAULT_CROSSOVER_CELLS)
            return DEFAULT_CROSSOVER_CELLS
        if cells < 0:
            log.warning(
                "%s=%d is negative; clamping to 0 (every solve routes to "
                "the sharded mesh kernel)", var, cells)
            return 0
        return cells
    return DEFAULT_CROSSOVER_CELLS


class ShapeRouter:
    """Sticky single-vs-sharded route off the bucket plan. Per-solver
    instance (route state is an attribute of the resident device state,
    not a global): the solver service builds one per synced solver, all
    sharing the service's crossover."""

    def __init__(self, n_devices: int = 1,
                 crossover_cells: "Optional[int]" = None,
                 hysteresis: int = HYSTERESIS_FACTOR):
        self.n_devices = max(1, int(n_devices))
        self.hi = (crossover_cells if crossover_cells is not None
                   else crossover_cells_default())
        self.lo = max(1, self.hi // max(1, hysteresis))
        self._route = "single"

    def route(self, plan: BucketPlan) -> str:
        """"single" or "sharded". Sticky: between lo and hi the previous
        route wins, so jitter near the crossover cannot flap."""
        if self.n_devices < 2:
            return "single"
        cells = plan.cells()
        if cells >= self.hi:
            self._route = "sharded"
        elif cells < self.lo:
            self._route = "single"
        return self._route

    def steady_route(self, plan: BucketPlan) -> str:
        """The route a steady stream of this plan would settle on — pure
        function of the plan, does NOT touch the sticky state. Warmup uses
        this so pre-jitting a bucket can't flip the live route."""
        if self.n_devices < 2:
            return "single"
        return "sharded" if plan.cells() >= self.hi else "single"


# -- residency / compile observability ---------------------------------------

# Host->device upload accounting: every device_put the solver performs goes
# through core._device_put_tracked, labeled by what crossed. The
# device-residency contract ("Sync-then-repeat-Solve performs zero redundant
# uploads of unchanged catalog tensors") is asserted against these counters
# — a metric delta is deterministic where wall-clock never is.
UPLOADS = REGISTRY.counter(
    "karpenter_solver_host_to_device_uploads_total",
    "Host->device transfers performed by the solver, by tensor class "
    "(catalog = Sync-resident arrays, delta = per-solve problem arrays).",
    ("tensor",))
UPLOAD_BYTES = REGISTRY.counter(
    "karpenter_solver_host_to_device_bytes_total",
    "Bytes shipped host->device by the solver, by tensor class.",
    ("tensor",))

COMPILE_HITS = REGISTRY.counter(
    "karpenter_solver_compile_cache_hits_total",
    "Solves served by an already-compiled pack program.")
COMPILE_MISSES = REGISTRY.counter(
    "karpenter_solver_compile_cache_misses_total",
    "Solves that paid an XLA compile (a shape bucket seen for the first "
    "time escaped warmup).")
COMPILE_WARMUPS = REGISTRY.counter(
    "karpenter_solver_compile_cache_warmups_total",
    "Pack programs compiled ahead of traffic by Sync-time warmup "
    "(TPUSolver.warm_shapes).")

# How full buckets run: ratio of the raw dimension to its padded rung.
# Persistently low occupancy on a dimension means the ladder is too coarse
# for the deployment's workload (wasted padded compute); near-1.0 means the
# next pod added tips into the next rung.
BUCKET_OCCUPANCY = REGISTRY.histogram(
    "karpenter_solver_bucket_occupancy_ratio",
    "Raw size / padded bucket size per solve, by dimension.",
    ("dim",),
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
BUCKET_SOLVES = REGISTRY.counter(
    "karpenter_solver_bucket_solves_total",
    "Solves dispatched per bucket plan and route.",
    ("bucket", "route"))


# -- HBM residency ledger -----------------------------------------------------

HBM_RESIDENT_BYTES = REGISTRY.gauge(
    "karpenter_solver_hbm_resident_bytes",
    "Device bytes resident per solver key (catalog content hash pair) and "
    "tensor class — catalog classes accumulate across Sync, per-solve "
    "delta classes carry the LAST solve's bytes per BucketPlan rung "
    "(donated buffers reuse, they don't stack). The LRU reads the summed "
    "pressure against KARPENTER_TPU_HBM_CAPACITY_BYTES.",
    ("solver_key", "tensor"))

HBM_CAPACITY_ENV = "KARPENTER_TPU_HBM_CAPACITY_BYTES"

# delta bytes tracked mid-solve land on this pending rung until
# attribute_delta files them under the solve's actual bucket label
_PENDING_RUNG = "_pending"


def hbm_capacity_default() -> "Optional[int]":
    """Env-declared device HBM budget in bytes; None (unset/invalid) means
    capacity is unknown and pressure-based eviction stays disarmed — the
    right default on CPU hosts where "HBM" is just process heap."""
    raw = os.environ.get(HBM_CAPACITY_ENV)
    if raw is None:
        return None
    try:
        cap = int(raw)
    except ValueError:
        log.warning("%s=%r is not an integer; HBM pressure disabled",
                    HBM_CAPACITY_ENV, raw)
        return None
    if cap <= 0:
        log.warning("%s=%d is not positive; HBM pressure disabled",
                    HBM_CAPACITY_ENV, cap)
        return None
    return cap


class _HbmScope(threading.local):
    solver_key: str = ""
    bucket: str = ""


_SCOPE = _HbmScope()


@contextlib.contextmanager
def hbm_scope(solver_key: str, bucket: str = ""):
    """Attribute every tracked device put on this thread to `solver_key`
    (and, for delta tensors, to `bucket` when known at entry). The scope
    travels through core.py untouched — call sites keep their signatures;
    the service wraps build/solve in the scope it already knows the key
    for."""
    prev_key, prev_bucket = _SCOPE.solver_key, _SCOPE.bucket
    _SCOPE.solver_key, _SCOPE.bucket = solver_key, bucket
    try:
        yield
    finally:
        _SCOPE.solver_key, _SCOPE.bucket = prev_key, prev_bucket


class HbmLedger:
    """Bytes resident on device per solver key, split static vs delta.

    * STATIC classes ("catalog", anything Sync-resident) accumulate: each
      tracked upload is new residency (tracked_device_put already skips
      arrays that are resident, so re-Sync of unchanged content adds 0).
    * DELTA classes (per-solve problem arrays) REPLACE per BucketPlan
      rung: donated ping-pong buffers reuse the same device allocation,
      so the latest solve's bytes per rung are what is actually held.
      Mid-solve the bytes sit on a pending rung; `attribute_delta` files
      them under the solve's real bucket label once the service knows it.

    `pressure()` (resident / declared capacity) is the eviction signal
    the resident-solver LRU consults at Sync."""

    # tensor classes that accumulate (everything else is per-solve delta);
    # "assignment" is the incremental plane's resident packing state —
    # static (carried between cycles) but REPLACE-semantics via
    # set_resident, since it is patched in place rather than re-uploaded
    STATIC_CLASSES = ("catalog", "assignment")

    def __init__(self):
        self._lock = threading.Lock()
        self._static: "dict[str, dict[str, float]]" = {}
        self._delta: "dict[str, dict[str, float]]" = {}

    def track(self, nbytes: float, tensor: str) -> None:
        """File `nbytes` of fresh device residency under the current
        thread's hbm_scope (no scope = no attribution: uploads outside a
        solver context, e.g. tests poking device_put, stay unledgered)."""
        key = _SCOPE.solver_key
        if not key or nbytes <= 0:
            return
        with self._lock:
            if tensor in self.STATIC_CLASSES:
                per = self._static.setdefault(key, {})
                per[tensor] = per.get(tensor, 0.0) + nbytes
                HBM_RESIDENT_BYTES.set(per[tensor], solver_key=key,
                                       tensor=tensor)
            else:
                rung = _SCOPE.bucket or _PENDING_RUNG
                per = self._delta.setdefault(key, {})
                per[rung] = per.get(rung, 0.0) + nbytes

    def set_resident(self, solver_key: str, tensor: str,
                     nbytes: float) -> None:
        """REPLACE a static class's residency for `solver_key` (vs track's
        accumulate): resident state that is patched in place — the
        incremental plane's `assignment` arrays — holds `nbytes` total, so
        each sync files the current footprint, not another increment."""
        if tensor not in self.STATIC_CLASSES:
            raise ValueError(f"set_resident is for static classes, "
                             f"got {tensor!r}")
        with self._lock:
            per = self._static.setdefault(solver_key, {})
            per[tensor] = float(nbytes)
            HBM_RESIDENT_BYTES.set(per[tensor], solver_key=solver_key,
                                   tensor=tensor)

    def attribute_delta(self, solver_key: str, bucket: str) -> None:
        """Move the pending delta bytes onto the solve's actual bucket
        rung, REPLACING that rung's previous residency (donated buffers
        reuse the allocation; stacking them would double-count)."""
        with self._lock:
            per = self._delta.get(solver_key)
            if per is None:
                return
            pending = per.pop(_PENDING_RUNG, None)
            if pending is None:
                return
            per[f"delta:{bucket}"] = pending
            HBM_RESIDENT_BYTES.set(pending, solver_key=solver_key,
                                   tensor=f"delta:{bucket}")

    def release(self, solver_key: str) -> float:
        """Drop every ledger entry for an evicted solver; returns the
        bytes freed. Gauges zero rather than vanish so the eviction is
        visible as a step, not a gap."""
        with self._lock:
            freed = 0.0
            for table in (self._static, self._delta):
                per = table.pop(solver_key, None)
                if per:
                    for tensor, b in per.items():
                        freed += b
                        label = (tensor if table is self._static
                                 else (tensor if tensor.startswith("delta:")
                                       else f"delta:{tensor}"))
                        HBM_RESIDENT_BYTES.set(0.0, solver_key=solver_key,
                                               tensor=label)
            return freed

    def resident_bytes(self, solver_key: "Optional[str]" = None) -> float:
        with self._lock:
            keys = ([solver_key] if solver_key is not None
                    else set(self._static) | set(self._delta))
            return sum(
                sum(self._static.get(k, {}).values()) +
                sum(self._delta.get(k, {}).values())
                for k in keys)

    def pressure(self) -> "Optional[float]":
        """resident / capacity, or None when no capacity is declared (the
        LRU treats None as "pressure eviction disarmed")."""
        cap = hbm_capacity_default()
        if cap is None:
            return None
        return self.resident_bytes() / cap

    def snapshot(self) -> dict:
        """The statusz `hbm` section: per-solver residency split by
        class, fleet totals, and the pressure signal."""
        with self._lock:
            solvers = {}
            for key in sorted(set(self._static) | set(self._delta)):
                static = dict(self._static.get(key, {}))
                delta = dict(self._delta.get(key, {}))
                solvers[key] = {
                    "static_bytes": static,
                    "delta_bytes": delta,
                    "total_bytes": sum(static.values()) +
                    sum(delta.values()),
                }
        total = sum(s["total_bytes"] for s in solvers.values())
        cap = hbm_capacity_default()
        return {
            "solvers": solvers,
            "resident_bytes_total": total,
            "capacity_bytes": cap,
            "pressure": (total / cap) if cap else None,
        }


HBM = HbmLedger()


def tracked_device_put(arr, tensor: str, sharding=None):
    """The solver's ONE device_put: counts what actually crosses the
    host->device boundary. An array that is already a device array (with
    the requested sharding, when one is given) is returned as-is and
    counts nothing — that no-op IS the residency win the counters exist
    to prove."""
    import jax

    if isinstance(arr, jax.Array):
        if sharding is None or arr.sharding == sharding:
            return arr
    UPLOADS.inc(tensor=tensor)
    nbytes = getattr(arr, "nbytes", None)
    if nbytes:
        UPLOAD_BYTES.inc(float(nbytes), tensor=tensor)
        HBM.track(float(nbytes), tensor)
    return jax.device_put(arr, sharding) if sharding is not None \
        else jax.device_put(arr)


def tracked_tree_put(tree, tensor: str, shardings=None):
    """tracked_device_put over a pytree (None leaves skipped). shardings,
    when given, is a matching pytree of shardings (None = replicated/plain
    put). The plain-put case counts host-side then ships the whole tree in
    ONE jax.device_put call — per-leaf puts cost a C++ round trip each,
    measurable on the per-solve delta path."""
    import jax

    if shardings is not None:
        return jax.tree.map(
            lambda a, sh: tracked_device_put(a, tensor, sh), tree, shardings)
    n = nbytes = 0
    for a in jax.tree.leaves(tree):
        if not isinstance(a, jax.Array):
            n += 1
            nbytes += getattr(a, "nbytes", 0) or 0
    if n:
        UPLOADS.inc(float(n), tensor=tensor)
        if nbytes:
            UPLOAD_BYTES.inc(float(nbytes), tensor=tensor)
            HBM.track(float(nbytes), tensor)
    return jax.device_put(tree)


def observe_plan(plan: BucketPlan, n_groups: int, n_slots: int,
                 n_existing: int, route: str) -> None:
    BUCKET_SOLVES.inc(bucket=plan.label(), route=route)
    BUCKET_OCCUPANCY.observe(n_groups / plan.groups, dim="groups")
    BUCKET_OCCUPANCY.observe(n_slots / plan.slots, dim="slots")
    if plan.existing:
        BUCKET_OCCUPANCY.observe(n_existing / plan.existing, dim="existing")
