"""Solver gRPC service: the TPU-resident half of the architecture.

Parity/architecture target: SURVEY.md §7.1 — controller half <-> solver half
over gRPC (SolveRequest/SolveResponse), catalog arrays device-resident and
versioned by seqnum so only the pod delta crosses the boundary per solve.
The liveness Health RPC mirrors the reference's chained LivenessProbe
(/root/reference/pkg/cloudprovider/cloudprovider.go:163-168).

Service stubs are registered with grpc generic handlers (the image has
grpcio but not grpcio-tools, so messages come from protoc --python_out and
the method table is wired by hand).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent import futures
from typing import Optional, Sequence

import grpc

from ..apis.provisioner import Provisioner
from ..models.instancetype import Catalog
from .core import SolveResult, TPUSolver
from . import solver_pb2 as pb
from . import wire

log = logging.getLogger("karpenter.solver.service")

SERVICE_NAME = "karpenter.solver.Solver"

METHODS = {
    "Sync": (pb.SyncRequest, pb.SyncResponse),
    "Solve": (pb.SolveRequest, pb.SolveResponse),
    "Health": (pb.HealthRequest, pb.HealthResponse),
}


def result_to_response(result: SolveResult, solve_ms: float,
                       seqnum: int) -> pb.SolveResponse:
    def counts(d: "dict[int, int]"):
        return [pb.GroupCount(group=g, count=c) for g, c in sorted(d.items())]

    return pb.SolveResponse(
        nodes=[pb.NodeDecisionMsg(
            instance_type=n.option.itype.name,
            zone=n.option.zone,
            capacity_type=n.option.capacity_type,
            price=n.option.price,
            provisioner=n.provisioner.name,
            pods=counts(n.pod_counts),
        ) for n in result.nodes],
        existing=[pb.ExistingAssignmentMsg(node=name, pods=counts(per_group))
                  for name, per_group in sorted(result.existing_by_group.items())],
        unschedulable=counts(result.unschedulable),
        groups=[pb.GroupMsg(pod_names=list(g.pod_names)) for g in result.groups],
        solve_ms=solve_ms,
        catalog_seqnum=seqnum,
    )


class SolverService:
    """Stateful solver host: one synced (catalog, provisioners) pair, one
    TPUSolver whose device-resident grid persists across Solve calls."""

    def __init__(self):
        self._lock = threading.Lock()
        self._solver: Optional[TPUSolver] = None
        self._seqnum: int = -1
        self._prov_hash: int = 0

    # -- RPC methods (called by the generic handler) -------------------------------

    def Sync(self, request: pb.SyncRequest, context) -> pb.SyncResponse:
        provisioners = [wire.provisioner_from_wire(m) for m in request.provisioners]
        prov_hash = wire.provisioners_hash(provisioners)
        with self._lock:
            unchanged = (self._solver is not None
                         and self._seqnum == request.catalog.seqnum
                         and self._prov_hash == prov_hash)
            outdated = self._solver is not None and self._seqnum > request.catalog.seqnum
            newest = self._seqnum
        if unchanged:
            # idempotent re-Sync: keep the device-resident grid (per-reconcile
            # clients re-Sync freely; only a real seqnum/spec change pays)
            return pb.SyncResponse(seqnum=request.catalog.seqnum)
        if outdated:
            # the caller's catalog is older than what's installed: don't pay a
            # solver build that would only be discarded; the returned seqnum
            # tells the client it is the stale side
            return pb.SyncResponse(seqnum=newest)
        catalog = wire.catalog_from_wire(request.catalog)
        solver = TPUSolver(catalog, provisioners)
        # build + device-put the option grid OUTSIDE the lock so Health stays
        # responsive during catalog churn, then swap atomically
        solver.grid()
        with self._lock:
            if self._solver is not None and self._seqnum > catalog.seqnum:
                # a newer catalog won the race while we built; keep it
                return pb.SyncResponse(seqnum=self._seqnum)
            self._solver = solver
            self._seqnum = catalog.seqnum
            self._prov_hash = prov_hash
        log.info("synced catalog seqnum=%d (%d types, %d provisioners)",
                 self._seqnum, len(catalog.types), len(provisioners))
        return pb.SyncResponse(seqnum=self._seqnum)

    def Solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        with self._lock:
            solver, seqnum, phash = self._solver, self._seqnum, self._prov_hash
        if solver is None or request.catalog_seqnum != seqnum \
                or request.provisioner_hash != phash:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"catalog out of sync: server seqnum={seqnum}, "
                f"request seqnum={request.catalog_seqnum}; re-Sync required")
        pods = [wire.pod_from_wire(m) for m in request.pods]
        existing = [wire.existing_from_wire(m) for m in request.existing]
        overhead = list(request.daemon_overhead) or None
        t0 = time.perf_counter()
        result = solver.solve(pods, existing=existing, daemon_overhead=overhead)
        solve_ms = (time.perf_counter() - t0) * 1000
        return result_to_response(result, solve_ms, seqnum)

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        with self._lock:
            seqnum = self._seqnum
            n_types = len(self._solver.catalog.types) if self._solver else 0
        return pb.HealthResponse(ok=True, backend=jax.devices()[0].platform,
                                 catalog_seqnum=seqnum, n_types=n_types)


def _generic_handler(service: SolverService) -> grpc.GenericRpcHandler:
    table = {}
    for name, (req_cls, _resp_cls) in METHODS.items():
        table[name] = grpc.unary_unary_rpc_method_handler(
            getattr(service, name),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, table)


def serve(address: str = "127.0.0.1:0", max_workers: int = 4,
          service: Optional[SolverService] = None) -> "tuple[grpc.Server, int, SolverService]":
    """Start the solver service; returns (server, bound_port, service).
    Solves are serialized per-solver by the GIL+device anyway; max_workers>1
    keeps Health responsive during long solves."""
    service = service or SolverService()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((_generic_handler(service),))
    port = server.add_insecure_port(address)
    server.start()
    log.info("solver service listening on port %d", port)
    return server, port, service
