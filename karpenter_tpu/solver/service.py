"""Solver gRPC service: the TPU-resident half of the architecture.

Parity/architecture target: SURVEY.md §7.1 — controller half <-> solver half
over gRPC (SolveRequest/SolveResponse), catalog arrays device-resident and
versioned by seqnum so only the pod delta crosses the boundary per solve.
The liveness Health RPC mirrors the reference's chained LivenessProbe
(/root/reference/pkg/cloudprovider/cloudprovider.go:163-168).

Service stubs are registered with grpc generic handlers (the image has
grpcio but not grpcio-tools, so messages come from protoc --python_out and
the method table is wired by hand).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from concurrent import futures
from typing import Optional, Sequence

import grpc

from ..apis.provisioner import Provisioner
from ..models.instancetype import Catalog
from ..tracing import TRACER
from .. import overload
from ..overload import eviction as overload_eviction
from ..overload import metrics as overload_metrics
from .core import SolveResult, TPUSolver
from . import buckets
from . import solver_pb2 as pb
from . import wire

log = logging.getLogger("karpenter.solver.service")

SERVICE_NAME = "karpenter.solver.Solver"

# Requests arriving with less remaining deadline budget (ms) than this are
# shed up front (DEADLINE_EXCEEDED): the caller's reconcile cycle will have
# given up on the answer before the solve finishes, so computing it only
# burns device time someone else is queued for.
SHED_MIN_BUDGET_MS = 10.0

# Consolidation requests with at least this many nodes run their candidate
# lanes over the lane mesh (pure data parallelism); below it the mesh's
# collective/pad overhead beats the win, mirroring the solve router's
# crossover doctrine.
CONSOLIDATE_LANE_MESH_MIN = 64

# Most shape buckets a single Sync will pre-jit: warmup runs inline in the
# Sync RPC, and each compile is hundreds of ms — the cap bounds Sync
# latency, the shape history keeps the spent compiles the most useful ones.
WARMUP_LIMIT = 8

# When the HBM ledger reports residency above this fraction of the declared
# device capacity (KARPENTER_TPU_HBM_CAPACITY_BYTES), Sync evicts extra LRU
# entries beyond the count cap until pressure clears — a count-only LRU is
# blind to one giant catalog crowding out three small ones. Disarmed (no-op)
# when no capacity is declared, which is the CPU-host default.
HBM_PRESSURE_EVICT = 0.9

# With the overload plane enabled, a pressure eviction pass drains to this
# fraction in ONE pass instead of evicting exactly back under the trigger:
# per-request single evictions under sustained churn are the eviction-storm
# signature (evict one, next Sync re-triggers, repeat) — hysteresis between
# trigger and low-water makes pressure passes rare instead of constant.
HBM_LOW_WATER = 0.7

# Sliding window (in installs) over which a re-install of a recently
# evicted key counts as a thrash event. Always-on measurement: the churn
# drill's A/B needs the OFF window to report its thrash honestly too.
THRASH_WINDOW = 32


def hbm_key(key: "tuple[int, int]") -> str:
    """The ledger/metric label for a resident solver: the content-hash
    pair that IS the LRU identity, hex (matches the eviction log lines)."""
    return f"{key[0]:x}/{key[1]:x}"

def _hint_shape(pods: int) -> tuple:
    """Crude pod-count -> problem-shape mapping for warm_pod_counts hints:
    ~16 pods fold into one scheduling group in the deployment's workloads
    and slot demand tracks group count. Only the ladder rung matters —
    plan_for() buckets the result, so being 2x off usually lands on the
    same compiled program anyway."""
    g = max(1, pods // 16)
    return (g, max(8, g), 0)


METHODS = {
    "Sync": (pb.SyncRequest, pb.SyncResponse),
    "Solve": (pb.SolveRequest, pb.SolveResponse),
    "Consolidate": (pb.ConsolidateRequest, pb.ConsolidateResponse),
    "Health": (pb.HealthRequest, pb.HealthResponse),
}


def result_to_response(result: SolveResult, solve_ms: float,
                       seqnum: int) -> pb.SolveResponse:
    def counts(d: "dict[int, int]"):
        return [pb.GroupCount(group=g, count=c) for g, c in sorted(d.items())]

    return pb.SolveResponse(
        nodes=[pb.NodeDecisionMsg(
            instance_type=n.option.itype.name,
            zone=n.option.zone,
            capacity_type=n.option.capacity_type,
            price=n.option.price,
            provisioner=n.provisioner.name,
            pods=counts(n.pod_counts),
        ) for n in result.nodes],
        existing=[pb.ExistingAssignmentMsg(node=name, pods=counts(per_group))
                  for name, per_group in sorted(result.existing_by_group.items())],
        unschedulable=counts(result.unschedulable),
        groups=[pb.GroupMsg(pod_names=list(g.pod_names)) for g in result.groups],
        solve_ms=solve_ms,
        catalog_seqnum=seqnum,
    )


class SolverService:
    """Stateful solver host: a small LRU of synced (catalog, provisioners)
    pairs, each with a TPUSolver whose device-resident grid persists across
    Solve calls. The LRU (vs a single slot) keeps multiple controller
    replicas with briefly divergent catalogs from thrashing grid rebuilds
    against each other — each replica's grid stays resident and its Solves
    are served directly."""

    LRU_CAPACITY = 4

    # probation side-car width: at most this many unearned newcomers hold
    # HBM at once — a churn stream of one-shot catalogs recycles this slot
    # among themselves and never touches the warm residents
    PROBATION_CAPACITY = 1

    def __init__(self, trace_dir: "Optional[str]" = None,
                 trace_every: int = 100,
                 crossover_cells: "Optional[int]" = None):
        self._lock = threading.Lock()
        # (cat_hash, prov_hash) -> (TPUSolver, seqnum); insertion order = LRU
        self._cache: "OrderedDict[tuple[int, int], tuple[TPUSolver, int]]" = \
            OrderedDict()
        # in-flight pin refcounts: a pinned entry can NEVER be evicted, so
        # a concurrent Sync's eviction pass cannot release a solver mid
        # solve_many (checkout/checkin). Unconditional correctness — not
        # gated on the overload plane.
        self._pins: "dict[tuple[int, int], int]" = {}
        # probation side-car (overload plane only): an unearned newcomer
        # lands here instead of displacing a warm resident; it is promoted
        # into the main LRU once the admission filter sees it again
        self._probation: "OrderedDict[tuple[int, int], tuple[TPUSolver, int]]" = \
            OrderedDict()
        self._admission = overload.AdmissionFilter()
        # always-on eviction-thrash accounting (see THRASH_WINDOW):
        # recently evicted key -> install-seq at eviction time
        self._installs = 0
        self._evictions = 0
        self._thrash_events = 0
        self._recent_evicted: "OrderedDict[tuple[int, int], int]" = \
            OrderedDict()
        # single-vs-sharded crossover shared by every solver's router
        # (None = env/default); tests force 0 to shard everything
        self._crossover_cells = crossover_cells
        # persistent device context (parallel/sharded.ShardedContext):
        # built lazily at first Sync, lives for the process — the mesh and
        # the sharded-resident catalog arrays inside it are what make
        # repeat Solves upload nothing. None on single-device hosts.
        self._mesh_ctx = None
        self._mesh_ctx_built = False
        # raw shape keys of recent Solves (most recent last, bounded):
        # the warmup working set a re-Sync pre-jits first
        self._shape_seen: "OrderedDict[tuple, int]" = OrderedDict()
        # device-path profiling (SURVEY §5.1): when trace_dir is set, every
        # trace_every-th Solve runs under jax.profiler.trace so production
        # captures the on-chip timeline continuously (the evidence class of
        # benchmarks/results/traces/ — see docs/designs/solver-boundary.md)
        self._trace_dir = trace_dir
        self._trace_every = max(1, trace_every)
        self._solve_count = 0
        self._trace_active = False  # single-flight: jax has ONE global profiler

    def _mru(self) -> "tuple[Optional[TPUSolver], int, int]":
        """(solver, seqnum, cat_hash) of the most recently used entry.
        Callers must hold self._lock."""
        if not self._cache:
            return None, -1, 0
        key = next(reversed(self._cache))
        solver, seqnum = self._cache[key]
        return solver, seqnum, key[0]

    @property
    def _cat_hash(self) -> int:
        """Most-recently-used catalog hash (observability/tests)."""
        with self._lock:
            return self._mru()[2]

    # -- residency: pins, admission, eviction accounting ---------------------------

    def checkout(self, key: "tuple[int, int]") \
            -> "Optional[tuple[TPUSolver, int]]":
        """Pin + fetch the resident (solver, seqnum) for `key` (main LRU
        or the probation slot); None when not synced. While the pin is
        held no eviction pass — capacity, HBM pressure, or low-water —
        can release this solver, so a dispatch can never race an
        eviction. Callers MUST pair with checkin()."""
        with self._lock:
            entry = self._cache.get(key)
            if entry is not None:
                self._cache.move_to_end(key)
            else:
                entry = self._probation.get(key)
            if entry is None:
                return None
            self._pins[key] = self._pins.get(key, 0) + 1
            return entry

    def checkin(self, key: "tuple[int, int]") -> None:
        """Release one checkout() pin."""
        with self._lock:
            n = self._pins.get(key, 0) - 1
            if n > 0:
                self._pins[key] = n
            else:
                self._pins.pop(key, None)

    def _note_install_locked(self, key: "tuple[int, int]") -> None:
        self._installs += 1
        if key in self._recent_evicted:
            self._thrash_events += 1
            del self._recent_evicted[key]
        while self._recent_evicted:
            oldest, seq = next(iter(self._recent_evicted.items()))
            if self._installs - seq > THRASH_WINDOW:
                del self._recent_evicted[oldest]
            else:
                break

    def _note_eviction_locked(self, key: "tuple[int, int]") -> None:
        self._evictions += 1
        self._recent_evicted.pop(key, None)
        self._recent_evicted[key] = self._installs

    def _evict_one_locked(self, stores, *,
                          protect: "Optional[tuple[int, int]]" = None) \
            -> "Optional[tuple[int, int]]":
        """Evict the first UNPINNED entry (LRU order, probation before the
        main cache when both are offered) other than `protect`; releases
        its HBM ledger rows. None when every candidate is pinned — the
        count/pressure bound then yields to correctness and the caller
        stops evicting."""
        for store in stores:
            for k in store:
                if k == protect or self._pins.get(k, 0) > 0:
                    continue
                del store[k]
                buckets.HBM.release(hbm_key(k))
                self._note_eviction_locked(k)
                return k
        return None

    def eviction_stats(self) -> dict:
        """Always-on thrash accounting (statusz + churn drill A/B): a
        thrash event is an install of a key evicted within the last
        THRASH_WINDOW installs — the work-to-retain-nothing signature."""
        with self._lock:
            installs, evictions = self._installs, self._evictions
            thrash = self._thrash_events
            resident, probation = len(self._cache), len(self._probation)
            pinned = sum(1 for n in self._pins.values() if n > 0)
        ratio = (thrash / installs) if installs else 0.0
        return {"installs": installs, "evictions": evictions,
                "thrash_events": thrash, "thrash_ratio": round(ratio, 4),
                "window": THRASH_WINDOW, "resident": resident,
                "probation": probation, "pinned": pinned}

    def _device_context(self):
        """The process-lifetime mesh context (parallel/sharded
        .ShardedContext), built at the FIRST Sync — never in __init__, so
        constructing a service object can't initialize a JAX backend.
        None on single-device hosts (router then always picks
        single-chip)."""
        with self._lock:
            if self._mesh_ctx_built:
                return self._mesh_ctx
        import jax

        ctx = None
        try:
            if len(jax.devices()) >= 2:
                from ..parallel.sharded import ShardedContext

                ctx = ShardedContext()
        except Exception as e:  # mesh trouble degrades to single-chip
            log.warning("mesh context unavailable, serving single-chip: %s",
                        e)
        with self._lock:
            if not self._mesh_ctx_built:
                self._mesh_ctx = ctx
                self._mesh_ctx_built = True
            return self._mesh_ctx

    def _record_shape(self, solver: TPUSolver) -> None:
        key = solver.last_shape_key
        if key is None:
            return
        with self._lock:
            self._shape_seen[key] = self._shape_seen.pop(key, 0) + 1
            while len(self._shape_seen) > 32:
                self._shape_seen.popitem(last=False)

    def _warm(self, solver: TPUSolver, request: pb.SyncRequest) -> int:
        """Sync-time compile-cache warmup: pre-jit the shape buckets traffic
        actually hits — the service's own recent-solve history first (exact
        shape keys), then the client's pod-count hints (crude pods->shape
        mapping; the ladder's coarse rungs absorb the sloppiness). Guarded:
        warmup can never fail a Sync."""
        shapes: "list[tuple]" = []
        with self._lock:
            shapes.extend(reversed(self._shape_seen))  # most recent first
        for count in request.warm_pod_counts:
            shapes.append(_hint_shape(int(count)))
        if not shapes:
            return 0
        try:
            return len(solver.warm_shapes(shapes, limit=WARMUP_LIMIT))
        except Exception as e:
            log.warning("shape warmup failed (serving cold): %s", e)
            return 0

    # -- RPC methods (called by the generic handler) -------------------------------

    def Sync(self, request: pb.SyncRequest, context) -> pb.SyncResponse:
        with TRACER.start_span(
                "solver.service.Sync",
                context=wire.trace_context_from_wire(request.trace_context),
                types=len(request.catalog.types)):
            return self._sync_traced(request, context)

    def _sync_traced(self, request: pb.SyncRequest,
                     context) -> pb.SyncResponse:
        provisioners = [wire.provisioner_from_wire(m) for m in request.provisioners]
        prov_hash = wire.provisioners_hash(provisioners)
        # Staleness is keyed on catalog CONTENT, not seqnum: seqnums are
        # process-local counters that reset when a controller restarts, so a
        # fresh client with a low seqnum but identical content must be treated
        # as synced, and a content change must rebuild even if its seqnum is
        # lower than an installed one (content owns identity, not ordering).
        cat_hash = wire.catalog_hash(request.catalog)
        key = (cat_hash, prov_hash)
        ctx = self._device_context()
        with self._lock:
            hit = self._cache.get(key)
            in_probation = False
            if hit is not None:
                # idempotent re-Sync: keep the device-resident grid
                self._cache.move_to_end(key)
                self._cache[key] = (hit[0], request.catalog.seqnum)
            else:
                hit = self._probation.get(key)
                in_probation = hit is not None
        if hit is not None:
            if in_probation:
                # a repeat sighting of a probationer: offer it to the
                # admission filter again — earning promotes the EXISTING
                # device-resident solver into the main LRU (no rebuild)
                earned = self._admission.offer(hbm_key(key))
                with self._lock:
                    entry = self._probation.pop(key, None)
                    if entry is not None and earned:
                        self._cache[key] = (entry[0], request.catalog.seqnum)
                        self._cache.move_to_end(key)
                        while len(self._cache) > self.LRU_CAPACITY:
                            if self._evict_one_locked((self._cache,),
                                                      protect=key) is None:
                                break
                    elif entry is not None:
                        self._probation[key] = (entry[0],
                                                request.catalog.seqnum)
                        self._probation.move_to_end(key)
            # re-Sync still warms: the client may ship fresh hints and the
            # shape history may have grown since the solver was installed
            warmed = self._warm(hit[0], request)
            return self._sync_response(request.catalog.seqnum, cat_hash,
                                       ctx, warmed)
        catalog = wire.catalog_from_wire(request.catalog)
        solver = TPUSolver(
            catalog, provisioners, mesh_ctx=ctx,
            router=buckets.ShapeRouter(
                n_devices=ctx.device_count if ctx is not None else 1,
                crossover_cells=self._crossover_cells))
        # the most recent resident solver donates its static grid arrays +
        # group-encode folds: an ICE-only catalog change (spot storms bump
        # content per message) then skips the grid rebuild AND the device
        # re-put of alloc/tiebreak — the layout check inside build_grid
        # decides, so a real layout change still rebuilds from scratch
        with self._lock:
            donor, _, _ = self._mru()
        if donor is not None:
            # the donor keeps serving its own clients from the LRU: copy the
            # static fold level rather than sharing the live cache dict
            solver.adopt_static(donor, share_group_cache=False)
        # build + device-put the option grid OUTSIDE the lock so Health stays
        # responsive during catalog churn, then swap atomically; the hbm
        # scope files the grid's device puts under this solver's ledger key
        plane_on = overload.enabled()
        to_probation = False
        if plane_on:
            with self._lock:
                full = len(self._cache) >= self.LRU_CAPACITY
            # a residency cap that fits fewer solvers than LRU_CAPACITY
            # means the COUNT never fills — crowding shows up as ledger
            # pressure instead, and above the low-water mark one more
            # resident forces a drain just as surely as a full LRU does
            pressure = buckets.HBM.pressure()
            crowded = pressure is not None and pressure >= HBM_LOW_WATER
            if full or crowded:
                # installing would evict a warm resident: a newcomer must
                # have EARNED that (one-shot catalog hashes stay on
                # probation and recycle one slot instead)
                to_probation = not self._admission.offer(hbm_key(key))
        with buckets.hbm_scope(hbm_key(key)):
            solver.grid()
        with self._lock:
            if to_probation:
                while len(self._probation) >= self.PROBATION_CAPACITY:
                    if self._evict_one_locked((self._probation,),
                                              protect=key) is None:
                        break
                self._probation[key] = (solver, catalog.seqnum)
                self._note_install_locked(key)
            else:
                if not plane_on and self._probation:
                    # plane toggled off with probationers resident: drain
                    # them — disabled must behave like the plain LRU
                    while self._probation:
                        if self._evict_one_locked((self._probation,),
                                                  protect=key) is None:
                            break
                self._cache[key] = (solver, catalog.seqnum)
                self._cache.move_to_end(key)
                self._note_install_locked(key)
                while len(self._cache) > self.LRU_CAPACITY:
                    evicted_key = self._evict_one_locked((self._cache,),
                                                         protect=key)
                    if evicted_key is None:
                        break  # all pinned: bound yields to correctness
                    log.info("evicted solver for catalog hash=%x",
                             evicted_key[0])
                    if plane_on:
                        overload_metrics.EVICTIONS.inc(cause="capacity")
            # HBM pressure pass: residency, not count, is what actually
            # overflows a device — keep at least the entry just installed
            pressure = buckets.HBM.pressure()
            if plane_on:
                # low-water drain: one pass down to HBM_LOW_WATER — the
                # hysteresis band between trigger and mark keeps pressure
                # passes rare under churn instead of one-per-request
                evicted_n = 0
                if pressure is not None and pressure > HBM_PRESSURE_EVICT:
                    while (pressure is not None
                           and pressure > HBM_LOW_WATER
                           and len(self._cache) + len(self._probation) > 1):
                        evicted_key = self._evict_one_locked(
                            (self._probation, self._cache), protect=key)
                        if evicted_key is None:
                            break
                        evicted_n += 1
                        log.info("HBM pressure %.2f: evicted solver for "
                                 "catalog hash=%x (low-water drain)",
                                 pressure, evicted_key[0])
                        pressure = buckets.HBM.pressure()
                    overload_eviction.note_lowwater(evicted_n)
            else:
                while (pressure is not None
                       and pressure > HBM_PRESSURE_EVICT
                       and len(self._cache) > 1):
                    evicted_key = self._evict_one_locked((self._cache,),
                                                         protect=key)
                    if evicted_key is None:
                        break  # all pinned: bound yields to correctness
                    log.info("HBM pressure %.2f: evicted solver for "
                             "catalog hash=%x", pressure, evicted_key[0])
                    pressure = buckets.HBM.pressure()
        if plane_on:
            overload_metrics.THRASH_RATIO.set(
                self.eviction_stats()["thrash_ratio"])
        warmed = self._warm(solver, request)
        log.info("synced catalog seqnum=%d hash=%x (%d types, %d "
                 "provisioners, %d buckets warmed)",
                 catalog.seqnum, cat_hash, len(catalog.types),
                 len(provisioners), warmed)
        return self._sync_response(catalog.seqnum, cat_hash, ctx, warmed)

    @staticmethod
    def _sync_response(seqnum: int, cat_hash: int, ctx,
                       warmed: int) -> pb.SyncResponse:
        return pb.SyncResponse(
            seqnum=seqnum, catalog_hash=cat_hash,
            device_count=ctx.device_count if ctx is not None else 1,
            mesh=ctx.describe() if ctx is not None else "",
            warmed_buckets=warmed)

    def Solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        # join the caller's trace when it sent one (wire trace_context);
        # an untraced caller roots a fresh service-local trace instead
        span = TRACER.start_span(
            "solver.service.Solve",
            context=wire.trace_context_from_wire(request.trace_context),
            pods=len(request.pods))
        if request.tenant_id:
            # multi-tenant fleet callers (karpenter_tpu/fleet/) tag their
            # cluster; the solver stays tenant-blind but the trace shouldn't
            span.set_attribute("tenant", request.tenant_id)
        try:
            return self._solve_traced(request, context, span)
        except BaseException as e:  # noqa: BLE001 — context.abort raises
            span.set_attribute("error", True)
            span.set_attribute("error.type", type(e).__name__)
            raise
        finally:
            span.end()

    def _solve_traced(self, request: pb.SolveRequest, context,
                      span) -> pb.SolveResponse:
        key = (request.catalog_hash, request.provisioner_hash)
        # checkout pins the entry for the whole dispatch: a concurrent
        # Sync's eviction pass (capacity, pressure, or low-water) can
        # never release this solver's device grid mid-solve
        entry = self.checkout(key)
        if entry is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"catalog hash={request.catalog_hash:x} not synced; "
                f"re-Sync required")
        try:
            return self._solve_pinned(request, context, key, entry, span)
        finally:
            self.checkin(key)

    def _solve_pinned(self, request: pb.SolveRequest, context,
                      key: "tuple[int, int]",
                      entry: "tuple[TPUSolver, int]",
                      span) -> pb.SolveResponse:
        if request.deadline_ms and request.deadline_ms < SHED_MIN_BUDGET_MS:
            context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"{request.deadline_ms}ms of cycle budget remaining; "
                f"shedding solve")
        solver, seqnum = entry
        from ..profiling import GAP_LEDGER

        # the gap ledger's OUTERMOST wall bracket for remote callers: wire
        # decode + solve + response encode all file against this wall, and
        # the residue (lock handoffs, trace glue) is published as
        # `unaccounted` rather than silently disappearing
        with GAP_LEDGER.solve_scope("service"):
            w0 = time.perf_counter()
            pods = [wire.pod_from_wire(m) for m in request.pods]
            existing = [wire.existing_from_wire(m) for m in request.existing]
            overhead = list(request.daemon_overhead) or None
            wire_in_s = time.perf_counter() - w0
            TRACER.record_span("solver.serialize", wire_in_s,
                               direction="decode", pods=len(pods))
            GAP_LEDGER.note("serialize", wire_in_s, lane="wire")
            with self._lock:
                self._solve_count += 1
                trace_now = (self._trace_dir is not None
                             and (self._solve_count - 1) % self._trace_every == 0
                             and not self._trace_active)  # jax: ONE global profiler
                if trace_now:
                    self._trace_active = True
            t0 = time.perf_counter()
            # the hbm scope attributes this solve's delta uploads to the
            # resident solver; the rung is attributed after the solve, once
            # the bucket label is known (attribute_delta below)
            if trace_now:
                # profiling must never fail a production Solve: start/stop are
                # individually guarded so an unwritable dir or a wedged profiler
                # degrades to an untraced solve, never an aborted RPC
                started = False
                try:
                    import jax

                    jax.profiler.start_trace(self._trace_dir)
                    started = True
                except Exception as e:
                    log.warning("profiler start failed: %s", e)
                try:
                    with buckets.hbm_scope(hbm_key(key)):
                        result = solver.solve(pods, existing=existing,
                                              daemon_overhead=overhead)
                finally:
                    if started:
                        try:
                            jax.profiler.stop_trace()
                            log.info("profiler trace for solve #%d -> %s",
                                     self._solve_count, self._trace_dir)
                        except Exception as e:
                            log.warning("profiler stop failed: %s", e)
                    with self._lock:
                        self._trace_active = False
            else:
                with buckets.hbm_scope(hbm_key(key)):
                    result = solver.solve(pods, existing=existing,
                                          daemon_overhead=overhead)
            solve_ms = (time.perf_counter() - t0) * 1000
            self._record_shape(solver)
            e0 = time.perf_counter()
            resp = result_to_response(result, solve_ms, seqnum)
            wire_out_s = time.perf_counter() - e0
            TRACER.record_span("solver.serialize", wire_out_s,
                               direction="encode")
            GAP_LEDGER.note("serialize", wire_out_s, lane="wire")
            # echo the device-path observability back over the wire so the
            # CLIENT-side rpc span carries the same attributes this span does
            info = getattr(solver, "last_solve_info", None) or {}
            resp.routing = str(info.get("routing", "tpu"))
            resp.compile_cache = str(info.get("compile_cache", "unknown"))
            resp.transfer_ms = float(info.get("transfer_ms", 0.0))
            resp.bucket = str(info.get("bucket", ""))
            resp.device_count = int(info.get("device_count", 1))
            # file the solve's pending delta bytes under its actual rung
            buckets.HBM.attribute_delta(hbm_key(key), resp.bucket or "unknown")
            span.set_attributes(routing=resp.routing,
                                compile_cache=resp.compile_cache,
                                transfer_ms=resp.transfer_ms,
                                bucket=resp.bucket,
                                device_count=resp.device_count,
                                solve_ms=solve_ms)
            return resp

    def Consolidate(self, request: pb.ConsolidateRequest,
                    context) -> pb.ConsolidateResponse:
        """The consolidation search on the service's device: the controller
        ships cluster-state views (with its PDB/do-not-evict eligibility
        verdicts pre-computed), the service runs the batched candidate/pair
        kernels against the SYNCED catalog and returns the chosen action —
        the deployment's chip never has to live in the controller container
        (SURVEY.md 7.1 split)."""
        from ..models.cluster import ClusterState
        from ..oracle.consolidation import MAX_PAIR_CANDIDATES
        from ..ops.consolidate import run_consolidation

        with TRACER.start_span(
                "solver.service.Consolidate",
                context=wire.trace_context_from_wire(request.trace_context),
                nodes=len(request.nodes)) as span:
            key = (request.catalog_hash, request.provisioner_hash)
            # checkout pins the entry for the candidate search — the same
            # eviction-vs-dispatch race Solve closes (see _solve_traced)
            entry = self.checkout(key)
            if entry is None:
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"catalog hash={request.catalog_hash:x} not synced; "
                    f"re-Sync required")
            try:
                if request.deadline_ms \
                        and request.deadline_ms < SHED_MIN_BUDGET_MS:
                    context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        f"{request.deadline_ms}ms of cycle budget "
                        f"remaining; shedding consolidation")
                solver, _seqnum = entry
                cluster = ClusterState()
                eligible_names: "set[str]" = set()
                for msg in request.nodes:
                    node, node_eligible = \
                        wire.consolidation_node_from_wire(msg)
                    cluster.add_node(node)
                    if node_eligible:
                        eligible_names.add(node.name)
                overhead = list(request.daemon_overhead) or None
                # big clusters shard their candidate lanes over the
                # persistent lane mesh (data parallelism); small ones stay
                # single-chip — same crossover doctrine as the solve router
                ctx = self._device_context()
                lane_mesh = (ctx.lane_mesh if ctx is not None
                             and len(request.nodes) >= CONSOLIDATE_LANE_MESH_MIN
                             else None)
                t0 = time.perf_counter()
                action = run_consolidation(
                    cluster, solver.catalog, solver.provisioners,
                    daemon_overhead=overhead, now=request.now,
                    grid=solver.grid(),  # Sync'd device-resident — no rebuild
                    mesh=lane_mesh,
                    multi_node=request.multi_node,
                    # -1 = unset sentinel -> server default; 0 legitimately
                    # DISABLES the pair search (proto3 zero-value trap)
                    max_pair_candidates=(MAX_PAIR_CANDIDATES
                                         if request.max_pair_candidates < 0
                                         else request.max_pair_candidates),
                    candidate_filter=lambda n: n.name in eligible_names)
                ms = (time.perf_counter() - t0) * 1000
                span.set_attributes(found=action is not None,
                                    consolidate_ms=ms)
                return wire.action_to_response(action, ms)
            finally:
                self.checkin(key)

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        with self._lock:
            solver, seqnum, _ = self._mru()
            n_types = len(solver.catalog.types) if solver else 0
        return pb.HealthResponse(ok=True, backend=jax.devices()[0].platform,
                                 catalog_seqnum=seqnum, n_types=n_types)


def _generic_handler(service: SolverService) -> grpc.GenericRpcHandler:
    table = {}
    for name, (req_cls, _resp_cls) in METHODS.items():
        table[name] = grpc.unary_unary_rpc_method_handler(
            getattr(service, name),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, table)


def serve(address: str = "127.0.0.1:0", max_workers: int = 4,
          service: Optional[SolverService] = None) -> "tuple[grpc.Server, int, SolverService]":
    """Start the solver service; returns (server, bound_port, service).
    Solves are serialized per-solver by the GIL+device anyway; max_workers>1
    keeps Health responsive during long solves."""
    service = service or SolverService()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((_generic_handler(service),))
    port = server.add_insecure_port(address)
    server.start()
    log.info("solver service listening on port %d", port)
    return server, port, service
