"""Concrete batchers over the fake/real cloud API.

Parity targets:
- CreateFleet batcher — /root/reference/pkg/batcher/createfleet.go:29-110:
  merges N identical 1-capacity CreateFleet calls into one N-capacity call
  (35ms idle / 1s max / 1000 items), splits returned instance IDs back to
  callers, fans partial-fulfillment errors out to the unfilled tail.
- DescribeInstances batcher — describeinstances.go:35-120: coalesces by
  filter hash (100ms / 1s / 500), splits results per caller, per-ID retry
  fallback when an ID is missing from the batched response.
- TerminateInstances batcher — terminateinstances.go:34-128: one bucket
  (100ms / 1s / 500), splits state-changes, per-ID retry for failures.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..recovery.crashpoints import crashpoint
from ..utils import errors as cloud_errors
from ..utils.clock import Clock
from . import Batcher, one_bucket_hasher
from ..fake.cloud import CloudInstance, CreateFleetRequest, CreateFleetResponse


def _fleet_hasher(req: CreateFleetRequest):
    """Identical fleet shapes (everything except capacity) share a bucket."""
    return (req.launch_template, tuple(req.overrides), req.capacity_type,
            tuple(sorted(req.tags.items())), req.image_id, req.fleet_context)


# Transient cloud-API failures worth a budgeted retry at this layer.
# ConnectivityError (the HTTP backend's post-retry give-up) and FleetError
# (a business outcome, not a transport failure) are deliberately excluded —
# retrying them here would stack retries on retries.
_TRANSIENT_CODES = frozenset(
    {"InternalError", "ServiceUnavailable", "RequestLimitExceeded",
     "Throttling"})


def transient_cloud_failure(e: BaseException) -> bool:
    if isinstance(e, (TimeoutError, ConnectionError)):
        return True
    return (isinstance(e, cloud_errors.CloudError)
            and not isinstance(e, cloud_errors.FleetError)
            and e.code in _TRANSIENT_CODES)


def _through_policy(policy, fn):
    """Route one cloud call through the shared cloud-edge RetryPolicy
    (breaker fail-fast + budgeted backoff); None = direct call."""
    if policy is None:
        return fn()
    return policy.call(fn, retriable=transient_cloud_failure)


class CreateFleetBatcher:
    def __init__(self, cloud, clock: Optional[Clock] = None,
                 idle=0.035, max_wait=1.0, max_items=1000, policy=None):
        self.cloud = cloud
        self.policy = policy
        self._batcher: Batcher = Batcher(
            self._exec, idle, max_wait, max_items,
            hasher=_fleet_hasher, clock=clock, name="create-fleet")

    def create_fleet(self, request: CreateFleetRequest) -> CreateFleetResponse:
        """Callers send capacity=1 requests; one merged N-capacity call runs."""
        # crashpoint on the CALLER's thread (not _exec): the launch intent is
        # journaled and the request claimed, but nothing was dispatched — a
        # BaseException on the batcher's trigger thread would instead kill
        # the flush loop and wedge every waiting caller
        crashpoint("fleet.pre_dispatch")
        return self._batcher.add(request)

    def depth(self) -> int:
        return self._batcher.depth()

    def _exec(self, requests):
        total = sum(r.capacity for r in requests)
        merged = dataclasses.replace(requests[0], capacity=total)
        try:
            resp = _through_policy(self.policy,
                                   lambda: self.cloud.create_fleet(merged))
        except Exception as e:
            return [e] * len(requests)
        results = []
        ids = list(resp.instance_ids)
        orphans: "list[str]" = []
        for r in requests:
            take, ids = ids[:r.capacity], ids[r.capacity:]
            if len(take) == r.capacity:
                results.append(CreateFleetResponse(instance_ids=take, errors=list(resp.errors)))
            else:
                # partial fulfillment: unfilled callers get the pool errors
                # as an exception (createfleet.go error fan-out); any IDs in
                # their short slice are given back, not leaked
                orphans.extend(take)
                pools = [(e.instance_type, e.zone) for e in resp.errors]
                code = resp.errors[0].code if resp.errors else "UnfulfillableCapacity"
                results.append(cloud_errors.FleetError(code, pools, "fleet under-fulfilled"))
        if orphans:
            try:
                self.cloud.terminate_instances(orphans)
            except Exception:
                pass  # best-effort give-back
        return results

    def stop(self):
        self._batcher.stop()


class DescribeInstancesBatcher:
    def __init__(self, cloud, clock: Optional[Clock] = None,
                 idle=0.1, max_wait=1.0, max_items=500, policy=None):
        self.cloud = cloud
        self.policy = policy
        self._batcher: Batcher = Batcher(
            self._exec, idle, max_wait, max_items,
            hasher=one_bucket_hasher, clock=clock, name="describe-instances")

    def describe(self, instance_id: str) -> CloudInstance:
        return self._batcher.add(instance_id)

    def depth(self) -> int:
        return self._batcher.depth()

    def _exec(self, ids):
        unique = list(dict.fromkeys(ids))
        try:
            found = {i.id: i for i in _through_policy(
                self.policy, lambda: self.cloud.describe_instances(unique))}
        except Exception:
            found = {}
        results = []
        for i in ids:
            inst = found.get(i)
            if inst is None:
                # per-ID retry fallback (describeinstances.go:97-120)
                try:
                    single = _through_policy(
                        self.policy,
                        lambda i=i: self.cloud.describe_instances([i]))
                    inst = single[0] if single else None
                except Exception as e:
                    results.append(e)
                    continue
            if inst is None:
                results.append(cloud_errors.CloudError(
                    "InvalidInstanceID.NotFound", f"instance {i} not found"))
            else:
                results.append(inst)
        return results

    def stop(self):
        self._batcher.stop()


class TerminateInstancesBatcher:
    def __init__(self, cloud, clock: Optional[Clock] = None,
                 idle=0.1, max_wait=1.0, max_items=500, policy=None):
        self.cloud = cloud
        self.policy = policy
        self._batcher: Batcher = Batcher(
            self._exec, idle, max_wait, max_items,
            hasher=one_bucket_hasher, clock=clock, name="terminate-instances")

    def terminate(self, instance_id: str) -> "tuple[str, str]":
        return self._batcher.add(instance_id)

    def depth(self) -> int:
        return self._batcher.depth()

    def _exec(self, ids):
        unique = list(dict.fromkeys(ids))
        changes = {}
        try:
            for iid, state in _through_policy(
                    self.policy,
                    lambda: self.cloud.terminate_instances(unique)):
                changes[iid] = (iid, state)
        except Exception:
            # batch failed: per-ID retry (terminateinstances.go:53-128)
            for i in unique:
                try:
                    for iid, state in _through_policy(
                            self.policy,
                            lambda i=i: self.cloud.terminate_instances([i])):
                        changes[iid] = (iid, state)
                except Exception as e:
                    changes[i] = e
        return [changes.get(i, cloud_errors.CloudError(
            "InvalidInstanceID.NotFound", i)) for i in ids]

    def stop(self):
        self._batcher.stop()
