"""Generic request-coalescing engine.

Parity target: /root/reference/pkg/batcher/batcher.go — hash-bucketed queues
(:55-61), Add returning a per-caller result channel (:85-100), trigger loop
with idle/max timeout windows (waitForIdle :130-151), batched execution with
fan-out of results to callers (runCalls :153-171), DefaultHasher (hash of the
request, :103) and OneBucketHasher (:112).

Python shape: thread-based; `add()` blocks the caller on a Future while a
trigger thread coalesces same-bucket requests inside the idle/max window and
invokes the batch executor once.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Callable, Generic, Hashable, Optional, Sequence, TypeVar

from ..utils.clock import Clock

I = TypeVar("I")
O = TypeVar("O")


def default_hasher(request) -> Hashable:
    """Bucket by request equality (DefaultHasher: hashstructure of input)."""
    try:
        hash(request)
        return request
    except TypeError:
        return repr(request)


def one_bucket_hasher(request) -> Hashable:
    return "single"


class _Bucket(Generic[I, O]):
    def __init__(self):
        self.requests: "list[I]" = []
        self.futures: "list[Future]" = []
        self.first_ts: float = 0.0
        self.last_ts: float = 0.0


class Batcher(Generic[I, O]):
    """idle_seconds: flush after no new request for this long.
    max_seconds: flush no later than this after the first request.
    max_items: flush immediately at this size.
    exec_fn(requests) -> list of per-request results OR per-request Exception.
    """

    def __init__(
        self,
        exec_fn: Callable[[Sequence[I]], "Sequence[object]"],
        idle_seconds: float,
        max_seconds: float,
        max_items: int,
        hasher: Callable[[I], Hashable] = default_hasher,
        clock: Optional[Clock] = None,
        name: str = "batcher",
    ):
        self.exec_fn = exec_fn
        self.idle_seconds = idle_seconds
        self.max_seconds = max_seconds
        self.max_items = max_items
        self.hasher = hasher
        self.clock = clock or Clock()
        self.name = name
        self._buckets: "dict[Hashable, _Bucket]" = {}
        self._cond = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, name=f"{name}-trigger", daemon=True)
        self._thread.start()

    def add(self, request: I, timeout: Optional[float] = None) -> O:
        """Block until the batched call resolves this request's slice."""
        fut: Future = Future()
        key = self.hasher(request)
        with self._cond:
            if self._stopped:
                raise RuntimeError(f"{self.name} stopped")
            bucket = self._buckets.get(key)
            now = self.clock.now()
            if bucket is None:
                bucket = self._buckets[key] = _Bucket()
                bucket.first_ts = now
            bucket.requests.append(request)
            bucket.futures.append(fut)
            bucket.last_ts = now
            flush_now = len(bucket.requests) >= self.max_items
            self._cond.notify_all()
        if flush_now:
            self._flush(key)
        result = fut.result(timeout=timeout)
        if isinstance(result, Exception):
            raise result
        return result

    def _loop(self):
        while True:
            with self._cond:
                if self._stopped:
                    return
                now = self.clock.now()
                due = []
                soonest = None
                for key, b in self._buckets.items():
                    if not b.requests:
                        continue
                    deadline = min(b.last_ts + self.idle_seconds,
                                   b.first_ts + self.max_seconds)
                    if now >= deadline:
                        due.append(key)
                    else:
                        soonest = deadline if soonest is None else min(soonest, deadline)
                if not due:
                    # cap the real-time wait so FakeClock-driven deadlines are
                    # re-checked promptly even though step() can't notify us
                    timeout = None if soonest is None else min(0.05, max(0.001, soonest - now))
                    self._cond.wait(timeout=timeout)
                    continue
            for key in due:
                self._flush(key)

    def _flush(self, key) -> None:
        with self._cond:
            bucket = self._buckets.pop(key, None)
        if bucket is None or not bucket.requests:
            return
        try:
            results = self.exec_fn(bucket.requests)
            if len(results) != len(bucket.requests):
                raise RuntimeError(
                    f"{self.name}: executor returned {len(results)} results "
                    f"for {len(bucket.requests)} requests")
        except Exception as e:  # executor blew up: fan the error out to all
            results = [e] * len(bucket.requests)
        for fut, res in zip(bucket.futures, results):
            fut.set_result(res)

    def depth(self) -> int:
        """Requests currently queued awaiting a flush (statusz/introspection
        read side — a stuck executor shows up as a growing depth)."""
        with self._cond:
            return sum(len(b.requests) for b in self._buckets.values())

    def stop(self):
        with self._cond:
            self._stopped = True
            pending = list(self._buckets)
            self._cond.notify_all()
        # resolve in-flight callers instead of abandoning their futures
        for key in pending:
            self._flush(key)
        self._thread.join(timeout=2)
